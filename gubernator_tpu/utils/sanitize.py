"""Runtime concurrency sanitizers: the dynamic twin of guberlint.

``GUBER_SANITIZERS=1`` turns every named lock in the package into a
tracked wrapper feeding a per-process lock-order DAG, and arms the shm
slab rings' single-writer checks (docs/concurrency.md).  The static
rules (G007/G008/G009 in :mod:`gubernator_tpu.analysis`) prove what the
AST can see; these sanitizers catch what it cannot — orders that only
materialize under a particular interleaving, writer threads that only
exist behind a config flag — and they fail loudly at the *first*
violating acquisition, with both stacks, instead of deadlocking later.

Zero cost when off is a hard contract: :func:`lock`, :func:`rlock` and
:func:`condition` return the bare stdlib primitive (``type(lock("x"))
is type(threading.Lock())``), and the ring hooks collapse to a single
``is not None`` test.  The env knob is read once at import; tests that
need the tracked path construct :class:`LockOrderTracker` /
:class:`SlabStateSanitizer` directly or pass ``enabled=True`` to the
factories rather than mutating the environment.

Lock identity is the *name* (class-scoped, e.g. ``"TickEngine._lock"``)
not the instance, mirroring guberlint's G008 identity rule: two
engines' ``_lock`` instances never deadlock each other, but an ordering
inversion between the classes is a bug wherever the instances live.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

from gubernator_tpu.config import env_knob


def _parse_flag(v: str) -> bool:
    return v.strip().lower() not in ("", "0", "false", "no", "off")


_ENABLED: bool = bool(env_knob("GUBER_SANITIZERS", False, parse=_parse_flag))


def enabled() -> bool:
    """Whether the sanitizers were armed at process start."""
    return _ENABLED


class LockOrderViolation(AssertionError):
    """Two lock names were acquired in both orders somewhere in this
    process — a latent deadlock.  The message carries the stack that
    recorded the first order and the stack that just inverted it."""


class SingleWriterViolation(AssertionError):
    """An shm ring slab-state transition was driven from the wrong
    thread (SPSC role pin) or from an illegal prior state."""


class LockOrderTracker:
    """Process-wide happens-in-this-order DAG over lock *names*.

    Every acquisition taken while other locks are held records
    ``outer -> inner`` edges with the acquiring stack; the first
    acquisition that would close a cycle raises
    :class:`LockOrderViolation` before the process can deadlock.
    Reentrant acquisition of a name already on the thread's held stack
    (RLocks, condition reacquire) records no edge.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (outer, inner) -> formatted stack of the acquisition that
        # first established the order.
        self._edges: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()

    def held(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Node path src -> ... -> dst over recorded edges, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for (a, b) in self._edges:
                if a == node and b not in seen:
                    if b == dst:
                        return path + [b]
                    seen.add(b)
                    stack.append((b, path + [b]))
        return None

    def note_acquired(self, name: str) -> None:
        held = self.held()
        if held and name not in held:
            here = "".join(traceback.format_stack(limit=16))
            with self._mu:
                for outer in held:
                    key = (outer, name)
                    if key in self._edges:
                        continue
                    path = self._find_path(name, outer)
                    if path is not None:
                        prior = self._edges[(path[0], path[1])]
                        chain = " -> ".join(path + [name])
                        raise LockOrderViolation(
                            f"lock-order inversion: acquiring '{name}' "
                            f"while holding '{outer}', but the reverse "
                            f"order {chain} is already on record.\n"
                            f"--- stack that recorded "
                            f"'{path[0]}' -> '{path[1]}':\n{prior}"
                            f"--- stack acquiring '{name}' now:\n{here}"
                        )
                    self._edges[key] = here
        held.append(name)

    def note_released(self, name: str) -> None:
        held = self.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def reset(self) -> None:
        """Forget recorded edges (test isolation); held stacks are
        thread-local and drain naturally."""
        with self._mu:
            self._edges.clear()


# The process-wide tracker all factory-made locks feed.
TRACKER = LockOrderTracker()


class _TrackedLock:
    """``threading.Lock``/``RLock`` wrapper feeding the order DAG.
    Signature-compatible with the stdlib primitive; unknown attributes
    delegate to the inner lock."""

    __slots__ = ("_name", "_inner", "_tracker")

    def __init__(self, name: str, inner, tracker: LockOrderTracker):
        self._name = name
        self._inner = inner
        self._tracker = tracker

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            try:
                self._tracker.note_acquired(self._name)
            except BaseException:
                # Don't wedge other threads behind a lock the violating
                # acquisition will never release.
                self._inner.release()
                raise
        return got

    def release(self) -> None:
        self._inner.release()
        self._tracker.note_released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __repr__(self) -> str:
        return f"<sanitized {self._inner!r} name={self._name!r}>"


class _TrackedCondition:
    """``threading.Condition`` wrapper: acquire/release feed the order
    DAG, and ``wait``/``wait_for`` mirror the condition's internal
    release-reacquire so a parked waiter neither poisons the DAG nor
    misses the edges its reacquisition creates."""

    __slots__ = ("_name", "_inner", "_tracker")

    def __init__(self, name: str, inner: threading.Condition,
                 tracker: LockOrderTracker):
        self._name = name
        self._inner = inner
        self._tracker = tracker

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            self._tracker.note_acquired(self._name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._tracker.note_released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._tracker.note_released(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._tracker.note_acquired(self._name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._tracker.note_released(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._tracker.note_acquired(self._name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __repr__(self) -> str:
        return f"<sanitized {self._inner!r} name={self._name!r}>"


def lock(name: str, enabled: Optional[bool] = None):
    """A ``threading.Lock`` — bare when the sanitizers are off (the
    zero-cost contract), order-tracked under ``name`` when on."""
    if not (_ENABLED if enabled is None else enabled):
        return threading.Lock()
    return _TrackedLock(name, threading.Lock(), TRACKER)


def rlock(name: str, enabled: Optional[bool] = None):
    """A ``threading.RLock`` — bare when off, order-tracked when on
    (reentrant re-acquisition records no edge)."""
    if not (_ENABLED if enabled is None else enabled):
        return threading.RLock()
    return _TrackedLock(name, threading.RLock(), TRACKER)


def condition(name: str, enabled: Optional[bool] = None):
    """A ``threading.Condition`` — bare when off, order-tracked when on
    with wait()'s release/reacquire mirrored into the held stack."""
    if not (_ENABLED if enabled is None else enabled):
        return threading.Condition()
    return _TrackedCondition(name, threading.Condition(), TRACKER)


class SlabStateSanitizer:
    """Single-writer discipline for one shm slab ring, per process.

    The rings' SPSC contract (shmring.py docstring) says each ring has
    exactly one producer and one consumer; this pins the first thread
    seen in each role and asserts every later transition comes from the
    pinned thread.  ``free`` is the deliberate exception: a leased slab
    may be released from any thread (the resolver thread carries the
    :class:`ShmSlabLease`), so legality there is by *prior state*, not
    by role — freeing a slab that was popped (leased here) is the
    contract, freeing a PUBLISHED-never-popped slab loses a request and
    asserts, and freeing an already-FREE slab is tolerated (an
    idempotent stale release after :meth:`note_reset`).
    """

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self._producer: Optional[int] = None
        self._consumer: Optional[int] = None
        self._leased: Set[int] = set()

    def _pin(self, role: str, current: Optional[int]) -> int:
        me = threading.get_ident()
        if current is not None and current != me:
            raise SingleWriterViolation(
                f"{self.name}: {role} role is pinned to thread "
                f"{current} but thread {me} drove a {role} transition "
                f"— the ring's SPSC contract has two {role}s.\n"
                + "".join(traceback.format_stack(limit=16))
            )
        return me

    def note_publish(self, idx: int) -> None:
        with self._mu:
            self._producer = self._pin("producer", self._producer)

    def note_pop(self, idx: int) -> None:
        with self._mu:
            self._consumer = self._pin("consumer", self._consumer)
            self._leased.add(idx)

    def note_free(self, idx: int, was_published: bool) -> None:
        with self._mu:
            if idx in self._leased:
                self._leased.discard(idx)
                return
            if was_published:
                raise SingleWriterViolation(
                    f"{self.name}: slab {idx} freed while PUBLISHED and "
                    f"never popped — a request the consumer still owes "
                    f"an answer for was silently dropped.\n"
                    + "".join(traceback.format_stack(limit=16))
                )
            # FREE -> FREE: stale idempotent release after a reset.

    def note_reset(self) -> None:
        """Crash recovery re-legitimizes new role threads and drops
        every outstanding lease."""
        with self._mu:
            self._producer = None
            self._consumer = None
            self._leased.clear()


def ring_sanitizer(name: str,
                   enabled: Optional[bool] = None
                   ) -> Optional[SlabStateSanitizer]:
    """A fresh per-ring :class:`SlabStateSanitizer`, or None when the
    sanitizers are off — callers gate every hook on ``is not None`` so
    the off path is one attribute test."""
    if not (_ENABLED if enabled is None else enabled):
        return None
    return SlabStateSanitizer(name)
