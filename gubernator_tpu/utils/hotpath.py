"""The ``@hot_path`` marker: per-tick serving-path functions.

A function carrying this decorator is on the dispatch side of the tick
pipeline — it runs for every serving window and must QUEUE device work,
never materialize it.  The decorator is a no-op at runtime (one
attribute write at import); its value is the contract it names:
guberlint rule G001 (``gubernator_tpu/analysis``) rejects device-sync
primitives (``np.asarray`` on device values, ``.item()``,
``block_until_ready``, ``jax.device_get``, ``float()``/``bool()``
scalar materialization) inside marked functions, because one per-tick
host/device round trip is the exact regression the fused-tick
architecture exists to avoid (BASELINE.md; the bench ladder gates the
dispatch *counts*, G001 gates the *source*).

Syncs belong on the resolver side — ``TickHandle.result`` /
``resolve_ticks`` — where many windows amortize one D2H.  Nested
functions defined inside a marked function are NOT checked (they are
deferred callbacks that run elsewhere); host-side numpy work that G001
can't distinguish from a device sync is answered inline with
``# guber: allow-G001(reason)``.
"""

from __future__ import annotations


def hot_path(fn):
    """Mark ``fn`` as per-tick serving-path code (see module docstring)."""
    fn.__guber_hot_path__ = True
    return fn
