"""Prometheus metrics with the reference's family names.

Reproduces the metric catalog spread through the reference
(``gubernator.go:60-111``, ``lrucache.go:48-59``, ``global.go:50-67``,
``grpc_stats.go:41-121``; full list in ``docs/prometheus.md``) so existing
dashboards/alerts — and the metrics-as-test-oracle pattern the reference's
distributed tests rely on (``functional_test.go:2184-2276``) — carry over
unchanged.  Each daemon gets its own registry (the in-process test cluster
runs many daemons per process, like ``cluster/cluster.go``).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from gubernator_tpu.utils import sanitize

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Summary,
    generate_latest,
)
from prometheus_client.core import HistogramMetricFamily

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> Tuple[float, ...]:
    """Fixed log-spaced bucket bounds from ``lo`` up to at least ``hi``."""
    step = 10.0 ** (1.0 / per_decade)
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * step)
    return tuple(round(b, 12) for b in out)


# 100 µs … ~56 s at 4 buckets/decade — covers fastwire decode (~10 µs at
# the floor bucket) through a pathological multi-second window.
DEFAULT_BUCKETS = log_buckets(100e-6, 56.0)


class _HistogramChild:
    """One label-combination series.  The observe path takes no lock:
    a single ``list[i] += 1`` is serialized by the GIL, and the worst
    race outcome is one scrape reading a bucket/sum pair mid-update —
    acceptable skew for telemetry, and what keeps the hot serving path
    lock-free."""

    __slots__ = ("_bounds", "_counts", "_sum", "_exemplars")

    def __init__(self, bounds: Sequence[float]):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        # Per-bucket last exemplar: (trace_id, value, unix_ts) or None.
        self._exemplars: list = [None] * (len(bounds) + 1)

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        i = bisect.bisect_left(self._bounds, value)
        self._counts[i] += 1
        self._sum += value
        if trace_id is None:
            trace_id = _current_trace_id()
        if trace_id is not None:
            self._exemplars[i] = (trace_id, value, time.time())


def _current_trace_id() -> Optional[str]:
    """Trace id of the active span, or None when tracing is off.  Late
    import keeps utils.metrics importable without utils.tracing."""
    from gubernator_tpu.utils import tracing

    if not tracing.enabled():
        return None
    span = tracing.current_span()
    return None if span is None else span.context.trace_id


class Histogram:
    """Lock-light fixed-bucket histogram with optional OpenMetrics
    exemplars.

    Buckets are log-spaced and fixed at construction (DEFAULT_BUCKETS:
    100 µs – 56 s, 4/decade).  Registered as a custom collector so
    ``Metrics.expose()`` / ``Metrics.sample()`` see the standard
    ``_bucket``/``_sum``/``_count`` series; ``openmetrics()`` renders the
    OpenMetrics exposition including ``# {trace_id="…"}`` exemplars so a
    bad p99 bucket links back to the trace that landed in it."""

    def __init__(
        self,
        name: str,
        documentation: str,
        labelnames: Sequence[str] = (),
        registry: Optional[CollectorRegistry] = None,
        buckets: Optional[Sequence[float]] = None,
    ):
        self._name = name
        self._doc = documentation
        self._labelnames = tuple(labelnames)
        self._bounds = tuple(buckets if buckets is not None else DEFAULT_BUCKETS)
        if list(self._bounds) != sorted(self._bounds):
            raise ValueError("histogram buckets must be sorted")
        self._lock = sanitize.lock("Histogram._lock")  # guards child creation only
        self._children: Dict[Tuple[str, ...], _HistogramChild] = {}
        if not self._labelnames:
            self._children[()] = _HistogramChild(self._bounds)
        if registry is not None:
            registry.register(self)

    # -- write path ----------------------------------------------------
    def labels(self, **labelvalues: str) -> _HistogramChild:
        key = tuple(str(labelvalues[n]) for n in self._labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    key, _HistogramChild(self._bounds))
        return child

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        if self._labelnames:
            raise ValueError(f"{self._name} needs labels(); has labelnames")
        self._children[()].observe(value, trace_id)

    # -- read path -----------------------------------------------------
    def collect(self):
        fam = HistogramMetricFamily(
            self._name, self._doc, labels=list(self._labelnames))
        for key, child in list(self._children.items()):
            cum = 0
            rows = []
            counts = list(child._counts)
            for bound, n in zip(self._bounds, counts):
                cum += n
                rows.append((_fmt_le(bound), cum))
            rows.append(("+Inf", cum + counts[-1]))
            fam.add_metric(list(key), rows, sum_value=child._sum)
        yield fam

    def openmetrics(self) -> str:
        """OpenMetrics exposition for this family, with exemplars."""
        lines = [f"# TYPE {self._name} histogram",
                 f"# HELP {self._name} {self._doc}"]
        for key, child in sorted(self._children.items()):
            base = list(zip(self._labelnames, key))
            cum = 0
            counts = list(child._counts)
            bounds = list(self._bounds) + [float("inf")]
            for i, bound in enumerate(bounds):
                cum += counts[i]
                le = "+Inf" if bound == float("inf") else _fmt_le(bound)
                labels = "".join(f'{k}="{v}",' for k, v in base)
                line = f'{self._name}_bucket{{{labels}le="{le}"}} {cum}'
                ex = child._exemplars[i]
                if ex is not None:
                    tid, val, ts = ex
                    line += (f' # {{trace_id="{tid}"}} {_fmt_le(val)}'
                             f" {ts:.3f}")
                lines.append(line)
            label_str = ",".join(f'{k}="{v}"' for k, v in base)
            braces = f"{{{label_str}}}" if label_str else ""
            lines.append(f"{self._name}_count{braces} {cum}")
            lines.append(f"{self._name}_sum{braces} {_fmt_le(child._sum)}")
        return "\n".join(lines) + "\n"


def _fmt_le(v: float) -> str:
    """Shortest float repr (Prometheus le label convention)."""
    s = repr(float(v))
    return s[:-2] if s.endswith(".0") else s


class Metrics:
    """Per-daemon metric registry (names match the reference catalog)."""

    def __init__(self):
        self.registry = CollectorRegistry()
        reg = self.registry

        # Build stamp (Prometheus build_info convention; the reference
        # stamps Version via ldflags and logs it at startup,
        # cmd/gubernator/main.go:39,53).
        import platform as _platform

        from gubernator_tpu.version import VERSION

        self.build_info = Gauge(
            "gubernator_build_info",
            "Build/version stamp; value is always 1.",
            ["version", "python", "machine"],
            registry=reg,
        )
        self.build_info.labels(
            version=VERSION,
            python=_platform.python_version(),
            machine=_platform.machine(),
        ).set(1)

        # gubernator.go:60-111 service families.
        self.getratelimit_counter = Counter(
            "gubernator_getratelimit_counter",
            "The count of getLocalRateLimit() calls. Label \"calltype\" may "
            "be \"local\" for calls handled by the same peer, \"forward\" for "
            "calls forwarded to another peer, or \"global\" for global rate limits.",
            ["calltype"],
            registry=reg,
        )
        self.func_duration = Summary(
            "gubernator_func_duration",
            "The timings of key functions in Gubernator in seconds.",
            ["name"],
            registry=reg,
        )
        self.over_limit_counter = Counter(
            "gubernator_over_limit_counter",
            "The number of rate limit checks that are over the limit.",
            registry=reg,
        )
        self.concurrent_checks = Gauge(
            "gubernator_concurrent_checks_counter",
            "The number of concurrent GetRateLimits API calls.",
            registry=reg,
        )
        self.check_error_counter = Counter(
            "gubernator_check_error_counter",
            "The number of errors while checking rate limits.",
            ["error"],
            registry=reg,
        )
        self.command_counter = Counter(
            "gubernator_command_counter",
            "The count of commands processed by each worker in WorkerPool.",
            ["worker", "method"],
            registry=reg,
        )
        self.worker_queue_length = Gauge(
            "gubernator_worker_queue_length",
            "The count of requests queued up in WorkerPool.",
            ["method", "worker"],
            registry=reg,
        )

        # Batch-forwarding families (gubernator.go:95-110).
        self.batch_send_duration = Summary(
            "gubernator_batch_send_duration",
            "The timings of batch send operations to a remote peer.",
            ["peerAddr"],
            registry=reg,
        )
        self.batch_send_retries = Counter(
            "gubernator_batch_send_retries",
            "The count of retries occurred in asyncRequest() forwarding a "
            "request to another peer.",
            registry=reg,
        )
        self.batch_queue_length = Gauge(
            "gubernator_batch_queue_length",
            "The getRateLimitsBatch() queue length in PeerClient.",
            ["peerAddr"],
            registry=reg,
        )

        # GLOBAL manager families (global.go:50-67).
        self.global_send_duration = Summary(
            "gubernator_global_send_duration",
            "The duration of GLOBAL async sends in seconds.",
            registry=reg,
        )
        self.broadcast_duration = Summary(
            "gubernator_broadcast_duration",
            "The duration of GLOBAL broadcasts to peers in seconds.",
            registry=reg,
        )
        self.global_send_queue_length = Gauge(
            "gubernator_global_send_queue_length",
            "The count of requests queued up for global broadcast.",
            registry=reg,
        )
        self.global_queue_length = Gauge(
            "gubernator_global_queue_length",
            "The count of requests queued up for update all peers.",
            registry=reg,
        )

        # Cache families (lrucache.go:48-59 + collector :180-214).
        self.cache_size = Gauge(
            "gubernator_cache_size",
            "The number of items in LRU Cache which holds the rate limits.",
            registry=reg,
        )
        self.cache_access_count = Counter(
            "gubernator_cache_access_count",
            "Cache access counts. Label \"type\" = \"miss\" or \"hit\".",
            ["type"],
            registry=reg,
        )
        self.unexpired_evictions = Counter(
            "gubernator_unexpired_evictions_count",
            "Count the number of cache items which were evicted while "
            "unexpired.",
            registry=reg,
        )

        # gRPC stats families (grpc_stats.go:41-121).
        self.grpc_request_counts = Counter(
            "gubernator_grpc_request_counts",
            "The count of gRPC requests.",
            ["status", "method"],
            registry=reg,
        )
        self.grpc_request_duration = Summary(
            "gubernator_grpc_request_duration",
            "The timings of gRPC requests in seconds.",
            ["method"],
            registry=reg,
        )

        # TPU-native additions (no reference analog): device tick telemetry.
        self.tick_duration = Summary(
            "gubernator_tpu_tick_duration",
            "Wall time of one device tick (H2D + kernel + D2H) in seconds.",
            registry=reg,
        )
        self.tick_batch_size = Summary(
            "gubernator_tpu_tick_batch_size",
            "Requests applied per device tick.",
            registry=reg,
        )
        # Algorithm zoo (docs/algorithms.md): per-policy traffic split of
        # the mixed-policy device table.  Label \"algorithm\" is the enum
        # name (token_bucket, leaky_bucket, sliding_window, gcra,
        # concurrency); out-of-range wire values are rejected at the edge
        # and never counted here.
        self.algorithm_requests = Counter(
            "gubernator_tpu_algorithm_requests",
            "Rate-limit items accepted for ticking, by algorithm.",
            ["algorithm"],
            registry=reg,
        )
        # GLOBAL mesh reconcile telemetry: steps this daemon drove, mesh
        # programs those steps launched, and dense-fallback steps.  One
        # dispatch per step is the fused sparse/dense normal case; 2 means
        # an envelope overflow ran the dense fallback (rare by design) —
        # a sustained dispatch/step ratio near 2.0 means the envelope is
        # under-sized for the traffic (or the probe fusion regressed).
        self.mesh_reconcile_count = Counter(
            "gubernator_tpu_mesh_reconcile_count",
            "GLOBAL mesh reconcile steps driven by this daemon.",
            registry=reg,
        )
        self.mesh_reconcile_dispatches = Counter(
            "gubernator_tpu_mesh_reconcile_dispatches",
            "Jitted mesh programs launched by this daemon's reconcile "
            "steps (1 per fused sparse or dense step; +1 when an "
            "envelope overflow runs the dense fallback).",
            registry=reg,
        )
        self.mesh_dense_fallbacks = Counter(
            "gubernator_tpu_mesh_dense_fallbacks",
            "Sparse reconcile steps that overflowed the envelope and "
            "fell back to the dense program.",
            registry=reg,
        )
        # Sharded serving table (parallel/mesh_engine.py): the ragged
        # flat tick is the ONE serving format — each shard walks its
        # own extent of the slot-sorted batch, so there is no per-shard
        # width to overflow.  The overflow counter survives as a
        # pinned-zero canary (check_bench_regression gates it at 0).
        self.mesh_routed_windows = Counter(
            "gubernator_tpu_mesh_routed_windows",
            "Serving windows dispatched through the ragged flat tick "
            "(each shard walks its own extent of the slot-sorted "
            "batch on device).",
            registry=reg,
        )
        self.mesh_routed_overflows = Counter(
            "gubernator_tpu_mesh_routed_overflows",
            "Pinned-zero canary: the retired routed path's skew "
            "fallback count. The ragged dispatch has no per-shard "
            "width, so any increment is a bug.",
            registry=reg,
        )

        # Tiered bucket state (docs/tiering.md): demote/promote traffic
        # between the device table and the host-side cold store, tier
        # occupancy, and requests shed with per-item errors when the
        # table is truly full (eviction freed nothing).
        self.cold_demotions = Counter(
            "gubernator_tpu_cold_demotions",
            "Bucket rows demoted from the device table into the "
            "host-side cold store (readback-then-evict).",
            registry=reg,
        )
        self.cold_promotions = Counter(
            "gubernator_tpu_cold_promotions",
            "Bucket rows promoted from the cold store back into the "
            "device table (batched restore scatter on the miss path).",
            registry=reg,
        )
        self.cold_hits = Counter(
            "gubernator_tpu_cold_hits",
            "Cache misses that found their bucket in the cold store.",
            registry=reg,
        )
        self.cold_size = Gauge(
            "gubernator_tpu_cold_size",
            "The number of entries currently held by the cold store.",
            registry=reg,
        )
        # SSD third tier (docs/tiering.md): demote/promote traffic
        # between the cold store and the slab store, slab occupancy in
        # bytes, compaction rounds, and the writer queue level.
        self.ssd_demotions = Counter(
            "gubernator_tpu_ssd_demotions",
            "Bucket rows demoted from the cold store into the SSD slab "
            "store (batched write-behind on cold-tier overflow).",
            registry=reg,
        )
        self.ssd_promotions = Counter(
            "gubernator_tpu_ssd_promotions",
            "Bucket rows promoted from the SSD slab store back up the "
            "tiers (one batched lookup per miss tick).",
            registry=reg,
        )
        self.ssd_hits = Counter(
            "gubernator_tpu_ssd_hits",
            "Miss-path SSD lookups that found their bucket in a slab.",
            registry=reg,
        )
        self.ssd_compactions = Counter(
            "gubernator_tpu_ssd_compactions",
            "Log-structured compaction rounds (a sealed slab's live "
            "rows rewritten forward, the file retired).",
            registry=reg,
        )
        self.ssd_bytes = Gauge(
            "gubernator_tpu_ssd_bytes",
            "Bytes currently held across SSD slab files.",
            registry=reg,
        )
        self.ssd_queue_depth = Gauge(
            "gubernator_tpu_ssd_queue_depth",
            "Demote batches waiting on the SSD writer queue (at the "
            "configured depth, demote sweeps block — backpressure).",
            registry=reg,
        )
        self.hot_occupancy = Gauge(
            "gubernator_tpu_hot_occupancy",
            "Fraction of device bucket-table slots holding a mapped key "
            "(0.0-1.0).",
            registry=reg,
        )
        self.h2d_overlap_ratio = Gauge(
            "gubernator_tpu_h2d_overlap_ratio",
            "Fraction of serving windows whose request upload was "
            "dispatched while an earlier window's tick was still "
            "unresolved (0.0 serial, ~1.0 pipelined steady state).",
            registry=reg,
        )
        self.shed_requests = Counter(
            "gubernator_tpu_shed_requests",
            "Requests answered with a per-item 'table full' error "
            "because the table was full and eviction freed nothing "
            "(the rest of their batch was still served).",
            registry=reg,
        )

        # Fault-tolerant peer path (docs/resilience.md): per-peer breaker
        # state, redelivery accounting for GLOBAL hits/broadcasts that
        # failed to flush, degraded GLOBAL answers served while the
        # owner's breaker was open, and background-loop crash restarts.
        self.breaker_state = Gauge(
            "gubernator_breaker_state",
            "Circuit breaker state per peer: 0=closed, 1=half-open, 2=open.",
            ["peerAddr"],
            registry=reg,
        )
        self.breaker_transitions = Counter(
            "gubernator_breaker_transitions",
            "Circuit breaker state transitions per peer; label \"to\" is "
            "the state entered (closed/half_open/open).",
            ["peerAddr", "to"],
            registry=reg,
        )
        self.degraded_answers = Counter(
            "gubernator_degraded_answers",
            "GLOBAL requests answered from local non-owner state while "
            "the owning peer's circuit breaker was open (degraded mode).",
            registry=reg,
        )
        self.global_redelivered_hits = Counter(
            "gubernator_global_redelivered_hits",
            "GLOBAL hit records re-enqueued into the redelivery buffer "
            "after a failed flush to the owning peer.",
            registry=reg,
        )
        self.global_dropped_hits = Counter(
            "gubernator_global_dropped_hits",
            "GLOBAL hit records dropped because the redelivery buffer "
            "was at its cap (GUBER_REDELIVERY_LIMIT) — lost accounting.",
            registry=reg,
        )
        self.global_redelivered_broadcasts = Counter(
            "gubernator_global_redelivered_broadcasts",
            "GLOBAL update records re-enqueued for broadcast after a "
            "failed push to one or more peers.",
            registry=reg,
        )
        self.global_dropped_broadcasts = Counter(
            "gubernator_global_dropped_broadcasts",
            "GLOBAL update records dropped because the broadcast "
            "redelivery buffer was at its cap.",
            registry=reg,
        )
        # Crash-safe persistence (docs/persistence.md): snapshot write
        # traffic, restore damage, and GLOBAL ownership handoff on ring
        # churn.
        self.snapshot_writes = Counter(
            "gubernator_tpu_snapshot_writes",
            "Snapshot records durably written; label \"kind\" is \"delta\" "
            "(incremental dirty export) or \"base\" (full compaction / "
            "final shutdown snapshot).",
            ["kind"],
            registry=reg,
        )
        self.snapshot_items = Counter(
            "gubernator_tpu_snapshot_items",
            "Bucket rows carried by durably written snapshot records, "
            "by record kind.",
            ["kind"],
            registry=reg,
        )
        self.snapshot_duration = Summary(
            "gubernator_tpu_snapshot_duration",
            "Wall time of one snapshot write (engine export + encode + "
            "fsync) in seconds, by record kind.",
            ["kind"],
            registry=reg,
        )
        self.snapshot_corrupt_records = Counter(
            "gubernator_tpu_snapshot_corrupt_records",
            "Corrupt or truncated snapshot records skipped during "
            "startup restore (replay stops at the last good prefix; "
            "the service still starts).",
            registry=reg,
        )
        self.snapshot_restored_items = Counter(
            "gubernator_tpu_snapshot_restored_items",
            "Bucket rows replayed from the snapshot store at startup "
            "(before TTL expiry filtering).",
            registry=reg,
        )
        self.ownership_transfers = Counter(
            "gubernator_tpu_ownership_transfers",
            "GLOBAL keys whose accumulated state was handed to a new "
            "owning peer after a ring change; label \"result\" is "
            "\"pushed\" (landed on the new owner), \"requeued\" (push "
            "failed; retried via the broadcast redelivery buffer), or "
            "\"untracked\" (tracker at GUBER_REDELIVERY_LIMIT when the "
            "key updated — its state will not ride a handoff).",
            ["result"],
            registry=reg,
        )
        # Elastic live resharding (docs/resharding.md): transition
        # outcomes, the running transition's phase/size, verification
        # counters gated at zero by the reshard_live bench rung, and the
        # transition wall time.
        self.reshard_transitions = Counter(
            "gubernator_tpu_reshard_transitions",
            "Reshard transitions by terminal outcome: \"committed\" (new "
            "layout serving), \"aborted\" (rolled back to the old "
            "layout), \"interrupted\" (a begin record with no terminal "
            "record found at startup — the process died mid-transition "
            "and restarted on the last snapshot).",
            ["result"],
            registry=reg,
        )
        self.reshard_phase = Gauge(
            "gubernator_tpu_reshard_phase",
            "Current reshard protocol phase: 0=idle, 1=freeze, 2=drain, "
            "3=relayout, 4=cutover, 5=verify (returns to 0 on commit or "
            "abort).",
            registry=reg,
        )
        self.reshard_shards = Gauge(
            "gubernator_tpu_reshard_shards",
            "Serving shard count after the most recent committed "
            "transition (the engine's live mesh width).",
            registry=reg,
        )
        self.reshard_state_loss = Counter(
            "gubernator_tpu_reshard_state_loss",
            "Bucket rows live before a transition but missing from the "
            "post-cutover table (verify phase). Must stay 0; gated at "
            "ABSOLUTE_ZERO by the reshard_live bench rung.",
            registry=reg,
        )
        self.reshard_double_served = Counter(
            "gubernator_tpu_reshard_double_served",
            "Keys resident on more than one shard after a cutover "
            "(verify phase) — each is a potential double-serve. Must "
            "stay 0; gated at ABSOLUTE_ZERO by the reshard_live rung.",
            registry=reg,
        )
        self.reshard_duration = Summary(
            "gubernator_tpu_reshard_duration",
            "Wall time of one reshard transition (freeze through verify) "
            "in seconds, by terminal outcome.",
            ["result"],
            registry=reg,
        )
        # Multi-region federation (docs/federation.md): envelope traffic,
        # redelivery attempts, worst-case cross-region drift age, and
        # MULTI_REGION answers served while a peer region was down.
        self.federation_envelopes = Counter(
            "gubernator_tpu_federation_envelopes",
            "Federation envelopes by outcome: \"sent\" (acked by the "
            "remote owning peer), \"applied\" (received from a peer "
            "region and applied locally), \"duplicate\" (received again "
            "after a lost ack; acked without re-applying).",
            ["result"],
            registry=reg,
        )
        self.federation_redeliveries = Counter(
            "gubernator_tpu_federation_redeliveries",
            "Federation envelope send attempts that failed (breaker "
            "open, RPC error, malformed ack) and will retry the same "
            "envelope after a jittered backoff.",
            registry=reg,
        )
        self.federation_staleness = Gauge(
            "gubernator_tpu_federation_staleness_seconds",
            "Age of the oldest cross-region hit delta not yet acked by "
            "its target region (pending or in flight); the live bound "
            "on inter-region over-admission drift.",
            registry=reg,
        )
        self.federation_degraded_answers = Counter(
            "gubernator_tpu_federation_degraded_answers",
            "MULTI_REGION requests answered from region-local state "
            "while at least one peer region was unreachable (its "
            "channel failing or breaker open) — each may over-admit up "
            "to the staleness budget.",
            registry=reg,
        )
        # Guardrailed shard autoscaler (docs/autoscaling.md): every
        # control decision, every actuated transition, and every
        # guardrail veto by name — the outside view of the controller.
        self.autoscale_decisions = Counter(
            "gubernator_tpu_autoscale_decisions",
            "Autoscaler control decisions by action: \"act\" (a "
            "transition was actuated, or would have been in dry-run), "
            "\"hold\" (no sustained pressure / already at a bound), "
            "\"veto\" (a guardrail blocked an otherwise-justified "
            "transition).",
            ["action"],
            registry=reg,
        )
        self.autoscale_transitions = Counter(
            "gubernator_tpu_autoscale_transitions",
            "Committed shard transitions actuated by the autoscaler, by "
            "direction (\"up\"/\"down\"); dry-run decisions and aborted "
            "transitions are not counted here.",
            ["direction"],
            registry=reg,
        )
        self.autoscale_vetoes = Counter(
            "gubernator_tpu_autoscale_vetoes",
            "Autoscaler decisions blocked by a guardrail, by reason: "
            "breaker_open, reshard_busy, cooldown_up, cooldown_down, "
            "flap_cap, reshard_error.",
            ["reason"],
            registry=reg,
        )
        self.loop_restarts = Counter(
            "gubernator_loop_restarts",
            "Background loops (global_hits, global_broadcast, peer_batch) "
            "restarted by their crash supervisor after an unexpected "
            "exception.",
            ["loop"],
            registry=reg,
        )

        # Serving telemetry plane (docs/observability.md): per-method RPC
        # latency and per-stage window latency as log-spaced histograms
        # (exemplars link a bad bucket to its trace when tracing is on),
        # plus the slow-window watchdog counter.
        self.grpc_duration_hist = Histogram(
            "gubernator_tpu_grpc_duration_seconds",
            "Per-method gRPC request latency histogram (log-spaced "
            "buckets; OpenMetrics exemplars carry the request span's "
            "trace id).",
            ["method"],
            registry=reg,
        )
        self.stage_duration = Histogram(
            "gubernator_tpu_stage_duration_seconds",
            "Per-stage serving-window latency histogram (stages: decode, "
            "lease, pack, h2d, tick, resolve, encode), fed by the flight "
            "recorder when one is installed.",
            ["stage"],
            registry=reg,
        )
        self.slow_windows = Counter(
            "gubernator_tpu_slow_windows",
            "Serving windows whose summed stage time exceeded "
            "GUBER_SLOW_WINDOW_MS; each one's flight record is dumped to "
            "the log by the watchdog.",
            registry=reg,
        )

        # Overload control plane (docs/overload.md): bounded-ingest
        # fallback accounting, shed verdicts by reason, and the adaptive
        # limiter's admitted window width / queue occupancy.
        self.arena_fallbacks = Counter(
            "gubernator_tpu_arena_fallbacks",
            "Wire-decode batches served from plain numpy allocations "
            "because every arena slab was busy; capped per window by "
            "GUBER_INGEST_FALLBACK_LIMIT, shed beyond the cap.",
            registry=reg,
        )
        self.admission_shed = Counter(
            "gubernator_tpu_admission_shed",
            "Requests shed by the admission plane, by reason: expired "
            "(deadline passed before packing), overflow (bounded queue "
            "full), shutdown (drained at close), backpressure (ingest "
            "arena exhausted past the fallback cap).",
            ["reason"],
            registry=reg,
        )
        self.admission_queue_depth = Gauge(
            "gubernator_tpu_admission_queue_depth",
            "Requests waiting in the bounded two-class admission queue "
            "(peer reconcile traffic + client traffic).",
            registry=reg,
        )
        self.admission_window_limit = Gauge(
            "gubernator_tpu_admission_window_limit",
            "Current AIMD-admitted window width in requests (static "
            "batch_limit when GUBER_TARGET_P99_MS is 0).",
            registry=reg,
        )
        self.admission_expired_served = Counter(
            "gubernator_tpu_admission_expired_served",
            "Invariant violations: requests whose deadline had already "
            "expired at pack time but that reached the engine anyway. "
            "Must stay 0; gated by the overload_shed bench rung.",
            registry=reg,
        )

        # Cooperative quota-lease families (docs/leases.md).
        self.lease_grants = Counter(
            "gubernator_tpu_lease_grants",
            "Quota leases minted: budget delegated to a client for "
            "TTL-bounded local self-enforcement.",
            registry=reg,
        )
        self.lease_renewals = Counter(
            "gubernator_tpu_lease_renewals",
            "Cheap lease extensions: held budget re-signed with a "
            "pushed-out TTL instead of a fresh decision (the overload "
            "degrade path).",
            registry=reg,
        )
        self.lease_revocations = Counter(
            "gubernator_tpu_lease_revocations",
            "Lease generations bumped (limit config changed or explicit "
            "revoke); outstanding tokens die at their next sync.",
            registry=reg,
        )
        self.lease_sync_loss = Counter(
            "gubernator_tpu_lease_sync_loss",
            "Admissions reported by lease syncs beyond the granted "
            "budget (stale-generation or misbehaving clients); "
            "force-charged to the bucket on reconcile.",
            registry=reg,
        )
        self.lease_sync_dropped = Counter(
            "gubernator_tpu_lease_sync_dropped",
            "Lease reconcile accounting that never reached the bucket: "
            "credit/charge decisions shed under overload, force-charges "
            "bounced off the bucket floor, or excess synced against a "
            "key with no known config.",
            registry=reg,
        )

        # Multi-process streaming edge families (docs/edge.md): worker
        # processes write a shm counter block; the owner's supervisor
        # delta-syncs it into these, labelled per worker so one hot
        # worker is visible as itself.
        self.edge_decode_seconds = Counter(
            "gubernator_tpu_edge_decode_seconds",
            "Wire-decode CPU spent inside edge worker processes "
            "(off the device-owner's GIL).",
            ["worker"],
            registry=reg,
        )
        self.edge_windows = Counter(
            "gubernator_tpu_edge_windows",
            "Request windows decoded and published into the shm slab "
            "ring by each edge worker.",
            ["worker"],
            registry=reg,
        )
        self.edge_rows = Counter(
            "gubernator_tpu_edge_rows",
            "Request rows (rate-limit items) published by each edge "
            "worker.",
            ["worker"],
            registry=reg,
        )
        self.edge_acked_windows = Counter(
            "gubernator_tpu_edge_acked_windows",
            "Windows whose response matrix came back through the shm "
            "response ring and was acked by the worker.",
            ["worker"],
            registry=reg,
        )
        self.edge_backpressure_waits = Counter(
            "gubernator_tpu_edge_backpressure_waits",
            "Worker waits on its own full slab ring or response depth — "
            "the per-producer backpressure bound engaging.",
            ["worker"],
            registry=reg,
        )
        self.edge_shed = Counter(
            "gubernator_tpu_edge_shed",
            "Edge rows shed retriably, by reason: 'local' (worker spun "
            "out on its full ring), 'crash' (in-flight slabs of a dead "
            "worker), 'shutdown' (plane close).",
            ["worker", "reason"],
            registry=reg,
        )
        self.edge_worker_restarts = Counter(
            "gubernator_tpu_edge_worker_restarts",
            "Edge worker processes respawned by the supervisor after a "
            "crash.",
            ["worker"],
            registry=reg,
        )

    def register_flag_collectors(self, metric_flags: int) -> None:
        """Register OS / runtime collectors behind ``GUBER_METRIC_FLAGS``
        (reference flags.go:20-23 + daemon.go:276-287).  "os" → process
        collector under the ``gubernator`` namespace; "golang" → the
        host-runtime collectors (Python GC + platform, the analog of Go's
        GoCollector)."""
        from gubernator_tpu.config import FLAG_OS_METRICS, FLAG_RUNTIME_METRICS

        if metric_flags & FLAG_OS_METRICS:
            from prometheus_client import ProcessCollector

            ProcessCollector(namespace="gubernator", registry=self.registry)
        if metric_flags & FLAG_RUNTIME_METRICS:
            from prometheus_client import GCCollector, PlatformCollector

            GCCollector(registry=self.registry)
            PlatformCollector(registry=self.registry)

    def sample(self, name: str, labels: dict | None = None) -> float:
        """Read one sample value (0.0 when unobserved) — the oracle the
        reference's distributed tests poll instead of sleeping
        (functional_test.go:2184-2276 waitForBroadcast/waitForUpdate).
        Summaries expose ``<name>_count`` / ``<name>_sum``."""
        v = self.registry.get_sample_value(name, labels or {})
        return 0.0 if v is None else v

    def expose(self) -> bytes:
        """Render the registry in Prometheus text exposition format."""
        return generate_latest(self.registry)
