"""Per-window stage timer / flight recorder (docs/observability.md).

A preallocated ring of serving-window records: each window that flows
through ``TickLoop`` → ``TickEngine``/``MeshTickEngine`` gets one row
holding its per-stage wall time (decode, arena lease, pack, H2D
dispatch, tick, resolve, encode) plus queue depth and batch width.

Gating mirrors ``tracing.enabled()``: recording happens only while a
recorder is installed (``install()``), so an un-instrumented daemon pays
a single ``is None`` check per window.  The record path itself is
``@hot_path`` code — host-scalar writes into preallocated numpy arrays,
no device syncs, no locks on the per-stage ``note`` path (each
(window, stage) cell has exactly one writer).

Stage semantics:

- ``decode``/``encode`` are transport edges recorded per request batch
  via ``edge()``; decode time accumulates and folds into the *next*
  window begun, encode attaches to the most recently finished window
  (a window's decode is the CPU that fed it; its encode trails it).
- ``pack`` includes the arena ``lease`` (also broken out separately);
  ``ssd`` is the miss path's batched slab-store lookup, broken OUT of
  ``pack`` (the engine subtracts it), so a pack regression can't hide
  SSD I/O and vice versa.
- ``tick`` is the shared D2H wait of the resolver drain that resolved
  the window; windows resolved in one drain report the same tick time.

The slow-window watchdog is split so the hot path stays cheap:
``finish()`` only compares the row total against ``slow_threshold_s``
and parks offenders in a small deque; a supervised loop in the daemon
drains them (``drain_slow()``), dumps each record, and bumps
``gubernator_tpu_slow_windows``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from gubernator_tpu.utils.hotpath import hot_path
from gubernator_tpu.utils import sanitize

STAGES = (
    "decode", "lease", "pack", "ssd", "h2d", "tick", "resolve", "encode",
)
_IDX = {s: i for i, s in enumerate(STAGES)}
_DECODE = _IDX["decode"]
_ENCODE = _IDX["encode"]


class FlightRecorder:
    """Preallocated ring of per-window stage records."""

    def __init__(
        self,
        windows: int = 256,
        clock: Callable[[], float] = time.time,
        slow_threshold_s: float = 0.0,
    ):
        if windows < 2:
            raise ValueError("flight recorder needs at least 2 windows")
        self.windows = windows
        self.clock = clock
        self.slow_threshold_s = slow_threshold_s
        # Optional sink: called as observer(stage, seconds) at finish()
        # (the daemon wires it to the per-stage latency histogram).
        self.observer: Optional[Callable[[str, float], None]] = None
        self._lock = sanitize.lock("FlightRecorder._lock")
        self._stage_s = np.zeros((windows, len(STAGES)), np.float64)
        self._width = np.zeros(windows, np.int64)
        self._depth = np.zeros(windows, np.int64)
        self._wall = np.zeros(windows, np.float64)
        self._valid = np.zeros(windows, bool)
        self._seq = 0
        self._active: Optional[int] = None
        self._pending_decode = 0.0
        self.slow_total = 0
        self._slow: deque = deque(maxlen=32)

    # -- record path (hot) ---------------------------------------------
    @hot_path
    def begin(self, width: int, depth: int) -> int:
        """Open a window record at dispatch time; returns its id."""
        with self._lock:
            wid = self._seq
            self._seq = wid + 1
            slot = wid % self.windows
            self._stage_s[slot, :] = 0.0
            self._valid[slot] = False
            self._width[slot] = width
            self._depth[slot] = depth
            self._wall[slot] = self.clock()
            self._stage_s[slot, _DECODE] = self._pending_decode
            self._pending_decode = 0.0
            self._active = wid
        return wid

    @hot_path
    def note(self, wid: Optional[int], stage: str, seconds: float) -> None:
        """Accumulate ``seconds`` into one stage cell of window ``wid``."""
        if wid is None or wid < 0 or self._seq - wid > self.windows:
            return
        self._stage_s[wid % self.windows, _IDX[stage]] += seconds

    @hot_path
    def finish(self, wid: int) -> None:
        """Seal a window record; runs the cheap slow-window check."""
        if wid < 0 or self._seq - wid > self.windows:
            return
        slot = wid % self.windows
        self._valid[slot] = True
        obs = self.observer
        if obs is not None:
            row = self._stage_s[slot]
            for stage, i in _IDX.items():
                if row[i] > 0.0:
                    obs(stage, row[i])
        thresh = self.slow_threshold_s
        if thresh > 0.0:
            total = self._stage_s[slot].sum()
            if total > thresh:
                with self._lock:
                    self.slow_total += 1
                    self._slow.append((
                        wid,
                        self._stage_s[slot].copy(),
                        self._width[slot],
                        self._depth[slot],
                        self._wall[slot],
                    ))

    def active(self) -> Optional[int]:
        """Window id currently in engine dispatch (``None`` between)."""
        return self._active

    def end_dispatch(self, wid: int) -> None:
        if self._active == wid:
            self._active = None

    def edge(self, stage: str, seconds: float) -> None:
        """Record a transport-edge stage (decode/encode) for one batch."""
        if stage == "decode":
            with self._lock:
                self._pending_decode += seconds
        else:
            with self._lock:
                last = self._seq - 1
                if last >= 0:
                    self._stage_s[last % self.windows, _ENCODE] += seconds
        obs = self.observer
        if obs is not None:
            obs(stage, seconds)

    # -- read path -----------------------------------------------------
    def recent(self, n: int = 64) -> List[dict]:
        """Finished window records, oldest→newest, as JSON-ready dicts."""
        out: List[dict] = []
        with self._lock:
            seq = self._seq
            lo = max(0, seq - min(n, self.windows))
            for wid in range(lo, seq):
                slot = wid % self.windows
                if not self._valid[slot]:
                    continue
                stages = {
                    s: round(float(self._stage_s[slot, i]) * 1e3, 4)
                    for s, i in _IDX.items()
                }
                out.append({
                    "window": wid,
                    "wall": float(self._wall[slot]),
                    "width": int(self._width[slot]),
                    "queue_depth": int(self._depth[slot]),
                    "stages_ms": stages,
                    "total_ms": round(sum(stages.values()), 4),
                })
        return out

    def stage_percentiles(self) -> Dict[str, Dict[str, float]]:
        """Per-stage p50/p99 (ms) over finished windows in the ring.
        Zero cells (stage never ran in that window) are excluded."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            mask = self._valid.copy()
            stage_s = self._stage_s.copy()
        for s, i in _IDX.items():
            col = stage_s[mask, i]
            col = col[col > 0.0]
            if col.size == 0:
                out[s] = {"p50_ms": 0.0, "p99_ms": 0.0}
            else:
                out[s] = {
                    "p50_ms": round(float(np.percentile(col, 50)) * 1e3, 4),
                    "p99_ms": round(float(np.percentile(col, 99)) * 1e3, 4),
                }
        return out

    def snapshot(self) -> dict:
        """Per-stage and whole-window p50/p99 (ms) plus ring metadata —
        the control plane's view (autoscaler, /debug/autoscaler).  Not
        ``@hot_path``: one lock-copy on the controller's cadence."""
        with self._lock:
            mask = self._valid.copy()
            stage_s = self._stage_s.copy()
            slow_total = self.slow_total
        totals = stage_s[mask].sum(axis=1)
        totals = totals[totals > 0.0]
        if totals.size == 0:
            total = {"p50_ms": 0.0, "p99_ms": 0.0}
        else:
            total = {
                "p50_ms": round(float(np.percentile(totals, 50)) * 1e3, 4),
                "p99_ms": round(float(np.percentile(totals, 99)) * 1e3, 4),
            }
        out: Dict[str, Dict[str, float]] = {}
        for s, i in _IDX.items():
            col = stage_s[mask, i]
            col = col[col > 0.0]
            if col.size == 0:
                out[s] = {"p50_ms": 0.0, "p99_ms": 0.0}
            else:
                out[s] = {
                    "p50_ms": round(float(np.percentile(col, 50)) * 1e3, 4),
                    "p99_ms": round(float(np.percentile(col, 99)) * 1e3, 4),
                }
        return {
            "stages": out,
            "total": total,
            "windows": int(mask.sum()),
            "ring_size": self.windows,
            "slow_total": slow_total,
        }

    def drain_slow(self) -> List[dict]:
        """Pop pending slow-window dumps (watchdog loop calls this)."""
        out: List[dict] = []
        with self._lock:
            while self._slow:
                wid, row, width, depth, wall = self._slow.popleft()
                out.append({
                    "window": int(wid),
                    "wall": float(wall),
                    "width": int(width),
                    "queue_depth": int(depth),
                    "stages_ms": {
                        s: round(float(row[i]) * 1e3, 4)
                        for s, i in _IDX.items()
                    },
                    "total_ms": round(float(row.sum()) * 1e3, 4),
                })
        return out


# ---------------------------------------------------------------------
# Process-global recorder slot (mirrors tracing's global tracer: the
# in-process test cluster shares one recorder across daemons).
_recorder: Optional[FlightRecorder] = None


def install(recorder: FlightRecorder) -> None:
    global _recorder
    _recorder = recorder


def uninstall() -> None:
    global _recorder
    _recorder = None


def get() -> Optional[FlightRecorder]:
    return _recorder


def enabled() -> bool:
    return _recorder is not None
