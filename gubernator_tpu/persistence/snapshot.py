"""Crash-safe columnar snapshot store: base + append-only deltas + manifest.

The durable layer under the engine's Loader-v2 columnar snapshots
(``engine.SNAP_FIELDS``; docs/persistence.md).  On disk, one snapshot
directory holds a *generation*: a full **base** snapshot, one append-only
**delta log** fed by ``export_columns(dirty_only=True)`` flushes, and a
**manifest** naming both.  Restore = load base, replay delta records in
append order (``load_columns`` applies them as upserts, last write wins),
TTL-expire stale rows (the engine's ``expire_at`` filter), serve.

Durability discipline:

* Every record — base and delta alike — is framed ``MAGIC | crc32 | len``
  with the CRC over the payload; a torn write is detected, never parsed.
* Base and manifest writes go write-to-temp → ``fsync`` → ``rename``
  (atomic on POSIX): a crash mid-write leaves the previous generation
  intact.  Delta appends ``flush`` + ``fsync`` before returning, so an
  acknowledged delta survives power loss.
* Replay **never raises** on bad data: a corrupt or truncated record
  stops that file's replay at the last good prefix and counts the damage
  (``corrupt_records``) — a half-written tail from a kill -9 costs at
  most the records after it, not the restore.
* Compaction (every ``deltas_per_base`` appended records) folds base +
  deltas into a fresh base under the NEXT generation number, then
  retires the old files — the old generation stays valid until the new
  manifest rename lands.
* A missing/corrupt manifest falls back to scanning the directory for
  the newest generation with a readable base — losing the manifest
  costs nothing but the scan.
"""

from __future__ import annotations

import io
import json
import logging
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("gubernator.persistence")

MAGIC = b"GSNP"
_HEADER = struct.Struct("<4sIQ")  # magic, crc32(payload), payload length
MANIFEST = "MANIFEST.json"


def _base_name(gen: int) -> str:
    return f"base-{gen:08d}.snap"


def _delta_name(gen: int) -> str:
    return f"delta-{gen:08d}.log"


def _fsync_dir(path: str) -> None:
    """Durably record renames/creates in ``path`` (best-effort: not every
    filesystem supports directory fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def encode_snapshot(snap: dict) -> bytes:
    """Columnar snapshot dict → npz payload bytes (the ColumnFileLoader
    encoding: ``key_blob`` rides as a uint8 array)."""
    enc = dict(snap)
    enc["key_blob"] = np.frombuffer(
        bytes(snap["key_blob"]), np.uint8
    )
    buf = io.BytesIO()
    np.savez(buf, **enc)
    return buf.getvalue()


def decode_snapshot(payload: bytes) -> dict:
    """Inverse of :func:`encode_snapshot`."""
    with np.load(io.BytesIO(payload)) as z:
        snap = {k: z[k] for k in z.files}
    snap["key_blob"] = snap["key_blob"].tobytes()
    return snap


def snapshot_items(snap: dict) -> int:
    return max(0, len(snap["key_offsets"]) - 1)


def write_record(f, payload: bytes) -> int:
    """Append one CRC-framed record; returns bytes written (header incl.)."""
    header = _HEADER.pack(MAGIC, zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    f.write(header)
    f.write(payload)
    return len(header) + len(payload)


def read_records(path: str) -> Tuple[List[bytes], int]:
    """All valid record payloads from ``path``, stopping at the first
    corrupt or truncated record: ``(payloads, corrupt_records)``.  Never
    raises on bad data — a missing file is simply ``([], 0)``."""
    payloads: List[bytes] = []
    corrupt = 0
    try:
        f = open(path, "rb")
    except OSError:
        return payloads, corrupt
    with f:
        while True:
            header = f.read(_HEADER.size)
            if not header:
                break  # clean EOF
            if len(header) < _HEADER.size:
                corrupt += 1  # torn header (partial final write)
                break
            magic, crc, length = _HEADER.unpack(header)
            if magic != MAGIC:
                corrupt += 1  # framing lost; nothing after is trustworthy
                break
            payload = f.read(length)
            if len(payload) < length:
                corrupt += 1  # truncated tail
                break
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                corrupt += 1  # bit rot / torn payload
                break
            payloads.append(payload)
    return payloads, corrupt


@dataclass
class RestoreResult:
    """What a restore read: snapshots in replay order + damage counters."""

    snapshots: List[dict] = field(default_factory=list)
    generation: int = 0
    items: int = 0
    delta_records: int = 0
    corrupt_records: int = 0
    manifest_missing: bool = False


class SnapshotStore:
    """One snapshot directory (see module doc).  Not thread-safe by
    itself — the SnapshotWriter serializes all writers; restore runs
    before serving starts."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        # Adopt the newest on-disk generation immediately so a writer
        # that skips load() still appends to the log the manifest names
        # (not a phantom generation 0 that restore would never read).
        manifest = self._read_manifest()
        if manifest is not None:
            self.generation = int(manifest["generation"])
        else:
            gens = self._scan_generations()
            self.generation = gens[0] if gens else 0
        self.delta_records = 0   # records appended to the current log
        self._delta_f = None

    # ------------------------------------------------------------------
    # Restore
    # ------------------------------------------------------------------
    def _read_manifest(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, MANIFEST)) as f:
                m = json.load(f)
            if not isinstance(m.get("generation"), int):
                return None
            return m
        except (OSError, json.JSONDecodeError):
            return None

    def _scan_generations(self) -> List[int]:
        gens = set()
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            # Delta logs count too: a fresh store's generation 0 has
            # deltas before its first compaction ever writes a base.
            for prefix, suffix in (("base-", ".snap"), ("delta-", ".log")):
                if name.startswith(prefix) and name.endswith(suffix):
                    try:
                        gens.add(int(name[len(prefix): -len(suffix)]))
                    except ValueError:
                        pass
        return sorted(gens, reverse=True)

    def load(self) -> RestoreResult:
        """Read the newest restorable generation: base first, then its
        delta records in append order.  Adopts that generation as the
        store's current one (subsequent appends continue its log)."""
        out = RestoreResult()
        manifest = self._read_manifest()
        candidates: List[int] = []
        if manifest is not None:
            candidates.append(int(manifest["generation"]))
        else:
            out.manifest_missing = True
        for g in self._scan_generations():
            if g not in candidates:
                candidates.append(g)
        for gen in candidates:
            base_path = os.path.join(self.dir, _base_name(gen))
            base_payloads, base_bad = read_records(base_path)
            delta_payloads, delta_bad = read_records(
                os.path.join(self.dir, _delta_name(gen))
            )
            snaps: List[dict] = []
            if base_payloads:
                try:
                    snaps.append(decode_snapshot(base_payloads[0]))
                except Exception:
                    base_bad += 1
                    base_payloads = []
            if not base_payloads:
                # No readable base.  Generation 0 legitimately has none
                # before its first compaction (deltas upsert onto an
                # empty table); any other generation only exists because
                # write_base completed, so a missing/corrupt base there
                # means rot — fall back to an older generation.
                if os.path.exists(base_path) or not delta_payloads:
                    out.corrupt_records += base_bad + delta_bad
                    continue
            out.corrupt_records += base_bad + delta_bad
            n_base = len(snaps)
            for p in delta_payloads:
                try:
                    snaps.append(decode_snapshot(p))
                except Exception:
                    # An undetected-by-CRC decode failure still must not
                    # kill the restore; everything before it stands.
                    out.corrupt_records += 1
                    break
            out.snapshots = snaps
            out.generation = gen
            out.delta_records = len(snaps) - n_base
            out.items = sum(snapshot_items(s) for s in snaps)
            self.generation = gen
            self.delta_records = out.delta_records
            return out
        return out  # empty directory (or nothing restorable): fresh start

    # ------------------------------------------------------------------
    # Write path
    # ------------------------------------------------------------------
    def _atomic_write(self, name: str, write_fn) -> None:
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.dir)

    def _write_manifest(self) -> None:
        doc = json.dumps({
            "generation": self.generation,
            "base": _base_name(self.generation),
            "delta": _delta_name(self.generation),
        }).encode()
        self._atomic_write(MANIFEST, lambda f: f.write(doc))

    def append_delta(self, snap: dict) -> int:
        """Append one dirty-delta snapshot to the current generation's
        log (CRC record + fsync); returns records now in the log."""
        if self._delta_f is None:
            self._delta_f = open(
                os.path.join(self.dir, _delta_name(self.generation)), "ab"
            )
        write_record(self._delta_f, encode_snapshot(snap))
        self._delta_f.flush()
        os.fsync(self._delta_f.fileno())
        self.delta_records += 1
        return self.delta_records

    def write_base(self, snap: dict) -> int:
        """Start a new generation from a FULL snapshot: write its base
        atomically, reset the delta log, publish the manifest, retire the
        previous generation's files.  Returns the new generation."""
        old_gen = self.generation
        if self._delta_f is not None:
            self._delta_f.close()
            self._delta_f = None
        self.generation += 1
        payload = encode_snapshot(snap)
        self._atomic_write(
            _base_name(self.generation), lambda f: write_record(f, payload)
        )
        # Fresh (empty) delta log for the new generation — created before
        # the manifest names it so restore never chases a missing file.
        self._atomic_write(_delta_name(self.generation), lambda f: None)
        self.delta_records = 0
        self._write_manifest()
        # Old generation retires only after the new manifest landed: a
        # crash anywhere above restores the previous generation intact.
        for name in (_base_name(old_gen), _delta_name(old_gen)):
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass
        return self.generation

    def close(self) -> None:
        if self._delta_f is not None:
            self._delta_f.close()
            self._delta_f = None
