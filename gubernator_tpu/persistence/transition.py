"""Crash-safe reshard transition record (docs/resharding.md).

The reshard coordinator journals the layout transition to an append-only
CRC-framed record file (same framing as the snapshot store) so a crash
mid-cutover lands in a *defined* state on restart:

* ``begin`` record written before any state moves;
* ``commit``/``abort`` record written once the transition reaches a
  terminal phase (new layout serving, or old layout restored).

On startup :func:`check_interrupted` reads the journal: a ``begin``
without a matching terminal record means the process died inside the
transition window — the restored snapshot (which the coordinator never
mutates mid-flight) is authoritative, the stale journal is cleared, and
the interruption is surfaced to metrics so operators see it.  A missing
or corrupt journal is never fatal: the torn tail is dropped exactly like
a torn delta record.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

from gubernator_tpu.persistence.snapshot import read_records, write_record

TRANSITION_LOG = "reshard-transition.log"

PHASE_BEGIN = "begin"
PHASE_COMMIT = "commit"
PHASE_ABORT = "abort"
_TERMINAL = (PHASE_COMMIT, PHASE_ABORT)


@dataclass
class TransitionRecord:
    """One journal entry: the n→m transition and where it got to."""

    phase: str
    from_shards: int
    to_shards: int
    epoch: int

    def encode(self) -> bytes:
        return json.dumps({
            "phase": self.phase,
            "from": self.from_shards,
            "to": self.to_shards,
            "epoch": self.epoch,
        }, sort_keys=True).encode()

    @classmethod
    def decode(cls, payload: bytes) -> Optional["TransitionRecord"]:
        try:
            doc = json.loads(payload.decode())
            return cls(
                phase=str(doc["phase"]),
                from_shards=int(doc["from"]),
                to_shards=int(doc["to"]),
                epoch=int(doc["epoch"]),
            )
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return None


class TransitionLog:
    """Append-only reshard journal under a persistence directory.

    ``dir_path=None`` (no persistence configured) degrades to a no-op
    journal — the coordinator still runs, it just cannot detect crashes
    across restarts.
    """

    def __init__(self, dir_path: Optional[str]):
        self.path = (
            os.path.join(dir_path, TRANSITION_LOG) if dir_path else None)

    def append(self, rec: TransitionRecord) -> None:
        if self.path is None:
            return
        with open(self.path, "ab") as f:
            write_record(f, rec.encode())
            f.flush()
            os.fsync(f.fileno())

    def records(self) -> list:
        if self.path is None:
            return []
        payloads, _corrupt = read_records(self.path)
        recs = [TransitionRecord.decode(p) for p in payloads]
        return [r for r in recs if r is not None]

    def clear(self) -> None:
        if self.path is None:
            return
        try:
            os.remove(self.path)
        except OSError:
            pass


def check_interrupted(log: TransitionLog) -> Optional[TransitionRecord]:
    """Startup check: the last ``begin`` with no terminal record after it
    (crash inside the transition window), else None.  Always clears the
    journal — records only matter across exactly one restart."""
    last_open: Optional[TransitionRecord] = None
    for rec in log.records():
        if rec.phase == PHASE_BEGIN:
            last_open = rec
        elif rec.phase in _TERMINAL:
            last_open = None
    log.clear()
    return last_open
