"""Supervised background loop feeding the snapshot store.

Every ``GUBER_SNAPSHOT_INTERVAL`` the writer drains the engine's dirty
set — ``export_columns(dirty_only=True)`` covers both the device table
and the cold tier (engine ``_export_with_cold``) — and appends the delta
to the :class:`SnapshotStore`.  After ``deltas_per_base`` appended
records it compacts: one full export becomes the next generation's base
and the delta log restarts.  The loop runs under ``spawn_supervised``
(a crashed flush logs, counts a restart, and comes back), and all engine
export / disk work runs in the default executor so a multi-MB delta
never stalls the event loop.

Loss bound: the engine resets its dirty set the moment ``export_columns``
returns, so a delta that then fails to reach disk would silently vanish —
the writer therefore *carries* failed deltas and prepends them to the
next flush (upsert replay order keeps last-write-wins).  A hard kill
loses at most the dirty set accumulated since the last fsync'd delta —
one snapshot interval; a graceful :meth:`close` writes a final FULL base,
so clean shutdown loses nothing.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import List, Optional

from gubernator_tpu.persistence.snapshot import SnapshotStore, snapshot_items
from gubernator_tpu.resilience import spawn_supervised
from gubernator_tpu.utils import sanitize

log = logging.getLogger("gubernator.persistence")


class SnapshotWriter:
    """Owns the delta-flush cadence for one engine + store pair."""

    def __init__(
        self,
        engine,
        store: SnapshotStore,
        interval: float = 5.0,
        deltas_per_base: int = 64,
        metrics=None,
    ):
        self.engine = engine
        self.store = store
        self.interval = interval
        self.deltas_per_base = max(1, int(deltas_per_base))
        self.metrics = metrics
        self._running = True
        self._carry: List[dict] = []  # deltas that failed to reach disk
        # Serializes flush/write_base bodies: close() can cancel the
        # loop task while its executor thread is still inside flush(),
        # then run the final base on another thread — the store's log
        # rotation must never interleave with an append.
        self._write_lock = sanitize.lock("SnapshotWriter._write_lock")
        self._task: Optional[asyncio.Task] = None
        # Host-side counters (mirrored into Prometheus when wired).
        self.metric_delta_writes = 0
        self.metric_base_writes = 0
        self.metric_items_written = 0
        self.metric_write_failures = 0

    def start(self) -> None:
        """Spawn the supervised flush loop on the running event loop."""
        if self._task is None:
            self._task = spawn_supervised(
                self._loop, name="snapshot-writer",
                should_restart=lambda: self._running,
                metrics=self.metrics, loop_label="snapshot_writer",
            )

    async def _loop(self) -> None:
        loop = asyncio.get_running_loop()
        while self._running:
            await asyncio.sleep(self.interval)
            if not self._running:
                return
            await loop.run_in_executor(None, self.flush)

    # ------------------------------------------------------------------
    def _observe(self, kind: str, dt: float, items: int) -> None:
        if self.metrics is not None:
            self.metrics.snapshot_writes.labels(kind=kind).inc()
            self.metrics.snapshot_duration.labels(kind=kind).observe(dt)
            if items:
                self.metrics.snapshot_items.labels(kind=kind).inc(items)

    def flush(self) -> int:
        """One cadence tick: export the dirty delta, append it (plus any
        carried failures), compact when the log is long enough.  Returns
        items persisted.  Synchronous — call from an executor."""
        with self._write_lock:
            if not self._running:
                # A flush queued on the executor before close() landed
                # must not run after the final base / store close.
                return 0
            t0 = time.perf_counter()
            snap = self.engine.export_columns(dirty_only=True)
            items = snapshot_items(snap)
            batch = self._carry + ([snap] if items else [])
            self._carry = []
            written = 0
            for s in batch:
                try:
                    # guber: allow-G007(_write_lock exists to serialize writer I/O against close; it is never taken on the serving path, so blocking under it is its purpose)
                    self.store.append_delta(s)
                except OSError as e:
                    # The engine's dirty set is already reset: losing
                    # this delta silently would break the loss bound.
                    # Carry it.
                    self._carry.append(s)
                    self.metric_write_failures += 1
                    log.warning(
                        "snapshot delta write failed (carried): %s", e
                    )
                    continue
                n = snapshot_items(s)
                written += n
                self.metric_delta_writes += 1
                self.metric_items_written += n
                self._observe("delta", time.perf_counter() - t0, n)
            if self.store.delta_records >= self.deltas_per_base:
                # guber: allow-G007(writer-only lock - see append_delta above)
                self._write_base_locked()
            return written

    def write_base(self) -> None:
        """Compaction / final-snapshot path: one FULL export becomes the
        next generation's base (carried deltas fold in for free — a full
        export supersedes every delta)."""
        with self._write_lock:
            # guber: allow-G007(writer-only lock - see append_delta above)
            self._write_base_locked()

    def _write_base_locked(self) -> None:
        t0 = time.perf_counter()
        snap = self.engine.export_columns(dirty_only=False)
        try:
            self.store.write_base(snap)
        except OSError as e:
            self.metric_write_failures += 1
            log.warning("snapshot base write failed: %s", e)
            return
        self._carry = []
        self.metric_base_writes += 1
        items = snapshot_items(snap)
        self.metric_items_written += items
        self._observe("base", time.perf_counter() - t0, items)

    async def close(self, final_base: bool = True) -> None:
        """Stop the loop, then (by default) write a final full base —
        the zero-loss half of graceful shutdown."""
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if final_base:
            await asyncio.get_running_loop().run_in_executor(
                None, self.write_base
            )
        self.store.close()
