"""Crash-safe bucket-state persistence (docs/persistence.md).

Turns "restart = amnesia" into a bounded-loss guarantee: a checksummed,
atomically-written snapshot store (:class:`SnapshotStore` — base snapshot
+ append-only dirty-delta log + manifest, CRC per record, write-to-temp +
fsync + rename, periodic compaction) fed by a supervised background loop
(:class:`SnapshotWriter`) that drains ``export_columns(dirty_only=True)``
from the device table and the cold tier.  On startup the service loads
the base, replays deltas in order (corrupt/truncated tails are counted
and skipped, never fatal), TTL-expires stale rows, then serves.

Loss bounds: ≤ one ``GUBER_SNAPSHOT_INTERVAL`` of dirty state on a hard
kill; zero on graceful shutdown (close writes a final full base).
"""

from gubernator_tpu.persistence.snapshot import (
    RestoreResult,
    SnapshotStore,
    decode_snapshot,
    encode_snapshot,
    read_records,
    snapshot_items,
    write_record,
)
from gubernator_tpu.persistence.transition import (
    TransitionLog,
    TransitionRecord,
    check_interrupted,
)
from gubernator_tpu.persistence.writer import SnapshotWriter

__all__ = [
    "RestoreResult",
    "SnapshotStore",
    "SnapshotWriter",
    "TransitionLog",
    "TransitionRecord",
    "check_interrupted",
    "decode_snapshot",
    "encode_snapshot",
    "read_records",
    "snapshot_items",
    "write_record",
]
