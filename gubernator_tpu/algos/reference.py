"""Scalar Python references for the zoo algorithms — the test oracle.

Each function mirrors its vmapped counterpart line for line (same
clamps, same precedence, same integer math) the way
``tests/test_token_bucket.py`` / ``test_leaky_bucket.py`` pin the
reference Go semantics for the legacy pair.  The parity fuzz drives the
real engine and this module with identical request streams and demands
bit-identical responses and exported state.

State is a plain dict of the logical BucketState fields (``None`` for
an absent item); requests are dicts with ``hits``/``limit``/
``duration``/``algorithm``/``behavior``/``burst``/``created_at``.
All arithmetic is on Python ints, which do not wrap — callers keep
parameters inside int64 range (the kernels wrap two's-complement
beyond it, like Go).
"""

from __future__ import annotations

from typing import Optional, Tuple

from gubernator_tpu.types import Algorithm, Behavior, Status

StateDict = dict
RespDict = dict


def _exists(s: Optional[dict], now: int, algorithm: int) -> bool:
    """The shared cache-existence predicate (cf. bucket_transition):
    present, in use, not expired, stored algorithm matches."""
    return (
        s is not None
        and bool(s.get("in_use", True))
        and now <= s["expire_at"]
        and s.get("algorithm", 0) == algorithm
    )


def _base_state(req: dict) -> StateDict:
    """The request-uniform state fields every zoo transition stores."""
    return {
        "algorithm": int(req["algorithm"]),
        "limit": req["limit"],
        "remaining_f": 0.0,
        "duration": req["duration"],
        "updated_at": req["created_at"],
        "burst": req.get("burst", 0),
        "in_use": True,
        "tat": 0,
        "prev_count": 0,
    }


def sliding_window(s: Optional[dict], req: dict, now: int
                   ) -> Tuple[StateDict, RespDict]:
    """Scalar mirror of algos/sliding_window.py (see its docstring)."""
    behavior = req.get("behavior", 0)
    reset_b = bool(behavior & Behavior.RESET_REMAINING)
    drain_b = bool(behavior & Behavior.DRAIN_OVER_LIMIT)
    ex = _exists(s, now, Algorithm.SLIDING_WINDOW) and not reset_b

    t = max(req["created_at"], 0)
    dur = max(req["duration"], 1)
    aligned = t - t % dur

    ws0 = s["created_at"] if ex else aligned
    cur0 = max(s["remaining"], 0) if ex else 0
    prev0 = max(s["prev_count"], 0) if ex else 0

    delta = max(t - ws0, 0)
    k = delta // dur
    if k == 0:
        prev1, cur1, ws1 = prev0, cur0, ws0
    elif k == 1:
        prev1, cur1, ws1 = cur0, 0, aligned
    else:
        prev1, cur1, ws1 = 0, 0, aligned

    frac = min(max(dur - (t - ws1), 0), dur)
    wprev = prev1 * frac // dur
    used = wprev + cur1
    avail = max(req["limit"] - used, 0)

    h = req["hits"]
    admit = h > 0 and h <= avail
    over = h > 0 and not admit
    if admit:
        cur2 = cur1 + h
    elif over and drain_b:
        cur2 = cur1 + avail
    elif h < 0:
        cur2 = max(cur1 + h, 0)
    else:
        cur2 = cur1

    resp_rem = max(req["limit"] - (wprev + cur2), 0)
    status = Status.OVER_LIMIT if (over or (h == 0 and avail == 0)) \
        else Status.UNDER_LIMIT
    touch = h != 0 or not ex
    expire = t + 2 * dur if touch else s["expire_at"]

    new_state = _base_state(req)
    new_state.update(
        remaining=cur2, created_at=ws1, status=int(status),
        expire_at=expire, prev_count=prev1,
    )
    resp = {
        "status": int(status), "limit": req["limit"],
        "remaining": resp_rem, "reset_time": ws1 + dur,
        "over_limit": over,
    }
    return new_state, resp


def gcra(s: Optional[dict], req: dict, now: int
         ) -> Tuple[StateDict, RespDict]:
    """Scalar mirror of algos/gcra.py (see its docstring)."""
    behavior = req.get("behavior", 0)
    reset_b = bool(behavior & Behavior.RESET_REMAINING)
    ex = _exists(s, now, Algorithm.GCRA) and not reset_b

    t = req["created_at"]
    safe_limit = req["limit"] if req["limit"] > 0 else 1
    T = max(req["duration"], 0) // safe_limit
    burst = req.get("burst", 0)
    burst_eff = burst if burst > 0 else req["limit"]
    tau = (burst_eff - 1) * T

    tat0 = s["tat"] if ex else t
    tat1 = max(tat0, t)

    h = req["hits"]
    horizon = t + tau
    conform = tat1 + (h - 1) * T <= horizon
    admit = h > 0 and conform
    over = h > 0 and not conform
    if admit:
        tat2 = tat1 + h * T
    elif h < 0:
        tat2 = max(tat1 + h * T, t)
    else:
        tat2 = tat1

    slack = horizon - tat2
    if slack < 0:
        rem = 0
    elif T == 0:
        rem = burst_eff
    else:
        rem = min(slack // T + 1, burst_eff)
    rem = max(rem, 0)

    status = Status.OVER_LIMIT if (over or (h == 0 and rem == 0)) \
        else Status.UNDER_LIMIT
    touch = h != 0 or not ex
    expire = max(t + req["duration"], tat2) if touch else s["expire_at"]

    new_state = _base_state(req)
    new_state.update(
        remaining=rem,
        created_at=s["created_at"] if ex else t,
        status=int(status), expire_at=expire, tat=tat2,
    )
    resp = {
        "status": int(status), "limit": req["limit"], "remaining": rem,
        "reset_time": max(tat2 - tau, t), "over_limit": over,
    }
    return new_state, resp


def concurrency(s: Optional[dict], req: dict, now: int
                ) -> Tuple[StateDict, RespDict]:
    """Scalar mirror of algos/concurrency.py (see its docstring)."""
    behavior = req.get("behavior", 0)
    reset_b = bool(behavior & Behavior.RESET_REMAINING)
    ex = _exists(s, now, Algorithm.CONCURRENCY) and not reset_b

    t = req["created_at"]
    if ex:
        rem0 = max(s["remaining"] + (req["limit"] - s["limit"]), 0)
    else:
        rem0 = max(req["limit"], 0)

    h = req["hits"]
    admit = h > 0 and h <= rem0
    over = h > 0 and not admit
    if admit:
        rem1 = rem0 - h
    elif h < 0:
        rem1 = max(min(rem0 - h, req["limit"]), 0)
    else:
        rem1 = rem0

    touch = h != 0 or not ex
    expire = t + req["duration"] if touch else s["expire_at"]
    status = Status.OVER_LIMIT if (over or (h == 0 and rem1 == 0)) \
        else Status.UNDER_LIMIT

    new_state = _base_state(req)
    new_state.update(
        remaining=rem1,
        created_at=s["created_at"] if ex else t,
        status=int(status), expire_at=expire,
    )
    resp = {
        "status": int(status), "limit": req["limit"], "remaining": rem1,
        "reset_time": expire, "over_limit": over,
    }
    return new_state, resp


REFERENCE = {
    Algorithm.SLIDING_WINDOW: sliding_window,
    Algorithm.GCRA: gcra,
    Algorithm.CONCURRENCY: concurrency,
}


def transition(s: Optional[dict], req: dict, now: int
               ) -> Tuple[StateDict, RespDict]:
    """Dispatch on ``req['algorithm']`` (zoo members only)."""
    return REFERENCE[Algorithm(int(req["algorithm"]))](s, req, now)
