"""The N-way branchless policy table and its arithmetic backends.

Every zoo algorithm (:mod:`sliding_window`, :mod:`gcra`,
:mod:`concurrency`) is written once against the small ops protocol
defined here and evaluated through two interchangeable backends:

- :class:`X64Ops` — plain int64 jnp arrays (the logical/oracle path of
  ``ops/buckets.py``).
- :class:`PartsOps` — (lo, hi) int32 pairs via
  :mod:`gubernator_tpu.ops.i64pair` (Mosaic-compilable; the
  ``ops/transition32.py`` / fused-Pallas path).

One formula, two instantiations: structural parity between the oracle
and the kernel is by construction, not by testing alone.

The adapters only cover what the zoo needs — elementwise 64-bit
add/sub/mul/compare/select plus *non-negative* floor division (backed by
``i64pair.div_floor_pos`` on parts; callers clamp operands into the
``a >= 0, b > 0`` domain).  i32 lanes (status) and boolean masks use
``jnp.where`` directly in both backends; boolean *values* are kept as
0/1 int32 lanes through selects, the Mosaic-supported idiom (see
transition32's ``sel32`` note).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp

from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.types import Algorithm
from gubernator_tpu.utils.hotpath import hot_path

I32 = jnp.int32
I64 = jnp.int64


class X64Ops:
    """int64 jnp-array backend (logical/oracle path)."""

    @staticmethod
    def const(v, like):
        return jnp.full(jnp.shape(like), v, I64)

    @staticmethod
    def add(a, b):
        return a + b

    @staticmethod
    def sub(a, b):
        return a - b

    @staticmethod
    def mul(a, b):
        return a * b  # wrapping two's-complement, like i64pair.mul

    @staticmethod
    def eq(a, b):
        return a == b

    @staticmethod
    def ne(a, b):
        return a != b

    @staticmethod
    def lt(a, b):
        return a < b

    @staticmethod
    def le(a, b):
        return a <= b

    @staticmethod
    def gt(a, b):
        return a > b

    @staticmethod
    def ge(a, b):
        return a >= b

    @staticmethod
    def is_zero(a):
        return a == 0

    @staticmethod
    def is_neg(a):
        return a < 0

    @staticmethod
    def select(c, a, b):
        return jnp.where(c, a, b)

    @staticmethod
    def max_(a, b):
        return jnp.maximum(a, b)

    @staticmethod
    def min_(a, b):
        return jnp.minimum(a, b)

    @staticmethod
    def floor_div(a, b):
        # Domain a >= 0, b > 0 (callers clamp) — floor == trunc here.
        return a // b

    @staticmethod
    def mod(a, b):
        return a % b  # same a >= 0, b > 0 domain


class PartsOps:
    """(lo, hi) int32-pair backend (Mosaic-compilable parts path)."""

    const = staticmethod(p64.const)
    add = staticmethod(p64.add)
    sub = staticmethod(p64.sub)
    mul = staticmethod(p64.mul)
    eq = staticmethod(p64.eq)
    ne = staticmethod(p64.ne)
    lt = staticmethod(p64.lt)
    le = staticmethod(p64.le)
    gt = staticmethod(p64.gt)
    ge = staticmethod(p64.ge)
    is_zero = staticmethod(p64.is_zero)
    is_neg = staticmethod(p64.is_neg)
    select = staticmethod(p64.select)
    max_ = staticmethod(p64.max_)
    min_ = staticmethod(p64.min_)
    floor_div = staticmethod(p64.div_floor_pos)

    @staticmethod
    def mod(a, b):
        # a - (a // b) * b on the same a >= 0, b > 0 domain.
        return p64.sub(a, p64.mul(p64.div_floor_pos(a, b), b))


class ZooState(NamedTuple):
    """The state fields a zoo transition decides per lane.  The rest of
    the row is uniform across zoo algorithms (algorithm/limit/duration/
    burst echo the request; remaining_f is 0; updated_at = created_at;
    in_use = 1) and is filled by the caller."""

    remaining: Any    # i64 / I64 pair: window count, GCRA slack, free slots
    created_at: Any   # i64: window start (sliding) / first-seen (others)
    status: Any       # i32 lanes
    expire_at: Any    # i64
    tat: Any          # i64: GCRA theoretical arrival time; 0 elsewhere
    prev_count: Any   # i64: sliding-window previous count; 0 elsewhere


class ZooResp(NamedTuple):
    """Response fields (cf. PResp); ``over_limit`` is 0/1 i32 lanes."""

    status: Any       # i32 lanes
    remaining: Any    # i64
    reset_time: Any   # i64
    over_limit: Any   # i32 0/1 lanes


def _pick(o, alg, sw, gc, cc):
    """3-way zoo select on 64-bit values (alg >= ZOO_MIN lanes only;
    unknown values resolve to sliding-window — the edges reject them
    before they ever reach the device)."""
    is_gc = alg == jnp.int32(Algorithm.GCRA)
    is_cc = alg == jnp.int32(Algorithm.CONCURRENCY)
    return o.select(is_gc, gc, o.select(is_cc, cc, sw))


def _pick32(alg, sw, gc, cc):
    """3-way zoo select on i32 lanes."""
    is_gc = alg == jnp.int32(Algorithm.GCRA)
    is_cc = alg == jnp.int32(Algorithm.CONCURRENCY)
    return jnp.where(is_gc, gc, jnp.where(is_cc, cc, sw))


@hot_path
def zoo_transitions(o, s, r, exists, reset_b, drain_b
                    ) -> tuple[ZooState, ZooResp]:
    """Run all three zoo transitions branchlessly and fold them into one
    per-lane result keyed on ``r.algorithm``.

    ``s``/``r`` are duck-typed state/request batches in the backend's
    representation (BucketState/ReqBatch for :class:`X64Ops`,
    PState/PReq for :class:`PartsOps` — the field names coincide).
    ``exists``/``reset_b``/``drain_b`` are the caller's shared masks, so
    cache-expiry semantics stay identical across all five algorithms.
    """
    from gubernator_tpu.algos import concurrency, gcra, sliding_window

    sw_s, sw_r = sliding_window.transition(o, s, r, exists, reset_b, drain_b)
    gc_s, gc_r = gcra.transition(o, s, r, exists, reset_b, drain_b)
    cc_s, cc_r = concurrency.transition(o, s, r, exists, reset_b, drain_b)

    alg = r.algorithm
    st = ZooState(
        remaining=_pick(o, alg, sw_s.remaining, gc_s.remaining,
                        cc_s.remaining),
        created_at=_pick(o, alg, sw_s.created_at, gc_s.created_at,
                         cc_s.created_at),
        status=_pick32(alg, sw_s.status, gc_s.status, cc_s.status),
        expire_at=_pick(o, alg, sw_s.expire_at, gc_s.expire_at,
                        cc_s.expire_at),
        tat=_pick(o, alg, sw_s.tat, gc_s.tat, cc_s.tat),
        prev_count=_pick(o, alg, sw_s.prev_count, gc_s.prev_count,
                         cc_s.prev_count),
    )
    resp = ZooResp(
        status=_pick32(alg, sw_r.status, gc_r.status, cc_r.status),
        remaining=_pick(o, alg, sw_r.remaining, gc_r.remaining,
                        cc_r.remaining),
        reset_time=_pick(o, alg, sw_r.reset_time, gc_r.reset_time,
                         cc_r.reset_time),
        over_limit=_pick32(alg, sw_r.over_limit, gc_r.over_limit,
                           cc_r.over_limit),
    )
    return st, resp
