"""Sliding-window counter transition (Algorithm.SLIDING_WINDOW).

Epoch-aligned windows of ``duration`` ms: the current window's count
lives in ``remaining`` (reused as the *consumed* counter — unlike
token bucket it counts up), the previous window's final count in the
``prev_count`` column, and the current window start in ``created_at``
(aligned to ``t - t % duration``).  The effective usage at time ``t``
weights the previous window by its remaining overlap with the sliding
window ending at ``t``::

    used = prev * (duration - (t - window_start)) // duration + cur

which is the standard Cloudflare-style approximation that kills the
2x-burst artifact at fixed-window edges (see docs/algorithms.md for the
window-edge analysis).  All math is integer, so the x64 oracle, the
parts kernel and the scalar reference agree bit-exactly.

Semantics:

- ``hits > 0``  admit iff ``hits <= limit - used``; admitted hits add to
  the current window.  Rejected hits consume nothing unless
  DRAIN_OVER_LIMIT, which consumes exactly the available budget.
- ``hits < 0``  un-counts from the current window (clamped at 0).
- ``hits == 0`` status query; reports OVER_LIMIT iff nothing is
  available.  Window rotation still persists, cache expiry is not
  bumped.
- RESET_REMAINING discards the stored window (fresh bucket).
- ``reset_time`` is the current window's end; expiry is ``t + 2 *
  duration`` so the previous-window count survives one full extra
  window.
"""

from __future__ import annotations

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp

from gubernator_tpu.algos.table import ZooResp, ZooState
from gubernator_tpu.types import Algorithm, Status
from gubernator_tpu.utils.hotpath import hot_path

I32 = jnp.int32


@hot_path
def transition(o, s, r, exists, reset_b, drain_b
               ) -> tuple[ZooState, ZooResp]:
    """Elementwise sliding-window step over backend ``o`` (see table.py)."""
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)
    zero = o.const(0, r.algorithm)
    one = o.const(1, r.algorithm)

    ex = exists & ~reset_b & (s.algorithm == jnp.int32(
        Algorithm.SLIDING_WINDOW))
    # Window math needs t >= 0 and duration >= 1 (floor_div domain);
    # epoch-ms inputs already satisfy both, the clamps keep the kernel
    # total for hostile values.
    t = o.max_(r.created_at, zero)
    dur = o.max_(r.duration, one)
    aligned = o.sub(t, o.mod(t, dur))

    ws0 = o.select(ex, s.created_at, aligned)
    cur0 = o.select(ex, o.max_(s.remaining, zero), zero)
    prev0 = o.select(ex, o.max_(s.prev_count, zero), zero)

    # Rotation: k full windows elapsed since the stored window start.
    # k == 1 promotes current -> previous; k >= 2 clears both.  A
    # duration change re-aligns the grid organically (k computed with
    # the new duration).
    delta = o.max_(o.sub(t, ws0), zero)  # clock-regress clamp
    k = o.floor_div(delta, dur)
    k0 = o.is_zero(k)
    k1 = o.eq(k, one)
    prev1 = o.select(k0, prev0, o.select(k1, cur0, zero))
    cur1 = o.select(k0, cur0, zero)
    ws1 = o.select(k0, ws0, aligned)

    # Weighted previous-window overlap: frac in (0, dur].
    frac = o.min_(o.max_(o.sub(dur, o.sub(t, ws1)), zero), dur)
    wprev = o.floor_div(o.mul(prev1, frac), dur)
    used = o.add(wprev, cur1)
    avail = o.max_(o.sub(r.limit, used), zero)

    h = r.hits
    h_pos = o.gt(h, zero)
    h_neg = o.lt(h, zero)
    h_query = o.is_zero(h)
    fits = o.le(h, avail)
    admit = h_pos & fits
    over = h_pos & ~fits

    cur2 = o.select(
        admit,
        o.add(cur1, h),
        o.select(
            over & drain_b,
            o.add(cur1, avail),
            o.select(h_neg, o.max_(o.add(cur1, h), zero), cur1),
        ),
    )
    resp_rem = o.max_(o.sub(r.limit, o.add(wprev, cur2)), zero)
    status = jnp.where(over | (h_query & o.is_zero(avail)), OVER, UNDER)
    reset = o.add(ws1, dur)
    touch = ~h_query | ~ex
    expire = o.select(touch, o.add(t, o.add(dur, dur)), s.expire_at)

    st = ZooState(
        remaining=cur2,
        created_at=ws1,
        status=status,
        expire_at=expire,
        tat=zero,
        prev_count=prev1,
    )
    resp = ZooResp(
        status=status,
        remaining=resp_rem,
        reset_time=reset,
        over_limit=over.astype(I32),
    )
    return st, resp
