"""GCRA (generic cell rate algorithm) transition (Algorithm.GCRA).

Virtual-scheduling leaky bucket on a single ``tat`` (theoretical
arrival time) column: with emission interval ``T = duration // limit``
and tolerance ``tau = (burst_eff - 1) * T`` (``burst_eff = burst`` when
positive, else ``limit``), a batch of ``hits`` conforms iff its *last*
cell's theoretical arrival ``tat + (hits - 1) * T`` is within ``tau``
of now.  Conforming hits advance ``tat`` by ``hits * T``; a stale
``tat`` first catches up to now (``max(tat, t)``), which is what makes
GCRA window-edge free — admission smooths at the single-cell scale
instead of resetting at window boundaries (the perceived-fairness
argument in docs/algorithms.md).

Everything is exact integer math via the same non-negative floor-
division machinery the group fold uses (``i64pair.div_floor_pos``'s
triple-f32 quotient + exact correction), so oracle, parts kernel and
scalar reference agree bit-exactly.

Semantics:

- ``hits > 0``  admit iff the whole batch conforms (all-or-nothing);
  DRAIN_OVER_LIMIT is a no-op for GCRA (there is no stored count to
  drain — over-limit leaves ``tat`` untouched).
- ``hits < 0``  returns credit: ``tat' = max(tat + hits * T, t)``.
- ``hits == 0`` status query (reports OVER_LIMIT iff no cell would
  conform right now); does not bump cache expiry.
- ``remaining`` reports the number of cells that would still conform:
  ``min(slack // T + 1, burst_eff)`` for ``slack = t + tau - tat >= 0``,
  else 0; ``T == 0`` (limit exceeds duration in ms) admits everything
  and reports ``burst_eff``.
- ``reset_time = max(tat - tau, t)``: the instant the next cell
  conforms.  Expiry is ``max(t + duration, tat)`` so a bucket with
  booked-ahead ``tat`` cannot expire before its debt drains.
"""

from __future__ import annotations

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp

from gubernator_tpu.algos.table import ZooResp, ZooState
from gubernator_tpu.types import Algorithm, Status
from gubernator_tpu.utils.hotpath import hot_path

I32 = jnp.int32


@hot_path
def transition(o, s, r, exists, reset_b, drain_b
               ) -> tuple[ZooState, ZooResp]:
    """Elementwise GCRA step over backend ``o`` (see table.py)."""
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)
    zero = o.const(0, r.algorithm)
    one = o.const(1, r.algorithm)

    ex = exists & ~reset_b & (s.algorithm == jnp.int32(Algorithm.GCRA))
    t = r.created_at
    # Emission interval; floor_div domain needs duration >= 0, limit > 0
    # (service validation rejects limit <= 0, the kernel stays total).
    safe_limit = o.select(o.le(r.limit, zero), one, r.limit)
    T = o.floor_div(o.max_(r.duration, zero), safe_limit)
    burst_eff = o.select(o.gt(r.burst, zero), r.burst, r.limit)
    tau = o.mul(o.sub(burst_eff, one), T)

    tat0 = o.select(ex, s.tat, t)
    tat1 = o.max_(tat0, t)  # stale tat catches up to now

    h = r.hits
    h_pos = o.gt(h, zero)
    h_neg = o.lt(h, zero)
    h_query = o.is_zero(h)
    # Last cell of the batch: tat1 + (h - 1) * T must be <= t + tau.
    need = o.add(tat1, o.mul(o.sub(h, one), T))
    horizon = o.add(t, tau)
    conform = o.le(need, horizon)
    admit = h_pos & conform
    over = h_pos & ~conform

    stepped = o.add(tat1, o.mul(h, T))
    tat2 = o.select(
        admit, stepped,
        o.select(h_neg, o.max_(stepped, t), tat1),
    )

    slack = o.sub(horizon, tat2)
    t_zero = o.is_zero(T)
    rem_div = o.add(o.floor_div(o.max_(slack, zero), o.max_(T, one)), one)
    rem = o.select(
        o.lt(slack, zero), zero,
        o.select(t_zero, burst_eff, o.min_(rem_div, burst_eff)),
    )
    rem = o.max_(rem, zero)  # burst_eff <= 0 (limit <= 0) floors at 0

    status = jnp.where(over | (h_query & o.is_zero(rem)), OVER, UNDER)
    reset = o.max_(o.sub(tat2, tau), t)
    touch = ~h_query | ~ex
    expire = o.select(
        touch, o.max_(o.add(t, r.duration), tat2), s.expire_at)

    st = ZooState(
        remaining=rem,
        created_at=o.select(ex, s.created_at, t),
        status=status,
        expire_at=expire,
        tat=tat2,
        prev_count=zero,
    )
    resp = ZooResp(
        status=status,
        remaining=rem,
        reset_time=reset,
        over_limit=over.astype(I32),
    )
    return st, resp
