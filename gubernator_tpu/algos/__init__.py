"""On-device algorithm zoo: sliding-window, GCRA, concurrency limits.

This package generalizes :mod:`gubernator_tpu.ops.buckets`'s two-way
``is_token`` select into an N-way branchless policy table over the SAME
SoA state columns.  Each algorithm is a pure elementwise state
transition ``(state_cols, req_cols) -> (state_cols', resp_cols)``
written ONCE against an ops adapter (:mod:`gubernator_tpu.algos.table`)
and instantiated twice:

- **x64**: logical int64 jnp arrays — the oracle path used by
  :func:`gubernator_tpu.ops.buckets.bucket_transition`.
- **parts**: (lo, hi) int32 pairs (:mod:`gubernator_tpu.ops.i64pair`) —
  Mosaic-compilable, used by
  :func:`gubernator_tpu.ops.transition32.transition32` and therefore by
  the fused/ragged Pallas ticks.

Because both paths run the *same* formula through different arithmetic
backends, the oracle/kernel parity that the fuzz suite enforces for
token/leaky extends to the zoo for free.  Selection happens per lane on
the existing ``algorithm`` column, so a mixed-policy window (all five
algorithms in one batch) still ticks in ONE device dispatch with no new
programs per algorithm.

Scalar Python references (the test ground truth) live in
:mod:`gubernator_tpu.algos.reference`.
"""

from __future__ import annotations

import numpy as np

from gubernator_tpu.types import ALGORITHM_MAX, Algorithm
from gubernator_tpu.utils.hotpath import hot_path

# Zoo members (selected when ``algorithm >= ZOO_MIN``); token/leaky stay
# on the legacy two-way select inside the bucket transitions.
ZOO_MIN = int(Algorithm.SLIDING_WINDOW)
ZOO_ALGORITHMS = (
    Algorithm.SLIDING_WINDOW,
    Algorithm.GCRA,
    Algorithm.CONCURRENCY,
)

# New SoA columns the zoo threads through the whole state plane
# (snapshots, cold tier, mesh relayout).  Pre-zoo snapshots/slabs load
# these as zeros — the PR 10 lease-column compatibility pattern.
ZOO_STATE_FIELDS = ("tat", "prev_count")


@hot_path
def invalid_algorithm_mask(algorithm: np.ndarray) -> np.ndarray:
    """Boolean mask of wire ``algorithm`` values outside the enum range.

    Used by the edges (fastwire / protobuf conversion / instance
    validation) to reject unknown algorithms with INVALID_ARGUMENT
    instead of letting them fall through the select tree as
    token-bucket.  Runs once per decoded wire window (fastwire
    ``parse_req``) — marked so G001 visits it directly.
    """
    # guber: allow-G001(wire validation over the host-decoded algorithm column - never a device value)
    a = np.asarray(algorithm)
    return (a < 0) | (a > int(ALGORITHM_MAX))


def algorithm_error(value: int) -> str:
    """The per-item error string for an out-of-range algorithm value."""
    return (
        f"invalid algorithm '{int(value)}': must be in "
        f"[0, {int(ALGORITHM_MAX)}]"
    )
