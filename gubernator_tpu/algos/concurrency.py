"""Concurrency / in-flight limit transition (Algorithm.CONCURRENCY).

``remaining`` holds the free slot count.  Positive hits *acquire*
slots (all-or-nothing, like a semaphore try-acquire), negative hits
*release* them (clamped into ``[0, limit]``), and a bucket whose TTL
lapses is simply re-created full — which IS the leaked-slot
reclamation: a client that acquired and never released stops pinning
its slots once the item's ``duration`` passes without a refresh, since
every acquire/release bumps ``expire_at = t + duration`` and the shared
cache-existence predicate treats ``now > expire_at`` as a miss.

Semantics:

- ``hits > 0``  acquire iff ``hits <= remaining``; rejected acquires
  take nothing (DRAIN_OVER_LIMIT has no meaning for slots and is
  ignored).
- ``hits < 0``  release: ``remaining = clamp(remaining - hits, 0,
  limit)``; always UNDER_LIMIT.
- ``hits == 0`` status query (OVER_LIMIT iff no slot is free); does not
  refresh the TTL.
- A limit change re-bases the free count by the delta, token-bucket
  style: ``remaining += new_limit - old_limit`` clamped at 0.
- ``reset_time`` is ``expire_at`` — the moment leaked slots would be
  reclaimed if every holder vanished.
"""

from __future__ import annotations

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax.numpy as jnp

from gubernator_tpu.algos.table import ZooResp, ZooState
from gubernator_tpu.types import Algorithm, Status
from gubernator_tpu.utils.hotpath import hot_path

I32 = jnp.int32


@hot_path
def transition(o, s, r, exists, reset_b, drain_b
               ) -> tuple[ZooState, ZooResp]:
    """Elementwise concurrency-limit step over backend ``o`` (table.py)."""
    UNDER = jnp.int32(Status.UNDER_LIMIT)
    OVER = jnp.int32(Status.OVER_LIMIT)
    zero = o.const(0, r.algorithm)

    ex = exists & ~reset_b & (s.algorithm == jnp.int32(
        Algorithm.CONCURRENCY))
    t = r.created_at
    # Existing bucket re-bases on a limit change; new/expired bucket
    # starts full (leaked slots reclaimed).  Clamp keeps hostile stored
    # values and limit <= 0 total: nothing is ever available below 0.
    rebased = o.add(s.remaining, o.sub(r.limit, s.limit))
    rem0 = o.max_(o.select(ex, rebased, r.limit), zero)

    h = r.hits
    h_pos = o.gt(h, zero)
    h_neg = o.lt(h, zero)
    h_query = o.is_zero(h)
    fits = o.le(h, rem0)
    admit = h_pos & fits
    over = h_pos & ~fits

    rem1 = o.select(
        admit,
        o.sub(rem0, h),
        o.select(
            h_neg,
            o.max_(o.min_(o.sub(rem0, h), r.limit), zero),
            rem0,
        ),
    )

    touch = ~h_query | ~ex
    expire = o.select(touch, o.add(t, r.duration), s.expire_at)
    status = jnp.where(over | (h_query & o.is_zero(rem1)), OVER, UNDER)

    st = ZooState(
        remaining=rem1,
        created_at=o.select(ex, s.created_at, t),
        status=status,
        expire_at=expire,
        tat=zero,
        prev_count=zero,
    )
    resp = ZooResp(
        status=status,
        remaining=rem1,
        reset_time=expire,
        over_limit=over.astype(I32),
    )
    return st, resp
