"""Async peer RPC client with request batching.

The TPU-native counterpart of the reference's ``peer_client.go``: one gRPC
connection per peer, an app-level batching queue in front of it (flush at
``batch_limit`` items or ``batch_wait`` after the first enqueue — the same
window policy as ``peer_client.go:284-337``), strict order-preserving
response distribution (``:390-398``), a TTL'd error record feeding
HealthCheck (``:206-235``), and graceful drain on shutdown (``:408-435``).

Differences from the reference are idiomatic, not semantic: goroutine +
channel plumbing becomes one asyncio task per peer; the one-shot interval
timer becomes ``asyncio.wait_for`` deadlines.

Fault tolerance (docs/resilience.md): every client owns a per-peer
circuit breaker (open = fail fast with :class:`BreakerOpenError`, no
dial), consults the optional fault injector before each RPC (chaos
hook), runs its batch loop under a crash supervisor, and drains — never
strands — futures enqueued around shutdown.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import List, Optional, Sequence

import grpc
import grpc.aio

from gubernator_tpu.admission import (
    DEADLINE_METADATA_KEY,
    BudgetExhaustedError,
    batch_deadline,
    budget_header_value,
)
from gubernator_tpu.config import BehaviorConfig, env_knob, parse_duration
from gubernator_tpu.pb import peers_pb2 as peers_pb
from gubernator_tpu.resilience import (
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
    ResilienceConfig,
    spawn_supervised,
)
from gubernator_tpu.transport import convert
from gubernator_tpu.transport.grpc_api import PeersV1Stub
from gubernator_tpu.types import (
    Behavior,
    GlobalUpdate,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    has_behavior,
)
from gubernator_tpu.utils import tracing

log = logging.getLogger("gubernator.peer_client")


class ErrorRecorder:
    """Recent peer-error strings with TTL expiry (reference keeps a 5-minute
    TTL LRU per peer, peer_client.go:206-235); feeds HealthCheck."""

    def __init__(self, ttl: float = 300.0, cap: int = 100):
        self.ttl = ttl
        self.cap = cap
        self._errs: "collections.OrderedDict[str, float]" = collections.OrderedDict()

    def record(self, msg: str) -> None:
        now = time.monotonic()
        self._errs.pop(msg, None)
        self._errs[msg] = now
        while len(self._errs) > self.cap:
            self._errs.popitem(last=False)

    def errors(self) -> List[str]:
        cutoff = time.monotonic() - self.ttl
        for k in [k for k, t in self._errs.items() if t < cutoff]:
            del self._errs[k]
        return list(self._errs.keys())


class PeerClient:
    """RPC client for one peer, with batched GetPeerRateLimits."""

    def __init__(
        self,
        info: PeerInfo,
        behaviors: Optional[BehaviorConfig] = None,
        channel_credentials: Optional[grpc.ChannelCredentials] = None,
        metrics=None,
        resilience: Optional[ResilienceConfig] = None,
        fault_injector=None,
        clock=time.monotonic,
        self_address: str = "",
    ):
        self._info = info
        # This node's own advertise address: the caller identity handed
        # to the fault injector so directional (asymmetric) schedules can
        # fail one direction of a peer pair only.
        self.self_address = self_address
        self.behaviors = behaviors or BehaviorConfig()
        self.credentials = channel_credentials
        self.metrics = metrics
        # Deadline propagation (docs/overload.md): RPC timeouts derive
        # from the forwarded request's remaining budget, floored so a
        # nearly-spent budget still gets one real attempt on the wire.
        self._clock = clock
        try:
            self.timeout_floor = env_knob(
                "GUBER_PEER_TIMEOUT_FLOOR", 0.05, parse=parse_duration)
        except ValueError:
            self.timeout_floor = 0.05
        self.last_errs = ErrorRecorder()
        self.resilience = resilience or ResilienceConfig()
        self.faults = fault_injector
        rc = self.resilience
        self.breaker = CircuitBreaker(
            failure_threshold=rc.breaker_failure_threshold,
            min_requests=rc.breaker_min_requests,
            window=rc.breaker_window,
            open_for=rc.breaker_open_for,
            open_cap=rc.breaker_open_cap,
            half_open_probes=rc.breaker_half_open_probes,
            enabled=rc.breaker_enabled,
            clock=clock,
            on_transition=self._on_breaker_transition,
            name=info.grpc_address,
        )
        if self.metrics is not None:
            self.metrics.breaker_state.labels(
                peerAddr=info.grpc_address
            ).set(int(BreakerState.CLOSED))
        self._channel: Optional[grpc.aio.Channel] = None
        self._stub: Optional[PeersV1Stub] = None
        self._queue: Optional[asyncio.Queue] = None
        self._batch_task: Optional[asyncio.Task] = None
        self._inflight: set = set()
        self._closed = False

    def _on_breaker_transition(
        self, old: BreakerState, new: BreakerState
    ) -> None:
        log.info(
            "peer %s circuit breaker: %s -> %s",
            self._info.grpc_address, old.name, new.name,
        )
        if self.metrics is not None:
            addr = self._info.grpc_address
            self.metrics.breaker_state.labels(peerAddr=addr).set(int(new))
            self.metrics.breaker_transitions.labels(
                peerAddr=addr, to=new.name.lower()
            ).inc()

    # `info` is attribute-or-callable in pickers; plain attribute here.
    @property
    def info(self) -> PeerInfo:
        return self._info

    def _ensure_channel(self) -> PeersV1Stub:
        if self._stub is None:
            if self.credentials is not None:
                self._channel = grpc.aio.secure_channel(
                    self._info.grpc_address, self.credentials
                )
            else:
                self._channel = grpc.aio.insecure_channel(self._info.grpc_address)
            self._stub = PeersV1Stub(self._channel)
        return self._stub

    def _ensure_batch_loop(self) -> asyncio.Queue:
        if self._queue is None:
            self._queue = asyncio.Queue(maxsize=1000)  # peer_client.go:87
            # Supervised: a crashed batch loop restarts (after failing the
            # batch it was holding) instead of leaving every subsequent
            # enqueue hanging forever.
            self._batch_task = spawn_supervised(
                self._batch_loop,
                name=f"peer-batch:{self._info.grpc_address}",
                should_restart=lambda: not self._closed,
                metrics=self.metrics,
                loop_label="peer_batch",
            )
        return self._queue

    # ------------------------------------------------------------------
    # Public surface
    # ------------------------------------------------------------------
    async def get_peer_rate_limit(self, req: RateLimitRequest) -> RateLimitResponse:
        """Forward one request to this peer, batching unless the request or
        config opts out (peer_client.go:125-161).

        The caller's trace context rides inside the request metadata (W3C
        traceparent, peer_client.go:140-141/359-360) — injected here, while
        the caller's span is still current, because the batched send happens
        later on the batch-loop task where the ambient context is gone."""
        if self._closed:
            raise RuntimeError("peer client is shut down")
        if self.breaker.is_open():
            # Fail fast without riding the batch window: the breaker
            # already knows this peer is down (non-consuming check — the
            # half-open probe slot belongs to the RPC layer).
            raise BreakerOpenError(
                f"circuit breaker open for peer {self._info.grpc_address}"
            )
        tracing.inject(req.metadata)
        if (
            has_behavior(req.behavior, Behavior.NO_BATCHING)
            or self.behaviors.disable_batching
        ):
            resp = await self.get_peer_rate_limits([req])
            return resp[0]
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        q = self._ensure_batch_loop()
        if self.metrics is not None:
            self.metrics.batch_queue_length.labels(
                peerAddr=self._info.grpc_address
            ).set(q.qsize())
        await q.put((req, fut))
        return await fut

    def rpc_budget(
        self, reqs: Sequence[RateLimitRequest]
    ) -> tuple:
        """(RPC timeout, ``guber-deadline-ms`` header value) for one
        forwarded batch: the earliest propagated remaining budget,
        floored by GUBER_PEER_TIMEOUT_FLOOR (a nearly-spent budget still
        gets one real wire attempt) and capped by ``batch_timeout``.  No
        propagated deadline → the fixed ``batch_timeout`` and no header.
        Raises :class:`BudgetExhaustedError` when the budget is already
        spent — the RPC must not be attempted at all."""
        deadline = batch_deadline(reqs)
        if deadline is None:
            return self.behaviors.batch_timeout, None
        now = self._clock()
        remaining = deadline - now
        if remaining <= 0:
            raise BudgetExhaustedError(
                "caller budget spent before forwarding to "
                f"{self._info.grpc_address}"
            )
        timeout = min(
            self.behaviors.batch_timeout,
            max(remaining, self.timeout_floor),
        )
        return timeout, budget_header_value(deadline, now)

    async def get_peer_rate_limits(
        self, reqs: Sequence[RateLimitRequest]
    ) -> List[RateLimitResponse]:
        """One unbatched GetPeerRateLimits RPC; responses in request order."""
        addr = self._info.grpc_address
        timeout, budget = self.rpc_budget(reqs)
        if not self.breaker.allow():
            msg_ = f"circuit breaker open for peer {addr}"
            self.last_errs.record(msg_)
            raise BreakerOpenError(msg_)
        stub = self._ensure_channel()
        msg = peers_pb.GetPeerRateLimitsReq(
            requests=[convert.req_to_pb(r) for r in reqs]
        )
        # gRPC-level trace header for the server interceptor; per-request
        # metadata already carries each caller's own context.  The
        # remaining deadline budget rides along so the peer's admission
        # plane sheds what this caller can no longer wait for.
        hdrs: dict = {}
        tracing.inject(hdrs)
        if budget is not None:
            hdrs[DEADLINE_METADATA_KEY] = budget
        try:
            if self.faults is not None:
                await self.faults.before_rpc(
                    addr, "GetPeerRateLimits", from_peer=self.self_address)
            out = await stub.GetPeerRateLimits(
                msg,
                timeout=timeout,
                metadata=tuple(hdrs.items()) or None,
            )
        except grpc.aio.AioRpcError as e:
            self.breaker.record_failure()
            self.last_errs.record(
                f"while fetching rate limits from peer "
                f"{addr}: {e.details()}"
            )
            raise
        self.breaker.record_success()
        if len(out.rate_limits) != len(reqs):
            raise RuntimeError(
                "server responded with incorrect rate limit list size"
            )
        return [convert.resp_from_pb(r) for r in out.rate_limits]

    async def update_peer_globals(self, updates: Sequence[GlobalUpdate]) -> None:
        """Push authoritative GLOBAL state to this peer."""
        addr = self._info.grpc_address
        if not self.breaker.allow():
            msg_ = f"circuit breaker open for peer {addr}"
            self.last_errs.record(msg_)
            raise BreakerOpenError(msg_)
        stub = self._ensure_channel()
        msg = peers_pb.UpdatePeerGlobalsReq()
        for u in updates:
            g = msg.globals.add()
            g.key = u.key
            g.algorithm = u.algorithm
            g.duration = u.duration
            g.created_at = u.created_at
            g.status.CopyFrom(convert.resp_to_pb(u.status))
        try:
            if self.faults is not None:
                await self.faults.before_rpc(
                    addr, "UpdatePeerGlobals", from_peer=self.self_address)
            await stub.UpdatePeerGlobals(msg, timeout=self.behaviors.global_timeout)
        except grpc.aio.AioRpcError as e:
            self.breaker.record_failure()
            self.last_errs.record(
                f"while updating peer globals on {addr}: "
                f"{e.details()}"
            )
            raise
        self.breaker.record_success()

    def _lease_raw(self, method: str):
        """Raw-bytes unary on this peer's channel for a V1 lease method
        (both services share the peer's port; the frames are the pure-
        Python codecs in transport/fastwire.py)."""
        self._ensure_channel()
        return self._channel.unary_unary(
            f"/pb.gubernator.V1/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

    async def lease_grant(self, specs):
        """Request quota leases from this peer (the key owner).  Breaker-
        gated like every peer RPC: an OPEN breaker raises
        :class:`BreakerOpenError`, which the client's LeaseCache answers
        by extending its held lease locally (docs/leases.md) — the lease
        analog of PR 3's degraded-answer path."""
        from gubernator_tpu.transport import fastwire

        addr = self._info.grpc_address
        if not self.breaker.allow():
            msg_ = f"circuit breaker open for peer {addr}"
            self.last_errs.record(msg_)
            raise BreakerOpenError(msg_)
        rpc = self._lease_raw("LeaseGrant")
        try:
            if self.faults is not None:
                await self.faults.before_rpc(
                    addr, "LeaseGrant", from_peer=self.self_address)
            out = await rpc(
                fastwire.encode_lease_grant_req(list(specs)),
                timeout=self.behaviors.batch_timeout,
            )
        except grpc.aio.AioRpcError as e:
            self.breaker.record_failure()
            self.last_errs.record(
                f"while granting leases from peer {addr}: {e.details()}"
            )
            raise
        self.breaker.record_success()
        tokens = fastwire.parse_lease_grant_resp(out)
        if tokens is None:
            raise RuntimeError("malformed LeaseGrant response frame")
        return tokens

    async def lease_sync(self, syncs):
        """Report lease consumption to this peer (the key owner)."""
        from gubernator_tpu.transport import fastwire

        addr = self._info.grpc_address
        if not self.breaker.allow():
            msg_ = f"circuit breaker open for peer {addr}"
            self.last_errs.record(msg_)
            raise BreakerOpenError(msg_)
        rpc = self._lease_raw("LeaseSync")
        try:
            if self.faults is not None:
                await self.faults.before_rpc(
                    addr, "LeaseSync", from_peer=self.self_address)
            out = await rpc(
                fastwire.encode_lease_sync_req(list(syncs)),
                timeout=self.behaviors.batch_timeout,
            )
        except grpc.aio.AioRpcError as e:
            self.breaker.record_failure()
            self.last_errs.record(
                f"while syncing leases to peer {addr}: {e.details()}"
            )
            raise
        self.breaker.record_success()
        acks = fastwire.parse_lease_sync_resp(out)
        if acks is None:
            raise RuntimeError("malformed LeaseSync response frame")
        return acks

    async def federation_sync(self, env, timeout: Optional[float] = None):
        """Ship one federation envelope to this peer (the key owner in a
        *remote* region) and return its FederationAck.  Breaker-gated
        like every peer RPC — the per-region breaker IS this peer's
        breaker, since the sender routes a region's keys to one owning
        peer per flush (docs/federation.md)."""
        from gubernator_tpu.transport import fastwire

        addr = self._info.grpc_address
        if not self.breaker.allow():
            msg_ = f"circuit breaker open for peer {addr}"
            self.last_errs.record(msg_)
            raise BreakerOpenError(msg_)
        rpc = self._lease_raw("FederationSync")
        try:
            if self.faults is not None:
                await self.faults.before_rpc(
                    addr, "FederationSync", from_peer=self.self_address)
            out = await rpc(
                fastwire.encode_federation_envelope(env),
                timeout=timeout if timeout else self.behaviors.batch_timeout,
            )
        except grpc.aio.AioRpcError as e:
            self.breaker.record_failure()
            self.last_errs.record(
                f"while federating to peer {addr}: {e.details()}"
            )
            raise
        self.breaker.record_success()
        ack = fastwire.parse_federation_ack(out)
        if ack is None:
            raise RuntimeError("malformed FederationSync response frame")
        return ack

    def get_last_err(self) -> List[str]:
        return self.last_errs.errors()

    # ------------------------------------------------------------------
    # Batch loop
    # ------------------------------------------------------------------
    async def _batch_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            item = await self._queue.get()
            if item is None:
                # Shutdown sentinel: anything enqueued after it raced the
                # close — fail those waiters instead of stranding them.
                self._fail_queued("peer client is shut down")
                return
            batch = [item]
            try:
                deadline = loop.time() + self.behaviors.batch_wait
                while len(batch) < self.behaviors.batch_limit:
                    timeout = deadline - loop.time()
                    if timeout <= 0:
                        break
                    try:
                        nxt = await asyncio.wait_for(self._queue.get(), timeout)
                    except asyncio.TimeoutError:
                        break
                    if nxt is None:
                        await self._send_batch(batch)
                        self._fail_queued("peer client is shut down")
                        return
                    batch.append(nxt)
            except Exception as e:
                # Window assembly crashed: fail this batch's waiters and
                # keep serving — never die holding futures.
                log.exception(
                    "peer %s batch window crashed", self._info.grpc_address
                )
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            # Send concurrently so the window keeps filling during the RPC.
            t = asyncio.create_task(self._send_batch(batch))
            self._inflight.add(t)
            t.add_done_callback(self._inflight.discard)

    def _fail_queued(self, msg: str) -> None:
        """Drain the batch queue, failing every waiter with ``msg``."""
        while self._queue is not None and not self._queue.empty():
            item = self._queue.get_nowait()
            if item is None:
                continue
            _, fut = item
            if not fut.done():
                fut.set_exception(RuntimeError(msg))

    async def _send_batch(self, batch: List[tuple]) -> None:
        """One RPC for the whole window; distribute ordered responses, or
        fail every waiter (peer_client.go:341-404).  Span parity:
        peer_client.go:351 sendBatch."""
        t0 = time.perf_counter()
        reqs = [r for r, _ in batch]
        try:
            # root=True: this runs on the batch-loop task, whose ambient
            # context is whatever request first created the loop — per-item
            # trace continuity rides the request metadata instead.
            with tracing.maybe_span(
                "PeerClient.sendBatch",
                {"batch.size": len(batch),
                 "peer": self._info.grpc_address},
                root=True,
            ):
                out = await self.get_peer_rate_limits(reqs)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        finally:
            if self.metrics is not None:
                self.metrics.batch_send_duration.labels(
                    peerAddr=self._info.grpc_address
                ).observe(time.perf_counter() - t0)
        for (_, fut), resp in zip(batch, out):
            if not fut.done():
                fut.set_result(resp)

    async def shutdown(self) -> None:
        """Drain queued/in-flight work, then close the channel
        (peer_client.go:408-435)."""
        self._closed = True
        if self._queue is not None:
            await self._queue.put(None)
        if self._batch_task is not None:
            try:
                await asyncio.wait_for(self._batch_task, self.behaviors.batch_timeout)
            except asyncio.TimeoutError:
                self._batch_task.cancel()
        if self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)
        # Stragglers that enqueued between the sentinel drain and the batch
        # task exiting (or after a cancel) must not hang forever.
        self._fail_queued("peer client is shut down")
        if self._channel is not None:
            await self._channel.close()
