"""The tick loop: batch accumulation in front of the device engine.

Replaces the reference's per-request worker dispatch (``workers.go:190-258``
channel hops) with the BASELINE.json north star: requests accumulate on the
host and flush to the TPU once per tick.  The window policy matches the
reference's peer-batching policy (``peer_client.go:284-337``): flush when
``batch_limit`` requests are waiting or ``batch_wait`` has elapsed since the
first queued request — so an idle service adds zero latency and a busy one
amortizes the device round trip over the whole window.

Two threads pipeline the ticks (SURVEY §7 "may need double-buffered
ticks"): the *dispatch* thread packs window N+1 and queues its device work
while the *resolver* thread waits out window N's D2H and completes the
waiters' futures — so sustained throughput is bounded by
max(host pack, device tick), not their sum.  ``submit`` is thread-safe and
returns a ``concurrent.futures.Future`` the caller can await.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Sequence

import numpy as np

from gubernator_tpu.types import RateLimitRequest, RateLimitResponse
from gubernator_tpu.utils import flightrec
from gubernator_tpu.utils.hotpath import hot_path

_EMPTY_MATRIX = np.zeros((5, 0), np.int64)

# Default for how many dispatched-but-unresolved windows may be in
# flight.  2 is full double-buffering; deeper rides out D2H jitter AND
# matters directly on high-RTT links: the resolver drains every queued
# window into ONE device-to-host transfer, so depth bounds how many
# windows amortize each round trip.  The bound is the backpressure:
# when the device falls behind, dispatch blocks instead of queueing
# unbounded work.  GUBER_TICK_PIPELINE_DEPTH overrides — read via the
# config registry at TickLoop construction (NOT import: an import-time
# read froze the knob for the whole process, so config changes and
# tests silently saw the stale value).
from gubernator_tpu.config import env_knob

DEFAULT_PIPELINE_DEPTH = 4


def resolve_pipeline_depth(depth=None) -> int:
    """The effective tick pipeline depth: an explicit constructor value
    wins, else GUBER_TICK_PIPELINE_DEPTH, else the default — evaluated
    at call time so the environment is re-read per constructed loop."""
    if depth is not None:
        return max(1, int(depth))
    try:
        return max(1, env_knob(
            "GUBER_TICK_PIPELINE_DEPTH", DEFAULT_PIPELINE_DEPTH,
            parse=int))
    except ValueError:
        return DEFAULT_PIPELINE_DEPTH


def _complete(fut: Future, result) -> None:
    """set_result tolerating a concurrent cancel: asyncio.wrap_future
    propagates waiter cancellation to the concurrent Future at any moment
    (it is never 'running'), so check-then-set is inherently racy."""
    try:
        if not fut.cancelled():
            fut.set_result(result)
    except Exception:  # InvalidStateError: cancelled between check and set
        pass


def _fail_waiters(waiters, exc: Exception) -> None:
    for _, fut in waiters:
        try:
            if not fut.cancelled():
                fut.set_exception(exc)
        except Exception:
            pass


class TickLoop:
    """Accumulates request batches and applies them to an engine per tick."""

    def __init__(
        self,
        engine,
        batch_wait: float = 500e-6,
        batch_limit: int = 1000,
        metrics=None,
        pipeline_depth: int = None,
    ):
        self.engine = engine
        self.batch_wait = float(batch_wait)
        self.batch_limit = int(batch_limit)
        self.metrics = metrics
        self.pipeline_depth = resolve_pipeline_depth(pipeline_depth)
        # Engine counter mirrors already synced into prometheus families
        # (the engine counts in plain ints; deltas flow here per tick).
        self._synced_hits = 0
        self._synced_misses = 0
        self._synced_unexpired = 0
        self._synced_cold_hits = 0
        self._synced_promotions = 0
        self._synced_demotions = 0
        self._synced_shed = 0
        self._synced_routed = 0
        self._synced_routed_overflows = 0
        self._cond = threading.Condition()
        self._pending: List[tuple] = []  # (requests, future)
        self._pending_count = 0
        self._running = True
        self._resolve_q: "queue.Queue" = queue.Queue(
            maxsize=self.pipeline_depth)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tick-loop"
        )
        self._resolver = threading.Thread(
            target=self._resolve_loop, daemon=True, name="tick-resolve"
        )
        self._thread.start()
        self._resolver.start()

    def submit(
        self, requests: Sequence[RateLimitRequest]
    ) -> "Future[List[RateLimitResponse]]":
        """Queue a request batch for the next tick."""
        return self._enqueue("obj", list(requests), len(requests))

    def submit_columns(self, cols) -> "Future":
        """Queue a columnar batch; the future resolves to the
        ``((5, n) matrix, errors)`` pair — no response objects anywhere
        on the path (the transport fast path; engine must expose
        submit_cols)."""
        return self._enqueue("cols", cols, len(cols))

    def _enqueue(self, kind: str, payload, n: int) -> Future:
        fut: Future = Future()
        if n == 0:
            fut.set_result(
                [] if kind == "obj" else (_EMPTY_MATRIX, {})
            )
            return fut
        with self._cond:
            if not self._running:
                fut.set_exception(RuntimeError("tick loop is shut down"))
                return fut
            self._pending.append((kind, payload, n, fut))
            self._pending_count += n
            if self.metrics is not None:
                self.metrics.worker_queue_length.labels(
                    method="GetRateLimits", worker="0"
                ).set(self._pending_count)
            self._cond.notify()
        return fut

    @hot_path
    def _run(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._pending:
                    self._cond.wait()
                if not self._running and not self._pending:
                    self._resolve_q.put(None)  # drain + stop the resolver
                    return
                # Batch window: once something is queued, wait out the tick
                # (or until the batch fills) to let more requests coalesce.
                deadline = time.monotonic() + self.batch_wait
                while (
                    self._running
                    and self._pending_count < self.batch_limit
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = self._pending
                self._pending = []
                self._pending_count = 0
            self._flush(batch)

    @hot_path
    def _flush(self, batch: List[tuple]) -> None:
        """Dispatch one window.  Object and columnar submissions each
        coalesce into (at most) one engine submission; both ride the same
        resolver handoff and resolve together in one D2H."""
        # Flight-recorder window open (docs/observability.md): the engine
        # notes lease/pack/h2d into the active window while we dispatch.
        fr = flightrec.get()
        wid = None
        if fr is not None:
            wid = fr.begin(
                sum(n for _, _, n, _ in batch), self._resolve_q.qsize())
        t0 = time.perf_counter()
        obj_items: List[tuple] = []   # (n, fut)
        reqs: List[RateLimitRequest] = []
        col_parts: List = []
        col_items: List[tuple] = []
        for kind, payload, n, fut in batch:
            if kind == "cols":
                col_parts.append(payload)
                col_items.append((n, fut))
            else:
                reqs.extend(payload)
                obj_items.append((n, fut))

        # Every engine (single-chip TickEngine AND the sharded
        # MeshTickEngine) speaks the dispatch/resolve split: submissions
        # queue device work and the resolver thread materializes many
        # windows in one D2H.  There is deliberately no synchronous
        # fallback — an engine without submit/submit_cols is a bug.
        subs = []
        if reqs:
            try:
                subs.append(("obj", self.engine.submit(reqs), obj_items,
                             len(reqs)))
            except Exception as e:
                _fail_waiters(obj_items, e)
        if col_parts:
            from gubernator_tpu.ops.reqcols import ReqColumns

            try:
                subs.append((
                    "cols",
                    self.engine.submit_cols(ReqColumns.concat(col_parts)),
                    col_items,
                    sum(n for n, _ in col_items),
                ))
            except Exception as e:
                _fail_waiters(col_items, e)
            finally:
                # Arena-backed batches (fastwire decode slabs) recycle
                # the moment the engine has packed them — submit_cols
                # copies every column into the device request matrix
                # before returning, so the views are dead here.
                for p in col_parts:
                    p.release()
        if not subs:
            if fr is not None and wid is not None:
                fr.end_dispatch(wid)
                fr.finish(wid)
            return
        if fr is not None and wid is not None:
            fr.end_dispatch(wid)
        # Bounded handoff: blocks when pipeline_depth windows are already
        # in flight (device behind), which is exactly the backpressure the
        # dispatch thread should feel.
        self._resolve_q.put((subs, time.perf_counter() - t0, wid))

    def _resolve_loop(self) -> None:
        while True:
            item = self._resolve_q.get()
            if item is None:
                return
            # Drain whatever else is queued: all drained windows resolve
            # with ONE device-to-host transfer (engine.resolve_ticks) —
            # per-transfer latency is the throughput ceiling when the
            # device is remote, so the resolver never fetches one window
            # at a time when several are in flight.
            items = [item]
            stop = False
            while True:
                try:
                    nxt = self._resolve_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    stop = True
                    break
                items.append(nxt)
            fr = flightrec.get()
            t_drain = time.perf_counter()
            try:
                from gubernator_tpu.ops.engine import resolve_ticks

                resolve_ticks([
                    h
                    for subs, _, _ in items
                    for _, sb, _, _ in subs
                    for h in sb.handles()
                ])
            except Exception:
                pass  # per-window resolution below surfaces real errors
            if fr is not None:
                # All drained windows shared this one D2H wait; each
                # reports it as its tick time (documented in flightrec).
                drain_s = time.perf_counter() - t_drain
                for _, _, wid in items:
                    if wid is not None:
                        fr.note(wid, "tick", drain_s)
            for subs, dispatch_s, wid in items:
                for kind, sb, waiters, n_reqs in subs:
                    # Guarded: an exception escaping this loop would kill
                    # the resolver thread and wedge the whole pipeline
                    # (dispatch eventually blocks on the bounded queue).
                    try:
                        t1 = time.perf_counter()
                        out = (
                            sb.responses() if kind == "obj" else sb.matrix()
                        )
                        resolve_s = time.perf_counter() - t1
                        if fr is not None and wid is not None:
                            fr.note(wid, "resolve", resolve_s)
                    except Exception as e:
                        _fail_waiters(waiters, e)
                        continue
                    try:
                        self._deliver_kind(
                            kind, waiters, out, n_reqs,
                            dispatch_s + resolve_s,
                        )
                    except Exception:
                        logging.getLogger("gubernator.tickloop").exception(
                            "tick delivery failed"
                        )
                if fr is not None and wid is not None:
                    fr.finish(wid)
            if stop:
                return

    def _deliver_kind(self, kind, waiters, out, n_reqs, tick_s) -> None:
        if kind == "obj":
            self._deliver(waiters, out, n_reqs, tick_s)
            return
        mat, errors = out
        self._metrics_sync(n_reqs, tick_s)
        off = 0
        for n, fut in waiters:
            errs = {
                i - off: msg for i, msg in errors.items()
                if off <= i < off + n
            } if errors else {}
            _complete(fut, (mat[:, off : off + n], errs))
            off += n

    def _deliver(self, waiters, out, n_reqs: int, tick_s: float) -> None:
        """Complete object waiters' futures + sync metrics.  ``tick_s`` is
        the window's own engine time (dispatch + resolve), NOT wall time
        since flush — under pipelining the latter would include time
        queued behind earlier windows and misreport device health."""
        self._metrics_sync(n_reqs, tick_s)
        off = 0
        for n, fut in waiters:
            _complete(fut, out[off : off + n])
            off += n

    def _metrics_sync(self, n_reqs: int, tick_s: float) -> None:
        if self.metrics is None:
            return
        m = self.metrics
        m.tick_duration.observe(tick_s)
        m.tick_batch_size.observe(n_reqs)
        m.worker_queue_length.labels(
            method="GetRateLimits", worker="0"
        ).set(self._pending_count)
        m.command_counter.labels(
            worker="0", method="GetRateLimits"
        ).inc(n_reqs)
        # Sync engine counter deltas (hit/miss on slot resolution,
        # LRU evictions of unexpired buckets) into the catalog families.
        hits = getattr(self.engine, "metric_hits", 0)
        misses = getattr(self.engine, "metric_misses", 0)
        unexp = getattr(self.engine, "metric_unexpired_evictions", 0)
        if hits > self._synced_hits:
            m.cache_access_count.labels(type="hit").inc(
                hits - self._synced_hits
            )
            self._synced_hits = hits
        if misses > self._synced_misses:
            m.cache_access_count.labels(type="miss").inc(
                misses - self._synced_misses
            )
            self._synced_misses = misses
        if unexp > self._synced_unexpired:
            m.unexpired_evictions.inc(unexp - self._synced_unexpired)
            self._synced_unexpired = unexp
        # Tiering families (docs/tiering.md).  Counters sync as deltas
        # like the cache families above; the occupancy gauges are set
        # directly (they are levels, not flows).
        cold_hits = getattr(self.engine, "metric_cold_hits", 0)
        promos = getattr(self.engine, "metric_promotions", 0)
        shed = getattr(self.engine, "metric_shed_requests", 0)
        cold = getattr(self.engine, "cold", None)
        if cold_hits > self._synced_cold_hits:
            m.cold_hits.inc(cold_hits - self._synced_cold_hits)
            self._synced_cold_hits = cold_hits
        if promos > self._synced_promotions:
            m.cold_promotions.inc(promos - self._synced_promotions)
            self._synced_promotions = promos
        if shed > self._synced_shed:
            m.shed_requests.inc(shed - self._synced_shed)
            self._synced_shed = shed
        if cold is not None:
            demos = cold.metric_demotions
            if demos > self._synced_demotions:
                m.cold_demotions.inc(demos - self._synced_demotions)
                self._synced_demotions = demos
            m.cold_size.set(len(cold))
        if hasattr(self.engine, "hot_occupancy"):
            m.hot_occupancy.set(self.engine.hot_occupancy())
        if hasattr(self.engine, "h2d_overlap_ratio"):
            m.h2d_overlap_ratio.set(self.engine.h2d_overlap_ratio())
        # Sharded-table routing telemetry (mesh-backed engines only).
        routed = getattr(self.engine, "metric_routed_windows", 0)
        if routed > self._synced_routed:
            m.mesh_routed_windows.inc(routed - self._synced_routed)
            self._synced_routed = routed
        r_over = getattr(self.engine, "metric_routed_overflows", 0)
        if r_over > self._synced_routed_overflows:
            m.mesh_routed_overflows.inc(
                r_over - self._synced_routed_overflows)
            self._synced_routed_overflows = r_over

    def _drain_resolve_q(self, err: Exception) -> None:
        """Fail every window still queued for resolution.  A drained None
        stop sentinel is re-enqueued: a resolver that was merely slow (not
        dead) must still find it when it loops back to get(), or it would
        block on the empty queue forever."""
        saw_sentinel = False
        while True:
            try:
                item = self._resolve_q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                saw_sentinel = True
                continue
            subs = item[0]
            for _, _, items, _ in subs:
                _fail_waiters(items, err)
        if saw_sentinel:
            self._resolve_q.put(None)

    def close(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # Dispatch thread wedged (e.g. blocked on a full resolve queue
            # with a dead resolver): don't hang close() — but don't leave
            # queued waiters hanging forever either; fail everything
            # still pending so callers awaiting wrap_future() return.
            with self._cond:
                stuck = self._pending
                self._pending = []
                self._pending_count = 0
            err = RuntimeError("tick loop shut down with requests pending")
            _fail_waiters([(n, fut) for _, _, n, fut in stuck], err)
            self._drain_resolve_q(err)
            return
        self._resolver.join(timeout=5)
        if self._resolver.is_alive():
            # Resolver wedged (e.g. a D2H that never completes): windows
            # already submitted for resolution would leave their callers
            # awaiting wrap_future forever — fail whatever is still queued.
            self._drain_resolve_q(
                RuntimeError("tick loop shut down with requests pending")
            )
