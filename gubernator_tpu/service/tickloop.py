"""The tick loop: batch accumulation in front of the device engine.

Replaces the reference's per-request worker dispatch (``workers.go:190-258``
channel hops) with the BASELINE.json north star: requests accumulate on the
host and flush to the TPU once per tick.  The window policy matches the
reference's peer-batching policy (``peer_client.go:284-337``): flush when
``batch_limit`` requests are waiting or ``batch_wait`` has elapsed since the
first queued request — so an idle service adds zero latency and a busy one
amortizes the device round trip over the whole window.

The loop runs on a dedicated thread (device dispatch must not block the
asyncio transport); ``submit`` is thread-safe and returns a
``concurrent.futures.Future`` the caller can await.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

from gubernator_tpu.types import RateLimitRequest, RateLimitResponse


class TickLoop:
    """Accumulates request batches and applies them to an engine per tick."""

    def __init__(
        self,
        engine,
        batch_wait: float = 500e-6,
        batch_limit: int = 1000,
        metrics=None,
    ):
        self.engine = engine
        self.batch_wait = float(batch_wait)
        self.batch_limit = int(batch_limit)
        self.metrics = metrics
        # Engine counter mirrors already synced into prometheus families
        # (the engine counts in plain ints; deltas flow here per tick).
        self._synced_hits = 0
        self._synced_misses = 0
        self._synced_unexpired = 0
        self._cond = threading.Condition()
        self._pending: List[tuple] = []  # (requests, future)
        self._pending_count = 0
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True, name="tick-loop")
        self._thread.start()

    def submit(
        self, requests: Sequence[RateLimitRequest]
    ) -> "Future[List[RateLimitResponse]]":
        """Queue a request batch for the next tick."""
        fut: Future = Future()
        if not requests:
            fut.set_result([])
            return fut
        with self._cond:
            if not self._running:
                fut.set_exception(RuntimeError("tick loop is shut down"))
                return fut
            self._pending.append((list(requests), fut))
            self._pending_count += len(requests)
            if self.metrics is not None:
                self.metrics.worker_queue_length.labels(
                    method="GetRateLimits", worker="0"
                ).set(self._pending_count)
            self._cond.notify()
        return fut

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._running and not self._pending:
                    self._cond.wait()
                if not self._running and not self._pending:
                    return
                # Batch window: once something is queued, wait out the tick
                # (or until the batch fills) to let more requests coalesce.
                deadline = time.monotonic() + self.batch_wait
                while (
                    self._running
                    and self._pending_count < self.batch_limit
                ):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = self._pending
                self._pending = []
                self._pending_count = 0
            self._flush(batch)

    def _flush(self, batch: List[tuple]) -> None:
        reqs: List[RateLimitRequest] = []
        for r, _ in batch:
            reqs.extend(r)
        t0 = time.perf_counter()
        try:
            out = self.engine.process(reqs)
        except Exception as e:  # engine failure fails every waiter in the tick
            for _, fut in batch:
                if not fut.cancelled():
                    fut.set_exception(e)
            return
        if self.metrics is not None:
            m = self.metrics
            m.tick_duration.observe(time.perf_counter() - t0)
            m.tick_batch_size.observe(len(reqs))
            m.worker_queue_length.labels(
                method="GetRateLimits", worker="0"
            ).set(self._pending_count)
            m.command_counter.labels(
                worker="0", method="GetRateLimits"
            ).inc(len(reqs))
            # Sync engine counter deltas (hit/miss on slot resolution,
            # LRU evictions of unexpired buckets) into the catalog families.
            hits = getattr(self.engine, "metric_hits", 0)
            misses = getattr(self.engine, "metric_misses", 0)
            unexp = getattr(self.engine, "metric_unexpired_evictions", 0)
            if hits > self._synced_hits:
                m.cache_access_count.labels(type="hit").inc(
                    hits - self._synced_hits
                )
                self._synced_hits = hits
            if misses > self._synced_misses:
                m.cache_access_count.labels(type="miss").inc(
                    misses - self._synced_misses
                )
                self._synced_misses = misses
            if unexp > self._synced_unexpired:
                m.unexpired_evictions.inc(unexp - self._synced_unexpired)
                self._synced_unexpired = unexp
        off = 0
        for r, fut in batch:
            if not fut.cancelled():  # waiter may have timed out/cancelled
                fut.set_result(out[off : off + len(r)])
            off += len(r)

    def close(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify()
        self._thread.join(timeout=5)
