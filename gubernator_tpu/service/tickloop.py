"""The tick loop: batch accumulation in front of the device engine.

Replaces the reference's per-request worker dispatch (``workers.go:190-258``
channel hops) with the BASELINE.json north star: requests accumulate on the
host and flush to the TPU once per tick.  The window policy matches the
reference's peer-batching policy (``peer_client.go:284-337``): flush when
``batch_limit`` requests are waiting or ``batch_wait`` has elapsed since the
first queued request — so an idle service adds zero latency and a busy one
amortizes the device round trip over the whole window.

Two threads pipeline the ticks (SURVEY §7 "may need double-buffered
ticks"): the *dispatch* thread packs window N+1 and queues its device work
while the *resolver* thread waits out window N's D2H and completes the
waiters' futures — so sustained throughput is bounded by
max(host pack, device tick), not their sum.  ``submit`` is thread-safe and
returns a ``concurrent.futures.Future`` the caller can await.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Sequence

import numpy as np

from gubernator_tpu.admission import (
    CLASS_CLIENT,
    POLICY_FAIL_CLOSED,
    SHED_EXPIRED_MSG,
    SHED_RESHARD_MSG,
    SHED_SHUTDOWN_MSG,
    AdmissionConfig,
    AdmissionQueue,
    AimdLimiter,
    QueueItem,
    under_pressure,
)
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse, Status
from gubernator_tpu.utils import flightrec
from gubernator_tpu.utils.hotpath import hot_path

_EMPTY_MATRIX = np.zeros((5, 0), np.int64)

# Default for how many dispatched-but-unresolved windows may be in
# flight.  2 is full double-buffering; deeper rides out D2H jitter AND
# matters directly on high-RTT links: the resolver drains every queued
# window into ONE device-to-host transfer, so depth bounds how many
# windows amortize each round trip.  The bound is the backpressure:
# when the device falls behind, dispatch blocks instead of queueing
# unbounded work.  GUBER_TICK_PIPELINE_DEPTH overrides — read via the
# config registry at TickLoop construction (NOT import: an import-time
# read froze the knob for the whole process, so config changes and
# tests silently saw the stale value).
from gubernator_tpu.config import env_knob
from gubernator_tpu.utils import sanitize

DEFAULT_PIPELINE_DEPTH = 4


def resolve_pipeline_depth(depth=None) -> int:
    """The effective tick pipeline depth: an explicit constructor value
    wins, else GUBER_TICK_PIPELINE_DEPTH, else the default — evaluated
    at call time so the environment is re-read per constructed loop."""
    if depth is not None:
        return max(1, int(depth))
    try:
        return max(1, env_knob(
            "GUBER_TICK_PIPELINE_DEPTH", DEFAULT_PIPELINE_DEPTH,
            parse=int))
    except ValueError:
        return DEFAULT_PIPELINE_DEPTH


def _complete(fut: Future, result) -> None:
    """set_result tolerating a concurrent cancel: asyncio.wrap_future
    propagates waiter cancellation to the concurrent Future at any moment
    (it is never 'running'), so check-then-set is inherently racy."""
    try:
        if not fut.cancelled():
            fut.set_result(result)
    except Exception:  # InvalidStateError: cancelled between check and set
        pass


def _fail_waiters(waiters, exc: Exception) -> None:
    for _, fut in waiters:
        try:
            if not fut.cancelled():
                fut.set_exception(exc)
        except Exception:
            pass


class TickLoop:
    """Accumulates request batches and applies them to an engine per tick."""

    def __init__(
        self,
        engine,
        batch_wait: float = 500e-6,
        batch_limit: int = 1000,
        metrics=None,
        pipeline_depth: int = None,
        admission: AdmissionConfig = None,
        clock=time.monotonic,
    ):
        self.engine = engine
        self.batch_wait = float(batch_wait)
        self.batch_limit = int(batch_limit)
        self.metrics = metrics
        self.pipeline_depth = resolve_pipeline_depth(pipeline_depth)
        # Overload control plane (docs/overload.md).  The injected clock
        # drives ONLY deadline math (ManualClock in tests); the batch
        # window below stays on real time so a frozen test clock cannot
        # wedge the dispatch thread's timed wait.
        self.admission = (
            admission if admission is not None else AdmissionConfig.from_env()
        )
        self._clock = clock
        self.shed_policy = self.admission.shed_policy
        self.limiter = AimdLimiter(
            self.admission.target_p99_ms, max_limit=self.batch_limit)
        self._queue = AdmissionQueue(
            self.admission.effective_pending_limit(self.batch_limit))
        self.metric_shed_admission = {}  # reason -> shed request count
        self.metric_expired_served = 0  # invariant: stays 0
        self._synced_expired_served = 0
        # Engine counter mirrors already synced into prometheus families
        # (the engine counts in plain ints; deltas flow here per tick).
        self._synced_hits = 0
        self._synced_misses = 0
        self._synced_unexpired = 0
        self._synced_cold_hits = 0
        self._synced_promotions = 0
        self._synced_demotions = 0
        self._synced_ssd_hits = 0
        self._synced_ssd_promotions = 0
        self._synced_ssd_demotions = 0
        self._synced_ssd_compactions = 0
        self._synced_shed = 0
        self._synced_routed = 0
        self._synced_routed_overflows = 0
        self._cond = sanitize.condition("TickLoop._cond")
        self._pending_count = 0
        self._running = True
        # Reshard admission freeze (docs/resharding.md): level 1 sheds
        # new CLIENT windows with a retriable status while PEER windows
        # keep draining; level 2 (cutover) sheds both.  Queued work is
        # never dropped by a freeze — it drains through _flush as usual.
        self._freeze_level = 0
        # Windows handed to the resolver but not yet delivered; quiesce()
        # waits for this to reach zero (resolve_q.empty() alone races the
        # resolver's in-progress item).
        self._inflight_windows = 0
        self._resolve_q: "queue.Queue" = queue.Queue(
            maxsize=self.pipeline_depth)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tick-loop"
        )
        self._resolver = threading.Thread(
            target=self._resolve_loop, daemon=True, name="tick-resolve"
        )
        self._thread.start()
        self._resolver.start()

    def submit(
        self,
        requests: Sequence[RateLimitRequest],
        deadline: float = None,
        klass: int = CLASS_CLIENT,
    ) -> "Future[List[RateLimitResponse]]":
        """Queue a request batch for the next tick.  ``deadline`` is the
        batch's absolute admission deadline on this loop's clock (None =
        never shed); ``klass`` is the admission class (peer reconcile
        traffic outranks client traffic under overload)."""
        return self._enqueue("obj", list(requests), len(requests),
                             deadline, klass)

    def under_pressure(self) -> bool:
        """True while the overload plane is actively backing off —
        the lease tier's cue to answer grants with cheap TTL extension
        instead of full decisions (admission.under_pressure)."""
        return under_pressure(
            self.limiter, self._pending_count,
            self.admission.effective_pending_limit(self.batch_limit),
            self.batch_limit,
        )

    def admission_snapshot(self) -> dict:
        """One consistent view of the admission plane for the control
        plane (autoscaler, /debug/autoscaler): limiter state, queue
        depth, cumulative shed counts, freeze level.  Takes the loop
        condition briefly; not ``@hot_path`` — it runs on the
        controller's sampling cadence, never inside a tick."""
        with self._cond:
            return {
                "limiter": self.limiter.snapshot(),
                "queue": self._queue.snapshot(),
                "pending": self._pending_count,
                "shed": dict(self.metric_shed_admission),
                "frozen": self._freeze_level > 0,
            }

    def submit_columns(self, cols, deadline: float = None,
                       klass: int = CLASS_CLIENT) -> "Future":
        """Queue a columnar batch; the future resolves to the
        ``((5, n) matrix, errors)`` pair — no response objects anywhere
        on the path (the transport fast path; engine must expose
        submit_cols)."""
        return self._enqueue("cols", cols, len(cols), deadline, klass)

    def _enqueue(self, kind: str, payload, n: int, deadline: float = None,
                 klass: int = CLASS_CLIENT) -> Future:
        fut: Future = Future()
        if n == 0:
            fut.set_result(
                [] if kind == "obj" else (_EMPTY_MATRIX, {})
            )
            return fut
        with self._cond:
            if not self._running:
                fut.set_exception(RuntimeError("tick loop is shut down"))
                return fut
            item = QueueItem(kind, payload, n, fut, deadline, klass)
            lvl = self._freeze_level
            if lvl and (lvl >= 2 or klass == CLASS_CLIENT):
                frozen, shed = item, ()
            else:
                frozen = None
                shed = self._queue.push(item)
                self._pending_count = self._queue.requests
                if self.metrics is not None:
                    self.metrics.worker_queue_length.labels(
                        method="GetRateLimits", worker="0"
                    ).set(self._pending_count)
                    self.metrics.admission_queue_depth.set(
                        self._pending_count)
                self._cond.notify()
        if frozen is not None:
            # Answered outside the lock like overflow victims: a frozen
            # window gets the retriable reshard status immediately (it
            # was never queued), so callers retry after the bounded
            # cutover instead of waiting it out.
            self._shed_item(frozen, "reshard")
            return fut
        # Answer overflow victims outside the lock: they are already
        # unlinked from the queue, and shed answers may release arena
        # leases / complete futures with waiting callbacks.
        for victim in shed:
            self._shed_item(victim, "overflow")
        return fut

    @hot_path
    def _run(self) -> None:
        while True:
            batch: List[QueueItem] = []
            stopping = False
            with self._cond:
                while self._running and not self._queue:
                    self._cond.wait()
                if not self._running and not self._queue:
                    stopping = True
                else:
                    # Batch window: once something is queued, wait out the
                    # tick (or until the batch fills) to let more requests
                    # coalesce.
                    deadline = time.monotonic() + self.batch_wait
                    while (
                        self._running
                        and self._pending_count < self.batch_limit
                    ):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                    # Admitted window width: the AIMD limiter narrows it
                    # under measured saturation; shutdown drains at full
                    # width so a throttled loop still closes promptly.
                    # Whatever does not fit stays queued (in priority
                    # order) for the next tick.
                    width = self.batch_limit
                    if self._running and self.limiter.enabled:
                        width = min(width, self.limiter.window_limit)
                    batch = self._queue.pop_window(width)
                    # Written only under _cond; the one unlocked reader is
                    # under_pressure(), a per-grant heuristic that tolerates
                    # one-tick staleness of a GIL-atomic int by design.
                    # guber: allow-g009(advisory queue-depth mirror - the unlocked under_pressure read tolerates one-tick staleness of a GIL-atomic int)
                    self._pending_count = self._queue.requests
                    # Count the window from the moment it leaves the queue:
                    # quiesce must see a batch wedged inside engine dispatch
                    # (it is neither queued nor at the resolver yet, but the
                    # cutover cannot run until it resolves).
                    if batch:
                        self._inflight_windows += 1
            if stopping:
                # The drain/stop sentinel ships OUTSIDE the condition: the
                # resolver handoff queue is bounded, and a full pipeline
                # must park the dispatch thread without wedging every
                # _cond waiter behind it (guberlint G007).
                # guber: allow-G001(shutdown-only drain sentinel - runs once at loop exit, never inside a serving tick)
                self._resolve_q.put(None)
                return
            if batch:
                self._flush(batch)

    @hot_path
    def _flush(self, batch: List[QueueItem]) -> None:
        """Dispatch one window.  Object and columnar submissions each
        coalesce into (at most) one engine submission; both ride the same
        resolver handoff and resolve together in one D2H."""
        # Deadline-aware admission (docs/overload.md): shed anything
        # already expired BEFORE packing — the device never burns a tick
        # answering an RPC whose caller has given up.  Shed items are
        # answered with a retriable error status, never dropped.
        now = self._clock()
        expired = [it for it in batch if it.expired(now)]
        if expired:
            batch = [it for it in batch if not it.expired(now)]
            for it in expired:
                self._shed_item(it, "expired")
        if not batch:
            self._window_done()
            return
        # Flight-recorder window open (docs/observability.md): the engine
        # notes lease/pack/h2d into the active window while we dispatch.
        fr = flightrec.get()
        wid = None
        if fr is not None:
            wid = fr.begin(
                sum(it.n for it in batch), self._resolve_q.qsize())
        t0 = time.perf_counter()
        obj_items: List[tuple] = []   # (n, fut)
        reqs: List[RateLimitRequest] = []
        col_parts: List = []
        col_items: List[tuple] = []
        for it in batch:
            # Invariant counter for the overload_shed gate: an expired
            # item reaching the pack stage means the partition above
            # regressed.  Counted (and exported), never silently served.
            if it.expired(now):
                self.metric_expired_served += it.n
            if it.kind == "cols":
                col_parts.append(it.payload)
                col_items.append((it.n, it.fut))
            else:
                reqs.extend(it.payload)
                obj_items.append((it.n, it.fut))

        # Every engine (single-chip TickEngine AND the sharded
        # MeshTickEngine) speaks the dispatch/resolve split: submissions
        # queue device work and the resolver thread materializes many
        # windows in one D2H.  There is deliberately no synchronous
        # fallback — an engine without submit/submit_cols is a bug.
        subs = []
        if reqs:
            try:
                subs.append(("obj", self.engine.submit(reqs), obj_items,
                             len(reqs)))
            except Exception as e:
                _fail_waiters(obj_items, e)
        if col_parts:
            from gubernator_tpu.ops.reqcols import ReqColumns

            try:
                subs.append((
                    "cols",
                    self.engine.submit_cols(ReqColumns.concat(col_parts)),
                    col_items,
                    sum(n for n, _ in col_items),
                ))
            except Exception as e:
                _fail_waiters(col_items, e)
            finally:
                # Arena-backed batches (fastwire decode slabs) recycle
                # the moment the engine has packed them — submit_cols
                # copies every column into the device request matrix
                # before returning, so the views are dead here.
                for p in col_parts:
                    p.release()
        if not subs:
            if fr is not None and wid is not None:
                fr.end_dispatch(wid)
                fr.finish(wid)
            self._window_done()
            return
        if fr is not None and wid is not None:
            fr.end_dispatch(wid)
        # Bounded handoff: blocks when pipeline_depth windows are already
        # in flight (device behind), which is exactly the backpressure the
        # dispatch thread should feel.  The in-flight count was taken at
        # pop time in _run; the resolver releases it after the D2H drain.
        # guber: allow-G001(deliberate bounded-pipeline backpressure - blocking here when pipeline_depth windows are in flight IS the flow control)
        self._resolve_q.put((subs, time.perf_counter() - t0, wid))

    def _window_done(self) -> None:
        """Release one window's in-flight count without a resolver trip
        (the window shed or failed entirely before dispatch)."""
        with self._cond:
            self._inflight_windows = max(0, self._inflight_windows - 1)
            self._cond.notify_all()

    def _resolve_loop(self) -> None:
        while True:
            item = self._resolve_q.get()
            if item is None:
                return
            # Drain whatever else is queued: all drained windows resolve
            # with ONE device-to-host transfer (engine.resolve_ticks) —
            # per-transfer latency is the throughput ceiling when the
            # device is remote, so the resolver never fetches one window
            # at a time when several are in flight.
            items = [item]
            stop = False
            while True:
                try:
                    nxt = self._resolve_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    stop = True
                    break
                items.append(nxt)
            fr = flightrec.get()
            t_drain = time.perf_counter()
            try:
                from gubernator_tpu.ops.engine import resolve_ticks

                resolve_ticks([
                    h
                    for subs, _, _ in items
                    for _, sb, _, _ in subs
                    for h in sb.handles()
                ])
            except Exception:
                pass  # per-window resolution below surfaces real errors
            if fr is not None:
                # All drained windows shared this one D2H wait; each
                # reports it as its tick time (documented in flightrec).
                drain_s = time.perf_counter() - t_drain
                for _, _, wid in items:
                    if wid is not None:
                        fr.note(wid, "tick", drain_s)
            for subs, dispatch_s, wid in items:
                for kind, sb, waiters, n_reqs in subs:
                    # Guarded: an exception escaping this loop would kill
                    # the resolver thread and wedge the whole pipeline
                    # (dispatch eventually blocks on the bounded queue).
                    try:
                        t1 = time.perf_counter()
                        out = (
                            sb.responses() if kind == "obj" else sb.matrix()
                        )
                        resolve_s = time.perf_counter() - t1
                        if fr is not None and wid is not None:
                            fr.note(wid, "resolve", resolve_s)
                    except Exception as e:
                        _fail_waiters(waiters, e)
                        continue
                    try:
                        self._deliver_kind(
                            kind, waiters, out, n_reqs,
                            dispatch_s + resolve_s,
                        )
                    except Exception:
                        logging.getLogger("gubernator.tickloop").exception(
                            "tick delivery failed"
                        )
                if fr is not None and wid is not None:
                    fr.finish(wid)
            with self._cond:
                self._inflight_windows = max(
                    0, self._inflight_windows - len(items))
                self._cond.notify_all()
            if stop:
                return

    def _deliver_kind(self, kind, waiters, out, n_reqs, tick_s) -> None:
        if kind == "obj":
            self._deliver(waiters, out, n_reqs, tick_s)
            return
        mat, errors = out
        self._metrics_sync(n_reqs, tick_s)
        off = 0
        for n, fut in waiters:
            errs = {
                i - off: msg for i, msg in errors.items()
                if off <= i < off + n
            } if errors else {}
            _complete(fut, (mat[:, off : off + n], errs))
            off += n

    def _deliver(self, waiters, out, n_reqs: int, tick_s: float) -> None:
        """Complete object waiters' futures + sync metrics.  ``tick_s`` is
        the window's own engine time (dispatch + resolve), NOT wall time
        since flush — under pipelining the latter would include time
        queued behind earlier windows and misreport device health."""
        self._metrics_sync(n_reqs, tick_s)
        off = 0
        for n, fut in waiters:
            _complete(fut, out[off : off + n])
            off += n

    # ------------------------------------------------------------------
    # Reshard admission freeze (docs/resharding.md)
    # ------------------------------------------------------------------
    def freeze(self, shed_peers: bool = False) -> None:
        """Stop admitting new windows into the transition epoch: CLIENT
        submissions answer the retriable reshard status immediately;
        PEER submissions keep draining (they outrank clients and must
        land before the cutover) until ``shed_peers`` escalates the
        freeze for the bounded cutover itself.  Idempotent; never
        downgrades an escalated freeze."""
        with self._cond:
            self._freeze_level = max(
                self._freeze_level, 2 if shed_peers else 1)

    def unfreeze(self) -> None:
        with self._cond:
            self._freeze_level = 0
            self._cond.notify_all()

    @property
    def frozen(self) -> bool:
        return self._freeze_level > 0

    def quiesce(self, timeout: float) -> bool:
        """Wait (bounded) until every admitted window has fully drained:
        nothing queued, nothing mid-dispatch, nothing awaiting the
        resolver.  Returns True when idle was reached — the cutover
        precondition; False means the budget expired with work still in
        flight (the coordinator aborts rather than cutting over under
        traffic)."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            with self._cond:
                idle = (
                    not self._queue
                    and self._pending_count == 0
                    and self._inflight_windows == 0
                    and self._resolve_q.empty()
                )
            if idle:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.001)

    def _shed_item(self, item: QueueItem, reason: str) -> None:
        """Answer one shed submission (docs/overload.md).  Expired,
        shutdown and reshard sheds answer a retriable per-item error so
        callers know to retry with a fresh budget / against another
        peer / after the cutover; overflow sheds answer the configured
        degradation policy (fail-open UNDER_LIMIT with full remaining,
        fail-closed OVER_LIMIT with zero remaining).  Columnar payloads
        release their arena lease here — a shed batch must not pin a
        decode slab."""
        self.metric_shed_admission[reason] = (
            self.metric_shed_admission.get(reason, 0) + item.n)
        if self.metrics is not None:
            self.metrics.admission_shed.labels(reason=reason).inc(item.n)
        retriable = reason in ("expired", "shutdown", "reshard")
        if reason == "expired":
            msg = SHED_EXPIRED_MSG
        elif reason == "reshard":
            msg = SHED_RESHARD_MSG
        else:
            msg = SHED_SHUTDOWN_MSG
        if item.kind == "obj":
            if retriable:
                out = [RateLimitResponse(error=msg)
                       for _ in range(item.n)]
            else:
                out = [self._policy_response(r) for r in item.payload]
            _complete(item.fut, out)
            return
        cols = item.payload
        try:
            if retriable:
                mat = np.zeros((5, item.n), np.int64)
                errs = {i: msg for i in range(item.n)}
            else:
                mat = self._policy_matrix(cols, item.n)
                errs = {}
        finally:
            cols.release()
        _complete(item.fut, (mat, errs))

    def _policy_response(self, r: RateLimitRequest) -> RateLimitResponse:
        reset = (getattr(r, "created_at", 0) or 0) + (r.duration or 0)
        if self.shed_policy == POLICY_FAIL_CLOSED:
            return RateLimitResponse(
                status=Status.OVER_LIMIT, limit=r.limit,
                remaining=0, reset_time=reset)
        return RateLimitResponse(
            status=Status.UNDER_LIMIT, limit=r.limit,
            remaining=r.limit, reset_time=reset)

    def _policy_matrix(self, cols, n: int) -> np.ndarray:
        """Degradation answers for a shed columnar batch, built from the
        request columns BEFORE the arena lease is recycled (rows: status,
        limit, remaining, reset_time, over_limit)."""
        mat = np.zeros((5, n), np.int64)
        mat[1] = cols.limit
        mat[3] = cols.created_at + cols.duration
        if self.shed_policy == POLICY_FAIL_CLOSED:
            mat[0] = int(Status.OVER_LIMIT)
            mat[4] = 1
        else:
            mat[2] = cols.limit
        return mat

    def _metrics_sync(self, n_reqs: int, tick_s: float) -> None:
        # AIMD feedback (docs/overload.md): every resolved window's own
        # engine time (dispatch + resolve) is one limiter sample.
        self.limiter.record(tick_s * 1000.0)
        if self.metrics is None:
            return
        m = self.metrics
        m.admission_window_limit.set(
            self.limiter.window_limit if self.limiter.enabled
            else self.batch_limit)
        m.admission_queue_depth.set(self._pending_count)
        if self.metric_expired_served > self._synced_expired_served:
            m.admission_expired_served.inc(
                self.metric_expired_served - self._synced_expired_served)
            self._synced_expired_served = self.metric_expired_served
        m.tick_duration.observe(tick_s)
        m.tick_batch_size.observe(n_reqs)
        m.worker_queue_length.labels(
            method="GetRateLimits", worker="0"
        ).set(self._pending_count)
        m.command_counter.labels(
            worker="0", method="GetRateLimits"
        ).inc(n_reqs)
        # Sync engine counter deltas (hit/miss on slot resolution,
        # LRU evictions of unexpired buckets) into the catalog families.
        hits = getattr(self.engine, "metric_hits", 0)
        misses = getattr(self.engine, "metric_misses", 0)
        unexp = getattr(self.engine, "metric_unexpired_evictions", 0)
        if hits > self._synced_hits:
            m.cache_access_count.labels(type="hit").inc(
                hits - self._synced_hits
            )
            self._synced_hits = hits
        if misses > self._synced_misses:
            m.cache_access_count.labels(type="miss").inc(
                misses - self._synced_misses
            )
            self._synced_misses = misses
        if unexp > self._synced_unexpired:
            m.unexpired_evictions.inc(unexp - self._synced_unexpired)
            self._synced_unexpired = unexp
        # Tiering families (docs/tiering.md).  Counters sync as deltas
        # like the cache families above; the occupancy gauges are set
        # directly (they are levels, not flows).
        cold_hits = getattr(self.engine, "metric_cold_hits", 0)
        promos = getattr(self.engine, "metric_promotions", 0)
        shed = getattr(self.engine, "metric_shed_requests", 0)
        cold = getattr(self.engine, "cold", None)
        if cold_hits > self._synced_cold_hits:
            m.cold_hits.inc(cold_hits - self._synced_cold_hits)
            self._synced_cold_hits = cold_hits
        if promos > self._synced_promotions:
            m.cold_promotions.inc(promos - self._synced_promotions)
            self._synced_promotions = promos
        if shed > self._synced_shed:
            m.shed_requests.inc(shed - self._synced_shed)
            self._synced_shed = shed
        if cold is not None:
            demos = cold.metric_demotions
            if demos > self._synced_demotions:
                m.cold_demotions.inc(demos - self._synced_demotions)
                self._synced_demotions = demos
            m.cold_size.set(len(cold))
        # SSD tier families: counters as deltas from the slab store's
        # plain-int mirrors; bytes/queue depth are levels, set directly.
        ssd = getattr(self.engine, "ssd", None)
        if ssd is not None:
            ssd_hits = getattr(self.engine, "metric_ssd_hits", 0)
            if ssd_hits > self._synced_ssd_hits:
                m.ssd_hits.inc(ssd_hits - self._synced_ssd_hits)
                self._synced_ssd_hits = ssd_hits
            if ssd.metric_promotions > self._synced_ssd_promotions:
                m.ssd_promotions.inc(
                    ssd.metric_promotions - self._synced_ssd_promotions)
                self._synced_ssd_promotions = ssd.metric_promotions
            if ssd.metric_demotions > self._synced_ssd_demotions:
                m.ssd_demotions.inc(
                    ssd.metric_demotions - self._synced_ssd_demotions)
                self._synced_ssd_demotions = ssd.metric_demotions
            if ssd.metric_compactions > self._synced_ssd_compactions:
                m.ssd_compactions.inc(
                    ssd.metric_compactions - self._synced_ssd_compactions)
                self._synced_ssd_compactions = ssd.metric_compactions
            m.ssd_bytes.set(ssd.bytes_used())
            m.ssd_queue_depth.set(ssd.queue_depth())
        if hasattr(self.engine, "hot_occupancy"):
            m.hot_occupancy.set(self.engine.hot_occupancy())
        if hasattr(self.engine, "h2d_overlap_ratio"):
            m.h2d_overlap_ratio.set(self.engine.h2d_overlap_ratio())
        # Sharded-table routing telemetry (mesh-backed engines only).
        routed = getattr(self.engine, "metric_routed_windows", 0)
        if routed > self._synced_routed:
            m.mesh_routed_windows.inc(routed - self._synced_routed)
            self._synced_routed = routed
        r_over = getattr(self.engine, "metric_routed_overflows", 0)
        if r_over > self._synced_routed_overflows:
            m.mesh_routed_overflows.inc(
                r_over - self._synced_routed_overflows)
            self._synced_routed_overflows = r_over

    def _drain_resolve_q(self, err: Exception) -> None:
        """Fail every window still queued for resolution.  A drained None
        stop sentinel is re-enqueued: a resolver that was merely slow (not
        dead) must still find it when it loops back to get(), or it would
        block on the empty queue forever."""
        saw_sentinel = False
        while True:
            try:
                item = self._resolve_q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                saw_sentinel = True
                continue
            subs = item[0]
            for _, _, items, _ in subs:
                _fail_waiters(items, err)
        if saw_sentinel:
            self._resolve_q.put(None)

    def close(self) -> None:
        """Shut down, draining the bounded queue deadline-aware: the
        dispatch thread flushes the backlog through ``_flush`` (which
        sheds expired work) before exiting; if it is wedged, everything
        still queued is answered with a retriable shed status instead of
        being abandoned behind a fixed join timeout."""
        with self._cond:
            self._running = False
            self._cond.notify()
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # Dispatch thread wedged (e.g. blocked on a full resolve queue
            # with a dead resolver): don't hang close() — but don't leave
            # queued waiters hanging forever either; answer everything
            # still pending so callers awaiting wrap_future() return and
            # know to retry elsewhere.
            with self._cond:
                stuck = self._queue.drain()
                self._pending_count = 0
            for item in stuck:
                self._shed_item(item, "shutdown")
            self._drain_resolve_q(
                RuntimeError("tick loop shut down with requests pending"))
            return
        self._resolver.join(timeout=5)
        if self._resolver.is_alive():
            # Resolver wedged (e.g. a D2H that never completes): windows
            # already submitted for resolution would leave their callers
            # awaiting wrap_future forever — fail whatever is still queued.
            self._drain_resolve_q(
                RuntimeError("tick loop shut down with requests pending")
            )
