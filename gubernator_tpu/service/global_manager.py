"""GLOBAL-behavior reconciliation: async hit forwarding + owner broadcasts.

The eventually-consistent half of the system (reference ``global.go``):

* **Hits loop** — non-owner peers answer GLOBAL limits from local state and
  queue the observed hits here; hits aggregate per key (sum ``hits``, OR in
  RESET_REMAINING, ``global.go:99-112``) and flush to the owning peers when
  ``global_batch_limit`` distinct keys accumulate or ``global_sync_wait``
  elapses, grouped per owner, fan-out bounded by
  ``global_peer_requests_concurrency`` (``global.go:144-187``).
* **Broadcast loop** — the owner queues every GLOBAL state change; per
  flush it re-reads current state with ``hits=0`` (a pure query through the
  kernel, ``global.go:241-249``) and pushes authoritative
  :class:`GlobalUpdate` records to every other peer (``global.go:234-283``).

Both loops are asyncio tasks on the daemon's event loop; enqueueing is a
plain dict update (the event loop serializes access, playing the role of
the reference's channel).

Unlike the reference — which drops a failed flush on the floor — failed
sends and broadcasts merge back into a bounded redelivery buffer and
retry each sync window (docs/resilience.md), and both loops run under a
crash supervisor that restarts them instead of letting reconciliation
die silently.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.resilience import ResilienceConfig, spawn_supervised
from gubernator_tpu.utils import tracing
from gubernator_tpu.types import (
    Behavior,
    GlobalUpdate,
    RateLimitRequest,
    has_behavior,
    set_behavior,
)

log = logging.getLogger("gubernator.global")


class GlobalManager:
    """Owns the two reconciliation loops for one V1Instance."""

    def __init__(self, instance, behaviors: BehaviorConfig, metrics=None,
                 resilience: Optional[ResilienceConfig] = None):
        self.instance = instance
        self.conf = behaviors
        self.metrics = metrics
        self.resilience = resilience or ResilienceConfig()
        self._hits: Dict[str, RateLimitRequest] = {}
        self._updates: Dict[str, RateLimitRequest] = {}
        # Inter-region federation feed (docs/federation.md): installed by
        # V1Instance when GUBER_FEDERATION_ENABLED; every owner-side
        # update queued here also feeds the per-region envelope buffers.
        self.federation = None
        # GLOBAL keys this node has answered as owner, key → prototype
        # request (algorithm/limit/duration — what a state re-read
        # needs).  The ownership-handoff working set: after a ring swap,
        # keys here whose new owner is a different peer get their
        # accumulated state pushed to that peer (transfer_ownership).
        # Bounded by the redelivery cap like the other buffers.
        self._owned: Dict[str, RateLimitRequest] = {}
        self._hits_kick = asyncio.Event()
        self._updates_kick = asyncio.Event()
        self._running = True
        # Supervised: a crashed loop logs, counts a restart, and comes
        # back — a silently dead hits loop would stop reconciliation
        # forever while requests keep answering from stale local state.
        self._tasks = [
            spawn_supervised(
                self._hits_loop, name="global-hits",
                should_restart=lambda: self._running,
                metrics=metrics, loop_label="global_hits",
            ),
            spawn_supervised(
                self._broadcast_loop, name="global-broadcast",
                should_restart=lambda: self._running,
                metrics=metrics, loop_label="global_broadcast",
            ),
        ]

    # ------------------------------------------------------------------
    # Enqueue (called from request handlers on the event loop)
    # ------------------------------------------------------------------
    def queue_hit(self, req: RateLimitRequest) -> None:
        """Record a non-owner hit for async forwarding (global.go:74-78);
        zero-hit queries are not forwarded."""
        if req.hits == 0:
            return
        prev = self._hits.get(req.hash_key())
        if prev is not None:
            if has_behavior(req.behavior, Behavior.RESET_REMAINING):
                prev.behavior = set_behavior(
                    prev.behavior, Behavior.RESET_REMAINING, True
                )
            prev.hits += req.hits
        else:
            clone = RateLimitRequest(**vars(req))
            # The caller was already answered locally — no one is waiting
            # on this flush.  A propagated admission budget must not ride
            # the queued copy: an owner outage longer than the budget
            # would otherwise make every redelivery raise BudgetExhausted
            # before the RPC, and the buffered hits could never land.
            clone.deadline = None
            self._hits[req.hash_key()] = clone
        if self.metrics is not None:
            self.metrics.global_send_queue_length.set(len(self._hits))
        self._hits_kick.set()

    def queue_update(self, req: RateLimitRequest) -> None:
        """Record an owner-side state change for broadcast (global.go:80-84)."""
        if req.hits == 0:
            return
        if self.federation is not None:
            # This is the one funnel every owner-side GLOBAL hit in the
            # region passes exactly once — the right tap for the
            # inter-region delta stream (queue() itself skips requests
            # applied FROM a peer region).
            self.federation.queue(req)
        key = req.hash_key()
        # Broadcast and ring-handoff redelivery are post-answer background
        # work: a serving-path admission deadline must not ride the stored
        # copy (queue_hit's rule, now enforced package-wide by G010) — an
        # owner outage longer than the budget would otherwise expire every
        # redelivery before its RPC and the state change could never land.
        clone = RateLimitRequest(**vars(req))
        clone.deadline = None
        self._updates[key] = clone
        if key in self._owned or len(self._owned) < self.resilience.redelivery_limit:
            self._owned[key] = clone
        else:
            # Tracker full (GUBER_REDELIVERY_LIMIT): this key's state will
            # NOT ride a ring-swap handoff.  Never silent — at reshard
            # scale a quietly lossy tracker re-creates the bug the
            # handoff machinery exists to prevent.
            if self.metrics is not None:
                self.metrics.ownership_transfers.labels(
                    result="untracked").inc()
            log.warning(
                "ownership tracker full (%d keys, GUBER_REDELIVERY_LIMIT"
                "=%d): %r will not be handed off on a ring change",
                len(self._owned), self.resilience.redelivery_limit, key,
            )
        if self.metrics is not None:
            self.metrics.global_queue_length.set(len(self._updates))
        self._updates_kick.set()

    # ------------------------------------------------------------------
    # Loops
    # ------------------------------------------------------------------
    async def _window(self, kick: asyncio.Event, queue: Dict) -> None:
        """Wait for the first queued item, then let the window fill until
        the sync interval elapses or the batch limit is reached."""
        await kick.wait()
        deadline = asyncio.get_running_loop().time() + self.conf.global_sync_wait
        while len(queue) < self.conf.global_batch_limit:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                break
            kick.clear()
            try:
                await asyncio.wait_for(kick.wait(), remaining)
            except asyncio.TimeoutError:
                break
        kick.clear()

    async def _hits_loop(self) -> None:
        while self._running:
            await self._window(self._hits_kick, self._hits)
            hits, self._hits = self._hits, {}
            # Gauge from actual dict size, not a hardcoded 0: enqueues
            # racing the swap (and requeues during the flush below) must
            # stay visible.
            if self.metrics is not None:
                self.metrics.global_send_queue_length.set(len(self._hits))
            if hits:
                await self._send_hits(list(hits.values()))
                if self.metrics is not None:
                    self.metrics.global_send_queue_length.set(len(self._hits))

    async def _broadcast_loop(self) -> None:
        while self._running:
            await self._window(self._updates_kick, self._updates)
            updates, self._updates = self._updates, {}
            if self.metrics is not None:
                self.metrics.global_queue_length.set(len(self._updates))
            if updates:
                await self._broadcast(list(updates.values()))
                if self.metrics is not None:
                    self.metrics.global_queue_length.set(len(self._updates))

    async def _send_hits(self, hits: List[RateLimitRequest]) -> None:
        """Group accumulated hits per owning peer and forward
        (global.go:144-187).  Span parity: global.go:91 sendHits scope."""
        t0 = time.perf_counter()
        with tracing.maybe_span("GlobalManager.sendHits", {"count": len(hits)},
                                root=True):
            await self._send_hits_traced(hits)
        if self.metrics is not None:
            self.metrics.global_send_duration.observe(time.perf_counter() - t0)

    async def _send_hits_traced(self, hits: List[RateLimitRequest]) -> None:
        by_owner: Dict[str, tuple] = {}
        local: List[RateLimitRequest] = []
        for r in hits:
            try:
                peer = self.instance.get_peer(r.hash_key())
            except Exception:
                continue
            if peer is None or peer.info.is_owner:
                # Ownership moved to this node between queueing and flush
                # (or we're standalone): the hits must still land — the
                # reference forwards to whatever GetPeer resolves
                # (global.go:153-168), which here is our own peer handler.
                local.append(r)
                continue
            addr = peer.info.grpc_address
            if addr in by_owner:
                by_owner[addr][1].append(r)
            else:
                by_owner[addr] = (peer, [r])
        sem = asyncio.Semaphore(self.conf.global_peer_requests_concurrency)
        limit = self.conf.global_batch_limit

        async def send(peer, reqs):
            # Chunk per RPC: queue_hit can outrun the flush window, and the
            # owner rejects batches over MAX_BATCH_SIZE.
            for i in range(0, len(reqs), limit):
                async with sem:
                    chunk = reqs[i : i + limit]
                    try:
                        await peer.get_peer_rate_limits(chunk)
                    except Exception:
                        # Peer records the error for HealthCheck; the hits
                        # must not vanish — merge them back into the
                        # (bounded) redelivery buffer for the next window.
                        self._requeue_hits(chunk)

        async def apply_self(reqs):
            # Same handler an owner applies to relayed batches: forces
            # DRAIN_OVER_LIMIT on GLOBAL hits and queues the broadcast.
            for i in range(0, len(reqs), limit):
                chunk = reqs[i : i + limit]
                try:
                    await self.instance.get_peer_rate_limits(chunk)
                except Exception:
                    self._requeue_hits(chunk)

        await asyncio.gather(
            *(send(p, reqs) for p, reqs in by_owner.values()),
            *((apply_self(local),) if local else ()),
        )

    def _requeue_hits(self, reqs: List[RateLimitRequest]) -> None:
        """Merge a failed flush chunk back into the hits buffer (the same
        per-key aggregation queue_hit applies), bounded by the redelivery
        cap: beyond it records drop and are counted — memory stays
        bounded even against a peer that never recovers."""
        limit = self.resilience.redelivery_limit
        redelivered = dropped = 0
        for r in reqs:
            k = r.hash_key()
            prev = self._hits.get(k)
            if prev is not None:
                if has_behavior(r.behavior, Behavior.RESET_REMAINING):
                    prev.behavior = set_behavior(
                        prev.behavior, Behavior.RESET_REMAINING, True
                    )
                prev.hits += r.hits
                redelivered += 1
            elif len(self._hits) < limit:
                self._hits[k] = r
                redelivered += 1
            else:
                dropped += 1
        if self.metrics is not None:
            if redelivered:
                self.metrics.global_redelivered_hits.inc(redelivered)
            if dropped:
                self.metrics.global_dropped_hits.inc(dropped)
            self.metrics.global_send_queue_length.set(len(self._hits))
        if dropped:
            log.warning(
                "GLOBAL redelivery buffer full (%d keys): dropped %d hit "
                "records", len(self._hits), dropped,
            )
        if redelivered:
            self._hits_kick.set()  # retry next sync window

    async def _broadcast(self, updates: List[RateLimitRequest]) -> None:
        """Re-read current state (hits=0 query) and push it to every other
        peer (global.go:234-283).  Span parity: global.go:193
        broadcastPeers scope."""
        t0 = time.perf_counter()
        with tracing.maybe_span("GlobalManager.broadcastPeers",
                                {"count": len(updates)}, root=True):
            await self._broadcast_traced(updates)
        if self.metrics is not None:
            self.metrics.broadcast_duration.observe(time.perf_counter() - t0)

    async def _broadcast_traced(self, updates: List[RateLimitRequest]) -> None:
        queries = []
        for u in updates:
            q = RateLimitRequest(**vars(u))
            q.hits = 0
            queries.append(q)
        statuses = await self.instance.apply_local(queries)
        globals_: List[GlobalUpdate] = []
        for u, st in zip(updates, statuses):
            if st.error:
                continue
            globals_.append(
                GlobalUpdate(
                    key=u.hash_key(),
                    status=st,
                    algorithm=u.algorithm,
                    duration=u.duration,
                    created_at=u.created_at or 0,
                )
            )
        if not globals_:
            return
        sem = asyncio.Semaphore(self.conf.global_peer_requests_concurrency)
        limit = self.conf.global_batch_limit
        by_key = {u.hash_key(): u for u in updates}
        failed_keys: set = set()

        async def push(peer):
            for i in range(0, len(globals_), limit):
                async with sem:
                    chunk = globals_[i : i + limit]
                    try:
                        await peer.update_peer_globals(chunk)
                    except Exception:
                        # Requeue the source updates: the next flush
                        # re-reads current state and re-pushes to every
                        # peer (idempotent — authoritative state install).
                        failed_keys.update(g.key for g in chunk)

        peers = [
            p for p in self.instance.get_peer_list() if not p.info.is_owner
        ]
        await asyncio.gather(*(push(p) for p in peers))
        if failed_keys:
            self._requeue_updates(
                [by_key[k] for k in failed_keys if k in by_key]
            )

    def _requeue_updates(self, reqs: List[RateLimitRequest]) -> None:
        """Re-enqueue updates whose broadcast failed for some peer, bounded
        by the redelivery cap.  A key already queued again (newer state
        pending) needs nothing — the coming broadcast supersedes this one."""
        limit = self.resilience.redelivery_limit
        redelivered = dropped = 0
        for r in reqs:
            k = r.hash_key()
            if k in self._updates:
                continue
            if len(self._updates) >= limit:
                dropped += 1
                continue
            self._updates[k] = r
            redelivered += 1
        if self.metrics is not None:
            if redelivered:
                self.metrics.global_redelivered_broadcasts.inc(redelivered)
            if dropped:
                self.metrics.global_dropped_broadcasts.inc(dropped)
            self.metrics.global_queue_length.set(len(self._updates))
        if dropped:
            log.warning(
                "GLOBAL broadcast redelivery buffer full (%d keys): "
                "dropped %d update records", len(self._updates), dropped,
            )
        if redelivered:
            self._updates_kick.set()

    # ------------------------------------------------------------------
    # Ownership handoff (ring churn) and graceful drain
    # ------------------------------------------------------------------
    async def transfer_ownership(self) -> int:
        """Push accumulated GLOBAL state to new owners after a ring swap.

        For every tracked owned key whose ``get_peer`` now resolves to a
        *different* peer: re-read current local state (hits=0 query, the
        broadcast's authoritative-read pattern) and install it on the new
        owner via ``UpdatePeerGlobals`` — the key keeps counting from its
        accumulated level instead of resetting (the process-scope twin of
        the tiering fresh-bucket fix).

        Region scoping (docs/federation.md): candidate owners resolve
        through ``get_peer`` — the *local* picker, which ``set_peers``
        builds only from this datacenter's members — never the
        RegionPicker.  GLOBAL state must not be pushed cross-datacenter
        here: remote regions converge through the federation envelope
        stream (bounded staleness, loop-tagged), and a raw
        UpdatePeerGlobals install over the WAN would bypass that
        discipline and double-apply on the next envelope.  A failed push re-enqueues the
        source update into the bounded broadcast redelivery buffer, whose
        next flush re-reads state and pushes to every peer — a slow new
        owner delays the transfer, never loses it.  Returns the number of
        keys pushed."""
        moved: List[tuple] = []  # (key, proto)
        for key in list(self._owned):
            try:
                peer = self.instance.get_peer(key)
            except Exception:
                continue
            if peer is None or peer.info.is_owner:
                continue  # still ours (or standalone)
            moved.append((key, self._owned.pop(key)))
        if not moved:
            return 0
        queries = []
        for _, proto in moved:
            q = RateLimitRequest(**vars(proto))
            q.hits = 0
            q.behavior = set_behavior(q.behavior, Behavior.GLOBAL, False)
            queries.append(q)
        statuses = await self.instance.apply_local(queries)
        by_peer: Dict[str, tuple] = {}
        for (key, proto), st in zip(moved, statuses):
            if st.error:
                continue
            # A bucket answering UNDER with full remaining carries no
            # accumulated state worth shipping — but shipping it is
            # harmless (idempotent install), so no filtering beyond
            # errors: simpler and covers RESET_REMAINING edge states.
            peer = self.instance.get_peer(key)
            if peer is None or peer.info.is_owner:
                continue  # ring moved again mid-read; next swap retries
            upd = GlobalUpdate(
                key=key,
                status=st,
                algorithm=proto.algorithm,
                duration=proto.duration,
                created_at=proto.created_at or 0,
            )
            by_peer.setdefault(
                peer.info.grpc_address, (peer, [], [])
            )
            by_peer[peer.info.grpc_address][1].append(upd)
            by_peer[peer.info.grpc_address][2].append(proto)
        pushed = 0
        limit = self.conf.global_batch_limit

        async def push(peer, updates, protos):
            nonlocal pushed
            for i in range(0, len(updates), limit):
                chunk = updates[i : i + limit]
                try:
                    await peer.update_peer_globals(chunk)
                except Exception:
                    # The new owner is slow/unreachable: the transfer
                    # rides the broadcast redelivery buffer instead of
                    # vanishing — its next flush re-reads and re-pushes.
                    if self.metrics is not None:
                        self.metrics.ownership_transfers.labels(
                            result="requeued"
                        ).inc(len(chunk))
                    self._requeue_updates(protos[i : i + limit])
                    continue
                pushed += len(chunk)
                if self.metrics is not None:
                    self.metrics.ownership_transfers.labels(
                        result="pushed"
                    ).inc(len(chunk))

        await asyncio.gather(
            *(push(p, u, pr) for p, u, pr in by_peer.values())
        )
        if pushed:
            log.info("ring change: transferred %d GLOBAL keys to new "
                     "owners", pushed)
        return pushed

    async def _final_flush(self) -> None:
        """Drain everything still buffered — pending hits, pending/
        redelivery updates — through the normal flush paths.  Failed
        chunks requeue themselves; a few bounded rounds give flapping
        peers a second chance while the caller's deadline caps the total
        (a permanently dead peer exhausts the rounds, not the process)."""
        for _ in range(4):
            if not (self._hits or self._updates):
                return
            hits, self._hits = self._hits, {}
            updates, self._updates = self._updates, {}
            if hits:
                await self._send_hits(list(hits.values()))
            if updates:
                await self._broadcast(list(updates.values()))
            if (len(self._hits) >= len(hits)
                    and len(self._updates) >= len(updates)):
                return  # everything requeued: peers are gone, stop early

    async def close(self, drain_timeout: float = 0.0) -> None:
        """Stop the loops, then (graceful-drain path) flush the GLOBAL
        hit/broadcast/redelivery buffers under a bounded deadline so the
        accounting lands on the owners instead of dying with the process
        — but a dead peer can never wedge shutdown past the budget."""
        self._running = False
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if drain_timeout > 0 and (self._hits or self._updates):
            try:
                await asyncio.wait_for(self._final_flush(), drain_timeout)
            except asyncio.TimeoutError:
                log.warning(
                    "graceful drain deadline (%.1fs) expired with %d hits"
                    " / %d updates unflushed",
                    drain_timeout, len(self._hits), len(self._updates),
                )
            except Exception:
                log.exception("graceful drain flush failed")
