"""GLOBAL-behavior reconciliation: async hit forwarding + owner broadcasts.

The eventually-consistent half of the system (reference ``global.go``):

* **Hits loop** — non-owner peers answer GLOBAL limits from local state and
  queue the observed hits here; hits aggregate per key (sum ``hits``, OR in
  RESET_REMAINING, ``global.go:99-112``) and flush to the owning peers when
  ``global_batch_limit`` distinct keys accumulate or ``global_sync_wait``
  elapses, grouped per owner, fan-out bounded by
  ``global_peer_requests_concurrency`` (``global.go:144-187``).
* **Broadcast loop** — the owner queues every GLOBAL state change; per
  flush it re-reads current state with ``hits=0`` (a pure query through the
  kernel, ``global.go:241-249``) and pushes authoritative
  :class:`GlobalUpdate` records to every other peer (``global.go:234-283``).

Both loops are asyncio tasks on the daemon's event loop; enqueueing is a
plain dict update (the event loop serializes access, playing the role of
the reference's channel).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.utils import tracing
from gubernator_tpu.types import (
    Behavior,
    GlobalUpdate,
    RateLimitRequest,
    has_behavior,
    set_behavior,
)


class GlobalManager:
    """Owns the two reconciliation loops for one V1Instance."""

    def __init__(self, instance, behaviors: BehaviorConfig, metrics=None):
        self.instance = instance
        self.conf = behaviors
        self.metrics = metrics
        self._hits: Dict[str, RateLimitRequest] = {}
        self._updates: Dict[str, RateLimitRequest] = {}
        self._hits_kick = asyncio.Event()
        self._updates_kick = asyncio.Event()
        self._running = True
        self._tasks = [
            asyncio.create_task(self._hits_loop(), name="global-hits"),
            asyncio.create_task(self._broadcast_loop(), name="global-broadcast"),
        ]

    # ------------------------------------------------------------------
    # Enqueue (called from request handlers on the event loop)
    # ------------------------------------------------------------------
    def queue_hit(self, req: RateLimitRequest) -> None:
        """Record a non-owner hit for async forwarding (global.go:74-78);
        zero-hit queries are not forwarded."""
        if req.hits == 0:
            return
        prev = self._hits.get(req.hash_key())
        if prev is not None:
            if has_behavior(req.behavior, Behavior.RESET_REMAINING):
                prev.behavior = set_behavior(
                    prev.behavior, Behavior.RESET_REMAINING, True
                )
            prev.hits += req.hits
        else:
            self._hits[req.hash_key()] = RateLimitRequest(**vars(req))
        if self.metrics is not None:
            self.metrics.global_send_queue_length.set(len(self._hits))
        self._hits_kick.set()

    def queue_update(self, req: RateLimitRequest) -> None:
        """Record an owner-side state change for broadcast (global.go:80-84)."""
        if req.hits == 0:
            return
        self._updates[req.hash_key()] = req
        if self.metrics is not None:
            self.metrics.global_queue_length.set(len(self._updates))
        self._updates_kick.set()

    # ------------------------------------------------------------------
    # Loops
    # ------------------------------------------------------------------
    async def _window(self, kick: asyncio.Event, queue: Dict) -> None:
        """Wait for the first queued item, then let the window fill until
        the sync interval elapses or the batch limit is reached."""
        await kick.wait()
        deadline = asyncio.get_running_loop().time() + self.conf.global_sync_wait
        while len(queue) < self.conf.global_batch_limit:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                break
            kick.clear()
            try:
                await asyncio.wait_for(kick.wait(), remaining)
            except asyncio.TimeoutError:
                break
        kick.clear()

    async def _hits_loop(self) -> None:
        while self._running:
            await self._window(self._hits_kick, self._hits)
            hits, self._hits = self._hits, {}
            if self.metrics is not None:
                self.metrics.global_send_queue_length.set(0)
            if hits:
                await self._send_hits(list(hits.values()))

    async def _broadcast_loop(self) -> None:
        while self._running:
            await self._window(self._updates_kick, self._updates)
            updates, self._updates = self._updates, {}
            if self.metrics is not None:
                self.metrics.global_queue_length.set(0)
            if updates:
                await self._broadcast(list(updates.values()))

    async def _send_hits(self, hits: List[RateLimitRequest]) -> None:
        """Group accumulated hits per owning peer and forward
        (global.go:144-187).  Span parity: global.go:91 sendHits scope."""
        t0 = time.perf_counter()
        with tracing.maybe_span("GlobalManager.sendHits", {"count": len(hits)},
                                root=True):
            await self._send_hits_traced(hits)
        if self.metrics is not None:
            self.metrics.global_send_duration.observe(time.perf_counter() - t0)

    async def _send_hits_traced(self, hits: List[RateLimitRequest]) -> None:
        by_owner: Dict[str, tuple] = {}
        local: List[RateLimitRequest] = []
        for r in hits:
            try:
                peer = self.instance.get_peer(r.hash_key())
            except Exception:
                continue
            if peer is None or peer.info.is_owner:
                # Ownership moved to this node between queueing and flush
                # (or we're standalone): the hits must still land — the
                # reference forwards to whatever GetPeer resolves
                # (global.go:153-168), which here is our own peer handler.
                local.append(r)
                continue
            addr = peer.info.grpc_address
            if addr in by_owner:
                by_owner[addr][1].append(r)
            else:
                by_owner[addr] = (peer, [r])
        sem = asyncio.Semaphore(self.conf.global_peer_requests_concurrency)
        limit = self.conf.global_batch_limit

        async def send(peer, reqs):
            # Chunk per RPC: queue_hit can outrun the flush window, and the
            # owner rejects batches over MAX_BATCH_SIZE.
            for i in range(0, len(reqs), limit):
                async with sem:
                    try:
                        await peer.get_peer_rate_limits(reqs[i : i + limit])
                    except Exception:
                        pass  # peer records the error for HealthCheck

        async def apply_self(reqs):
            # Same handler an owner applies to relayed batches: forces
            # DRAIN_OVER_LIMIT on GLOBAL hits and queues the broadcast.
            for i in range(0, len(reqs), limit):
                try:
                    await self.instance.get_peer_rate_limits(reqs[i : i + limit])
                except Exception:
                    pass

        await asyncio.gather(
            *(send(p, reqs) for p, reqs in by_owner.values()),
            *((apply_self(local),) if local else ()),
        )

    async def _broadcast(self, updates: List[RateLimitRequest]) -> None:
        """Re-read current state (hits=0 query) and push it to every other
        peer (global.go:234-283).  Span parity: global.go:193
        broadcastPeers scope."""
        t0 = time.perf_counter()
        with tracing.maybe_span("GlobalManager.broadcastPeers",
                                {"count": len(updates)}, root=True):
            await self._broadcast_traced(updates)
        if self.metrics is not None:
            self.metrics.broadcast_duration.observe(time.perf_counter() - t0)

    async def _broadcast_traced(self, updates: List[RateLimitRequest]) -> None:
        queries = []
        for u in updates:
            q = RateLimitRequest(**vars(u))
            q.hits = 0
            queries.append(q)
        statuses = await self.instance.apply_local(queries)
        globals_: List[GlobalUpdate] = []
        for u, st in zip(updates, statuses):
            if st.error:
                continue
            globals_.append(
                GlobalUpdate(
                    key=u.hash_key(),
                    status=st,
                    algorithm=u.algorithm,
                    duration=u.duration,
                    created_at=u.created_at or 0,
                )
            )
        if not globals_:
            return
        sem = asyncio.Semaphore(self.conf.global_peer_requests_concurrency)
        limit = self.conf.global_batch_limit

        async def push(peer):
            for i in range(0, len(globals_), limit):
                async with sem:
                    try:
                        await peer.update_peer_globals(globals_[i : i + limit])
                    except Exception:
                        pass

        peers = [
            p for p in self.instance.get_peer_list() if not p.info.is_owner
        ]
        await asyncio.gather(*(push(p) for p in peers))

    async def close(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
