"""Service layer: tick loop, instance router, peer client, GLOBAL manager."""

from gubernator_tpu.service.instance import V1Instance, InstanceConfig  # noqa: F401
from gubernator_tpu.service.tickloop import TickLoop  # noqa: F401
