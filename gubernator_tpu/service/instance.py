"""V1Instance: the request router (ownership decision + 3-way dispatch).

The service brain (reference ``gubernator.go:183-295``): for every item in
a GetRateLimits batch decide — local (we own the key), GLOBAL (answer from
local state, reconcile async), or forward (batched RPC to the owning peer,
≤5 retries with ownership re-resolution, ``gubernator.go:311-391``).

TPU-native deltas from the reference:

* All local work flows through the :class:`TickLoop` — one device tick per
  batch window instead of per-key worker dispatch.  Local items in one call
  are submitted *together*.
* A standalone instance (``set_peers`` never called) treats every key as
  local, so a single-node service needs no cluster bootstrap.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import grpc

from gubernator_tpu.admission import (
    CLASS_CLIENT,
    CLASS_PEER,
    BudgetExhaustedError,
    batch_deadline,
)
from gubernator_tpu.config import BehaviorConfig, Config
from gubernator_tpu.resilience import (
    BreakerOpenError,
    DecorrelatedJitterBackoff,
    ResilienceConfig,
)
from gubernator_tpu.parallel.hashring import (
    HASH_FUNCTIONS,
    RegionPicker,
    ReplicatedConsistentHash,
)
from gubernator_tpu.service.global_manager import GlobalManager
from gubernator_tpu.service.peer_client import PeerClient
from gubernator_tpu.service.tickloop import TickLoop
import numpy as np

from gubernator_tpu.algos import algorithm_error, invalid_algorithm_mask
from gubernator_tpu.types import (
    ALGORITHM_MAX,
    MAX_BATCH_SIZE,
    Algorithm,
    Behavior,
    GlobalUpdate,
    HealthCheckResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    Status,
    has_behavior,
    set_behavior,
)
from gubernator_tpu.utils import timeutil, tracing
from gubernator_tpu.utils.metrics import Metrics

log = logging.getLogger("gubernator.instance")


class BatchTooLargeError(ValueError):
    """Maps to gRPC OutOfRange at the transport edge (gubernator.go:189-193)."""


@dataclass
class InstanceConfig:
    """Wiring for one V1Instance (reference Config, config.go:73-123)."""

    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    cache_size: int = 50_000
    data_center: str = ""
    advertise_address: str = ""          # this node's own grpc address
    picker_hash: str = "fnv1"
    replicas: int = 512
    tpu_max_batch: int = 4096
    tpu_mesh_shards: int = 0             # 0 = single-chip engine
    mesh_routing: str = "auto"           # sharded key routing: auto/device
    mesh_local_width: int = 0            # DEPRECATED (ragged path; warns)
    tpu_platform: str = ""               # force jax platform ("cpu" for tests)
    tpu_table_layout: str = "auto"       # bucket-table storage (engine.py)
    tpu_bg_reclaim: str = "auto"         # background reclamation (engine.py)
    cold_cache_size: int = 0             # tiered cold store (docs/tiering.md)
    # SSD third tier (docs/tiering.md): slab directory (empty = off),
    # byte budget, compaction threshold, writer queue depth.
    ssd_dir: str = ""
    ssd_capacity_bytes: int = 1 << 30
    ssd_compact_ratio: float = 0.5
    ssd_queue_depth: int = 8
    # Crash-safe persistence (docs/persistence.md): snapshot directory
    # (empty = off), delta-flush cadence, compaction threshold, and the
    # graceful-drain budget for GlobalManager.close.
    snapshot_dir: str = ""
    snapshot_interval: float = 5.0
    snapshot_deltas_per_base: int = 64
    drain_timeout: float = 2.0
    # Elastic live resharding (docs/resharding.md): quiesce budget
    # before the cutover aborts, and the post-cutover table audit.
    reshard_freeze_timeout: float = 5.0
    reshard_verify: bool = True
    # GLOBAL collectives data plane (parallel/global_mesh.py): a shared
    # MeshGlobalEngine (mesh-resident peers) + this node's index on it.
    # When set, GLOBAL requests bypass the gRPC hits/broadcast loops.
    global_mesh: Optional[object] = None
    global_mesh_node: int = 0
    tpu_global_mesh_nodes: int = 0       # >0: build own engine at startup
    tpu_global_mesh_node: int = -1       # -1 = auto (jax.process_index())
    tpu_global_mesh_capacity: int = 1 << 16
    loader: Optional[object] = None
    store: Optional[object] = None
    metrics: Optional[Metrics] = None
    peer_credentials: Optional[grpc.ChannelCredentials] = None
    # Fault-tolerant peer path (docs/resilience.md): breaker/backoff/
    # redelivery knobs, plus the optional chaos-test fault injector the
    # peer clients consult before every RPC.
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    fault_injector: Optional[object] = None
    # Multi-region GLOBAL federation (docs/federation.md): inter-region
    # bounded-staleness envelope exchange over the breaker path.  Off by
    # default; requires data_center (setup_daemon_config enforces it).
    federation_enabled: bool = False
    federation_interval: float = 1.0
    federation_batch_limit: int = 1000
    federation_timeout: float = 1.0
    # Guardrailed shard autoscaler (docs/autoscaling.md): closes the
    # telemetry → reshard loop.  Off by default; dry-run by default
    # when on (decisions recorded, nothing actuated).
    autoscale_enabled: bool = False
    autoscale_interval: float = 10.0
    autoscale_windows: int = 3
    autoscale_target_p99_ms: float = 5.0
    autoscale_queue_high: int = 1000
    autoscale_hysteresis: float = 0.5
    autoscale_occupancy_low: float = 0.3
    autoscale_min_shards: int = 1
    autoscale_max_shards: int = 8
    autoscale_cooldown_up: float = 60.0
    autoscale_cooldown_down: float = 300.0
    autoscale_max_per_hour: int = 4
    autoscale_dry_run: bool = True

    @classmethod
    def from_config(cls, conf: Config, advertise_address: str = "", **kw):
        return cls(
            behaviors=conf.behaviors,
            resilience=conf.resilience,
            fault_injector=conf.fault_injector,
            cache_size=conf.cache_size,
            data_center=conf.data_center,
            advertise_address=advertise_address,
            picker_hash=conf.local_picker_hash,
            replicas=conf.replicas,
            tpu_max_batch=conf.tpu_max_batch,
            tpu_mesh_shards=conf.tpu_mesh_shards,
            mesh_routing=conf.mesh_routing,
            mesh_local_width=conf.mesh_local_width,
            tpu_platform=conf.tpu_platform,
            tpu_table_layout=conf.tpu_table_layout,
            tpu_bg_reclaim=conf.tpu_bg_reclaim,
            cold_cache_size=conf.cold_cache_size,
            ssd_dir=conf.ssd_dir,
            ssd_capacity_bytes=conf.ssd_capacity_bytes,
            ssd_compact_ratio=conf.ssd_compact_ratio,
            ssd_queue_depth=conf.ssd_queue_depth,
            snapshot_dir=conf.snapshot_dir,
            snapshot_interval=conf.snapshot_interval,
            snapshot_deltas_per_base=conf.snapshot_deltas_per_base,
            drain_timeout=conf.drain_timeout,
            reshard_freeze_timeout=conf.reshard_freeze_timeout,
            reshard_verify=conf.reshard_verify,
            tpu_global_mesh_nodes=conf.tpu_global_mesh_nodes,
            tpu_global_mesh_node=conf.tpu_global_mesh_node,
            tpu_global_mesh_capacity=conf.tpu_global_mesh_capacity,
            loader=conf.loader,
            store=conf.store,
            federation_enabled=conf.federation_enabled,
            federation_interval=conf.federation_interval,
            federation_batch_limit=conf.federation_batch_limit,
            federation_timeout=conf.federation_timeout,
            autoscale_enabled=conf.autoscale_enabled,
            autoscale_interval=conf.autoscale_interval,
            autoscale_windows=conf.autoscale_windows,
            autoscale_target_p99_ms=conf.autoscale_target_p99_ms,
            autoscale_queue_high=conf.autoscale_queue_high,
            autoscale_hysteresis=conf.autoscale_hysteresis,
            autoscale_occupancy_low=conf.autoscale_occupancy_low,
            autoscale_min_shards=conf.autoscale_min_shards,
            autoscale_max_shards=conf.autoscale_max_shards,
            autoscale_cooldown_up=conf.autoscale_cooldown_up,
            autoscale_cooldown_down=conf.autoscale_cooldown_down,
            autoscale_max_per_hour=conf.autoscale_max_per_hour,
            autoscale_dry_run=conf.autoscale_dry_run,
            **kw,
        )


def _make_engine(conf: InstanceConfig):
    import gubernator_tpu.jaxinit  # noqa: F401  (x64 + cache before jax use)
    import jax

    if conf.tpu_platform:
        # GUBER_TPU_PLATFORM: pin the jax platform before any device use
        # (e.g. "cpu" for tests/CI hosts without a TPU).
        jax.config.update("jax_platforms", conf.tpu_platform)
    if conf.tpu_mesh_shards > 1:
        from gubernator_tpu.parallel.mesh_engine import MeshTickEngine, make_mesh

        if conf.cold_cache_size:
            log.warning(
                "GUBER_COLD_CACHE_SIZE is not supported by the sharded "
                "mesh engine yet; tiering disabled"
            )
        if conf.ssd_dir:
            # Hard error (setup_daemon_config rejects this combination
            # too): a silently absent third tier is a robustness trap —
            # the operator sized the deployment around capacity the
            # engine never had.
            raise ValueError(
                "GUBER_SSD_DIR is not supported by the sharded mesh "
                "engine (GUBER_TPU_MESH_SHARDS > 1): the SSD tier "
                "hangs off the single-chip cold store; unset one"
            )
        devices = jax.devices()[: conf.tpu_mesh_shards]
        local_cap = max(1, conf.cache_size // len(devices))
        return MeshTickEngine(
            mesh=make_mesh(devices),
            local_capacity=local_cap,
            max_batch=conf.tpu_max_batch,
            store=conf.store,
            table_layout=conf.tpu_table_layout,
            routing=conf.mesh_routing,
            local_width=conf.mesh_local_width,
        )
    from gubernator_tpu.ops.engine import TickEngine

    bg = {"auto": None, "on": True, "off": False}[conf.tpu_bg_reclaim]
    ssd = None
    if conf.ssd_dir and conf.cold_cache_size > 0:
        from gubernator_tpu.tiering import SsdStore

        ssd = SsdStore(
            conf.ssd_dir,
            capacity_bytes=conf.ssd_capacity_bytes,
            compact_ratio=conf.ssd_compact_ratio,
            queue_depth=conf.ssd_queue_depth,
            metrics=conf.metrics,
        )
    return TickEngine(
        capacity=conf.cache_size,
        max_batch=conf.tpu_max_batch,
        store=conf.store,
        table_layout=conf.tpu_table_layout,
        bg_reclaim=bg,
        cold_capacity=conf.cold_cache_size,
        ssd=ssd,
    )


class V1Instance:
    """One service instance: engine + tick loop + pickers + GLOBAL manager.

    Create inside a running event loop (the GLOBAL manager starts its asyncio
    tasks immediately, like the reference's ``NewV1Instance`` spawning its
    loops, gubernator.go:115-148) — or via :meth:`create` which also runs the
    Loader restore.
    """

    def __init__(self, conf: InstanceConfig, engine=None):
        self.conf = conf
        self.log = log
        self.metrics = conf.metrics or Metrics()
        self.engine = engine if engine is not None else _make_engine(conf)
        # The window fills to the DEVICE program width by default, not
        # the peer-protocol BatchLimit: the device tick amortizes best
        # when several callers' batches coalesce into one program
        # invocation (the reference's worker pool has no analogous cap —
        # it drains whatever queued, workers.go:125-147).  An operator
        # who explicitly set GUBER_BATCH_LIMIT — even to the reference
        # default of 1000 — caps the window with it.
        window_limit = (
            conf.behaviors.batch_limit
            if conf.behaviors.batch_limit_set
            else conf.tpu_max_batch
        )
        self.tick_loop = TickLoop(
            self.engine,
            batch_wait=conf.behaviors.batch_wait,
            batch_limit=window_limit,
            metrics=self.metrics,
        )
        # Zero-copy ingest (docs/tpu-performance.md): the transport's
        # wire→columns decode lands in these preallocated slabs instead
        # of fresh per-batch allocations; the tick loop releases each
        # slab once the engine has packed it.  Sized to the public API
        # batch cap; slab count covers the tick pipeline depth plus
        # decode concurrency (GUBER_INGEST_ARENA_SLABS, 0 = off).
        from gubernator_tpu.config import env_knob
        from gubernator_tpu.ops.reqcols import ColumnArena

        try:
            slabs = env_knob("GUBER_INGEST_ARENA_SLABS", 8, parse=int)
        except ValueError:
            slabs = 8
        try:
            fallback_limit = env_knob(
                "GUBER_INGEST_FALLBACK_LIMIT", 32, parse=int)
        except ValueError:
            fallback_limit = 32
        self.ingest_arena = (
            ColumnArena(MAX_BATCH_SIZE, slabs=slabs,
                        fallback_limit=fallback_limit)
            if slabs > 0 else None
        )
        # Multi-process streaming edge (docs/edge.md): attached by the
        # daemon when GUBER_EDGE_WORKERS > 0; closed before the tick
        # loop so in-flight shm windows resolve while it still runs.
        self.edge_plane = None
        hash_fn = HASH_FUNCTIONS[conf.picker_hash]
        self._standalone = True  # no peers installed yet; see set_peers
        self.local_picker: ReplicatedConsistentHash[PeerClient] = (
            ReplicatedConsistentHash(hash_fn, conf.replicas)
        )
        self.region_picker: RegionPicker[PeerClient] = RegionPicker(
            hash_fn, conf.replicas
        )
        self.global_mgr = GlobalManager(
            self, conf.behaviors, self.metrics, resilience=conf.resilience
        )
        # Inter-region federation (docs/federation.md): constructed only
        # when GUBER_FEDERATION_ENABLED is set AND this node knows its
        # own datacenter — the transport rejects FederationSync frames
        # (and MULTI_REGION items, _get_rate_limits) when None.  Wired
        # into the GlobalManager so every owner-side GLOBAL update feeds
        # the inter-region pending buffers.
        self.federation = None
        if conf.federation_enabled and conf.data_center:
            from gubernator_tpu.federation import FederationManager

            self.federation = FederationManager(self, metrics=self.metrics)
        self.global_mgr.federation = self.federation
        # GLOBAL collectives data plane: use the shared engine if provided,
        # else build one when GUBER_TPU_GLOBAL_MESH_NODES asks for it.
        self.global_mesh = conf.global_mesh
        if self.global_mesh is None and conf.tpu_global_mesh_nodes > 0:
            from gubernator_tpu.parallel.global_mesh import (
                MeshGlobalEngine,
                make_global_mesh,
            )

            self.global_mesh = MeshGlobalEngine(
                mesh=make_global_mesh(conf.tpu_global_mesh_nodes),
                capacity=conf.tpu_global_mesh_capacity,
                max_batch=conf.tpu_max_batch,
                min_reconcile_ms=int(conf.behaviors.global_sync_wait * 500),
            )
            if conf.global_mesh_node == 0 and conf.tpu_global_mesh_node != 0:
                # Env-configured mode: this node's identity on the mesh is
                # its jax process index (multi-host meshes have one service
                # process per host); -1 means exactly that auto-default.
                import gubernator_tpu.jaxinit  # noqa: F401
                import jax

                conf.global_mesh_node = (
                    jax.process_index()
                    if conf.tpu_global_mesh_node < 0
                    else conf.tpu_global_mesh_node
                )
        self._mesh_task: Optional[asyncio.Task] = None
        if self.global_mesh is not None:
            self._mesh_task = asyncio.create_task(
                self._mesh_reconcile_loop(), name="global-mesh-reconcile"
            )
        # Doomed-peer shutdowns and ring-change ownership transfers run
        # as tasks (set_peers is sync); tracked here so close() awaits
        # them instead of abandoning work (and so tests can assert no
        # pending-task warnings).
        self._peer_shutdown_tasks: set = set()
        self._transfer_tasks: set = set()
        # Per-item forward tasks (_async_request): the dispatch loop
        # normally awaits each one, but an exception out of an EARLIER
        # await in _get_rate_limits would abandon the rest mid-flight —
        # tracked + done-callback-logged (the doomed-peer pattern) so no
        # forward ever dies silently, and close() can await stragglers.
        self._forward_tasks: set = set()
        # Cooperative quota leases (docs/leases.md): mints signed
        # TTL-bounded budget delegations, reconciles consumption as
        # batched engine work, and degrades to cheap TTL extension when
        # the tick loop reports pressure.  Always constructed — with
        # GUBER_LEASE_ENABLED=0 every grant is declined, which clients
        # read as "no lease tier here".
        from gubernator_tpu.leases import LeaseManager

        self.lease_mgr = LeaseManager(
            self.engine, tick_loop=self.tick_loop, metrics=self.metrics,
        )
        # Elastic live resharding (docs/resharding.md): the n→m
        # transition coordinator over this instance's engine + tick
        # loop.  The transition journal shares the snapshot directory;
        # peer breakers gate the cutover (a mid-transfer peer death
        # aborts rather than cutting over blind).
        from gubernator_tpu.parallel.reshard import ReshardCoordinator
        from gubernator_tpu.persistence import TransitionLog

        self.reshard_coord = ReshardCoordinator(
            self.engine,
            tick_loop=self.tick_loop,
            transition_log=TransitionLog(conf.snapshot_dir or None),
            breaker_check=lambda: any(
                p.breaker.is_open() for p in self.get_peer_list()),
            global_engine=self.global_mesh,
            # Reshard × federation interlock (docs/federation.md): no
            # envelope may be compacted from half-relayouted owner
            # state — sends pause for FREEZE→CUTOVER.
            federation=self.federation,
            metrics=self.metrics,
            freeze_timeout=conf.reshard_freeze_timeout,
            verify=conf.reshard_verify,
        )
        # Guardrailed shard autoscaler (docs/autoscaling.md): closes the
        # telemetry → reshard loop.  Constructed and started by
        # create() when enabled (spawn_supervised needs a running event
        # loop); None otherwise so /debug/autoscaler can answer 404.
        self.autoscaler = None
        # Crash-safe persistence (docs/persistence.md): wired by create().
        self._snapshot_writer = None
        self.restore_stats: dict = {}
        self._closed = False

    @classmethod
    async def create(cls, conf: InstanceConfig, engine=None) -> "V1Instance":
        inst = cls(conf, engine)
        if conf.loader is not None:
            # Columnar Loaders (v2) restore without dict materialization.
            if hasattr(conf.loader, "load_columns") and hasattr(
                inst.engine, "load_columns"
            ):
                snap = conf.loader.load_columns()
                if snap is not None:
                    inst.engine.load_columns(snap)
            else:
                items = conf.loader.load()
                inst.engine.load_items(list(items))
        if conf.snapshot_dir and hasattr(inst.engine, "load_columns"):
            await inst._start_persistence()
        # Crash-mid-cutover detection (docs/resharding.md): a begin
        # record with no terminal record means the process died inside a
        # reshard transition — the snapshot just restored (never mutated
        # mid-flight) is authoritative; count and clear the stale
        # journal.
        from gubernator_tpu.persistence import check_interrupted

        rec = check_interrupted(inst.reshard_coord.transition_log)
        if rec is not None:
            inst.reshard_coord.record_interrupted(rec)
        if conf.autoscale_enabled:
            inst._start_autoscaler()
        return inst

    async def _start_persistence(self) -> None:
        """Restore base + deltas from the snapshot store (corrupt tails
        are counted, never fatal; ``load_columns`` TTL-expires stale
        rows), then start the supervised delta-flush loop.  Runs before
        the daemon flips ready — a restoring node answers 503 on
        /readyz, not fresh-bucket allows."""
        from gubernator_tpu.persistence import SnapshotStore, SnapshotWriter

        store = SnapshotStore(self.conf.snapshot_dir)
        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(None, store.load)
        for snap in result.snapshots:
            await loop.run_in_executor(None, self.engine.load_columns, snap)
        self.restore_stats = {
            "generation": result.generation,
            "restored_items": result.items,
            "delta_records": result.delta_records,
            "corrupt_records": result.corrupt_records,
            "manifest_missing": result.manifest_missing,
        }
        if result.corrupt_records:
            self.metrics.snapshot_corrupt_records.inc(result.corrupt_records)
            self.log.warning(
                "snapshot restore skipped %d corrupt/truncated records "
                "(kept the last good prefix)", result.corrupt_records,
            )
        if result.items:
            self.metrics.snapshot_restored_items.inc(result.items)
            self.log.info(
                "restored %d bucket rows from %s (generation %d, %d "
                "delta records)", result.items, self.conf.snapshot_dir,
                result.generation, result.delta_records,
            )
        self._snapshot_writer = SnapshotWriter(
            self.engine, store,
            interval=self.conf.snapshot_interval,
            deltas_per_base=self.conf.snapshot_deltas_per_base,
            metrics=self.metrics,
        )
        self._snapshot_writer.start()

    # ------------------------------------------------------------------
    # Public API: GetRateLimits
    # ------------------------------------------------------------------
    async def get_rate_limits(
        self, requests: Sequence[RateLimitRequest]
    ) -> List[RateLimitResponse]:
        """The 3-way dispatch (gubernator.go:183-295); responses in request
        order."""
        if len(requests) > MAX_BATCH_SIZE:
            self.metrics.check_error_counter.labels(error="Request too large").inc()
            raise BatchTooLargeError(
                f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'"
            )
        self.metrics.concurrent_checks.inc()
        t0 = time.perf_counter()
        try:
            with tracing.maybe_span(
                "V1Instance.GetRateLimits", {"batch.size": len(requests)}
            ):
                return await self._get_rate_limits(requests)
        finally:
            self.metrics.concurrent_checks.dec()
            self.metrics.func_duration.labels(
                name="V1Instance.GetRateLimits"
            ).observe(time.perf_counter() - t0)

    async def _get_rate_limits(
        self, requests: Sequence[RateLimitRequest]
    ) -> List[RateLimitResponse]:
        created_at = timeutil.now_ms()
        out: List[Optional[RateLimitResponse]] = [None] * len(requests)
        local_idx: List[int] = []
        mesh_idx: List[int] = []       # GLOBAL over the collectives plane
        global_idx: List[tuple] = []   # (i, owner_addr)
        forward: List[tuple] = []      # (i, peer, req, key)

        for i, req in enumerate(requests):
            key = req.hash_key()
            if req.unique_key == "":
                self.metrics.check_error_counter.labels(error="Invalid request").inc()
                out[i] = RateLimitResponse(error="field 'unique_key' cannot be empty")
                continue
            if req.name == "":
                self.metrics.check_error_counter.labels(error="Invalid request").inc()
                out[i] = RateLimitResponse(error="field 'namespace' cannot be empty")
                continue
            if invalid_algorithm_mask(int(req.algorithm)):
                # Reject unknown enum values here: past the edge, the
                # kernels' branchless per-lane dispatch would silently
                # run them as token-bucket (algos/__init__.py).
                self.metrics.check_error_counter.labels(error="Invalid request").inc()
                out[i] = RateLimitResponse(error=algorithm_error(req.algorithm))
                continue
            if has_behavior(req.behavior, Behavior.MULTI_REGION):
                # Edge validation (docs/federation.md): past this point
                # MULTI_REGION is a silent no-op bit, so a node that
                # cannot federate must say so per item rather than
                # quietly serving region-local answers forever.
                if self.federation is None:
                    self.metrics.check_error_counter.labels(
                        error="Invalid request").inc()
                    out[i] = RateLimitResponse(
                        error="Behavior.MULTI_REGION requires "
                        "GUBER_DATA_CENTER and GUBER_FEDERATION_ENABLED "
                        "on this node"
                    )
                    continue
                # MULTI_REGION rides the GLOBAL plane inside the region:
                # region-local answer now, inter-region envelope later.
                req.behavior = set_behavior(req.behavior, Behavior.GLOBAL, True)
                if self.federation.is_degraded():
                    # A peer region is unreachable: this answer may
                    # over-admit up to the staleness budget.
                    self.metrics.federation_degraded_answers.inc()
            if req.created_at is None or req.created_at == 0:
                req.created_at = created_at
            if self.conf.behaviors.force_global:
                req.behavior = set_behavior(req.behavior, Behavior.GLOBAL, True)

            if self.global_mesh is not None and has_behavior(
                req.behavior, Behavior.GLOBAL
            ):
                # Mesh-resident GLOBAL: ownership is the slot range on the
                # device mesh, not the consistent-hash ring; every node
                # answers from its replica and reconciles via collectives.
                mesh_idx.append(i)
                continue

            peer = self.get_peer(key)
            if peer is None or peer.info.is_owner:
                local_idx.append(i)
            elif has_behavior(req.behavior, Behavior.GLOBAL):
                if peer.breaker.is_open():
                    # Degraded GLOBAL mode: the local answer below is the
                    # partition-tolerant fallback — count it so operators
                    # can see how much traffic runs on stale state.
                    self.metrics.degraded_answers.inc()
                global_idx.append((i, peer.info.grpc_address))
            else:
                forward.append((i, peer, req, key))

        # Local items: one tick-loop submission for the whole call.
        locals_done = None
        if local_idx:
            locals_done = self._submit_local(
                [requests[i] for i in local_idx], is_owner=True
            )

        # GLOBAL non-owner items: answer from local state, reconcile async.
        globals_done = None
        if global_idx:
            globals_done = asyncio.ensure_future(
                self._get_global_rate_limits(
                    [requests[i] for i, _ in global_idx]
                )
            )

        # GLOBAL items on the mesh data plane: one device tick, no RPC.
        mesh_done = None
        if mesh_idx:
            mesh_reqs = [requests[i] for i in mesh_idx]
            mesh_done = asyncio.get_running_loop().run_in_executor(
                None,
                lambda: self.global_mesh.process(
                    mesh_reqs, self.conf.global_mesh_node
                ),
            )

        # Forwarded items: per-item task with retry/ownership-reresolution,
        # retained and supervised (G003): tracked set + logged exceptions.
        fwd_tasks = [
            self._spawn_forward(peer, req, key)
            for _, peer, req, key in forward
        ]

        if locals_done is not None:
            for i, resp in zip(local_idx, await locals_done):
                out[i] = resp
        if globals_done is not None:
            for (i, owner), resp in zip(global_idx, await globals_done):
                resp.metadata = {"owner": owner}
                out[i] = resp
        if mesh_done is not None:
            for i, resp in zip(mesh_idx, await mesh_done):
                self.metrics.getratelimit_counter.labels(calltype="global").inc()
                if resp.status == Status.OVER_LIMIT:
                    self.metrics.over_limit_counter.inc()
                out[i] = resp
        for (i, _, _, _), t in zip(forward, fwd_tasks):
            out[i] = await t
        return out  # type: ignore[return-value]

    def columns_fast_path_ok(self) -> bool:
        """Whether GetRateLimits may run wire→columns→device with no
        per-request objects: requires every key to be local (standalone —
        an empty peer set, or one containing only this node's own
        entry, which discovery type "none" installs), no server-forced
        GLOBAL, no Store (read-through takes request objects), and an
        engine speaking columns.  The transport additionally falls back
        per batch when an item carries GLOBAL behavior, metadata (trace
        context), or a validation error."""
        return (
            self._standalone
            and self.global_mesh is None
            and not self.conf.behaviors.force_global
            and self.conf.store is None
            and hasattr(self.engine, "submit_cols")
        )

    async def get_rate_limits_columns(self, cols, deadline: float = None):
        """Columnar GetRateLimits (the fast path; see
        columns_fast_path_ok).  Returns ``((5, n) matrix, errors)`` in
        request order; the transport writes wire responses straight from
        the matrix.  ``deadline`` is the batch's absolute admission
        deadline stamped at the serving edge (docs/overload.md)."""
        if len(cols) > MAX_BATCH_SIZE:
            self.metrics.check_error_counter.labels(error="Request too large").inc()
            raise BatchTooLargeError(
                f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'"
            )
        return await self._columns_tick(cols, deadline=deadline)

    async def _columns_tick(self, cols, public: bool = True,
                            deadline: float = None):
        """One tick-loop submission for a columnar batch + metrics.

        ``public`` marks the public GetRateLimits edge, which alone
        carries the concurrent-checks gauge and the GetRateLimits
        duration family (reference gubernator.go:188-199); the peer
        relay edge records only the local-handling metrics its object
        path does (_submit_local).  It also picks the admission class:
        relayed peer batches outrank client traffic under overload."""
        if public:
            self.metrics.concurrent_checks.inc()
        t0 = time.perf_counter()
        try:
            mat, errors = await asyncio.wrap_future(
                self.tick_loop.submit_columns(
                    cols, deadline=deadline,
                    klass=CLASS_CLIENT if public else CLASS_PEER,
                )
            )
            self.metrics.getratelimit_counter.labels(calltype="local").inc(
                len(cols) - len(errors)
            )
            self._count_algorithms(cols.algorithm)
            from gubernator_tpu.ops.engine import masked_over_limit

            over = masked_over_limit(mat, errors)
            if over:
                self.metrics.over_limit_counter.inc(over)
            return mat, errors
        finally:
            dt = time.perf_counter() - t0
            if public:
                self.metrics.concurrent_checks.dec()
                self.metrics.func_duration.labels(
                    name="V1Instance.GetRateLimits"
                ).observe(dt)
            self.metrics.func_duration.labels(
                name="V1Instance.getLocalRateLimit"
            ).observe(dt)

    def _submit_local(self, reqs: List[RateLimitRequest], *, is_owner: bool,
                      klass: int = CLASS_CLIENT):
        """Send a batch through the tick loop; wraps the future for await and
        handles GLOBAL owner-side queueing + metrics.  The batch inherits
        its most urgent member's propagated deadline (docs/overload.md)."""

        async def run():
            t0 = time.perf_counter()
            resps = await asyncio.wrap_future(self.tick_loop.submit(
                reqs, deadline=batch_deadline(reqs), klass=klass))
            self.metrics.func_duration.labels(
                name="V1Instance.getLocalRateLimit"
            ).observe(time.perf_counter() - t0)
            self._count_algorithms([r.algorithm for r in reqs])
            for req, resp in zip(reqs, resps):
                if has_behavior(req.behavior, Behavior.GLOBAL):
                    self.global_mgr.queue_update(req)
                if is_owner:
                    self.metrics.getratelimit_counter.labels(calltype="local").inc()
                    if resp.status == Status.OVER_LIMIT:
                        self.metrics.over_limit_counter.inc()
            return resps

        return asyncio.ensure_future(run())

    def _count_algorithms(self, algorithms) -> None:
        """Per-algorithm traffic split (gubernator_tpu_algorithm_requests).

        ``algorithms`` is host-side (a list or the batch's numpy column —
        never a device value).  Out-of-range lanes were rejected with
        per-item errors at the edge and are skipped here.
        """
        a = np.asarray(algorithms, np.int64)
        ok = (a >= 0) & (a <= int(ALGORITHM_MAX))
        counts = np.bincount(a[ok], minlength=int(ALGORITHM_MAX) + 1)
        for v, c in enumerate(counts):
            if c:
                self.metrics.algorithm_requests.labels(
                    algorithm=Algorithm(v).name.lower()
                ).inc(int(c))

    async def apply_local(
        self, reqs: List[RateLimitRequest]
    ) -> List[RateLimitResponse]:
        """Apply requests to the local engine with no routing/queueing — the
        GLOBAL manager's state re-read path (global.go:241-249).  Peer
        admission class: reconcile traffic outranks client traffic."""
        t0 = time.perf_counter()
        try:
            return await asyncio.wrap_future(self.tick_loop.submit(
                reqs, deadline=batch_deadline(reqs), klass=CLASS_PEER))
        finally:
            self.metrics.func_duration.labels(
                name="V1Instance.getLocalRateLimit"
            ).observe(time.perf_counter() - t0)

    async def _get_global_rate_limits(
        self, reqs: List[RateLimitRequest]
    ) -> List[RateLimitResponse]:
        """Non-owner GLOBAL path (gubernator.go:395-421): answer from local
        state as if we owned it, then queue the hits for reconciliation.
        Span parity: gubernator.go:396 getGlobalRateLimit."""
        sp = tracing.current_span()
        if sp is not None:
            sp.add_event("getGlobalRateLimit", {"count": len(reqs)})
        clones = []
        for r in reqs:
            c = RateLimitRequest(**vars(r))
            c.behavior = set_behavior(c.behavior, Behavior.NO_BATCHING, True)
            c.behavior = set_behavior(c.behavior, Behavior.GLOBAL, False)
            clones.append(c)
        resps = await asyncio.wrap_future(self.tick_loop.submit(
            clones, deadline=batch_deadline(clones)))
        for r in reqs:
            self.global_mgr.queue_hit(r)
            self.metrics.getratelimit_counter.labels(calltype="global").inc()
        return resps

    async def _mesh_reconcile_loop(self) -> None:
        """Drive the collective reconcile at the GlobalSyncWait cadence
        (global.go:193-283's loops, collapsed into one device step).  Every
        mesh-resident instance runs this; the engine's min-interval gate
        dedupes concurrent drivers."""
        loop = asyncio.get_running_loop()
        while not self._closed:
            await asyncio.sleep(self.conf.behaviors.global_sync_wait)
            try:
                await loop.run_in_executor(None, self._mesh_reconcile_once)
            except Exception:
                self.log.exception("global mesh reconcile failed")

    def _mesh_reconcile_once(self) -> None:
        """One cadence tick: reconcile + export the engine's step/dispatch
        counters to this daemon's registry (the engine is shared across
        co-resident daemons, so each driver exports only the deltas of
        the steps its own call performed)."""
        eng = self.global_mesh
        before = (eng.metric_reconcile_dispatches, eng.metric_dense_fallbacks)
        if not eng.maybe_reconcile():
            return
        self.metrics.mesh_reconcile_count.inc()
        self.metrics.mesh_reconcile_dispatches.inc(
            max(0, eng.metric_reconcile_dispatches - before[0]))
        self.metrics.mesh_dense_fallbacks.inc(
            max(0, eng.metric_dense_fallbacks - before[1]))

    def _spawn_forward(
        self, peer: PeerClient, req: RateLimitRequest, key: str
    ) -> "asyncio.Task":
        """Spawn one supervised forward task (the doomed-peer pattern,
        set_peers): handle retained in ``_forward_tasks`` and failures
        logged on completion, so a forward abandoned by an exception
        earlier in the dispatch loop is never GC'd mid-flight with a
        swallowed error."""
        t = asyncio.ensure_future(self._async_request(peer, req, key))
        self._forward_tasks.add(t)

        def _done(task: "asyncio.Task") -> None:
            self._forward_tasks.discard(task)
            if task.cancelled():
                return
            exc = task.exception()
            if exc is not None:
                self.log.warning(
                    "forwarded request for %r failed: %s", key, exc,
                    exc_info=exc,
                )

        t.add_done_callback(_done)
        return t

    async def _async_request(
        self, peer: PeerClient, req: RateLimitRequest, key: str
    ) -> RateLimitResponse:
        """Forward one item to its owner with decorrelated-jitter backoff
        between attempts (≤ forward_max_attempts retries), fresh owner
        resolution per retry, self-upgrading if ownership moved here
        (gubernator.go:311-391), and breaker-aware degraded fallback for
        GLOBAL keys (docs/resilience.md).  Span parity: gubernator.go:315
        asyncRequest."""
        with tracing.maybe_span(
            "V1Instance.asyncRequest",
            {"ratelimit.key": req.unique_key, "ratelimit.name": req.name,
             "peer": peer.info.grpc_address},
        ):
            return await self._async_request_traced(peer, req, key)

    async def _async_request_traced(
        self, peer: PeerClient, req: RateLimitRequest, key: str
    ) -> RateLimitResponse:
        rconf = self.conf.resilience
        backoff = DecorrelatedJitterBackoff(
            rconf.forward_backoff_base, rconf.forward_backoff_cap
        )
        attempts = 0
        last_err: Optional[Exception] = None

        async def retry(err: Exception) -> None:
            # Decorrelated-jitter sleep, then re-resolve ownership: the
            # peer set may have changed while the RPC was failing (the
            # reference re-resolves too, gubernator.go:311-391 — but with
            # no backoff, hammering a dead peer in a tight loop).
            nonlocal attempts, last_err, peer
            attempts += 1
            last_err = err
            self.metrics.batch_send_retries.inc()
            await asyncio.sleep(backoff.next())
            peer = self.get_peer(key) or peer

        while True:
            if attempts > rconf.forward_max_attempts:
                self.metrics.check_error_counter.labels(error="Peer not connected").inc()
                return RateLimitResponse(
                    error=f"GetPeer() keeps returning peers that are not "
                    f"connected for '{key}': {last_err}"
                )
            # Deadline-aware retry budget (docs/overload.md): once the
            # caller's propagated budget is spent, stop riding the
            # backoff ladder — the client already gave up; answer a
            # retriable error instead of hammering a dead peer.
            if (
                attempts != 0
                and req.deadline is not None
                and time.monotonic() >= req.deadline
            ):
                self.metrics.check_error_counter.labels(
                    error="Deadline exceeded").inc()
                return RateLimitResponse(
                    error=f"deadline budget spent while forwarding "
                    f"'{key}': {last_err}"
                )
            if attempts != 0 and peer.info.is_owner:
                resps = await self._submit_local([req], is_owner=True)
                return resps[0]
            try:
                resp = await peer.get_peer_rate_limit(req)
            except BudgetExhaustedError as e:
                self.metrics.check_error_counter.labels(
                    error="Deadline exceeded").inc()
                return RateLimitResponse(
                    error=f"deadline budget spent while forwarding "
                    f"'{key}': {e}"
                )
            except BreakerOpenError as e:
                if has_behavior(req.behavior, Behavior.GLOBAL):
                    # Degraded mode: the non-owner GLOBAL state is a
                    # serviceable local answer (DRAIN_OVER_LIMIT semantics
                    # ride the behavior bits unchanged); hits queue for
                    # redelivery once the owner recovers.
                    self.metrics.degraded_answers.inc()
                    resp = (await self._get_global_rate_limits([req]))[0]
                    resp.metadata = {
                        "owner": peer.info.grpc_address, "degraded": "true"
                    }
                    return resp
                await retry(e)
                continue
            except grpc.aio.AioRpcError as e:
                if e.code() in (
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    grpc.StatusCode.CANCELLED,
                    grpc.StatusCode.UNAVAILABLE,
                ):
                    await retry(e)
                    continue
                return RateLimitResponse(
                    error=f"Error while fetching rate limit '{key}' from peer: "
                    f"{e.details()}"
                )
            except Exception as e:
                return RateLimitResponse(
                    error=f"Error while fetching rate limit '{key}' from peer: {e}"
                )
            self.metrics.getratelimit_counter.labels(calltype="forward").inc()
            resp.metadata = {"owner": peer.info.grpc_address}
            return resp

    # ------------------------------------------------------------------
    # Peer API (PeersV1)
    # ------------------------------------------------------------------
    def peer_columns_fast_path_ok(self) -> bool:
        """Whether GetPeerRateLimits may run wire→columns→device: unlike
        the public gate (columns_fast_path_ok) this does NOT require
        standalone — a relayed batch is processed locally regardless of
        ring ownership (the reference's peer side just processes what
        arrives, gubernator.go:497-536).  The transport still falls back
        per batch for GLOBAL/metadata/error items (GLOBAL owner-side
        queueing and trace extraction need request objects)."""
        return (
            self.conf.store is None
            and not self.conf.behaviors.force_global
            and self.global_mesh is None
            and hasattr(self.engine, "submit_cols")
        )

    async def get_peer_rate_limits_columns(self, cols, deadline: float = None):
        """Columnar owner-side handling of a relayed batch (the peer-edge
        twin of get_rate_limits_columns; eligibility per
        peer_columns_fast_path_ok).  Peer admission class: relayed
        reconcile traffic outranks client traffic under overload."""
        if len(cols) > MAX_BATCH_SIZE:
            self.metrics.check_error_counter.labels(error="Request too large").inc()
            raise BatchTooLargeError(
                f"'PeerRequest.rate_limits' list too large; max size is "
                f"'{MAX_BATCH_SIZE}'"
            )
        return await self._columns_tick(cols, public=False, deadline=deadline)

    async def get_peer_rate_limits(
        self, requests: Sequence[RateLimitRequest]
    ) -> List[RateLimitResponse]:
        """Owner-side handling of relayed batches (gubernator.go:462-539):
        forwarded GLOBAL hits get DRAIN_OVER_LIMIT forced."""
        if len(requests) > MAX_BATCH_SIZE:
            self.metrics.check_error_counter.labels(error="Request too large").inc()
            raise BatchTooLargeError(
                f"'PeerRequest.rate_limits' list too large; max size is "
                f"'{MAX_BATCH_SIZE}'"
            )
        created_at = timeutil.now_ms()
        # Continue the caller's trace: each forwarded request carries W3C
        # TraceContext in its metadata (extracted per request, the
        # reference's prop.Extract at gubernator.go:502-504).
        tracer = tracing.get_tracer()
        traced = tracing.enabled()  # skip span objects entirely when untraced
        spans = []
        for req in requests:
            remote = tracing.extract(req.metadata) if traced else None
            if remote is not None:
                spans.append(tracer.start_detached(
                    "PeersV1.GetPeerRateLimit",
                    {"ratelimit.key": req.unique_key,
                     "ratelimit.name": req.name},
                    parent=remote,
                ))
            if has_behavior(req.behavior, Behavior.GLOBAL):
                req.behavior = set_behavior(
                    req.behavior, Behavior.DRAIN_OVER_LIMIT, True
                )
            if req.created_at is None or req.created_at == 0:
                req.created_at = created_at
        try:
            return await self._submit_local(
                list(requests), is_owner=True, klass=CLASS_PEER)
        finally:
            for s in spans:
                tracer.finish(s)

    async def update_peer_globals(self, updates: Sequence[GlobalUpdate]) -> None:
        """Install owner-pushed GLOBAL state (gubernator.go:425-459).

        Runs in a worker thread: install is device work (and may trigger a
        one-off XLA compile for a new scatter width) — it must not stall the
        event loop.
        """
        await asyncio.get_running_loop().run_in_executor(
            None, self.engine.install_globals, list(updates)
        )

    # ------------------------------------------------------------------
    # Cooperative quota leases (docs/leases.md)
    # ------------------------------------------------------------------
    async def lease_grant(self, specs):
        """Mint quota leases: [LeaseSpec] → [Optional[LeaseToken]].
        Delegation is an ordinary batched decision through the tick
        loop (UNDER_LIMIT charges the slice up front; OVER_LIMIT
        declines with None), so grants ride the same admission plane
        as everything else."""
        return await self.lease_mgr.grant(list(specs))

    async def lease_sync(self, syncs):
        """Reconcile lease consumption: [LeaseSync] → [LeaseSyncAck].
        Credit-backs and excess force-charges flow through the tick
        loop in the peer class."""
        return await self.lease_mgr.sync(list(syncs))

    # ------------------------------------------------------------------
    # Elastic live resharding (docs/resharding.md)
    # ------------------------------------------------------------------
    async def reshard(self, new_shards: int) -> dict:
        """Run one n→m transition (admin-triggered via POST
        /debug/reshard, or the autoscaler).  The coordinator's
        freeze/drain/cutover is blocking device + lock work, so it runs
        in a worker thread; the event loop keeps serving the
        shed-with-retriable answers the freeze produces.  A concurrent
        transition returns the coordinator's ``{"result": "busy"}``
        dict — the coordinator lock is the single busy source of truth,
        so the autoscaler and the admin endpoint can never race into a
        double-freeze.  After a committed transition, tracked GLOBAL
        keys re-broadcast through the PR 4 ownership-handoff path so
        any peer holding pre-transition state converges."""
        result = await asyncio.get_running_loop().run_in_executor(
            None, self.reshard_coord.try_reshard, int(new_shards)
        )
        if result.get("result") == "busy":
            return result
        if result.get("outcome") == "committed" and self.global_mgr._owned:
            t = asyncio.get_running_loop().create_task(
                self.global_mgr.transfer_ownership(),
                name="reshard-ownership-rebroadcast",
            )
            self._transfer_tasks.add(t)
            t.add_done_callback(self._transfer_tasks.discard)
        return result

    def reshard_status(self) -> dict:
        """Coordinator phase/outcome snapshot for /debug/state."""
        return self.reshard_coord.status()

    def _start_autoscaler(self) -> None:
        """Construct and start the guardrailed autoscaler
        (docs/autoscaling.md) over this instance's telemetry and
        :meth:`reshard`.  Requires a running event loop (called from
        :meth:`create`); :meth:`close` stops it first."""
        from gubernator_tpu.autoscale import (
            Autoscaler,
            AutoscalePolicy,
            PolicyConfig,
            instance_sampler,
        )

        conf = self.conf
        policy = AutoscalePolicy(PolicyConfig(
            windows=conf.autoscale_windows,
            target_p99_ms=conf.autoscale_target_p99_ms,
            queue_high=conf.autoscale_queue_high,
            hysteresis=conf.autoscale_hysteresis,
            occupancy_low=conf.autoscale_occupancy_low,
            min_shards=conf.autoscale_min_shards,
            max_shards=conf.autoscale_max_shards,
        ))
        self.autoscaler = Autoscaler(
            instance_sampler(self, time.monotonic),
            self.reshard,
            policy=policy,
            interval=conf.autoscale_interval,
            cooldown_up=conf.autoscale_cooldown_up,
            cooldown_down=conf.autoscale_cooldown_down,
            max_per_hour=conf.autoscale_max_per_hour,
            dry_run=conf.autoscale_dry_run,
            metrics=self.metrics,
        )
        self.autoscaler.start()
        self.log.info(
            "autoscaler started (interval=%.1fs, dry_run=%s, shards "
            "[%d, %d])", conf.autoscale_interval, conf.autoscale_dry_run,
            conf.autoscale_min_shards, conf.autoscale_max_shards,
        )

    # ------------------------------------------------------------------
    # Health / peers
    # ------------------------------------------------------------------
    def health_check(self) -> HealthCheckResponse:
        """Aggregate recent per-peer errors (gubernator.go:542-586), plus
        the breaker quorum rule: when more than half of the local picker's
        peers have OPEN circuit breakers this node is partitioned from the
        cluster majority and reports unhealthy (the daemon's /healthz
        returns 503 so orchestrators rotate it out)."""
        errs: List[str] = []
        local_peers = self.local_picker.peers()
        for p in local_peers:
            for msg in p.get_last_err():
                errs.append(f"error returned from local peer.GetLastErr: {msg}")
        region_peers = self.region_picker.peers()
        for p in region_peers:
            for msg in p.get_last_err():
                errs.append(f"error returned from region peer.GetLastErr: {msg}")
        open_breakers = sum(1 for p in local_peers if p.breaker.is_open())
        if local_peers and open_breakers * 2 > len(local_peers):
            errs.append(
                f"{open_breakers}/{len(local_peers)} local peers have open "
                f"circuit breakers"
            )
        return HealthCheckResponse(
            status="unhealthy" if errs else "healthy",
            message="|".join(errs),
            peer_count=len(local_peers) + len(region_peers),
        )

    def occupancy(self) -> dict:
        """Tier occupancy snapshot (docs/tiering.md): device-table fill,
        cold-store size, and shed count — surfaced by the daemon's
        /healthz JSON and mirrored into the Prometheus gauges."""
        eng = self.engine
        return {
            "cache_size": eng.cache_size(),
            "hot_occupancy": round(
                getattr(eng, "hot_occupancy", lambda: 0.0)(), 4
            ),
            "cold_size": getattr(eng, "cold_size", lambda: 0)(),
            "shed_requests": getattr(eng, "metric_shed_requests", 0),
        }

    def set_peers(self, peer_info: Sequence[PeerInfo]) -> None:
        """Install a new peer set (gubernator.go:616-711): reuse existing
        clients, mark our own entry as owner, shut down removed peers."""
        local = self.local_picker.new()
        region = self.region_picker.new()
        replaced: List[PeerClient] = []
        for info in peer_info:
            if info.grpc_address == self.conf.advertise_address:
                info = PeerInfo(
                    grpc_address=info.grpc_address,
                    http_address=info.http_address,
                    datacenter=info.datacenter,
                    is_owner=True,
                )
            if info.datacenter and info.datacenter != self.conf.data_center:
                peer = self.region_picker.get_by_address(info.grpc_address)
                if peer is None:
                    peer = self._new_peer_client(info)
                region.add(peer)
                continue
            peer = self.local_picker.get_by_address(info.grpc_address)
            if peer is not None and peer.info != info:
                replaced.append(peer)  # same address, changed info: re-dial
                peer = None
            if peer is None:
                peer = self._new_peer_client(info)
            local.add(peer)

        old_local, old_region = self.local_picker, self.region_picker
        # Standalone = no peers, or only our own entry (discovery "none"
        # installs self): the columns fast path's gate, recomputed at the
        # sole mutation point so the hot path reads one bool.  Ordering
        # matters: when remote peers arrive, clear the flag BEFORE the
        # picker swap; when they leave, set it AFTER — either way the
        # fast path never sees standalone=True with remote peers live
        # (worst case it conservatively takes the slow path for a beat).
        standalone = all(p.info.is_owner for p in local.peers())
        if not standalone:
            self._standalone = False
        self.local_picker, self.region_picker = local, region
        if standalone:
            self._standalone = True
        if self.federation is not None:
            # Reroute federation channels whose target peer left its
            # region's ring: in-flight records requeue to the pending
            # buffer and rehash to the new remote owner on the next
            # flush instead of retrying a dead address forever.
            self.federation.on_ring_update()

        # Gracefully drain removed (and replaced) peers.
        doomed = replaced + [
            p
            for p in old_local.peers()
            if local.get_by_address(p.info.grpc_address) is None
        ]
        for picker in old_region.pickers().values():
            doomed.extend(
                p
                for p in picker.peers()
                if region.get_by_address(p.info.grpc_address) is None
            )
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (tests building instances synchronously)
        for p in doomed:
            # Tracked, not fire-and-forget: close() awaits these, and a
            # failed shutdown is logged instead of silently swallowed
            # (a bare create_task drops the exception with the task).
            t = loop.create_task(
                self._shutdown_peer(p),
                name=f"peer-shutdown:{p.info.grpc_address}",
            )
            self._peer_shutdown_tasks.add(t)
            t.add_done_callback(self._peer_shutdown_tasks.discard)
        # Ownership handoff: GLOBAL keys we owned whose new owner is a
        # different peer get their accumulated state pushed there (the
        # ring swap must not reset their accounting).  Skipped when no
        # owned keys are tracked — the overwhelmingly common set_peers.
        if self.global_mgr._owned:
            t = loop.create_task(
                self.global_mgr.transfer_ownership(),
                name="ownership-transfer",
            )
            self._transfer_tasks.add(t)
            t.add_done_callback(self._transfer_tasks.discard)

    async def _shutdown_peer(self, peer: PeerClient) -> None:
        try:
            await peer.shutdown()
        except Exception:
            self.log.warning(
                "shutdown of removed peer %s failed",
                peer.info.grpc_address, exc_info=True,
            )

    def _new_peer_client(self, info: PeerInfo) -> PeerClient:
        return PeerClient(
            info,
            behaviors=self.conf.behaviors,
            channel_credentials=self.conf.peer_credentials,
            metrics=self.metrics,
            resilience=self.conf.resilience,
            fault_injector=self.conf.fault_injector,
            self_address=self.conf.advertise_address,
        )

    def get_peer(self, key: str) -> Optional[PeerClient]:
        """Owning peer for a key; None when no peers are set (standalone →
        local processing)."""
        if len(self.local_picker) == 0:
            return None
        return self.local_picker.get(key)

    def get_peer_list(self) -> List[PeerClient]:
        return self.local_picker.peers()

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def attach_edge_plane(self, plane) -> None:
        """Adopt a started :class:`gubernator_tpu.edge.EdgePlane` so
        :meth:`close` tears it down in the right order (before the tick
        loop — its in-flight windows are tick futures over shm views)."""
        self.edge_plane = plane

    async def close(self) -> None:
        """Graceful drain + shutdown (gubernator.go:151-170, extended per
        docs/persistence.md): finish in-flight ring work (ownership
        transfers), flush the GLOBAL buffers under the bounded drain
        deadline, stop peers (awaiting the tracked teardown tasks), write
        the final full base snapshot / run Loader.Save, then stop the
        tick loop and engine."""
        if self._closed:
            return
        self._closed = True
        if self.autoscaler is not None:
            # First out: the controller must not start a transition
            # against an instance that is tearing down.
            await self.autoscaler.stop()
        # Pending ownership transfers need peers and the tick loop alive.
        if self._transfer_tasks:
            await asyncio.gather(
                *list(self._transfer_tasks), return_exceptions=True
            )
        await self.global_mgr.close(drain_timeout=self.conf.drain_timeout)
        if self.federation is not None:
            # After the GLOBAL drain (its final flush may queue the last
            # deltas here) and before peers shut down (the drain sends
            # envelopes through them).
            await self.federation.close(
                drain_timeout=self.conf.drain_timeout)
        if self._mesh_task is not None:
            self._mesh_task.cancel()
            try:
                await self._mesh_task
            except (asyncio.CancelledError, Exception):
                pass
        # Forward tasks abandoned by a failed dispatch loop would outlive
        # the instance; their done-callbacks already log failures.
        if self._forward_tasks:
            await asyncio.gather(
                *list(self._forward_tasks), return_exceptions=True
            )
        # Earlier ring changes spawned doomed-peer shutdowns; await them
        # (each logs its own failure) so no task outlives the instance.
        if self._peer_shutdown_tasks:
            await asyncio.gather(
                *list(self._peer_shutdown_tasks), return_exceptions=True
            )
        for p in set(self.local_picker.peers()) | set(self.region_picker.peers()):
            try:
                await p.shutdown()
            except Exception:
                self.log.warning(
                    "peer %s shutdown failed during close",
                    p.info.grpc_address, exc_info=True,
                )
        if self._snapshot_writer is not None:
            # Final FULL base: graceful shutdown loses zero state.
            await self._snapshot_writer.close(final_base=True)
        if self.conf.loader is not None:
            if hasattr(self.conf.loader, "save_columns") and hasattr(
                self.engine, "export_columns"
            ):
                self.conf.loader.save_columns(self.engine.export_columns())
            else:
                self.conf.loader.save(self.engine.export_items())
        if self.edge_plane is not None:
            # The edge plane's in-flight windows are tick-loop futures
            # holding zero-copy shm views; stop it while the loop can
            # still resolve them (docs/edge.md shutdown ordering).
            await asyncio.get_running_loop().run_in_executor(
                None, self.edge_plane.close
            )
        self.tick_loop.close()
        if hasattr(self.engine, "close"):
            self.engine.close()
        self.metrics.cache_size.set(self.engine.cache_size())
