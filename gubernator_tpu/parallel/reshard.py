"""Elastic live resharding: the n→m transition state machine.

The coordinator sequences a drain-then-cutover protocol
(docs/resharding.md) around the engine's atomic relayout
(:meth:`MeshTickEngine.reshard`):

``FREEZE``
    New CLIENT windows shed-with-retriable at the admission queue
    (:meth:`TickLoop.freeze`); PEER reconcile traffic keeps draining —
    it outranks clients and must land before the cutover.
``DRAIN``
    Bounded quiesce: every admitted window resolves (queue empty,
    nothing mid-dispatch, nothing at the resolver).  A drain that
    misses its budget aborts — the cutover never runs under traffic.
``RELAYOUT``/``CUTOVER``
    Freeze escalates to both classes for the bounded cutover window,
    a ``begin`` record lands in the transition journal, then the
    engine relayouts on-device and swaps layouts atomically (an engine
    failure rolls back to the old layout before raising).
``VERIFY``
    The post-cutover table is audited: every row live at relayout time
    is present exactly once (``reshard_state_loss`` /
    ``reshard_double_served``, both gated at ABSOLUTE_ZERO by the
    reshard_live bench rung) and the routed path agrees with the ring
    (``routing_parity_errors == 0``).

Every failure mode lands in a defined state: peer death surfaces as an
open breaker and aborts before the cutover; a crash mid-cutover leaves
a non-terminal journal record that startup detects (the snapshot store
— never mutated mid-flight — is authoritative); an engine error rolls
back to the old layout and the transition reports ``aborted``.

Engines without a native ``reshard`` (the single-chip
:class:`TickEngine`) get the degenerate identity transition: the full
protocol runs — freeze, drain, journal, breakers, verify — with no
relayout, which is what the chaos suite drives on its existing
clusters without building mesh engines.
"""

from __future__ import annotations

import logging
import threading
from gubernator_tpu.utils import sanitize
import time
from typing import Callable, Optional

log = logging.getLogger("gubernator.reshard")

PHASE_IDLE = "idle"
PHASE_FREEZE = "freeze"
PHASE_DRAIN = "drain"
PHASE_RELAYOUT = "relayout"
PHASE_CUTOVER = "cutover"
PHASE_VERIFY = "verify"
PHASE_COMMITTED = "committed"
PHASE_ABORTED = "aborted"

# Gauge encoding for gubernator_tpu_reshard_phase; terminal phases read
# as idle — the gauge tracks the *running* transition only.
_PHASE_IDS = {
    PHASE_IDLE: 0,
    PHASE_FREEZE: 1,
    PHASE_DRAIN: 2,
    PHASE_RELAYOUT: 3,
    PHASE_CUTOVER: 4,
    PHASE_VERIFY: 5,
    PHASE_COMMITTED: 0,
    PHASE_ABORTED: 0,
}


class ReshardError(RuntimeError):
    """A transition could not start (already running / bad target)."""


# The single source of truth for the concurrent-call outcome: every
# caller — Instance.reshard(), the /debug/reshard 409, the autoscaler's
# reshard_busy veto — consumes this one dict instead of string-matching
# a ReshardError.  The coordinator's non-blocking lock is the only busy
# check anywhere; two callers can never race into a double-freeze.
BUSY_RESULT = {
    "result": "busy",
    "error": "a reshard transition is already running",
}


class ReshardCoordinator:
    """Drives one transition at a time over an engine + tick loop.

    All hooks are optional so the coordinator composes with partial
    stacks (tests, bench, single-chip engines):

    * ``tick_loop`` — freeze/quiesce/unfreeze admission around the
      cutover; without one, the caller owns traffic exclusion.
    * ``transition_log`` — the crash journal
      (:class:`~gubernator_tpu.persistence.TransitionLog`).
    * ``breaker_check`` — callable returning True when the peer plane
      is unsafe (an open breaker mid-transfer); consulted after the
      drain and again immediately before the cutover.
    * ``global_engine`` — a :class:`MeshGlobalEngine` whose reconcile
      cadence is paused for the cutover window (collectives must not
      contend with the relayout dispatch on the same devices).
    * ``federation`` — a :class:`FederationManager` whose envelope
      flushes are paused for FREEZE→CUTOVER and resumed after
      commit/abort: an envelope compacted mid-relayout would snapshot
      half-moved owner state and export it to every remote region.
    * ``metrics`` — the daemon's :class:`Metrics` registry.
    """

    def __init__(
        self,
        engine,
        tick_loop=None,
        transition_log=None,
        breaker_check: Optional[Callable[[], bool]] = None,
        global_engine=None,
        federation=None,
        metrics=None,
        freeze_timeout: float = 5.0,
        verify: bool = True,
    ):
        self.engine = engine
        self.tick_loop = tick_loop
        self.transition_log = transition_log
        self.breaker_check = breaker_check
        self.global_engine = global_engine
        self.federation = federation
        self.metrics = metrics
        self.freeze_timeout = float(freeze_timeout)
        self.verify = bool(verify)
        self._lock = sanitize.lock("ReshardCoordinator._lock")
        self._epoch = 0
        self.phase = PHASE_IDLE
        self.last: dict = {}

    # ------------------------------------------------------------------
    # Introspection (daemon /debug/state)
    # ------------------------------------------------------------------
    def status(self) -> dict:
        return {
            "phase": self.phase,
            "epoch": self._epoch,
            "shards": getattr(self.engine, "n_shards", 1),
            "last": dict(self.last),
        }

    def _set_phase(self, phase: str) -> None:
        self.phase = phase
        if self.metrics is not None:
            self.metrics.reshard_phase.set(_PHASE_IDS[phase])

    def record_interrupted(self, rec) -> None:
        """Surface a non-terminal journal record found at startup (the
        process died mid-transition; the restored snapshot is
        authoritative)."""
        log.warning(
            "interrupted reshard transition detected at startup "
            "(%d -> %d shards, epoch %d); serving from the restored "
            "snapshot on the old layout",
            rec.from_shards, rec.to_shards, rec.epoch,
        )
        if self.metrics is not None:
            self.metrics.reshard_transitions.labels(
                result="interrupted").inc()

    # ------------------------------------------------------------------
    # The transition
    # ------------------------------------------------------------------
    def is_busy(self) -> bool:
        """True while a transition holds the coordinator lock.  Advisory
        only (the lock may flip between check and call) — callers that
        must not block use :meth:`try_reshard`, whose non-blocking
        acquire is the authoritative check."""
        return self._lock.locked()

    def try_reshard(self, new_shards: int) -> dict:
        """Run one n→m transition, or return ``BUSY_RESULT`` (a copy)
        when one is already running — the non-raising entry point the
        autoscaler and admin endpoint share, so neither can double-freeze
        the other.  Still raises :class:`ReshardError` for an invalid
        target; never raises on an *aborted* transition — abort is a
        defined outcome, not an error."""
        new_n = int(new_shards)
        if new_n < 1:
            raise ReshardError(f"target shard count must be >= 1: {new_n}")
        if not self._lock.acquire(blocking=False):
            return dict(BUSY_RESULT)
        try:
            return self._run(new_n)
        finally:
            self._lock.release()

    def reshard(self, new_shards: int) -> dict:
        """Raising wrapper over :meth:`try_reshard` (the original API):
        a concurrent transition surfaces as :class:`ReshardError`."""
        out = self.try_reshard(new_shards)
        if out.get("result") == "busy":
            raise ReshardError(out["error"])
        return out

    def _run(self, new_n: int) -> dict:
        from_n = int(getattr(self.engine, "n_shards", 1))
        self._epoch += 1
        t0 = time.monotonic()
        out = {
            "from_shards": from_n,
            "to_shards": new_n,
            "epoch": self._epoch,
            "state_loss": 0,
            "double_served": 0,
            "parity_errors": 0,
            "live_items": 0,
        }
        if new_n == from_n:
            out.update(outcome="noop", duration_s=0.0)
            self.last = out
            return out
        try:
            # FREEZE: clients shed retriable; peers keep draining first.
            self._set_phase(PHASE_FREEZE)
            if self.tick_loop is not None:
                self.tick_loop.freeze()
            if self.global_engine is not None:
                self.global_engine.pause_reconcile()
            if self.federation is not None:
                # No envelope may be compacted from half-relayouted
                # owner state; resumed in the finally below.
                self.federation.pause()
            # DRAIN: bounded quiesce — cutover never runs under traffic.
            self._set_phase(PHASE_DRAIN)
            if self.tick_loop is not None:
                if not self.tick_loop.quiesce(self.freeze_timeout):
                    return self._abort(out, t0, "drain timeout: in-flight "
                                       "windows did not quiesce")
            if self.breaker_check is not None and self.breaker_check():
                return self._abort(out, t0, "peer breaker open after drain")
            # RELAYOUT/CUTOVER: both classes frozen for the bounded
            # window; journal begin before any state moves.
            self._set_phase(PHASE_RELAYOUT)
            if self.tick_loop is not None:
                self.tick_loop.freeze(shed_peers=True)
            if self.breaker_check is not None and self.breaker_check():
                return self._abort(out, t0, "peer breaker open at cutover")
            self._journal("begin", out)
            self._set_phase(PHASE_CUTOVER)
            try:
                if hasattr(self.engine, "reshard"):
                    info = self.engine.reshard(new_n)
                    out["live_items"] = int(info.get("live_items", 0))
                else:
                    # Degenerate identity transition (single-chip
                    # engine): the protocol runs, no state moves.
                    out["live_items"] = int(self.engine.cache_size())
                    out["degenerate"] = True
            except Exception as e:  # engine rolled back before raising
                self._journal("abort", out)
                return self._abort(out, t0, f"engine relayout failed "
                                   f"(rolled back): {e}")
            # VERIFY: audit the post-cutover table before unfreezing.
            self._set_phase(PHASE_VERIFY)
            if self.verify:
                loss, dup, parity = self._verify(out["live_items"])
                out.update(state_loss=loss, double_served=dup,
                           parity_errors=parity)
                if self.metrics is not None:
                    if loss:
                        self.metrics.reshard_state_loss.inc(loss)
                    if dup:
                        self.metrics.reshard_double_served.inc(dup)
                if loss or dup or parity:
                    log.error(
                        "reshard verify found damage (loss=%d dup=%d "
                        "parity=%d) after %d -> %d; transition committed "
                        "— investigate before the next one",
                        loss, dup, parity, from_n, new_n,
                    )
            self._journal("commit", out)
            return self._finish(out, t0, "committed")
        finally:
            if self.global_engine is not None:
                self.global_engine.resume_reconcile()
            if self.federation is not None:
                self.federation.resume()
            if self.tick_loop is not None:
                self.tick_loop.unfreeze()
            self._set_phase(
                PHASE_COMMITTED if out.get("outcome") == "committed"
                else PHASE_ABORTED if out.get("outcome") == "aborted"
                else PHASE_IDLE
            )

    def _verify(self, expected_live: int) -> tuple:
        """(state_loss, double_served, parity_errors) for the serving
        table: readback every resident row, count keys missing vs. the
        relayout-time live set and keys resident more than once, then
        audit route==owner on the routed path when the engine has one."""
        items = self.engine.export_items()
        keys = [it["key"] for it in items]
        unique = set(keys)
        loss = max(0, int(expected_live) - len(unique))
        dup = len(keys) - len(unique)
        parity = 0
        if unique and hasattr(self.engine, "routing_parity_errors"):
            parity = int(self.engine.routing_parity_errors(sorted(unique)))
        return loss, dup, parity

    def _journal(self, phase: str, out: dict) -> None:
        if self.transition_log is None:
            return
        from gubernator_tpu.persistence.transition import TransitionRecord

        try:
            self.transition_log.append(TransitionRecord(
                phase=phase,
                from_shards=out["from_shards"],
                to_shards=out["to_shards"],
                epoch=self._epoch,
            ))
        except OSError:
            log.warning("transition journal append failed", exc_info=True)

    def _abort(self, out: dict, t0: float, reason: str) -> dict:
        out.update(outcome="aborted", reason=reason)
        log.warning(
            "reshard %d -> %d aborted: %s",
            out["from_shards"], out["to_shards"], reason,
        )
        return self._finish(out, t0, "aborted")

    def _finish(self, out: dict, t0: float, outcome: str) -> dict:
        out["outcome"] = outcome
        out["duration_s"] = time.monotonic() - t0
        if self.metrics is not None:
            self.metrics.reshard_transitions.labels(result=outcome).inc()
            self.metrics.reshard_duration.labels(result=outcome).observe(
                out["duration_s"])
            self.metrics.reshard_shards.set(
                getattr(self.engine, "n_shards", 1))
        self.last = out
        return out
