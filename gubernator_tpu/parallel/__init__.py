from gubernator_tpu.parallel.mesh_engine import (
    MeshTickEngine,
    make_mesh,
    make_sharded_tick_fn,
)

__all__ = ["MeshTickEngine", "make_mesh", "make_sharded_tick_fn"]
