from gubernator_tpu.parallel.mesh_engine import (
    MeshTickEngine,
    ShardedOps,
    make_mesh,
)

__all__ = ["MeshTickEngine", "ShardedOps", "make_mesh"]
