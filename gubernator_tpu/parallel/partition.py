"""Canonical PartitionSpec layouts for the sharded serving path.

One frozen spec-helper per mesh axis family — the SNIPPETS [3] idiom
(a ``SpecLayout`` dataclass whose methods name every placement a
subsystem uses) applied to the bucket table instead of transformer
parameters.  Every ``PartitionSpec`` the sharded tick engine
(:mod:`gubernator_tpu.parallel.mesh_engine`) and the GLOBAL collectives
engine (:mod:`gubernator_tpu.parallel.global_mesh`) place data with is
minted HERE, so the two engines can never drift on what "sharded over
the table axis" or "one replica row per node" means, and a reviewer can
read the whole placement story in one file:

* :class:`ShardLayout` — the partitioned serving table.  The SoA bucket
  state is split over the 1-D ``('shard',)`` mesh by contiguous slot
  range (device *d* owns global slots ``[d*local_cap, (d+1)*local_cap)``);
  tick request/response traffic is *flat replicated* — one slot-sorted
  (19, B) matrix plus a ragged ``offsets`` vector broadcast to every
  shard, each shard walking only its own extent on device
  (ops.raggedtick) — while maintenance blocks (evict/install/restore/
  readback) keep the leading shard axis.
* :class:`NodeLayout` — the replicated GLOBAL table.  One replica row
  per node (``P('node', None)``), accumulator/aux matrices alongside,
  scalars replicated.

The ragged extent spec lives here too (:class:`RaggedExtents`): the
flat batch is sorted by GLOBAL slot and ownership is ``slot //
local_capacity`` — nothing else — so each shard's rows form one
contiguous extent and the host-side per-shard counts compress to a
cumulative offsets vector.  Every producer of that vector (the serving
dispatch, reshard's post-cutover dispatches, the tests' extent audits)
derives it from this ONE dataclass, so the host packer and the
on-device extent walker can never drift on where a shard's rows live.
"""

from __future__ import annotations

from dataclasses import dataclass

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.buckets import BucketState
from gubernator_tpu.ops.rowtable import RowState


@dataclass(frozen=True)
class ShardLayout:
    """Canonical PartitionSpecs for the slot-partitioned serving table
    (the ``('shard',)`` mesh of :func:`mesh_engine.make_mesh`)."""

    shard_axis: str = "shard"

    def table_spec(self, layout: str):
        """Spec tree for the bucket table in storage layout ``layout``:
        every column (or the row table's leading axis) splits over the
        shard axis by contiguous slot range."""
        if layout == "row":
            return RowState(table=P(self.shard_axis, None))
        return jax.tree.map(lambda _: P(self.shard_axis), BucketState.zeros(0))

    def blocked2(self) -> P:
        """(n_shards, W) host-blocked matrix: one row block per shard."""
        return P(self.shard_axis, None)

    def blocked3(self) -> P:
        """(n_shards, ROWS, W) host-blocked request/column matrix."""
        return P(self.shard_axis, None, None)

    def flat2(self) -> P:
        """(ROWS, B) flat slot-sorted request matrix — replicated to
        every shard; each device walks only its own ragged extent."""
        return P(None, None)

    def offsets1(self) -> P:
        """(n_shards + 1,) ragged extent offsets (RaggedExtents.offsets)
        — replicated; each shard reads its own ``[my, my + 1]`` pair."""
        return P(None)

    def scalar(self) -> P:
        """Replicated scalar (``now`` stamps, flags)."""
        return P()

    def shardings(self, mesh: Mesh, spec_tree):
        """NamedShardings for a spec tree (or a bare spec) on ``mesh``.
        PartitionSpec is a tuple subclass, so tree traversal must treat
        it as a leaf."""
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda s: isinstance(s, P),
        )


@dataclass(frozen=True)
class NodeLayout:
    """Canonical PartitionSpecs for the replicated GLOBAL table (the
    ``('node',)`` mesh of :func:`global_mesh.make_global_mesh`): one
    replica row per node, reconciled with psum collectives only —
    nothing in this layout ever materializes densely on the host."""

    node_axis: str = "node"

    def replica_spec(self):
        """Spec tree for the per-node replica rows of the GLOBAL bucket
        table: (n_nodes, capacity) per column."""
        return jax.tree.map(
            lambda _: P(self.node_axis, None), BucketState.zeros(0)
        )

    def mat3(self) -> P:
        """(n_nodes, ROWS, capacity) per-node matrix (aux/accumulators/
        request blocks)."""
        return P(self.node_axis, None, None)

    def scalar(self) -> P:
        return P()

    def shardings(self, mesh: Mesh, spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda s: isinstance(s, P),
        )


# ----------------------------------------------------------------------
# Ragged extents (the on-device tick's wire spec).  The flat request
# matrix carries GLOBAL slots in its slot row and is sorted by them;
# ownership is derived from the slot value alone, so shard s's rows are
# the contiguous extent [offsets[s], offsets[s+1]).
# ----------------------------------------------------------------------
@dataclass(frozen=True)
class RaggedExtents:
    """Host-side ragged extent spec for one (n_shards, local_capacity)
    layout: how a resolved batch's per-shard row counts become the
    ``(n_shards + 1,)`` cumulative offsets vector the extent walker
    (ops.raggedtick) consumes.

    The spec is layout-bearing state: ``MeshTickEngine`` swaps it
    atomically in ``_cutover`` alongside the mesh/ops/slotmaps, so a
    reshard recomputes every subsequent window's offsets against the
    NEW ``cap_to``-derived ownership — there is no residual width knob
    to re-derive (the old routed path's ``local_width``)."""

    n_shards: int
    local_capacity: int

    def counts(self, sh: np.ndarray, ok: np.ndarray) -> np.ndarray:
        """Per-shard live row counts of one resolved batch (``sh`` the
        per-request shard route, ``ok`` the live mask)."""
        if not ok.any():
            return np.zeros(self.n_shards, np.int64)
        return np.bincount(sh[ok], minlength=self.n_shards)

    def offsets(self, counts: np.ndarray) -> np.ndarray:
        """Cumulative extent offsets: shard s owns sorted lanes
        ``[offsets[s], offsets[s+1])``.  Valid because the packed batch
        sorts by GLOBAL slot (engine.sort_packed_by_slot) and global
        slots of shard s are exactly ``[s*cap, (s+1)*cap)`` — shards
        ascend with the sort, error/padding lanes (sentinel slot) sort
        past every extent."""
        off = np.zeros(self.n_shards + 1, np.int32)
        off[1:] = np.cumsum(counts)
        return off


# ----------------------------------------------------------------------
# Layout transitions (elastic resharding; docs/resharding.md).  THE one
# n→m transition spec: both the on-device all-to-all re-layout program
# and every host-side remap audit derive ownership from this dataclass,
# so the engine, the bench verifier, and the unit tests can never drift
# on where a live slot lands after a reshard.
# ----------------------------------------------------------------------
@dataclass(frozen=True)
class LayoutTransition:
    """One n→m re-partitioning of the slot space.

    Global slot identity is preserved across the transition: slot ``g``
    of the old layout is slot ``g`` of the new one — only the ownership
    boundaries move.  Under the contiguous-range rule (``ShardLayout``:
    shard ``d`` owns ``[d*cap, (d+1)*cap)``) the new owner of ``g`` is
    ``g // cap_to`` and its new local offset ``g % cap_to`` — the same
    single derivation :class:`RaggedExtents` applies to request slots,
    now applied to the table itself.

    ``live_slots`` is the number of slots carrying state (the old
    layout's total capacity on a first transition); ``cap_to`` is sized
    ``ceil(live_slots / n_to)`` so every live slot fits, and threading
    ``live_slots`` through chained transitions (:meth:`then`) makes
    n→m→n a round trip: 8→3→8 at cap 128 passes through cap 342 and
    lands back at exactly cap 128."""

    n_from: int
    cap_from: int
    n_to: int
    cap_to: int
    live_slots: int

    # -- ownership derivation (host/np + traced/jnp alike) -------------
    def owner_of(self, g):
        """New owning shard of global slot ``g`` (vector or scalar)."""
        return g // self.cap_to

    def local_of(self, g):
        """New local offset of global slot ``g`` (vector or scalar)."""
        return g % self.cap_to

    def old_owner_of(self, g):
        """Old owning shard of global slot ``g``."""
        return g // self.cap_from

    @property
    def capacity_to(self) -> int:
        return self.n_to * self.cap_to

    @property
    def capacity_from(self) -> int:
        return self.n_from * self.cap_from

    def then(self, n_next: int) -> "LayoutTransition":
        """Chain a follow-up transition, threading ``live_slots`` so
        round trips are exact (8→3→8 == identity)."""
        return plan_transition(
            self.n_to, self.cap_to, n_next, live_slots=self.live_slots
        )

    def remap(self) -> np.ndarray:
        """(live_slots, 3) host audit table: ``[new_shard, new_local,
        new_flat]`` per live global slot — new_flat is provably the
        identity (``owner*cap_to + local == g``), which is what makes
        the device all-to-all a pure re-partitioning of the flat slot
        axis."""
        g = np.arange(self.live_slots, dtype=np.int64)
        own = self.owner_of(g)
        loc = self.local_of(g)
        return np.stack([own, loc, own * self.cap_to + loc], axis=1)


def plan_transition(
    n_from: int, cap_from: int, n_to: int, live_slots: int = None
) -> LayoutTransition:
    """Mint the :class:`LayoutTransition` for an n→m reshard.

    ``live_slots`` defaults to the old layout's full capacity
    (``n_from * cap_from``); pass a carried value when chaining (see
    :meth:`LayoutTransition.then`)."""
    if n_from < 1 or n_to < 1:
        raise ValueError(
            f"shard counts must be >= 1; got {n_from}→{n_to}")
    if cap_from < 1:
        raise ValueError(f"cap_from must be >= 1; got {cap_from}")
    live = n_from * cap_from if live_slots is None else int(live_slots)
    if not 0 < live <= n_from * cap_from:
        raise ValueError(
            f"live_slots {live} outside (0, {n_from * cap_from}]")
    cap_to = -(-live // n_to)  # ceil: every live slot keeps a home
    return LayoutTransition(
        n_from=int(n_from), cap_from=int(cap_from),
        n_to=int(n_to), cap_to=int(cap_to), live_slots=live,
    )


def relayout_block(x: jnp.ndarray, my: jnp.ndarray,
                   tr: LayoutTransition) -> jnp.ndarray:
    """Device-side half of the transition all-to-all (traced; runs per
    OLD shard inside a ``shard_map``).

    ``x`` is this shard's ``(cap_from, ...)`` slice of one table array
    (guard rows already stripped by the caller).  Each row's target
    placement in the NEW layout is derived from its global slot alone —
    ``slot // cap_to`` picks the new owner, ``slot % cap_to`` the new
    local offset — mirroring :class:`RaggedExtents`'s ownership rule.  The
    scatter lands rows in a zeroed ``(n_to * cap_to, ...)`` buffer;
    summing the per-shard buffers over the shard axis (one ``psum``,
    the caller's half) completes the exchange, because live slot ranges
    are disjoint across old shards."""
    g = my.astype(jnp.int64) * tr.cap_from + jnp.arange(
        tr.cap_from, dtype=jnp.int64
    )
    tgt = tr.owner_of(g) * tr.cap_to + tr.local_of(g)
    buf = jnp.zeros((tr.capacity_to,) + x.shape[1:], x.dtype)
    return buf.at[tgt].set(x, mode="drop")
