"""Canonical PartitionSpec layouts for the sharded serving path.

One frozen spec-helper per mesh axis family — the SNIPPETS [3] idiom
(a ``SpecLayout`` dataclass whose methods name every placement a
subsystem uses) applied to the bucket table instead of transformer
parameters.  Every ``PartitionSpec`` the sharded tick engine
(:mod:`gubernator_tpu.parallel.mesh_engine`) and the GLOBAL collectives
engine (:mod:`gubernator_tpu.parallel.global_mesh`) place data with is
minted HERE, so the two engines can never drift on what "sharded over
the table axis" or "one replica row per node" means, and a reviewer can
read the whole placement story in one file:

* :class:`ShardLayout` — the partitioned serving table.  The SoA bucket
  state is split over the 1-D ``('shard',)`` mesh by contiguous slot
  range (device *d* owns global slots ``[d*local_cap, (d+1)*local_cap)``);
  request/response blocks are either *blocked* (leading shard axis, the
  host-routed legacy format) or *flat replicated* (the device-routed
  format — one (19, B) matrix broadcast to every shard, each shard
  compacting its own rows on device).
* :class:`NodeLayout` — the replicated GLOBAL table.  One replica row
  per node (``P('node', None)``), accumulator/aux matrices alongside,
  scalars replicated.

The device-side routing kernels live here too (:func:`route_block`,
:func:`scatter_flat`): they are pure functions of the replicated flat
request matrix and the shard index, shared by every routed program the
mesh engine builds, and their contract (global-slot ownership derived
as ``slot // local_capacity`` — nothing else) IS the on-device routing
design: the host never regroups requests per shard, and the response
fan-in is one ``psum``.
"""

from __future__ import annotations

from dataclasses import dataclass

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.buckets import BucketState
from gubernator_tpu.ops.engine import REQ32_INDEX
from gubernator_tpu.ops.rowtable import RowState


@dataclass(frozen=True)
class ShardLayout:
    """Canonical PartitionSpecs for the slot-partitioned serving table
    (the ``('shard',)`` mesh of :func:`mesh_engine.make_mesh`)."""

    shard_axis: str = "shard"

    def table_spec(self, layout: str):
        """Spec tree for the bucket table in storage layout ``layout``:
        every column (or the row table's leading axis) splits over the
        shard axis by contiguous slot range."""
        if layout == "row":
            return RowState(table=P(self.shard_axis, None))
        return jax.tree.map(lambda _: P(self.shard_axis), BucketState.zeros(0))

    def blocked2(self) -> P:
        """(n_shards, W) host-blocked matrix: one row block per shard."""
        return P(self.shard_axis, None)

    def blocked3(self) -> P:
        """(n_shards, ROWS, W) host-blocked request/column matrix."""
        return P(self.shard_axis, None, None)

    def flat2(self) -> P:
        """(ROWS, B) device-routed flat request matrix — replicated to
        every shard; each device compacts its own rows on device."""
        return P(None, None)

    def scalar(self) -> P:
        """Replicated scalar (``now`` stamps, flags)."""
        return P()

    def shardings(self, mesh: Mesh, spec_tree):
        """NamedShardings for a spec tree (or a bare spec) on ``mesh``.
        PartitionSpec is a tuple subclass, so tree traversal must treat
        it as a leaf."""
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda s: isinstance(s, P),
        )


@dataclass(frozen=True)
class NodeLayout:
    """Canonical PartitionSpecs for the replicated GLOBAL table (the
    ``('node',)`` mesh of :func:`global_mesh.make_global_mesh`): one
    replica row per node, reconciled with psum collectives only —
    nothing in this layout ever materializes densely on the host."""

    node_axis: str = "node"

    def replica_spec(self):
        """Spec tree for the per-node replica rows of the GLOBAL bucket
        table: (n_nodes, capacity) per column."""
        return jax.tree.map(
            lambda _: P(self.node_axis, None), BucketState.zeros(0)
        )

    def mat3(self) -> P:
        """(n_nodes, ROWS, capacity) per-node matrix (aux/accumulators/
        request blocks)."""
        return P(self.node_axis, None, None)

    def scalar(self) -> P:
        return P()

    def shardings(self, mesh: Mesh, spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_tree,
            is_leaf=lambda s: isinstance(s, P),
        )


# ----------------------------------------------------------------------
# Device-side routing (traced; called inside the mesh engine's shard_map
# programs).  The flat request matrix carries GLOBAL slots in its slot
# row; ownership is derived from the slot value alone.
# ----------------------------------------------------------------------
def route_block(m: jnp.ndarray, my: jnp.ndarray, local_capacity: int,
                local_width: int):
    """Compact this shard's rows out of the replicated flat batch.

    ``m`` is the (REQ32_ROWS, B) compact request matrix, slot row
    carrying GLOBAL slots (padding/error lanes carry the global
    capacity sentinel and belong to no shard).  Returns ``(blk, src)``:

    * ``blk`` — the shard's (REQ32_ROWS, local_width) LOCAL request
      block: slot row rebased to ``[0, local_capacity)``, guard-padded
      (slot = local_capacity, valid = 0) past this shard's row count.
      Host-side slot-sorted order is preserved by the stable compaction,
      so the per-shard sorted-input tick contract holds for free.
    * ``src`` — the (local_width,) response scatter map: local lane p's
      response belongs at flat lane ``src[p]``; unfilled lanes aim one
      past the batch and drop.

    The host guarantees per-shard row counts fit ``local_width`` (it
    knows the counts before dispatch and falls back to the blocked
    format otherwise), so the compaction never truncates live rows.
    """
    R = REQ32_INDEX
    slot_g = m[R["slot"]]
    valid = m[R["valid"]] != 0
    b = slot_g.shape[0]
    lo = my.astype(slot_g.dtype) * local_capacity
    mine = valid & (slot_g >= lo) & (slot_g < lo + local_capacity)
    pos = jnp.cumsum(mine.astype(jnp.int32)) - 1
    tgt = jnp.where(mine, pos, local_width)
    local = m.at[R["slot"]].set(
        jnp.where(mine, slot_g - lo, local_capacity).astype(m.dtype)
    )
    local = local.at[R["valid"]].set(mine.astype(m.dtype))
    blk = jnp.zeros((m.shape[0], local_width), m.dtype)
    blk = blk.at[R["slot"]].set(local_capacity)
    blk = blk.at[:, tgt].set(local, mode="drop")
    src = jnp.full(local_width, b, jnp.int32).at[tgt].set(
        jnp.arange(b, dtype=jnp.int32), mode="drop"
    )
    return blk, src


def scatter_flat(resp: jnp.ndarray, src: jnp.ndarray, b: int) -> jnp.ndarray:
    """Scatter a shard's (ROWS, local_width) response block to its flat
    lanes: the per-shard half of the collective response gather (the
    cross-shard half is one ``psum`` — rows no shard owns stay zero)."""
    out = jnp.zeros(resp.shape[:-1] + (b,), resp.dtype)
    if resp.ndim == 1:
        return out.at[src].set(resp, mode="drop")
    return out.at[:, src].set(resp, mode="drop")


# ----------------------------------------------------------------------
# Layout transitions (elastic resharding; docs/resharding.md).  THE one
# n→m transition spec: both the on-device all-to-all re-layout program
# and every host-side remap audit derive ownership from this dataclass,
# so the engine, the bench verifier, and the unit tests can never drift
# on where a live slot lands after a reshard.
# ----------------------------------------------------------------------
@dataclass(frozen=True)
class LayoutTransition:
    """One n→m re-partitioning of the slot space.

    Global slot identity is preserved across the transition: slot ``g``
    of the old layout is slot ``g`` of the new one — only the ownership
    boundaries move.  Under the contiguous-range rule (``ShardLayout``:
    shard ``d`` owns ``[d*cap, (d+1)*cap)``) the new owner of ``g`` is
    ``g // cap_to`` and its new local offset ``g % cap_to`` — the same
    single derivation :func:`route_block` applies to request slots, now
    applied to the table itself.

    ``live_slots`` is the number of slots carrying state (the old
    layout's total capacity on a first transition); ``cap_to`` is sized
    ``ceil(live_slots / n_to)`` so every live slot fits, and threading
    ``live_slots`` through chained transitions (:meth:`then`) makes
    n→m→n a round trip: 8→3→8 at cap 128 passes through cap 342 and
    lands back at exactly cap 128."""

    n_from: int
    cap_from: int
    n_to: int
    cap_to: int
    live_slots: int

    # -- ownership derivation (host/np + traced/jnp alike) -------------
    def owner_of(self, g):
        """New owning shard of global slot ``g`` (vector or scalar)."""
        return g // self.cap_to

    def local_of(self, g):
        """New local offset of global slot ``g`` (vector or scalar)."""
        return g % self.cap_to

    def old_owner_of(self, g):
        """Old owning shard of global slot ``g``."""
        return g // self.cap_from

    @property
    def capacity_to(self) -> int:
        return self.n_to * self.cap_to

    @property
    def capacity_from(self) -> int:
        return self.n_from * self.cap_from

    def then(self, n_next: int) -> "LayoutTransition":
        """Chain a follow-up transition, threading ``live_slots`` so
        round trips are exact (8→3→8 == identity)."""
        return plan_transition(
            self.n_to, self.cap_to, n_next, live_slots=self.live_slots
        )

    def remap(self) -> np.ndarray:
        """(live_slots, 3) host audit table: ``[new_shard, new_local,
        new_flat]`` per live global slot — new_flat is provably the
        identity (``owner*cap_to + local == g``), which is what makes
        the device all-to-all a pure re-partitioning of the flat slot
        axis."""
        g = np.arange(self.live_slots, dtype=np.int64)
        own = self.owner_of(g)
        loc = self.local_of(g)
        return np.stack([own, loc, own * self.cap_to + loc], axis=1)


def plan_transition(
    n_from: int, cap_from: int, n_to: int, live_slots: int = None
) -> LayoutTransition:
    """Mint the :class:`LayoutTransition` for an n→m reshard.

    ``live_slots`` defaults to the old layout's full capacity
    (``n_from * cap_from``); pass a carried value when chaining (see
    :meth:`LayoutTransition.then`)."""
    if n_from < 1 or n_to < 1:
        raise ValueError(
            f"shard counts must be >= 1; got {n_from}→{n_to}")
    if cap_from < 1:
        raise ValueError(f"cap_from must be >= 1; got {cap_from}")
    live = n_from * cap_from if live_slots is None else int(live_slots)
    if not 0 < live <= n_from * cap_from:
        raise ValueError(
            f"live_slots {live} outside (0, {n_from * cap_from}]")
    cap_to = -(-live // n_to)  # ceil: every live slot keeps a home
    return LayoutTransition(
        n_from=int(n_from), cap_from=int(cap_from),
        n_to=int(n_to), cap_to=int(cap_to), live_slots=live,
    )


def relayout_block(x: jnp.ndarray, my: jnp.ndarray,
                   tr: LayoutTransition) -> jnp.ndarray:
    """Device-side half of the transition all-to-all (traced; runs per
    OLD shard inside a ``shard_map``).

    ``x`` is this shard's ``(cap_from, ...)`` slice of one table array
    (guard rows already stripped by the caller).  Each row's target
    placement in the NEW layout is derived from its global slot alone —
    ``slot // cap_to`` picks the new owner, ``slot % cap_to`` the new
    local offset — mirroring :func:`route_block`'s ownership rule.  The
    scatter lands rows in a zeroed ``(n_to * cap_to, ...)`` buffer;
    summing the per-shard buffers over the shard axis (one ``psum``,
    the caller's half) completes the exchange, because live slot ranges
    are disjoint across old shards."""
    g = my.astype(jnp.int64) * tr.cap_from + jnp.arange(
        tr.cap_from, dtype=jnp.int64
    )
    tgt = tr.owner_of(g) * tr.cap_to + tr.local_of(g)
    buf = jnp.zeros((tr.capacity_to,) + x.shape[1:], x.dtype)
    return buf.at[tgt].set(x, mode="drop")
