"""Multi-chip sharded tick engine: the bucket table over a TPU mesh.

The reference scales *within* a node by statically partitioning the key
space over N lock-free workers (``workers.go:19-37,125-147``) and *across*
nodes by consistent-hash ownership.  On TPU the intra-node story becomes a
table **sharded over the device mesh**: a 1-D ``Mesh(('shard',))`` where
device *d* owns the contiguous slot range ``[d*local_cap, (d+1)*local_cap)``.

The hot path is deliberately collective-free: the host resolves each key to
a global slot, routes it to the owning shard, and packs one request block
per shard — ``(n_shards, ROWS, B)`` — so a tick under ``shard_map`` is pure
data-parallel SPMD: every device gathers/updates only its own shard.  This
mirrors the reference's "no mutexes, keys statically routed to workers"
design, with devices in place of goroutines.  Collectives (``psum`` etc.)
enter only on the GLOBAL-behavior reconciliation path (the GLOBAL mesh
engine), matching how the reference keeps its hot loop local and
reconciles asynchronously (``global.go``).

Maintenance operations — evict, install, restore, readback — run as
per-shard blocked ``shard_map``s: the host builds one block per shard
(padding rows aim at the shard's local guard/sentinel) and each device
applies its block to its own slice.  Because the blocks reuse the
single-chip ops (`make_tick_fn` etc.) per shard, the mesh engine
supports BOTH table layouts: the int32-column SoA and the Pallas
row-DMA layout (rowtable.py) — the row layout's ~6-8x tick speedup is not
forfeited by going multi-chip.

**Ragged on-device dispatch (the only tick wire format).**  Keys are
strings, so hashing and the key→slot map stay host-side (SURVEY.md §7
"Host/device split") — but everything else is gone from the host: the
tick ships ONE flat slot-sorted (19, B) compact matrix carrying GLOBAL
slots plus a ``(n_shards + 1,)`` cumulative offsets vector
(:class:`partition.RaggedExtents` — the host already knows the
per-shard counts from the resolve), and each device walks only its own
``[offsets[my], offsets[my+1])`` extent of the flat matrix
(ops.raggedtick): no per-shard compaction into a padded
``local_width`` block, no skew fallback, one fixed-shape program per
batch capacity.  The flat batch sorts by GLOBAL slot and ownership is
``slot // local_capacity``, so each shard's rows are contiguous by
construction; responses merge into zeroed flat lanes per shard and
gather collectively with one exact ``psum``.  Adversarially skewed
windows (every key on one shard) run MORE ITERATIONS of the same
compiled extent walk — ``metric_routed_overflows`` stays wired as a
pinned-zero canary.  The upload reuses the single-chip engine's
staging-ring/async-H2D pipeline (ops.engine.StagingRing, single slab
shape) so window N+1's transfer rides under window N's tick.  All
PartitionSpecs come from :mod:`gubernator_tpu.parallel.partition`, the
canonical spec helper both mesh engines share.
"""

from __future__ import annotations

import threading
import time
import warnings
import zlib
from typing import Dict, List, Optional, Sequence

import collections
import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gubernator_tpu.utils.jaxcompat import shard_map

from gubernator_tpu.ops import rowtable
from gubernator_tpu.ops.buckets import BucketState, slice_field
from gubernator_tpu.ops.engine import (
    EVICT_CHUNK,
    ITEM_INT_ROWS,
    READBACK_ROWS,
    REQ32_INDEX,
    REQ32_ROWS,
    RESTORE_CHUNK,
    StagingRing,
    device_dead_mask,
    items_from_columns,
    make_evict_fn,
    make_install_fn,
    make_layout_choice,
    make_readback_fn,
    make_restore_fn,
    make_tick_fn,
    masked_over_limit,
    pack_cols_req32,
    pack_wide_rows,
    pad_pow2,
    select_reclaim_victims,
    sort_packed_by_slot,
    unpack_resp_compact,
)
from gubernator_tpu.ops.raggedtick import (
    choose_tile,
    make_fused_ragged_tick_fn,
    ragged_walk,
)
from gubernator_tpu.parallel.partition import (
    LayoutTransition,
    RaggedExtents,
    ShardLayout,
    plan_transition,
    relayout_block,
)
from gubernator_tpu.ops.rowtable import ROW_W, RowState
from gubernator_tpu.types import (
    Behavior, GlobalUpdate, RateLimitRequest, RateLimitResponse)
from gubernator_tpu.utils import flightrec, timeutil, tracing
from gubernator_tpu.utils.hotpath import hot_path
from gubernator_tpu.utils import sanitize


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D device mesh over the 'shard' axis (the slot-partition axis)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("shard",))


_LOCAL_WIDTH_WARNED = False


def _warn_local_width_deprecated() -> None:
    """One-time (per process) deprecation warning for the dead
    ``local_width`` / ``GUBER_MESH_LOCAL_WIDTH`` knob: the ragged
    extent walk has no per-shard width to bound, so the value is
    ignored.  The ENV_REGISTRY entry stays until removal (G004)."""
    global _LOCAL_WIDTH_WARNED
    if _LOCAL_WIDTH_WARNED:
        return
    _LOCAL_WIDTH_WARNED = True
    warnings.warn(
        "GUBER_MESH_LOCAL_WIDTH / MeshTickEngine(local_width=...) is "
        "deprecated and ignored: the ragged tick dispatch walks each "
        "shard's extent directly and has no per-shard width limit",
        DeprecationWarning,
        stacklevel=3,
    )


class ShardedOps:
    """The per-shard device ops for one (mesh, local_capacity, layout):
    tick/evict/install/restore/readback, each a shard_map of the
    corresponding single-chip op, jitted with state donation.  Ticks use
    ONE wire format — the ragged flat (19, B) + offsets dispatch (module
    docstring) — in two programs: the merge-capable x64 extent walker
    for duplicate-bearing windows and the duplicate-free parts program
    (the fused Pallas ragged kernel on the row layout).

    ``trace_counts`` increments once per TRACE of each program (the
    counter bump runs at trace time only): serving re-dispatch must hit
    the warmed executables, and tests pin the counts so a signature
    drift between warmup and serving (e.g. a committed ``device_put``
    where warmup used ``jnp.asarray``) fails loudly instead of silently
    re-tracing per tick — with the ragged wire there is exactly one
    program per batch capacity, so ANY skew- or width-driven growth of
    these counters is a regression."""

    def __init__(self, mesh: Mesh, local_capacity: int, layout: str):
        self.mesh = mesh
        self.layout = layout
        self.local_capacity = local_capacity
        n = mesh.devices.size
        self.trace_counts = collections.Counter()
        lay = ShardLayout()
        self.spec_layout = lay

        if layout == "row":
            # Each shard's block is its own (local_cap+1, ROW_W) row table
            # — per-shard guard rows included, so local slot arithmetic
            # inside the block is identical to the single-chip engine's.
            def zeros_global():
                return RowState(
                    table=jnp.zeros((n * (local_capacity + 1), ROW_W), jnp.int32)
                )
        else:
            def zeros_global():
                return BucketState.zeros(n * local_capacity)

        state_spec = lay.table_spec(layout)
        self.state_spec = state_spec
        self.state_shardings = lay.shardings(mesh, state_spec)
        self.zeros_global = zeros_global
        self.block_sharding2 = lay.shardings(mesh, lay.blocked2())
        self.block_sharding3 = lay.shardings(mesh, lay.blocked3())

        # Compact int32 wire formats (engine.REQ32 / pack_resp_compact):
        # per-shard request blocks cross host->devices at 76 B/request and
        # responses return at 24 — the same transfer win the single-chip
        # engine gets, per PCIe lane on real multi-chip hosts.
        tick = make_tick_fn(
            local_capacity, layout=layout, compact_req=True, compact_resp=True
        )
        evict = make_evict_fn(layout)
        install = make_install_fn(layout)
        restore = make_restore_fn(layout)
        readback = make_readback_fn(layout)

        def smap(fn, in_specs, out_specs):
            return jax.jit(
                shard_map(
                    fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_vma=False,
                ),
                donate_argnums=(0,),
            )

        # ---- Ragged flat tick programs (module docstring): one
        # replicated slot-sorted (19, B) batch plus the (n_shards + 1,)
        # extent offsets in; each shard walks only its own
        # [offsets[my], offsets[my+1]) extent of the flat matrix
        # (ops.raggedtick) and the responses gather with one psum.
        n_shards = n

        def _extent(offsets, my):
            start = offsets[my]
            count = offsets[my + 1] - start
            lo = my.astype(jnp.int32) * local_capacity
            return start, count, lo

        def _tick_ragged(state_blk, m, offsets, now):
            self.trace_counts["tick_ragged"] += 1
            my = lax.axis_index("shard")
            start, count, lo = _extent(offsets, my)
            st, out = ragged_walk(
                lambda s_, blk: tick(s_, blk, now),
                state_blk, m, start, count, lo, local_capacity,
                choose_tile(m.shape[1], n_shards),
                jnp.zeros((6, m.shape[1]), jnp.int32),
            )
            return st, lax.psum(out, "shard")

        flat_in = (state_spec, lay.flat2(), lay.offsets1(), lay.scalar())
        self.tick_ragged = jax.jit(
            shard_map(
                _tick_ragged, mesh=mesh, in_specs=flat_in,
                out_specs=(state_spec, lay.flat2()), check_vma=False,
            ),
            donate_argnums=(0,),
        )

        # The parts-native program for duplicate-free windows (the
        # production common case): host-dispatched as its OWN program —
        # not a traced lax.cond next to the x64 tick — so the row layout
        # keeps the fused Mosaic kernel per shard (Mosaic refuses x64
        # traces; tick32 module doc).  The fused ragged kernel walks the
        # extent inside the kernel (runtime chunk count); the unfused
        # variant tiles it with ragged_walk and returns its six response
        # rows unstacked (CPU concat-fusion pathology), stack6_ragged
        # reassembling the (6, B) matrix in its own program.
        from gubernator_tpu.ops.tick32 import (
            _resolve_fused, make_tick32_rows_fn)

        self._fused32 = layout == "row" and _resolve_fused(None)
        if self._fused32:
            fused_ragged = make_fused_ragged_tick_fn(local_capacity)

            def _tick32_ragged(state_blk, m, offsets, now):
                self.trace_counts["tick_unique_ragged"] += 1
                my = lax.axis_index("shard")
                start, count, lo = _extent(offsets, my)
                st, resp = fused_ragged(
                    state_blk, m, start, count, lo, now)
                return st, lax.psum(resp, "shard")

            self.tick_unique_ragged = jax.jit(
                shard_map(
                    _tick32_ragged, mesh=mesh, in_specs=flat_in,
                    out_specs=(state_spec, lay.flat2()), check_vma=False,
                ),
                donate_argnums=(0,),
            )
            self.stack6_ragged = None
        else:
            tick32_rows = make_tick32_rows_fn(local_capacity, layout)

            def _tick32_ragged(state_blk, m, offsets, now):
                self.trace_counts["tick_unique_ragged"] += 1
                my = lax.axis_index("shard")
                start, count, lo = _extent(offsets, my)
                b = m.shape[1]

                def tile_tick(s_, blk):
                    s2, rows = tick32_rows(s_, blk, now)
                    return s2, tuple(rows)

                st, rows = ragged_walk(
                    tile_tick, state_blk, m, start, count, lo,
                    local_capacity, choose_tile(b, n_shards),
                    tuple(jnp.zeros(b, jnp.int32) for _ in range(6)),
                )
                return st, tuple(lax.psum(r, "shard") for r in rows)

            self.tick_unique_ragged = jax.jit(
                shard_map(
                    _tick32_ragged, mesh=mesh, in_specs=flat_in,
                    out_specs=(
                        state_spec, tuple(P(None) for _ in range(6))),
                    check_vma=False,
                ),
                donate_argnums=(0,),
            )
            # Second-program stack, same as the single-chip engine
            # (stacking the six rows inside the tick hits the CPU
            # concat-fusion pathology; see make_tick32_rows_fn).
            self.stack6_ragged = jax.jit(lambda rows: jnp.stack(rows, axis=0))

        def _evict(state_blk, slots_blk):
            return evict(state_blk, slots_blk[0])

        self.evict = smap(
            _evict, (state_spec, P("shard", None)), state_spec
        )

        def _install(state_blk, cols_blk, now):
            return install(state_blk, cols_blk[0], now)

        self.install = smap(
            _install, (state_spec, P("shard", None, None), P()), state_spec
        )

        def _restore(state_blk, ints_blk, floats_blk):
            return restore(state_blk, ints_blk[0], floats_blk[0])

        self.restore = smap(
            _restore,
            (state_spec, P("shard", None, None), P("shard", None)),
            state_spec,
        )

        def _readback(state_blk, slots_blk):
            ints, floats = readback(state_blk, slots_blk[0])
            return ints[None], floats[None]

        # No donation: readback is a pure gather.
        self.readback = jax.jit(
            shard_map(
                _readback,
                mesh=mesh,
                in_specs=(state_spec, P("shard", None)),
                out_specs=(P("shard", None, None), P("shard", None)),
                check_vma=False,
            )
        )

    def init_state(self):
        return jax.tree.map(
            lambda a, sh: jax.device_put(a, sh),
            self.zeros_global(),
            self.state_shardings,
        )

    def run_tick_ragged_unique(self, state, m_dev, offsets_dev, now):
        """Dispatch the duplicate-free ragged tick; returns the flat
        (6, B) response whichever internal format the backend uses."""
        state, out = self.tick_unique_ragged(state, m_dev, offsets_dev, now)
        if self.stack6_ragged is not None:
            out = self.stack6_ragged(out)
        return state, out

    def put2(self, blk: np.ndarray):
        return jax.device_put(blk, self.block_sharding2)

    def put3(self, blk: np.ndarray):
        return jax.device_put(blk, self.block_sharding3)


class MeshRaggedTickHandle:
    """One dispatched ragged mesh tick: the flat (6, B) compact
    response is already in slot-sorted request-batch order (the shards'
    extent walks merged every lane in place; the psum gather summed the
    disjoint extents), so resolution is exactly the single-chip
    ``TickHandle`` contract — un-permute, rebuild the public (5, n)
    int64 matrix, run the deferred bookkeeping.  Duck-compatible with
    ``ops.engine.resolve_ticks`` (same-shape responses stack into one
    D2H)."""

    __slots__ = ("_engine", "_resp", "_n", "_inv", "errors", "_limit_req",
                 "_wt_args", "_done", "_flock")

    def __init__(self, engine, resp, n, inv, errors, limit_req, wt_args):
        self._engine = engine
        self._resp = resp
        self._n = n
        self._inv = inv
        self.errors = errors
        # Copied: callers may reuse their ReqColumns buffers between
        # submit and resolve (the pipelining pattern).
        # guber: allow-G001(host column snapshot - limit_req is a host array; the copy is the pipelining contract, not a device sync)
        self._limit_req = np.array(limit_req[:n], np.int64, copy=True)
        self._wt_args = wt_args
        self._done: Optional[np.ndarray] = None
        self._flock = sanitize.lock("MeshRaggedTickHandle._flock")

    def _finish(self, raw: np.ndarray) -> None:
        with self._flock:
            if self._done is not None:
                return
            rm = unpack_resp_compact(
                raw[:, : self._n][:, self._inv], self._limit_req
            )
            eng = self._engine
            with eng._lock:
                # This window is resolved: it no longer holds its H2D
                # staging slab, and later windows' uploads stop counting
                # it as overlap (metric_h2d_overlapped).
                eng._inflight = max(0, eng._inflight - 1)
                eng.metric_over_limit += masked_over_limit(rm, self.errors)
                if eng.store is not None and self._wt_args is not None:
                    eng._write_through(*self._wt_args)
            self._resp = None  # release the device buffer reference
            self._done = rm

    def result(self):
        if self._done is None:
            self._finish(np.asarray(self._resp))
        return self._done, self.errors


class MeshTickEngine:
    """Host driver for the sharded table (multi-chip WorkerPool analog).

    Same contract as :class:`gubernator_tpu.ops.engine.TickEngine` — row or
    column layout, optional Store write/read-through — but the table lives
    sharded across ``mesh``; total capacity is ``n_shards * local_capacity``.
    Key→shard routing reuses the engine's slot allocator: global slot ``g``
    lives on shard ``g // local_capacity`` at local offset
    ``g % local_capacity`` — the ONE ownership rule, derived identically by
    the host resolve and the on-device extent walker
    (partition.RaggedExtents / ops.raggedtick).

    Every tick ships the ragged flat wire format (module docstring);
    ``routing`` survives as a knob accepting ``"auto"``/``"device"``
    only — the legacy ``"host"`` blocked path is gone.  ``local_width``
    is dead (the ragged walk has no per-shard width to bound): a
    non-zero value warns once and is otherwise ignored.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        local_capacity: int = 1 << 14,
        max_batch: int = 1024,
        store=None,
        table_layout: str = "auto",
        routing: str = "auto",
        local_width: int = 0,
    ):
        from gubernator_tpu.config import env_knob
        from gubernator_tpu.ops.engine import make_slot_map

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        self.local_capacity = int(local_capacity)
        self.capacity = self.n_shards * self.local_capacity
        if self.capacity >= (1 << 31):
            # The flat wire format carries GLOBAL slots in an int32 row.
            raise ValueError(
                f"sharded table capacity {self.capacity} exceeds int32 "
                "global slots"
            )
        self.max_batch = int(max_batch)
        self.store = store
        if routing not in ("auto", "device"):
            raise ValueError(
                f"unknown mesh routing {routing!r} (the legacy 'host' "
                "blocked path was removed; the ragged device dispatch "
                "serves every window)")
        self.routing = "device"
        # As-configured layout knob, kept verbatim so reshard() can
        # re-derive the auto choice (layout fit) for the new shard
        # count instead of freezing this build's resolution.
        self._table_layout_conf = table_layout
        if int(local_width):
            _warn_local_width_deprecated()
        self.layout = make_layout_choice(
            table_layout, self.local_capacity,
            self.mesh.devices.flat[0], self.max_batch,
        )
        self.ragged = RaggedExtents(self.n_shards, self.local_capacity)
        self.ops = ShardedOps(self.mesh, self.local_capacity, self.layout)
        self.state = self.ops.init_state()
        # One slot allocator per shard; keys are routed to shards by hash,
        # the mesh analog of the reference's hash-range→worker routing
        # (workers.go:180-184).
        self.slots = [make_slot_map(self.local_capacity) for _ in range(self.n_shards)]
        self._last_access = np.zeros(self.capacity, np.int64)
        # Global slots assigned host-side but not yet written by a device
        # tick; device in_use/expire_at lag for these, so reclamation must
        # not treat them as dead (see TickEngine._pending).
        self._pending: set = set()
        self._tick_count = 0
        self._lock = sanitize.rlock("MeshTickEngine._lock")
        # Flat-upload staging ring + overlap telemetry (the PR 6
        # double-buffered H2D pipeline, shared via ops.engine.StagingRing;
        # sentinel is the GLOBAL capacity — flat padding lanes belong to
        # no shard).  The ragged wire has exactly ONE slab shape
        # (rows × max_batch), so the ring preallocates it up front.
        try:
            _depth = max(1, env_knob(
                "GUBER_TICK_PIPELINE_DEPTH", 4, parse=int))
        except ValueError:
            _depth = 4
        self._staging_slabs = 2 * _depth + 1
        self._staging = StagingRing(
            REQ32_ROWS, self.capacity, self._staging_slabs,
            width=self.max_batch)
        self._inflight = 0
        self.metric_h2d_windows = 0
        self.metric_h2d_overlapped = 0
        self.metric_routed_windows = 0
        self.metric_routed_overflows = 0
        self.metric_hits = 0
        self.metric_misses = 0
        self.metric_over_limit = 0
        self.metric_unexpired_evictions = 0
        self._warmup()

    def _warmup(self) -> None:
        """Compile the serving-path programs at startup (see
        TickEngine._warmup): both ragged ticks — the merge-capable x64
        extent walker and the duplicate-free parts program — with an
        all-sentinel batch and empty extents (offsets all zero: the
        walkers' dynamic trip counts are runtime values, so the empty
        window compiles the same single program serving traffic uses).
        Warmup MUST dispatch with the exact serving signature:
        ``jnp.asarray`` uploads (uncommitted) for the matrix AND the
        offsets vector, never a committed ``device_put`` — a committed
        sharding is a new jit signature that re-traces every warmed
        program (~0.6 s each; the ShardedOps.trace_counts pin in
        test_mesh_engine holds this)."""
        if jax.default_backend() == "tpu":
            # Eager tick compiles are a serving chip's live-deadline
            # concern (see TickEngine._warmup): on the CPU backend
            # (tests, the fast CI gate) each shard_map trace costs
            # seconds per engine and most tests tick only one of the
            # two programs — lazy is the right trade.
            m = np.zeros((REQ32_ROWS, self.max_batch), np.int32)
            m[REQ32_INDEX["slot"]] = self.capacity
            offs = self.ragged.offsets(np.zeros(self.n_shards, np.int64))
            self.state, resp = self.ops.tick_ragged(
                self.state, jnp.asarray(m), jnp.asarray(offs), jnp.int64(0)
            )
            # guber: allow-G001(init-time warmup D2H - deliberately materializes once at engine construction to pre-compile; never inside a serving tick)
            np.asarray(resp)  # warm the response D2H path
            self.state, resp = self.ops.run_tick_ragged_unique(
                self.state, jnp.asarray(m), jnp.asarray(offs), jnp.int64(0)
            )
            # guber: allow-G001(init-time warmup D2H - same as above)
            np.asarray(resp)
        cols = np.zeros((self.n_shards, 8, 1), np.int64)  # valid=0: no-op
        self.state = self.ops.install(
            self.state, self.ops.put3(cols), jnp.int64(0)
        )
        # Pre-compile the per-shard reclaim dead-scan (see TickEngine).
        self._shard_dead_mask(0, 0)
        # guber: allow-G001(init-time warmup barrier - construction completes only when the device programs are resident)
        jax.block_until_ready(self.state)

    def h2d_overlap_ratio(self) -> float:
        """Fraction of serving windows whose request upload overlapped an
        earlier window's still-running tick (see TickEngine)."""
        return self.metric_h2d_overlapped / max(1, self.metric_h2d_windows)

    # ------------------------------------------------------------------
    # Shard routing / reclamation
    # ------------------------------------------------------------------
    def _shard_of(self, key: str) -> int:
        return zlib.crc32(key.encode()) % self.n_shards

    def _shard_dead_mask(self, shard: int, now: int) -> np.ndarray:
        """Device-dead mask for one shard's slice of the table."""
        if self.layout == "row":
            lo = shard * (self.local_capacity + 1)
            return rowtable.row_device_dead_mask(
                RowState(table=self.state.table[lo : lo + self.local_capacity + 1]),
                now, self.local_capacity,
            )
        sl = slice(shard * self.local_capacity, (shard + 1) * self.local_capacity)
        return device_dead_mask(
            self.state.in_use[sl], slice_field(self.state.expire_at, sl),
            now, self.local_capacity,
        )

    def _resolve(self, key: str, shard: int, now: int) -> tuple[Optional[int], bool]:
        """(global slot, known) for key within its shard, reclaiming if
        needed; slot is None when the shard is full of same-tick live slots
        (caller spills the request to the next tick)."""
        sm = self.slots[shard]
        known = sm.get(key) is not None
        local = sm.assign(key)
        if local is None:
            self._reclaim(shard, now)
            known = sm.get(key) is not None  # reclaim may release the key
            local = sm.assign(key)
            if local is None:
                return None, known
        g = shard * self.local_capacity + local
        if not known:
            self._pending.add(g)
        self._last_access[g] = self._tick_count
        return g, known

    def _reclaim(self, shard: int, now: int) -> None:
        """Free expired slots in one shard; fall back to LRU eviction —
        the shared TTL/LRU policy (engine.select_reclaim_victims) over this
        shard's slice of the table."""
        sm = self.slots[shard]
        lo = shard * self.local_capacity
        mapped = sm.mapped_mask()
        if self._pending:
            pend = [g - lo for g in self._pending if lo <= g < lo + self.local_capacity]
            if pend:
                # guber: allow-G001(host index build over a small python set - no device data; reclaim runs at most once per full shard, not per tick)
                mapped[np.asarray(pend, np.int64)] = False
        freed, victims = select_reclaim_victims(
            mapped,
            self._shard_dead_mask(shard, now),
            self._last_access[lo : lo + self.local_capacity],
            self._tick_count,
            max(1, self.local_capacity // 16),
        )
        sm.release_batch(freed)
        if len(victims) == 0:
            return
        self.metric_unexpired_evictions += len(victims)
        sm.release_batch(victims)
        self._evict_local(shard, victims)

    def _evict_local(self, shard: int, victims: np.ndarray) -> None:
        """Blocked device evict of one shard's local victim slots.

        One whole-mesh dispatch per reclaiming shard (other shards' rows
        pad to the guard).  Reclaims are per-shard events driven from the
        resolve loop, so the common case is exactly one shard per tick;
        if profiling ever shows multi-shard reclaim storms, batch the
        victim blocks across shards the way install_globals does."""
        for start in range(0, len(victims), EVICT_CHUNK):
            part = victims[start : start + EVICT_CHUNK]
            w = min(EVICT_CHUNK, pad_pow2(len(part)))
            blk = np.full((self.n_shards, w), self.local_capacity, np.int64)
            blk[shard, : len(part)] = part
            self.state = self.ops.evict(self.state, self.ops.put2(blk))

    # ------------------------------------------------------------------
    # The tick — columnar, pipelined (the round-3 TickEngine host path,
    # uniform across however many shards exist: workers.go:125-147)
    # ------------------------------------------------------------------
    @hot_path
    def _gregorian_cols(self, cols, now: int, errors: Dict[int, str]):
        """Host-side Gregorian resolution (flagged rows only)."""
        n = len(cols)
        GREG = int(Behavior.DURATION_IS_GREGORIAN)
        greg_e = np.zeros(n, np.int64)
        greg_d = np.zeros(n, np.int64)
        greg = cols.behavior & GREG
        if greg.any():
            for i in np.flatnonzero(greg):
                try:
                    d = int(cols.duration[i])
                    greg_e[i] = timeutil.gregorian_expiration(now, d)
                    greg_d[i] = timeutil.gregorian_duration(now, d)
                except timeutil.GregorianError as exc:
                    errors[int(i)] = str(exc)
        return greg_e, greg_d

    @hot_path
    def _resolve_columns(self, cols, now: int, errors: Dict[int, str]):
        """The sharded-slotmap resolve: one vectorized CRC-32 batch
        routes keys to shards (bit-identical to the scalar ``_shard_of``
        router — and to the ownership the device derives from the
        resulting global slot), the key blob regroups by shard with one
        byte-gather, and one native blob resolve per shard assigns local
        slots, reclaiming on pressure.  Keys whose shard stays full
        after reclaim become per-item errors (the reference's
        error-in-item convention).  Returns ``(sh, slots, known)`` with
        resolved rows stamped live (``_last_access``/``_pending``)."""
        n = len(cols)
        # Named range + span like the single-chip tick path: host-side
        # shard routing shows up separated from device work in XProf
        # captures, and traced windows carry the resolve as a child span.
        with tracing.profile_annotation("guber.mesh.resolve"), \
                tracing.maybe_span("guber.mesh.resolve", {"batch": n}):
            return self._resolve_columns_locked(cols, now, errors, n)

    @hot_path
    def _resolve_columns_locked(self, cols, now, errors, n):
        from gubernator_tpu.native import crc32_batch

        # Key → shard (vectorized CRC-32 over the packed key blob).
        sh = (
            crc32_batch(cols.key_blob, cols.key_offsets)
            % np.uint32(self.n_shards)
        ).astype(np.int64)

        order = np.argsort(sh, kind="stable")
        # guber: allow-G001(key_offsets is host numpy, never device)
        offs = np.asarray(cols.key_offsets, np.int64)
        lens = np.diff(offs)
        lo = lens[order]
        so = offs[:-1][order]
        cum = np.cumsum(lo)
        blob_arr = np.frombuffer(cols.key_blob, np.uint8)
        if len(blob_arr):
            gather = (
                np.arange(int(cum[-1]), dtype=np.int64)
                - np.repeat(cum - lo, lo)
                + np.repeat(so, lo)
            )
            grouped_blob = blob_arr[gather].tobytes()
        else:
            grouped_blob = b""
        g_offsets = np.concatenate(
            [np.zeros(1, np.int64), cum]
        )
        shard_sorted = sh[order]
        starts = np.searchsorted(shard_sorted, np.arange(self.n_shards + 1))

        slots = np.full(n, -1, np.int64)
        known = np.zeros(n, np.uint8)
        for s in range(self.n_shards):
            a, z = int(starts[s]), int(starts[s + 1])
            if a == z:
                continue
            rows_s = order[a:z]
            off_s = g_offsets[a:z + 1] - g_offsets[a]
            blob_s = grouped_blob[g_offsets[a]:g_offsets[z]]
            sm = self.slots[s]
            sl, kn = sm.resolve_blob(blob_s, off_s)
            if (sl < 0).any():
                # Stamp already-resolved rows live before reclaiming
                # (an unstamped reclaim could hand a just-resolved
                # slot to the retried keys).
                okm = sl >= 0
                g = s * self.local_capacity + sl[okm]
                self._last_access[g] = self._tick_count
                self._pending.update(g[kn[okm] == 0].tolist())
                self._reclaim(s, now)
                retry = np.flatnonzero(sl < 0)
                s2, k2 = sm.resolve_batch(
                    [cols.key_bytes(int(rows_s[t])) for t in retry])
                sl[retry] = s2
                kn[retry] = k2
                for t in np.flatnonzero(sl < 0):
                    errors[int(rows_s[t])] = (
                        "rate-limit shard full; eviction failed")
            slots[rows_s] = sl
            known[rows_s] = kn

        resolved = slots >= 0
        g_res = sh[resolved] * self.local_capacity + slots[resolved]
        self._last_access[g_res] = self._tick_count
        self._pending.update(g_res[known[resolved] == 0].tolist())
        return sh, slots, known

    @hot_path
    def _account_misses(self, cols, sh, slots, known, now: int) -> None:
        """Hit/miss accounting + Store read-through for one resolved
        batch (``known`` is updated in place for store-restored rows)."""
        n = len(cols)
        resolved = slots >= 0
        miss_like = resolved & (known == 0)
        if self.store is not None and self._pending:
            g_all = sh * self.local_capacity + np.maximum(slots, 0)
            pend = self._pending
            miss_like = miss_like | (resolved & np.fromiter(
                (int(g) in pend for g in g_all), np.bool_, n))
        n_res = int(resolved.sum())
        n_miss = int(miss_like.sum())
        self.metric_hits += n_res - n_miss
        self.metric_misses += n_miss
        if self.store is not None and n_miss:
            if cols.refs is None:
                raise ValueError(
                    "Store read-through needs request objects; build "
                    "the batch with ReqColumns.from_requests(..., "
                    "keep_refs=True)")
            self._read_through(
                cols.refs, list(range(n)), sh, slots, known,
                np.flatnonzero(miss_like), now)

    @hot_path
    def submit_columns(self, cols, now: Optional[int] = None):
        """Build + dispatch one mesh tick (≤ max_batch rows) and return
        a handle; device work is queued, not awaited, so host packing of
        the next tick overlaps device execution of this one
        (TickEngine.submit_columns's contract, sharded).

        Every window — skewed or not — takes the ragged flat dispatch:
        the extent walk's trip counts are runtime values, so there is
        no per-shard width to overflow and no fallback format
        (``metric_routed_overflows`` stays a pinned-zero canary)."""
        n = len(cols)
        if n > self.max_batch:
            raise ValueError(
                f"batch of {n} exceeds engine max {self.max_batch}")
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            self._tick_count += 1
            errors: Dict[int, str] = {}
            greg_e, greg_d = self._gregorian_cols(cols, now, errors)
            sh, slots, known = self._resolve_columns(cols, now, errors)
            self._account_misses(cols, sh, slots, known, now)
            ok = slots >= 0
            for i in errors:
                ok[i] = False
            return self._dispatch_ragged(
                cols, now, sh, slots, known, ok, greg_e, greg_d, errors,
            )

    @hot_path
    def _dispatch_ragged(
        self, cols, now, sh, slots, known, ok, greg_e, greg_d, errors
    ) -> "MeshRaggedTickHandle":
        """The ragged flat dispatch: pack ONE slot-sorted (19, B)
        compact matrix carrying GLOBAL slots into a leased staging
        slab, derive the per-shard extent offsets from the resolve's
        counts (partition.RaggedExtents — the slot sort groups shards
        contiguously in ascending order), and upload both with async
        ``jnp.asarray`` copies (the transfer rides under the previous
        window's tick; the uncommitted signatures match warmup, so
        re-dispatch reuses the compiled program).  Each shard walks
        only its own extent on device — no per-shard host loop, no
        padded per-shard block, responses gathered with one psum."""
        n = len(cols)
        b = self.max_batch
        # Flight-recorder stage notes + named ranges/spans, mirroring the
        # single-chip TickEngine.submit_columns instrumentation.
        fr = flightrec.get()
        t0 = time.perf_counter() if fr is not None else 0.0
        m = self._staging.lease(b)
        if fr is not None:
            fr.note(fr.active(), "lease", time.perf_counter() - t0)
            t0 = time.perf_counter()
        ix = np.flatnonzero(ok)
        gslot = sh[ix] * self.local_capacity + slots[ix]
        pack_cols_req32(m, cols, gslot, known[ix], now, ix)
        pack_wide_rows(m, "greg_exp", greg_e[ix], ix)
        pack_wide_rows(m, "greg_dur", greg_d[ix], ix)
        inv, has_dups = sort_packed_by_slot(m, n, self.capacity)
        offs = self.ragged.offsets(self.ragged.counts(sh, ok))
        if fr is not None:
            fr.note(fr.active(), "pack", time.perf_counter() - t0)
            t0 = time.perf_counter()
        with tracing.profile_annotation("guber.mesh.tick"), \
                tracing.maybe_span("guber.mesh.dispatch_ragged",
                                   {"batch": n}):
            dev_m = jnp.asarray(m)
            dev_offs = jnp.asarray(offs)
            if has_dups:
                self.state, resp = self.ops.tick_ragged(
                    self.state, dev_m, dev_offs, jnp.int64(now)
                )
            else:
                self.state, resp = self.ops.run_tick_ragged_unique(
                    self.state, dev_m, dev_offs, jnp.int64(now)
                )
        if fr is not None:
            fr.note(fr.active(), "h2d", time.perf_counter() - t0)
        self._pending.clear()
        self.metric_routed_windows += 1
        wt_args = None
        if self.store is not None:
            wt_args = (cols.refs, list(range(n)), ix, sh, slots, now)
        handle = MeshRaggedTickHandle(
            self, resp, n, inv, errors, cols.limit, wt_args
        )
        self.metric_h2d_windows += 1
        if self._inflight > 0:
            self.metric_h2d_overlapped += 1
        self._inflight += 1
        self._staging.retire(handle)
        if self.store is not None:
            handle.result()
        return handle

    @hot_path
    def submit_cols(self, cols, now: Optional[int] = None):
        """Dispatch a columnar batch of any width (chunked into
        max_batch ticks; chunk k+1 packs while chunk k executes)."""
        from gubernator_tpu.ops.engine import SubmittedBatch

        n = len(cols)
        now = now if now is not None else timeutil.now_ms()
        spans = [
            (s, min(s + self.max_batch, n))
            for s in range(0, n, self.max_batch)
        ]
        handles = [
            self.submit_columns(
                cols if len(spans) == 1 else cols.slice_chunk(s, e), now
            )
            for s, e in spans
        ]
        return SubmittedBatch(handles, spans, n)

    @hot_path
    def submit(
        self, requests: Sequence[RateLimitRequest], now: Optional[int] = None
    ):
        """Object-level dispatch without awaiting the device (the tick
        loop's pipelining hook)."""
        from gubernator_tpu.ops.reqcols import ReqColumns

        return self.submit_cols(
            ReqColumns.from_requests(
                requests, keep_refs=self.store is not None
            ),
            now,
        )

    def process_columns(self, cols, now: Optional[int] = None):
        if len(cols) == 0:
            return np.zeros((5, 0), np.int64), {}
        return self.submit_cols(cols, now).matrix()

    def process(
        self, requests: Sequence[RateLimitRequest], now: Optional[int] = None
    ) -> List[RateLimitResponse]:
        """Apply a batch of requests; responses in request order."""
        if not requests:
            return []
        return self.submit(requests, now).responses()

    @staticmethod
    def _blocked_chunks(per_shard):
        """Chunk schedule for blocked per-shard matrices: yields (start, w)
        strided by RESTORE_CHUNK with w = pad_pow2 of the widest shard's
        remaining rows (capped at RESTORE_CHUNK).  The stride/width
        interplay is subtle — when the remainder fits, w covers ALL of
        every shard's remaining rows, so stepping a full RESTORE_CHUNK
        skips nothing — and lives only here."""
        lens = [len(v) for v in (
            per_shard.values() if isinstance(per_shard, dict) else per_shard
        )]
        widest = max(lens, default=0)
        start = 0
        while start < widest:
            w = pad_pow2(min(
                RESTORE_CHUNK,
                max((n - start for n in lens if n > start), default=0),
            ))
            if w <= 0:
                return
            yield start, w
            start += RESTORE_CHUNK

    # ------------------------------------------------------------------
    # Store write/read-through (reference store.go:49-65) — blocked
    # ------------------------------------------------------------------
    def _read_through(
        self, requests, idx, shards, slots, known, miss_sel, now: int
    ) -> None:
        """Store.Get for cache misses (algorithms.go:45-51): install the
        persisted items, blocked per shard, before the tick runs."""
        rows_by_shard: Dict[int, List[tuple]] = {}
        restored: set = set()
        for j in miss_sel:
            g = int(shards[j]) * self.local_capacity + int(slots[j])
            if g in restored:
                known[j] = 1
                continue
            item = self.store.get(requests[idx[j]])
            if item is None:
                continue
            restored.add(g)
            known[j] = 1
            self._pending.discard(g)
            rows_by_shard.setdefault(int(shards[j]), []).append(
                (
                    (int(slots[j]), item["algorithm"], item["limit"],
                     item["remaining"], item["duration"], item["created_at"],
                     item["updated_at"], item["burst"], item["status"],
                     item["expire_at"], item.get("tat", 0),
                     item.get("prev_count", 0), 1),
                    item.get("remaining_f", 0.0),
                )
            )
        if not rows_by_shard:
            return
        w = pad_pow2(max(len(v) for v in rows_by_shard.values()))
        ints = np.zeros((self.n_shards, len(ITEM_INT_ROWS), w), np.int64)
        floats = np.zeros((self.n_shards, w), np.float64)
        for s, rows in rows_by_shard.items():
            for k, (row, rf) in enumerate(rows):
                ints[s, :, k] = row
                floats[s, k] = rf
        self.state = self.ops.restore(
            self.state, self.ops.put3(ints), self.ops.put2(floats)
        )

    def _write_through(
        self, requests, idx, sel, shards, slots, now: int
    ) -> None:
        """Store.OnChange with each touched slot's post-tick state,
        gathered with one blocked readback (write-through,
        algorithms.go:149-153); slots cleared by the tick map to
        Store.remove (remove-on-reset, algorithms.go:78-90)."""
        # Unique (shard, local slot) per touched bucket, final state only.
        seen: set = set()
        per_shard: Dict[int, List[tuple]] = {}
        for j in sel:
            g = int(shards[j]) * self.local_capacity + int(slots[j])
            if g in seen:
                continue
            seen.add(g)
            per_shard.setdefault(int(shards[j]), []).append(
                (int(slots[j]), requests[idx[j]])
            )
        w = pad_pow2(max(len(v) for v in per_shard.values()))
        blk = np.full((self.n_shards, w), self.local_capacity, np.int64)
        for s, rows in per_shard.items():
            blk[s, : len(rows)] = [sl for sl, _ in rows]
        ints, floats = self.ops.readback(self.state, self.ops.put2(blk))
        ints = np.asarray(ints)
        floats = np.asarray(floats)
        for s, rows in per_shard.items():
            for k, (sl, req) in enumerate(rows):
                f = dict(zip(READBACK_ROWS, ints[s, :, k]))
                key = self.slots[s].key_of(sl)
                if key is None:
                    continue
                if not f["in_use"]:
                    self.store.remove(key)
                    continue
                self.store.on_change(
                    req,
                    {
                        "key": key,
                        "algorithm": int(f["algorithm"]),
                        "limit": int(f["limit"]),
                        "remaining": int(f["remaining"]),
                        "remaining_f": float(floats[s, k]),
                        "duration": int(f["duration"]),
                        "created_at": int(f["created_at"]),
                        "updated_at": int(f["updated_at"]),
                        "burst": int(f["burst"]),
                        "status": int(f["status"]),
                        "expire_at": int(f["expire_at"]),
                        "tat": int(f["tat"]),
                        "prev_count": int(f["prev_count"]),
                    },
                )

    # ------------------------------------------------------------------
    # GLOBAL installs (UpdatePeerGlobals receive path) — blocked
    # ------------------------------------------------------------------
    def install_globals(
        self, updates: Sequence[GlobalUpdate], now: Optional[int] = None
    ) -> None:
        """Install owner-pushed GLOBAL state; see TickEngine.install_globals.
        One blocked install per RESTORE_CHUNK of the widest shard — each
        device writes only its own shard's rows."""
        if not updates:
            return
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            # New logical tick so the "touched this tick" reclaim guard
            # doesn't pin the previous tick's slots (see TickEngine).
            self._tick_count += 1
            by_slot: Dict[int, tuple] = {}
            for u in updates:
                shard = self._shard_of(u.key)
                g, _ = self._resolve(u.key, shard, now)
                if g is None:
                    continue  # shard full; drop (the next broadcast retries)
                self._pending.discard(g)
                # Dedup by slot, LAST update wins (install order) — one
                # scatter row per slot (see TickEngine.install_globals).
                by_slot[g] = (
                    g % self.local_capacity, u.algorithm, u.status.limit,
                    u.status.remaining, u.status.status, u.duration,
                    u.status.reset_time, 1,
                )
            if not by_slot:
                return
            per_shard: Dict[int, List[tuple]] = {}
            for g, row in by_slot.items():
                per_shard.setdefault(g // self.local_capacity, []).append(row)
            for start, w in self._blocked_chunks(per_shard):
                blk = np.zeros((self.n_shards, 8, w), np.int64)
                for s, rows in per_shard.items():
                    part = rows[start : start + w]
                    if part:
                        blk[s, :, : len(part)] = np.array(part, np.int64).T
                self.state = self.ops.install(
                    self.state, self.ops.put3(blk), jnp.int64(now)
                )

    # ------------------------------------------------------------------
    # Snapshot / restore (Loader.Load/Save analog; see TickEngine)
    # ------------------------------------------------------------------
    def _host_state(self):
        """Host-side stored-layout columns of the whole sharded table."""
        if self.layout == "row":
            table = np.asarray(self.state.table)
            cap1 = self.local_capacity + 1
            # Drop each shard's guard row, re-concatenate the data rows.
            data = table.reshape(self.n_shards, cap1, ROW_W)[:, :-1, :]
            flat = np.ascontiguousarray(
                data.reshape(self.capacity, ROW_W)
            )
            return rowtable.host_columns_from_rows(flat)
        return jax.tree.map(np.asarray, self.state)

    def export_items(self) -> List[dict]:
        """Drain live bucket state to host dicts — one D2H gather of the
        sharded table + one native key export per shard."""
        with self._lock:
            st = self._host_state()
            mapped = np.concatenate([sm.mapped_mask() for sm in self.slots])
            live = np.flatnonzero(mapped & st.in_use)
            if len(live) == 0:
                return []
            keys: List[bytes] = []
            owner = live // self.local_capacity
            for d in range(self.n_shards):
                sel = live[owner == d] - d * self.local_capacity
                if len(sel):
                    keys.extend(self.slots[d].keys_batch(sel))
            return items_from_columns(keys, st, live)

    def load_items(self, items: Sequence[dict], now: Optional[int] = None) -> None:
        """Install snapshot items into the sharded table: route each key to
        its shard, batch-assign per shard, blocked restore scatters."""
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            self._tick_count += 1  # unblock LRU reclaim (see install_globals)
            # Dedup by key (last wins): duplicate keys resolve to one slot
            # and two restore rows aimed at the same slot are a data race
            # in the row layout's DMA scatter (see TickEngine.load_columns).
            live_by_key = {
                it["key"]: it for it in items if it["expire_at"] >= now
            }
            live = list(live_by_key.values())
            if not live:
                return
            by_shard: List[List[int]] = [[] for _ in range(self.n_shards)]
            for j, it in enumerate(live):
                by_shard[self._shard_of(it["key"])].append(j)
            lslots = np.full(len(live), -1, np.int64)
            for d, idxs in enumerate(by_shard):
                if not idxs:
                    continue
                lo = d * self.local_capacity
                ls = self.slots[d].assign_batch(
                    [live[j]["key"].encode() for j in idxs]
                )
                if (ls < 0).any():  # shard full: reclaim once, retry the rest
                    # Stamp the rows just assigned live first — device state
                    # is stale for them until the restore scatter runs, and
                    # an unstamped reclaim would hand their slots to the
                    # retried keys (same bug class as build_batch's retry).
                    got = ls[ls >= 0]
                    self._last_access[lo + got] = self._tick_count
                    self._pending.update((lo + got).tolist())
                    self._reclaim(d, now)
                    retry = np.flatnonzero(ls < 0)
                    ls[retry] = self.slots[d].assign_batch(
                        [live[idxs[r]]["key"].encode() for r in retry]
                    )
                lslots[idxs] = ls
            # Blocked restore: chunk by the widest shard.
            per_shard = [
                [j for j in idxs if lslots[j] >= 0]
                for idxs in by_shard
            ]
            for d, idxs in enumerate(per_shard):
                if idxs:
                    g = d * self.local_capacity + lslots[idxs]
                    self._last_access[g] = self._tick_count
            for start, w in self._blocked_chunks(per_shard):
                ints = np.zeros((self.n_shards, len(ITEM_INT_ROWS), w), np.int64)
                floats = np.zeros((self.n_shards, w), np.float64)
                for s, idxs in enumerate(per_shard):
                    part = idxs[start : start + w]
                    if not part:
                        continue
                    k = len(part)
                    ints[s, 0, :k] = lslots[part]
                    for r, name in enumerate(ITEM_INT_ROWS[1:-1], start=1):
                        # .get: pre-zoo snapshot items lack tat/prev_count.
                        ints[s, r, :k] = [live[j].get(name, 0) for j in part]
                    ints[s, -1, :k] = 1
                    floats[s, :k] = [live[j]["remaining_f"] for j in part]
                self.state = self.ops.restore(
                    self.state, self.ops.put3(ints), self.ops.put2(floats)
                )

    # ------------------------------------------------------------------
    # Elastic live resharding (docs/resharding.md).  The n→m transition
    # is planned by partition.plan_transition — the ONE layout-transition
    # spec — moved on device by a collective all-to-all keyed by
    # ``slot // cap_to`` (partition.relayout_block), and committed by an
    # atomic host-side cutover that swaps every layout-bearing field at
    # once.  Nothing before the cutover mutates the serving layout, so
    # any failure up to (and inside) it rolls back to the old layout
    # with the table untouched.
    # ------------------------------------------------------------------
    @hot_path
    def _dispatch_relayout(self, tr: LayoutTransition):
        """Run the transition all-to-all on the OLD mesh: every shard
        scatters its live rows into a zeroed new-layout buffer at the
        spec-derived target (``slot // cap_to``, ``slot % cap_to``) and
        one ``psum`` completes the exchange — the re-layout itself is
        collective device work, not a per-shard host gather.  Returns
        the replicated new-flat-layout table (device arrays, one D2H
        away); traces once per transition shape and never touches the
        serving programs' signatures."""
        cap_from = self.local_capacity
        if self.layout == "row":
            def _relayout(state_blk):
                self.ops.trace_counts["relayout"] += 1
                my = lax.axis_index("shard")
                return lax.psum(
                    relayout_block(state_blk.table[:cap_from], my, tr),
                    "shard",
                )

            out_specs = P(None, None)
        else:
            def _relayout(state_blk):
                self.ops.trace_counts["relayout"] += 1
                my = lax.axis_index("shard")
                return jax.tree.map(
                    lambda a: lax.psum(relayout_block(a, my, tr), "shard"),
                    state_blk,
                )

            out_specs = jax.tree.map(lambda _: P(None), BucketState.zeros(0))
        prog = jax.jit(
            shard_map(
                _relayout, mesh=self.mesh,
                in_specs=(self.ops.state_spec,), out_specs=out_specs,
                check_vma=False,
            )
        )
        # No donation: the old state must survive for abort-and-rollback.
        return prog(self.state)

    def _transition_items(self, flat) -> tuple:
        """Materialize the re-laid-out table and pair each live slot with
        its key: the host half of the transition.  Because the spec's
        flat remap is the identity on live slots, old global slot ``g``
        addresses row ``g`` of the relayout output directly — the keys
        come from the old slotmaps, the state from the collective."""
        if self.layout == "row":
            rows = np.ascontiguousarray(np.asarray(flat))
            st = rowtable.host_columns_from_rows(rows)
        else:
            st = jax.tree.map(np.asarray, flat)
        mapped = np.concatenate([sm.mapped_mask() for sm in self.slots])
        live = np.flatnonzero(mapped & st.in_use[: self.capacity])
        if len(live) == 0:
            return [], 0
        keys: List[bytes] = []
        owner = live // self.local_capacity
        for d in range(self.n_shards):
            sel = live[owner == d] - d * self.local_capacity
            if len(sel):
                keys.extend(self.slots[d].keys_batch(sel))
        return items_from_columns(keys, st, live), len(live)

    def _build_shard_set(self, tr: LayoutTransition, devices):
        """Everything the new layout needs, built OFF to the side (the
        old layout keeps serving identity until the cutover swap): mesh,
        compiled ShardedOps, zeroed sharded state, per-shard slotmaps,
        staging ring."""
        from types import SimpleNamespace

        from gubernator_tpu.ops.engine import make_slot_map

        mesh = Mesh(np.array(list(devices)), ("shard",))
        layout = make_layout_choice(
            self._table_layout_conf, tr.cap_to, mesh.devices.flat[0],
            self.max_batch,
        )
        ops = ShardedOps(mesh, tr.cap_to, layout)
        # The ragged extent spec IS the new layout's dispatch geometry:
        # post-cutover windows derive their offsets against cap_to's
        # ownership from this object — nothing width-shaped survives to
        # re-derive (the old routed path's local_width knob is dead).
        return SimpleNamespace(
            mesh=mesh, n_shards=tr.n_to, local_capacity=tr.cap_to,
            capacity=tr.capacity_to, layout=layout,
            ragged=RaggedExtents(tr.n_to, tr.cap_to),
            ops=ops, state=ops.init_state(),
            slots=[make_slot_map(tr.cap_to) for _ in range(tr.n_to)],
            last_access=np.zeros(tr.capacity_to, np.int64),
            staging=StagingRing(
                REQ32_ROWS, tr.capacity_to, self._staging_slabs,
                width=self.max_batch),
        )

    @hot_path
    def _cutover(self, new, items, now) -> None:
        """Atomically swap the serving layout to ``new`` and re-home the
        live items (keys re-route to ``crc32 % m`` so the ownership rule
        — route == ring == ``slot // local_capacity`` — holds in the new
        layout).  Every layout-bearing field swaps together under the
        engine lock; any failure restores the saved old layout verbatim
        (the old state was never donated), so the abort path is a plain
        tuple assignment — zero loss either way."""
        saved = (
            self.mesh, self.n_shards, self.local_capacity, self.capacity,
            self.ragged, self.layout, self.ops, self.state,
            self.slots, self._last_access, self._staging, self._pending,
        )
        self.mesh = new.mesh
        self.n_shards = new.n_shards
        self.local_capacity = new.local_capacity
        self.capacity = new.capacity
        self.ragged = new.ragged
        self.layout = new.layout
        self.ops = new.ops
        self.state = new.state
        self.slots = new.slots
        self._last_access = new.last_access
        self._staging = new.staging
        self._pending = set()
        self._inflight = 0
        try:
            if items:
                self.load_items(items, now)
            self._warmup()
        except Exception:
            (
                self.mesh, self.n_shards, self.local_capacity,
                self.capacity, self.ragged, self.layout, self.ops,
                self.state, self.slots, self._last_access, self._staging,
                self._pending,
            ) = saved
            raise

    def reshard(self, new_shards: int, devices=None,
                now: Optional[int] = None) -> dict:
        """Re-layout the live table over ``new_shards`` devices, in
        place, under the engine lock (callers quiesce the tick pipeline
        first — the ReshardCoordinator's job; a straggler window merely
        serializes behind the lock and resolves against whichever layout
        it observes).  Returns a summary dict; raises — with the old
        layout intact — on any failure before or inside the cutover."""
        new_n = int(new_shards)
        if new_n < 1:
            raise ValueError(f"new_shards must be >= 1; got {new_n}")
        with self._lock:
            if new_n == self.n_shards:
                return {
                    "from_shards": self.n_shards, "to_shards": new_n,
                    "live_items": 0, "noop": True,
                }
            avail = list(devices) if devices is not None else jax.devices()
            if len(avail) < new_n:
                raise ValueError(
                    f"reshard to {new_n} shards needs {new_n} devices; "
                    f"{len(avail)} available"
                )
            tr = plan_transition(self.n_shards, self.local_capacity, new_n)
            if tr.capacity_to >= (1 << 31):
                raise ValueError(
                    f"resharded capacity {tr.capacity_to} exceeds int32 "
                    "global slots"
                )
            flat = self._dispatch_relayout(tr)
            items, n_live = self._transition_items(flat)
            new = self._build_shard_set(tr, avail[:new_n])
            self._cutover(new, items, now)
            return {
                "from_shards": tr.n_from, "to_shards": tr.n_to,
                "cap_from": tr.cap_from, "cap_to": tr.cap_to,
                "live_items": n_live, "noop": False,
            }

    def routing_parity_errors(self, keys: Sequence[str]) -> int:
        """Audit key→shard routing parity for ``keys`` (post-serving):
        the vectorized CRC-32 route, the scalar ``_shard_of`` host ring,
        and actual slotmap residency must all agree, each resident key
        must live on exactly ONE shard (a key mapped on two shards is a
        double-serve; on zero shards after serving, a drop), and its
        global slot must derive back to the owning shard — the exact
        invariant the device router applies (``slot // local_capacity``).
        Returns the number of keys violating any of these; the bench
        mesh rungs export it as ``mesh_routing_parity_errors`` and CI
        gates it at exactly 0."""
        from gubernator_tpu.native import crc32_batch

        enc = [k.encode() for k in keys]
        blob = b"".join(enc)
        offsets = np.zeros(len(enc) + 1, np.int64)
        np.cumsum([len(e) for e in enc], out=offsets[1:])
        vec = (
            crc32_batch(blob, offsets) % np.uint32(self.n_shards)
        ).astype(np.int64)
        errs = 0
        with self._lock:
            for i, k in enumerate(keys):
                s = self._shard_of(k)
                owners = [
                    d for d in range(self.n_shards)
                    if self.slots[d].get(k) is not None
                ]
                if int(vec[i]) != s or owners != [s]:
                    errs += 1
                    continue
                local = self.slots[s].get(k)
                g = s * self.local_capacity + local
                if not (0 <= local < self.local_capacity) or \
                        g // self.local_capacity != s:
                    errs += 1
        return errs

    def cache_size(self) -> int:
        return sum(len(sm) for sm in self.slots)
