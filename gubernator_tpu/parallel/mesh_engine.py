"""Multi-chip sharded tick engine: the bucket table over a TPU mesh.

The reference scales *within* a node by statically partitioning the key
space over N lock-free workers (``workers.go:19-37,125-147``) and *across*
nodes by consistent-hash ownership.  On TPU the intra-node story becomes a
table **sharded over the device mesh**: a 1-D ``Mesh(('shard',))`` where
device *d* owns the contiguous slot range ``[d*local_cap, (d+1)*local_cap)``.

The hot path is deliberately collective-free: the host resolves each key to
a global slot, routes it to the owning shard, and packs one request block
per shard — ``(n_shards, ROWS, B)`` — so a tick under ``shard_map`` is pure
data-parallel SPMD: every device gathers/updates only its own shard.  This
mirrors the reference's "no mutexes, keys statically routed to workers"
design, with devices in place of goroutines.  Collectives (``psum`` etc.)
enter only on the GLOBAL-behavior reconciliation path (landing with the
GLOBAL manager), matching how the reference keeps its hot loop local and
reconciles asynchronously (``global.go``).

Why not route on-device (all-to-all)?  Keys are strings; hashing and the
key→slot map live on the host anyway (SURVEY.md §7 "Host/device split"), so
the host already knows every request's shard — an on-device shuffle would
add an all-to-all for nothing.
"""

from __future__ import annotations

import threading
import zlib
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.buckets import BucketState, np_logical, slice_field
from gubernator_tpu.ops.engine import (
    REQ_ROWS,
    REQ_ROW_INDEX,
    device_dead_mask,
    evict_chunked,
    items_from_columns,
    make_evict_fn,
    make_install_fn,
    make_restore_fn,
    make_tick_fn,
    pack_request_matrix,
    pack_restore_matrix,
    pad_pow2,
    resolve_gregorian,
)
from gubernator_tpu.types import GlobalUpdate, RateLimitRequest, RateLimitResponse
from gubernator_tpu.utils import timeutil


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D device mesh over the 'shard' axis (the slot-partition axis)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("shard",))


def make_sharded_tick_fn(mesh: Mesh, local_capacity: int):
    """Build the sharded tick: (state, reqs, now) → (state, responses).

    ``state`` arrays are length ``n_shards * local_capacity``, sharded along
    axis 0; ``reqs`` is ``(n_shards, len(REQ_ROWS), B)`` with block *d*
    holding requests whose **local** slot ids target shard *d* (padding rows
    carry slot == local_capacity and valid == 0).  Responses come back as
    ``(n_shards, 5, B)``; the host reassembles request order.
    """
    local_tick = make_tick_fn(local_capacity)

    def _local(state_blk: BucketState, req_blk: jnp.ndarray, now: jnp.ndarray):
        new_state, resp = local_tick(state_blk, req_blk[0], now)
        return new_state, resp[None]

    state_spec = jax.tree.map(lambda _: P("shard"), BucketState.zeros(0))
    return shard_map(
        _local,
        mesh=mesh,
        in_specs=(state_spec, P("shard", None, None), P()),
        out_specs=(state_spec, P("shard", None, None)),
        check_vma=False,
    )


class MeshTickEngine:
    """Host driver for the sharded table (multi-chip WorkerPool analog).

    Same contract as :class:`gubernator_tpu.ops.engine.TickEngine` but the
    table lives sharded across ``mesh``; total capacity is
    ``n_shards * local_capacity``.  Key→shard routing reuses the engine's
    slot allocator: global slot ``g`` lives on shard ``g // local_capacity``
    at local offset ``g % local_capacity``.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        local_capacity: int = 1 << 14,
        max_batch: int = 1024,
    ):
        from gubernator_tpu.ops.engine import make_slot_map

        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        self.local_capacity = int(local_capacity)
        self.capacity = self.n_shards * self.local_capacity
        self.max_batch = int(max_batch)

        state_spec = jax.tree.map(lambda _: P("shard"), BucketState.zeros(0))
        self._state_shardings = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec), state_spec
        )
        self.state: BucketState = jax.tree.map(
            lambda a, sh: jax.device_put(a, sh),
            BucketState.zeros(self.capacity),
            self._state_shardings,
        )
        self._tick = jax.jit(
            make_sharded_tick_fn(self.mesh, self.local_capacity),
            donate_argnums=(0,),
        )
        self._evict = jax.jit(make_evict_fn(), donate_argnums=(0,))
        self._install = jax.jit(make_install_fn(), donate_argnums=(0,))
        self._restore = jax.jit(make_restore_fn(), donate_argnums=(0,))
        # One slot allocator per shard; keys are routed to shards by hash,
        # the mesh analog of the reference's hash-range→worker routing
        # (workers.go:180-184).
        self.slots = [make_slot_map(self.local_capacity) for _ in range(self.n_shards)]
        self._last_access = np.zeros(self.capacity, np.int64)
        # Global slots assigned host-side but not yet written by a device
        # tick; device in_use/expire_at lag for these, so reclamation must
        # not treat them as dead (see TickEngine._pending).
        self._pending: set = set()
        self._tick_count = 0
        self._lock = threading.RLock()
        self.metric_over_limit = 0
        self._warmup()

    def _warmup(self) -> None:
        """Compile the sharded tick at startup (see TickEngine._warmup)."""
        m = np.zeros((self.n_shards, len(REQ_ROWS), self.max_batch), np.int64)
        m[:, REQ_ROW_INDEX["slot"], :] = self.local_capacity
        reqs_dev = jax.device_put(
            m, NamedSharding(self.mesh, P("shard", None, None))
        )
        self.state, resp = self._tick(self.state, reqs_dev, jnp.int64(0))
        np.asarray(resp)  # warm the response D2H path (see TickEngine._warmup)
        cols = np.zeros((8, 1), np.int64)  # valid=0 row: install is a no-op
        self.state = self._install(self.state, jnp.asarray(cols), jnp.int64(0))
        # Pre-compile the per-shard reclaim dead-scan (see TickEngine._warmup).
        sl = slice(0, self.local_capacity)
        device_dead_mask(
            self.state.in_use[sl], slice_field(self.state.expire_at, sl),
            0, self.local_capacity,
        )
        jax.block_until_ready(self.state)

    def _shard_of(self, key: str) -> int:
        return zlib.crc32(key.encode()) % self.n_shards

    def _resolve(self, key: str, shard: int, now: int) -> tuple[Optional[int], bool]:
        """(global slot, known) for key within its shard, reclaiming if
        needed; slot is None when the shard is full of same-tick live slots
        (caller spills the request to the next tick)."""
        sm = self.slots[shard]
        known = sm.get(key) is not None
        local = sm.assign(key)
        if local is None:
            self._reclaim(shard, now)
            known = sm.get(key) is not None  # reclaim may release the key
            local = sm.assign(key)
            if local is None:
                return None, known
        g = shard * self.local_capacity + local
        if not known:
            self._pending.add(g)
        self._last_access[g] = self._tick_count
        return g, known

    def _reclaim(self, shard: int, now: int) -> None:
        """Free expired slots in one shard; fall back to LRU eviction —
        the shared TTL/LRU policy (engine.select_reclaim_victims) over this
        shard's slice of the table."""
        from gubernator_tpu.ops.engine import select_reclaim_victims

        sm = self.slots[shard]
        lo = shard * self.local_capacity
        mapped = sm.mapped_mask()
        if self._pending:
            pend = [g - lo for g in self._pending if lo <= g < lo + self.local_capacity]
            if pend:
                mapped[np.asarray(pend, np.int64)] = False
        sl = slice(lo, lo + self.local_capacity)
        freed, victims = select_reclaim_victims(
            mapped,
            device_dead_mask(
                self.state.in_use[sl],
                slice_field(self.state.expire_at, sl),
                now, self.local_capacity,
            ),
            self._last_access[sl],
            self._tick_count,
            max(1, self.local_capacity // 16),
        )
        sm.release_batch(freed)
        if len(victims) == 0:
            return
        sm.release_batch(victims)
        self.state = evict_chunked(
            self._evict, self.state, lo + victims, self.capacity
        )

    def process(
        self, requests: Sequence[RateLimitRequest], now: Optional[int] = None
    ) -> List[RateLimitResponse]:
        """Apply a batch of requests; responses come back in request order.

        Requests that don't fit this tick's per-shard blocks (global
        overflow or hash skew) spill into follow-up ticks — the multi-chunk
        analog of TickEngine's chunk loop.
        """
        if not requests:
            return []
        out: List[Optional[RateLimitResponse]] = [None] * len(requests)
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            todo = list(range(len(requests)))
            while todo:
                left = self._tick_once(requests, todo, out, now)
                if left == todo:  # no progress: shard genuinely full
                    for i in left:
                        out[i] = RateLimitResponse(
                            error="rate-limit shard full; eviction failed"
                        )
                    break
                todo = left
        return out

    def _tick_once(
        self,
        requests: Sequence[RateLimitRequest],
        todo: List[int],
        out: List[Optional[RateLimitResponse]],
        now: int,
    ) -> List[int]:
        """Run one device tick over as many of ``todo`` as fit; return spill.

        Packing is column-vectorized like TickEngine.build_batch: one
        Python pass collects request fields, keys resolve in one native
        batch per shard (reclaim + retry on a full shard), and every
        request-matrix row is one fancy-indexed numpy write — the scalar
        per-request ``pack_request_col`` loop was the multi-chip host
        bottleneck."""
        b = self.max_batch
        R = REQ_ROW_INDEX
        self._tick_count += 1

        # One attribute pass: gregorian, key, shard.
        idx: List[int] = []
        keys: List[str] = []
        shard_l: List[int] = []
        greg_e: List[int] = []
        greg_d: List[int] = []
        for i in todo:
            r = requests[i]
            try:
                ge, gd = resolve_gregorian(r, now)
            except timeutil.GregorianError as e:
                out[i] = RateLimitResponse(error=str(e))
                continue
            k = r.hash_key()
            idx.append(i)
            keys.append(k)
            shard_l.append(self._shard_of(k))
            greg_e.append(ge)
            greg_d.append(gd)
        if not idx:
            return []
        n = len(idx)
        shards = np.asarray(shard_l, np.int64)

        # Resolve keys shard by shard in one native batch each.
        slots = np.full(n, -1, np.int64)  # local slot within the shard
        known = np.zeros(n, np.uint8)
        pos = np.full(n, -1, np.int64)
        for s in np.unique(shards):
            sel = np.flatnonzero(shards == s)
            kb = [keys[j].encode() for j in sel]
            sm = self.slots[s]
            sl, kn = sm.resolve_batch(kb)
            if (sl < 0).any():
                # Stamp already-resolved rows live before reclaiming
                # (see TickEngine.build_batch: an unstamped reclaim could
                # hand a just-resolved slot to the retried keys).
                okm = sl >= 0
                g = s * self.local_capacity + sl[okm]
                self._last_access[g] = self._tick_count
                self._pending.update(g[kn[okm] == 0].tolist())
                self._reclaim(s, now)
                retry = np.flatnonzero(sl < 0)
                s2, k2 = sm.resolve_batch([kb[t] for t in retry])
                sl[retry] = s2
                kn[retry] = k2
            slots[sel] = sl
            known[sel] = kn
            # Arrival-order position within the shard, assigned only to
            # requests whose key resolved: a full shard's failures must
            # not burn block columns that later resolvable requests need
            # (they spill; resolved overflow past the block width spills
            # too and retries with its slot already assigned).
            rs = sel[sl >= 0]
            pos[rs] = np.arange(len(rs))

        # Stamp EVERY resolved row live — including block-overflow spills
        # (pos >= b): their slots are assigned but unwritten until the
        # retry tick, and an unstamped reclaim (e.g. from install_globals
        # between calls) could unmap a slot whose spill retry is pending.
        resolved = slots >= 0
        g_res = shards[resolved] * self.local_capacity + slots[resolved]
        self._last_access[g_res] = self._tick_count
        self._pending.update(g_res[known[resolved] == 0].tolist())
        ok = resolved & (pos >= 0) & (pos < b)
        # New slots of spilled rows must survive the post-tick pending
        # clear: this tick does not write them.
        spilled_new = resolved & ~ok & (known == 0)
        g_spill_new = shards[spilled_new] * self.local_capacity + slots[spilled_new]
        spill = [idx[j] for j in np.flatnonzero(~ok)]
        sel = np.flatnonzero(ok)
        if len(sel) == 0:
            return spill

        m = np.zeros((self.n_shards, len(REQ_ROWS), b), np.int64)
        m[:, R["slot"], :] = self.local_capacity
        sh, ps = shards[sel], pos[sel]
        pack_request_matrix(
            m, ps, [requests[idx[j]] for j in sel], slots[sel], known[sel],
            now, nodes=sh,
            greg=(np.asarray(greg_e, np.int64)[sel],
                  np.asarray(greg_d, np.int64)[sel]),
        )

        reqs_dev = jax.device_put(
            m, NamedSharding(self.mesh, P("shard", None, None))
        )
        self.state, resp = self._tick(self.state, reqs_dev, jnp.int64(now))
        self._pending.clear()
        self._pending.update(g_spill_new.tolist())
        rm = np.asarray(resp)  # (n_shards, 5, B)
        self.metric_over_limit += int(rm[sh, 4, ps].sum())
        status, limit_o, remaining, reset = (
            rm[sh, r, ps].tolist() for r in range(4)
        )
        for t, j in enumerate(sel):
            out[idx[j]] = RateLimitResponse(
                status=status[t],
                limit=limit_o[t],
                remaining=remaining[t],
                reset_time=reset[t],
            )
        return spill

    def install_globals(
        self, updates: Sequence[GlobalUpdate], now: Optional[int] = None
    ) -> None:
        """Install owner-pushed GLOBAL state (UpdatePeerGlobals receive path);
        see TickEngine.install_globals.  Slot scatter crosses shards — XLA
        routes each row to its owning device; this path is off the hot loop
        (100ms broadcast cadence)."""
        if not updates:
            return
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            # New logical tick so the "touched this tick" reclaim guard
            # doesn't pin the previous tick's slots (see TickEngine).
            self._tick_count += 1
            cols = []
            for u in updates:
                shard = self._shard_of(u.key)
                g, _ = self._resolve(u.key, shard, now)
                if g is None:
                    continue  # shard full; drop this update (next broadcast retries)
                self._pending.discard(g)
                cols.append(
                    (g, u.algorithm, u.status.limit, u.status.remaining,
                     u.status.status, u.duration, u.status.reset_time, 1)
                )
            if cols:
                m = np.zeros((8, pad_pow2(len(cols))), np.int64)
                m[:, : len(cols)] = np.array(cols, np.int64).T
                self.state = self._install(self.state, jnp.asarray(m), jnp.int64(now))

    # ------------------------------------------------------------------
    # Snapshot / restore (Loader.Load/Save analog; see TickEngine)
    # ------------------------------------------------------------------
    def export_items(self) -> List[dict]:
        """Drain live bucket state to host dicts — one D2H gather of the
        sharded table + one native key export per shard."""
        with self._lock:
            st = jax.tree.map(np.asarray, self.state)
            mapped = np.concatenate([sm.mapped_mask() for sm in self.slots])
            live = np.flatnonzero(mapped & st.in_use)
            if len(live) == 0:
                return []
            keys: List[bytes] = []
            owner = live // self.local_capacity
            for d in range(self.n_shards):
                sel = live[owner == d] - d * self.local_capacity
                if len(sel):
                    keys.extend(self.slots[d].keys_batch(sel))
            return items_from_columns(keys, st, live)

    def load_items(self, items: Sequence[dict], now: Optional[int] = None) -> None:
        """Install snapshot items into the sharded table: route each key to
        its shard, batch-assign per shard, one jitted scatter for the data
        (XLA places each row on its owning device)."""
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            self._tick_count += 1  # unblock LRU reclaim (see install_globals)
            live = [it for it in items if it["expire_at"] >= now]
            if not live:
                return
            by_shard: List[List[int]] = [[] for _ in range(self.n_shards)]
            for j, it in enumerate(live):
                by_shard[self._shard_of(it["key"])].append(j)
            gslots = np.full(len(live), -1, np.int64)
            for d, idxs in enumerate(by_shard):
                if not idxs:
                    continue
                lo = d * self.local_capacity
                ls = self.slots[d].assign_batch(
                    [live[j]["key"].encode() for j in idxs]
                )
                if (ls < 0).any():  # shard full: reclaim once, retry the rest
                    # Stamp the rows just assigned live first — device state
                    # is stale for them until the restore scatter runs, and
                    # an unstamped reclaim would hand their slots to the
                    # retried keys (same bug class as build_batch's retry).
                    got = ls[ls >= 0]
                    self._last_access[lo + got] = self._tick_count
                    self._pending.update((lo + got).tolist())
                    self._reclaim(d, now)
                    retry = np.flatnonzero(ls < 0)
                    ls[retry] = self.slots[d].assign_batch(
                        [live[idxs[r]]["key"].encode() for r in retry]
                    )
                gslots[idxs] = np.where(ls >= 0, lo + ls, -1)
            ok = np.flatnonzero(gslots >= 0)  # full shards: drop those rows
            if len(ok) == 0:
                return
            ints, floats = pack_restore_matrix(live, ok, gslots)
            self._last_access[gslots[ok]] = self._tick_count
            self.state = self._restore(
                self.state, jnp.asarray(ints), jnp.asarray(floats)
            )

    def cache_size(self) -> int:
        return sum(len(sm) for sm in self.slots)
