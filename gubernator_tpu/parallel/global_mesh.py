"""GLOBAL reconciliation as mesh collectives: the TPU-native data plane.

The reference reconciles GLOBAL rate limits with two O(peers) RPC fans
(``global.go``): **sendHits** — every non-owner aggregates observed hits per
key and unicasts them to each key's owner (``global.go:144-187``) — and
**broadcastPeers** — every owner pushes authoritative state to every other
peer (``global.go:234-283``).  When the "peers" are shards of one TPU mesh
(chips of a host, or hosts of a multi-host ICI/DCN mesh), both fans collapse
into collectives on the device:

* Each node keeps a **full replica** of the GLOBAL bucket table (the analog
  of the reference's non-owner local cache answering GLOBAL requests,
  ``gubernator.go:395-421``) plus a per-node **hit accumulator** (the analog
  of ``globalManager.hits``, ``global.go:99-112``).
* Slot ownership is by contiguous range: node ``d`` owns slots
  ``[d*capacity/n, (d+1)*capacity/n)`` — the mesh analog of consistent-hash
  key ownership.
* One **reconcile step** (the 100ms ``GlobalSyncWait`` cadence) runs as a
  single SPMD program:

  1. ``all_gather`` the hit accumulators over the mesh and fold each node's
     window into the authority in node order (or ``psum`` them into one
     application when strict sequencing is waived).  This *is* sendHits: a
     keyed reduction instead of O(peers) unicasts.
  2. ``all_gather`` the per-node authoritative slices into a fresh
     replicated base table.  This *is* broadcastPeers: one replication step
     instead of O(peers^2) pushes.
  3. Apply the summed hits to the base via the same branch-free
     ``bucket_transition`` every request takes, with DRAIN_OVER_LIMIT forced
     (the reference forces it on forwarded GLOBAL hits,
     ``gubernator.go:510-512``) and RESET_REMAINING OR-folded across nodes
     (``global.go:105-110``).  Every node computes the identical result, so
     replicas re-synchronize with zero additional traffic.

Between reconciles each node answers GLOBAL requests from its own replica
(and applies them locally — the reference's non-owner drains its local
cache copy too, ``getLocalRateLimit`` with IsOwner=false), while hits on
slots the node doesn't own are scatter-added into its accumulator.  Hits on
*owned* slots mutate the authoritative slice directly, matching the
reference's owner path (``gubernator.go:604-606`` applies then broadcasts).

Request parameters for the aggregated application (limit/duration/behavior/
created_at of the *latest* request per slot, matching the reference keeping
the queued request proto and summing hits into it) ride a per-node aux
table; the winner across nodes is picked with a ``pmax`` over write stamps.

gRPC remains the reconciliation transport only *across* meshes (separate
clusters / DCs) — within a mesh no RPC is issued at all.

**Scaling envelope (read before raising ``capacity``).**  The DENSE
reconcile all-gathers the (ACC_ROWS, capacity) accumulators plus the
per-node authoritative slices and applies ``bucket_transition`` to every
slot — O(capacity · n_nodes) device work and ICI traffic per step,
independent of how many slots were actually hit.  That form is the
default up to 2^16 slots: one fused pass, no sparsity bookkeeping, and
at the reference's GLOBAL keyspace (its defaults cap the whole cache at
50K items, config.go:139) a dense 64K-slot reconcile is ~25 MB of
collective traffic every 100 ms — microseconds of a v5e ICI's
~10 GB/s/link.

Past that, the SPARSE reconcile takes over (``sparse_k`` envelope,
auto-enabled above 2^16 slots): each node compacts its hit window and
touched-slot set device-side, the collectives move those envelopes —
O(hits · n_nodes) ICI bytes — owners apply the windows to their
authoritative rows with K-row gather/scatter, and only changed rows
re-broadcast.  Reconcile cost then scales with traffic, not table size,
lifting the envelope to multi-million-slot GLOBAL tables (hard cap
2^24).  The overflow probe is FUSED into the sparse program — the step
compacts and gathers its envelope once and emits the probe bool
alongside the update — and an overflowing step applies nothing and
falls back to the dense pass (host dispatch), so the envelope is a
performance knob, never a correctness one.  Each node still holds a
full replica (~100 B/slot) — HBM, not ICI, bounds capacity.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import gubernator_tpu.jaxinit  # noqa: F401  (x64 + compile cache before jax use)
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gubernator_tpu.utils.jaxcompat import shard_map

from gubernator_tpu.parallel.partition import NodeLayout

# The canonical GLOBAL-mesh placement: one replica row per node,
# reconciled with psum collectives only (partition.py is the single
# source of every PartitionSpec both mesh engines place data with).
NODE_LAYOUT = NodeLayout()

from gubernator_tpu.ops.buckets import (
    BucketState,
    ReqBatch,
    bucket_transition,
    gather_state,
    logical_view,
    np_logical,
    scatter_state,
    slice_field,
    stored_view,
)
from gubernator_tpu.ops.engine import (
    REQ_ROWS,
    REQ_ROW_INDEX,
    pack_request_matrix,
    _slot_segments,
    make_slot_map,
    resolve_gregorian,
    unpack_reqs,
)
from gubernator_tpu.types import (
    Behavior,
    RateLimitRequest,
    RateLimitResponse,
)
from gubernator_tpu.utils import timeutil
from gubernator_tpu.utils import sanitize

I64 = jnp.int64
I32 = jnp.int32

# Aux rows: per-slot, per-node snapshot of the latest request's parameters —
# the mesh analog of the queued RateLimitReq the reference ships to owners
# (global.go:99-112 keeps the first request and sums hits into it; we keep
# the latest, which matches the reference's queue_update replacement
# semantics and lets limit changes propagate).
AUX_ROWS = (
    "limit", "duration", "algorithm", "behavior", "burst",
    "greg_exp", "greg_dur", "created_at", "stamp",
)
AUX = {name: i for i, name in enumerate(AUX_ROWS)}

# Accumulator rows (global.go:99-112's per-key aggregation, as dense arrays).
# ACC_TOUCH counts EVERY local application (owned, non-owned, queries):
# the sparse reconcile derives its restore/re-broadcast sets from it —
# which replica rows diverged provisionally, which owned rows were
# written directly.
ACC_HITS, ACC_RESET, ACC_COUNT, ACC_TOUCH = 0, 1, 2, 3
ACC_ROWS = 4


def make_global_mesh(n_nodes: Optional[int] = None,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the 'node' axis (one device = one logical peer)."""
    if devices is None:
        devices = jax.devices()
        if n_nodes is not None:
            if len(devices) < n_nodes:
                raise ValueError(
                    f"global mesh needs {n_nodes} devices, "
                    f"have {len(devices)}"
                )
            devices = devices[:n_nodes]
    return Mesh(np.array(list(devices)), ("node",))


def make_global_process_fn(mesh: Mesh, capacity: int, n_nodes: int,
                           track_touch: bool = False):
    """Per-node GLOBAL request application + hit accumulation.

    ``state``/``aux``/``accum`` carry one replica row per node (sharded over
    'node'); ``reqs`` is ``(n_nodes, len(REQ_ROWS), B)`` — block *d* holds
    the requests that arrived at node *d* this window.

    ``track_touch`` maintains the ACC_TOUCH row the sparse reconcile
    needs; dense-only engines skip it (the int64 scatter-add is the
    most expensive op in this program, and the dense step never reads
    the row).
    """
    slice_sz = capacity // n_nodes

    def _local(state_blk, aux_blk, accum_blk, reqs_blk, now, stamp):
        st = jax.tree.map(lambda a: a[0], state_blk)
        aux = aux_blk[0]
        acc = accum_blk[0]
        r = unpack_reqs(reqs_blk[0])
        my = lax.axis_index("node")

        rank, group_size, _, _ = _slot_segments(r.slot, r.valid, capacity)
        n_rounds = jnp.max(jnp.where(r.valid, rank, 0)) + 1
        b = r.slot.shape[0]
        resp0 = (
            jnp.zeros(b, I32), jnp.zeros(b, I64), jnp.zeros(b, I64),
            jnp.zeros(b, I64), jnp.zeros(b, jnp.bool_),
        )

        def cond(carry):
            k, _, _ = carry
            return k < n_rounds

        def body(carry):
            k, st, resp = carry
            active = r.valid & (rank == k)
            gathered = gather_state(st, r.slot)
            new_g, r_out = bucket_transition(now, gathered, r)
            scat = jnp.where(active, r.slot, capacity)
            st = scatter_state(st, scat, new_g)
            new_resp = (r_out.status, r_out.limit, r_out.remaining,
                        r_out.reset_time, r_out.over_limit)
            resp = tuple(
                jnp.where(active, n, o) for n, o in zip(new_resp, resp)
            )
            return k + 1, st, resp

        _, st, resp = lax.while_loop(cond, body, (jnp.int32(0), st, resp0))

        # Aux params: one last-writer scatter per tick, not one per round —
        # every round would write the same per-slot "latest request" row the
        # final rank writes anyway, and the (9, B) int64 scatter is the
        # most expensive op in the program.
        aux_vals = jnp.stack([
            r.limit, r.duration, r.algorithm.astype(I64),
            r.behavior.astype(I64), r.burst, r.greg_exp, r.greg_dur,
            r.created_at, jnp.full_like(r.limit, stamp),
        ])
        tail = r.valid & (rank == group_size - 1)
        aux = aux.at[:, jnp.where(tail, r.slot, capacity)].set(
            aux_vals, mode="drop"
        )

        # Hit accumulation for non-owned slots (global.go:99-112): sum hits,
        # OR RESET_REMAINING, count contributions.  Zero-hit queries are not
        # queued (global.go:74-78).  Order-independent → one scatter-add.
        # int64 accumulators: narrowing to int32 would wrap (not saturate)
        # under accumulated hits across a window — a credit-instead-of-
        # drain bypass — so the slower 64-bit scatter-add stays.
        owned = (r.slot // slice_sz) == my.astype(I32)
        queue = r.valid & ~owned & (r.hits != 0)
        qslot = jnp.where(queue, r.slot, capacity)
        reset = queue & ((r.behavior & Behavior.RESET_REMAINING) != 0)
        touch = acc[ACC_TOUCH]
        if track_touch:
            tslot = jnp.where(r.valid, r.slot, capacity)
            touch = touch.at[tslot].add(r.valid.astype(I64), mode="drop")
        acc = jnp.stack([
            acc[ACC_HITS].at[qslot].add(jnp.where(queue, r.hits, 0), mode="drop"),
            acc[ACC_RESET].at[qslot].add(reset.astype(I64), mode="drop"),
            acc[ACC_COUNT].at[qslot].add(queue.astype(I64), mode="drop"),
            touch,
        ])

        packed = jnp.stack([
            resp[0].astype(I64), resp[1], resp[2], resp[3],
            resp[4].astype(I64),
        ])
        return (
            jax.tree.map(lambda a: a[None], st),
            aux[None],
            acc[None],
            packed[None],
        )

    state_spec = NODE_LAYOUT.replica_spec()
    return shard_map(
        _local,
        mesh=mesh,
        in_specs=(state_spec, P("node", None, None), P("node", None, None),
                  P("node", None, None), P(), P()),
        out_specs=(state_spec, P("node", None, None), P("node", None, None),
                   P("node", None, None)),
        check_vma=False,
    )


def _make_compact(capacity: int):
    """Compactor: first ``width`` set slots of a mask (slot order), padded
    with ``capacity``; overflow rows drop (the overflow probe rejects
    such steps before sparse results are used)."""
    def compact(mask, width):
        arange_c = jnp.arange(capacity, dtype=I32)
        rank = jnp.cumsum(mask.astype(I32)) - 1
        tgt = jnp.where(mask & (rank < width), rank, width)
        return jnp.full(width + 1, capacity, I32).at[tgt].set(
            arange_c, mode="drop")[:width]

    return compact


def _make_gather_rows(n_nodes: int, my):
    """all_gather-by-one-hot-psum over 'node' (the one collective this
    toolchain is guaranteed to lower; see make_global_reconcile_fn)."""
    def gather_rows(x):
        buf = jnp.zeros((n_nodes,) + x.shape, x.dtype).at[my].set(x)
        return lax.psum(buf, "node")

    return gather_rows


def _sparse_sets(acc_me, compact, K: int):
    """The sparse step's working sets, derived ONCE here for both the
    overflow probe and the sparse program — any drift between the two
    would let an overflowing step run the truncating sparse path, so
    they must share this function: (wmask, tmask, wslots, tslots)."""
    wmask = acc_me[ACC_COUNT] > 0      # my queued-hit window
    tmask = acc_me[ACC_TOUCH] > 0      # every slot I wrote locally
    return wmask, tmask, compact(wmask, K), compact(tmask, K)


def _mark_touched(capacity: int, n_nodes: int, slot_sets):
    """Union of every node's compacted slot sets as a capacity mask
    (``slot_sets``: (n, m, K) — padding rows carry ``capacity`` and
    drop)."""
    touched = jnp.zeros(capacity, jnp.bool_)
    m = slot_sets.shape[1]

    def mark(d, t):
        for j in range(m):
            t = t.at[slot_sets[d, j]].set(True, mode="drop")
        return t

    return lax.fori_loop(0, n_nodes, mark, touched)


def make_global_overflow_fn(mesh: Mesh, capacity: int, n_nodes: int,
                            sparse_k: int):
    """Envelope probe for the sparse reconcile: (accum) → replicated
    bool, True when this step's windows, touch sets, or any owner's
    re-broadcast share exceed the sparse envelopes — the caller then
    runs the dense program instead (host dispatch; see
    make_global_reconcile_fn).

    The serving engine no longer dispatches this probe: the fused step
    (:func:`make_global_sparse_step_fn`) computes the same bool inside
    the sparse program itself, from the same compacted sets, so the
    envelope is gathered ONCE per step instead of twice.  This program
    stays as the reference half of the unfused two-program pair the
    parity fuzz tests run against the fused step."""
    slice_sz = capacity // n_nodes
    K, K2 = int(sparse_k), 2 * int(sparse_k)

    def _probe(accum_blk):
        my = lax.axis_index("node")
        acc_me = accum_blk[0]
        owned = (jnp.arange(capacity, dtype=I32) // slice_sz) == my.astype(I32)
        gather_rows = _make_gather_rows(n_nodes, my)
        wmask, tmask, wslots, tslots = _sparse_sets(
            acc_me, _make_compact(capacity), K)
        counts = gather_rows(jnp.stack([
            jnp.count_nonzero(wmask), jnp.count_nonzero(tmask)]))
        sets = gather_rows(jnp.stack([wslots, tslots]))   # (n, 2, K)
        touched = _mark_touched(capacity, n_nodes, sets)
        bcounts = gather_rows(jnp.count_nonzero(touched & owned))
        return (jnp.max(counts) > K) | (jnp.max(bcounts) > K2)

    return shard_map(
        _probe,
        mesh=mesh,
        in_specs=(P("node", None, None),),
        out_specs=P(),
        check_vma=False,
    )


def make_global_sparse_step_fn(mesh: Mesh, capacity: int, n_nodes: int,
                               sparse_k: int, strict_sequencing: bool = True,
                               with_envelope: bool = False):
    """The FUSED sparse reconcile: overflow probe + sparse step as one
    mesh program — (state, aux, accum, now) → (state', accum', overflow).

    The unfused pair (make_global_overflow_fn + the sparse branch of
    make_global_reconcile_fn) compacts the per-node (window, touch) sets
    and all-gathers them TWICE per step: once for the probe's envelope
    counts, then again for the actual reconcile — paying the compaction
    (an O(capacity) cumsum per set) and the set-gather collective twice
    for the same bytes.  Here the step compacts once, rides the probe's
    counts on two extra rows of the ONE envelope gather, and derives the
    overflow bool in-program.  Per-owner re-broadcast shares need no
    collective at all: the gathered touch union is replicated, so every
    node counts every owner's K2 share from its own copy.

    Overflow steps must not apply truncated envelopes, and an in-program
    cond would re-impose the O(capacity) copy the sparse step removes
    (see make_global_reconcile_fn) — instead the bool gates every
    scatter (indices aim at the drop row) and the accumulator zeroing,
    so an overflowing step returns ``state``/``accum`` bit-unchanged and
    the host runs the rare dense fallback on them: one program per
    normal step, two per overflowing step, never a wasted gather.

    ``with_envelope`` additionally returns the gathered
    ``(n_nodes, 4 + len(AUX_ROWS) + 3, K)`` envelope (windows + touch
    sets + probe counts) — the parity tests' window into what crossed
    the mesh; the serving engine leaves it off.

    ``strict_sequencing`` is accepted for signature parity with
    make_global_reconcile_fn but the sparse step always sequences
    per-node windows (their per-window params require it).
    """
    del strict_sequencing  # sparse always sequences; see docstring
    slice_sz = capacity // n_nodes
    K, K2 = int(sparse_k), 2 * int(sparse_k)
    NW = 4 + len(AUX_ROWS)           # window payload rows (see `payload`)
    T_ROW, CW_ROW, CT_ROW = NW, NW + 1, NW + 2

    def _step(state_blk, aux_blk, accum_blk, now):
        my = lax.axis_index("node")
        rep = jax.tree.map(lambda a: a[0], state_blk)
        aux = aux_blk[0]
        acc_me = accum_blk[0]
        owned = (jnp.arange(capacity, dtype=I32) // slice_sz) == my.astype(I32)
        gather_rows = _make_gather_rows(n_nodes, my)

        wmask, tmask, wslots, tslots = _sparse_sets(
            acc_me, _make_compact(capacity), K)
        wsl = jnp.clip(wslots, 0, capacity - 1)
        # One envelope per node, one gather per step: the window payload
        # (slots + hits/reset/count + aux params), the touch set, and the
        # probe's two set-size counts broadcast across the K lanes.
        payload = jnp.concatenate([
            wslots.astype(I64)[None],
            acc_me[ACC_HITS][wsl][None],
            acc_me[ACC_RESET][wsl][None],
            acc_me[ACC_COUNT][wsl][None],
            aux[:, wsl],
            tslots.astype(I64)[None],
            jnp.broadcast_to(
                jnp.count_nonzero(wmask).astype(I64), (1, K)),
            jnp.broadcast_to(
                jnp.count_nonzero(tmask).astype(I64), (1, K)),
        ])                                      # (NW + 3, K)
        W = gather_rows(payload)                # (n, NW + 3, K)

        sets = jnp.stack([W[:, 0], W[:, T_ROW]], axis=1)  # (n, 2, K)
        touched = _mark_touched(capacity, n_nodes, sets)
        # Probe, from the one gather: any node's set wider than K, or —
        # counted locally on the replicated union, owner d's share being
        # rows [d*slice_sz, (d+1)*slice_sz) — any owner's re-broadcast
        # share wider than K2.
        counts = W[:, CW_ROW:CT_ROW + 1, 0]     # (n, 2)
        bcounts = jnp.sum(
            touched.reshape(n_nodes, slice_sz).astype(I32), axis=1)
        overflow = (jnp.max(counts) > K) | (jnp.max(bcounts) > K2)

        # sendHits at the authority (identical to the unfused sparse
        # step's fold, with ``overflow`` gating validity so a truncated
        # envelope never lands).
        def fold(d, st):
            slots_d = W[d, 0].astype(I32)
            sl = jnp.clip(slots_d, 0, capacity - 1)
            ok = ((slots_d < capacity) & owned[sl] & (W[d, 3] > 0)
                  & ~overflow)
            auxd = W[d, 4:NW]
            havep = auxd[AUX["stamp"]] > 0
            gathered = gather_state(st, sl)
            beh = jnp.where(havep, auxd[AUX["behavior"]], 0).astype(I32)
            beh = beh & ~jnp.int32(Behavior.RESET_REMAINING)
            beh = beh | jnp.int32(Behavior.DRAIN_OVER_LIMIT)
            req = ReqBatch(
                slot=sl,
                known=jnp.ones(K, jnp.bool_),
                hits=W[d, 1],
                limit=jnp.where(
                    havep, auxd[AUX["limit"]], gathered.limit),
                duration=jnp.where(
                    havep, auxd[AUX["duration"]], gathered.duration),
                algorithm=jnp.where(
                    havep, auxd[AUX["algorithm"]],
                    gathered.algorithm.astype(I64)).astype(I32),
                behavior=jnp.where(
                    W[d, 2] > 0,
                    beh | jnp.int32(Behavior.RESET_REMAINING), beh),
                created_at=jnp.where(
                    havep, auxd[AUX["created_at"]], now),
                burst=jnp.where(
                    havep, auxd[AUX["burst"]], gathered.burst),
                greg_exp=jnp.where(havep, auxd[AUX["greg_exp"]], 0),
                greg_dur=jnp.where(havep, auxd[AUX["greg_dur"]], 0),
                valid=ok,
            )
            new_g, _ = bucket_transition(now, gathered, req)
            return scatter_state(
                st, jnp.where(ok, sl, capacity), new_g)

        st = lax.fori_loop(0, n_nodes, fold, rep)

        # broadcastPeers, sparse (see make_global_reconcile_fn): the
        # union was already derived above for the probe — reused here,
        # masked off entirely when the step overflowed.
        bmask = touched & owned & ~overflow
        bslots = _make_compact(capacity)(bmask, K2)
        bsl = jnp.clip(bslots, 0, capacity - 1)
        rows = gather_state(st, bsl)
        BS = gather_rows(bslots)
        BR = jax.tree.map(gather_rows, rows)

        def install(d, st2):
            sl2 = BS[d]
            scat = jnp.where(sl2 < capacity, sl2, capacity)
            return scatter_state(
                st2, scat, jax.tree.map(lambda a: a[d], BR))

        st = lax.fori_loop(0, n_nodes, install, st)
        # Overflow keeps the accumulators: the host's dense fallback
        # still has the window to apply.
        acc_out = jnp.where(overflow, acc_me, jnp.zeros_like(acc_me))
        out = (
            jax.tree.map(lambda a: a[None], st),
            acc_out[None],
            overflow,
        )
        return out + (W,) if with_envelope else out

    state_spec = NODE_LAYOUT.replica_spec()
    out_specs = (state_spec, P("node", None, None), P())
    if with_envelope:
        out_specs = out_specs + (P(),)
    return shard_map(
        _step,
        mesh=mesh,
        in_specs=(state_spec, P("node", None, None), P("node", None, None),
                  P()),
        out_specs=out_specs,
        check_vma=False,
    )


def make_global_reconcile_fn(
    mesh: Mesh, capacity: int, n_nodes: int, strict_sequencing: bool = True,
    sparse_k: int = 0,
):
    """The collective reconcile step: aggregate hits + replicate authority.

    Collapses the reference's sendHits (global.go:144-187) and
    broadcastPeers (global.go:234-283) RPC fans into collectives.  With
    ``strict_sequencing`` (default) each node's aggregated window applies
    to the authority as its own batch, in node order — bit-exact with the
    reference, where every peer's window arrives as a separate
    GetPeerRateLimits RPC and is applied sequentially (edge branches like
    the new-item over-ask, algorithms.go:240-248, are sequencing-
    sensitive).  The non-strict path folds all nodes into one psum and a
    single application — one dense pass instead of ``n_nodes``, for
    deployments that accept aggregate-application semantics.

    ``sparse_k > 0`` returns the SPARSE step instead: every node
    compacts its hit window and its touched-slot set to a
    ``sparse_k``-row envelope, the collectives move those envelopes
    instead of full tables — O(hits · n) ICI bytes and gather/scatter
    work instead of O(capacity · n) — owners apply the gathered windows
    to their authoritative rows only, and re-broadcast just the
    changed/touched rows (2·sparse_k envelope).  This is what lifts the
    dense form's ~2^20-slot envelope (module docstring) to
    multi-million-slot GLOBAL tables.  The sparse program ASSUMES no
    envelope overflow; callers consult :func:`make_global_overflow_fn`
    first and run the dense program for the rare overflowing step (host
    dispatch, not an in-program cond: a cond would copy the whole
    untouched table through its output buffer and re-impose the
    O(capacity) cost the sparse step removes).  The reference ships only
    touched keys the same way (global.go:91-140).

    Sparse parameter semantics are per-window (each node's aggregated
    window applies with ITS OWN latest-request params, like each peer's
    GetPeerRateLimits RPC carrying its own request protos) — the dense
    path's cross-node stamp winner is a superset that can also resurrect
    params from nodes with no hits this window; the reference does not.
    """
    slice_sz = capacity // n_nodes

    def _recon(state_blk, aux_blk, accum_blk, now):
        # Every cross-node exchange below is a ``psum``: sum all-reduce is
        # the one collective guaranteed to lower on every TPU toolchain in
        # play (the tunneled AOT compiler rejects max all-reduce), and it
        # rides ICI natively.  all_gather is expressed as a psum of
        # one-hot-row buffers; broadcast as an ownership-masked psum.
        my = lax.axis_index("node")
        rep = jax.tree.map(lambda a: a[0], state_blk)
        aux = aux_blk[0]
        acc_me = accum_blk[0]

        owned = (jnp.arange(capacity, dtype=I32) // slice_sz) == my.astype(I32)
        gather_rows = _make_gather_rows(n_nodes, my)

        def dense_recon(_):
            # broadcastPeers as a collective: every node contributes its
            # owned (authoritative) slice, masked psum reassembles the full
            # table in slot order on every node — replicas are now the
            # authoritative state, exactly what UpdatePeerGlobals installs
            # (gubernator.go:425-459).
            def bcast(a):
                if a.dtype == jnp.bool_:
                    return lax.psum(
                        jnp.where(owned, a, False).astype(I32), "node"
                    ) > 0
                return lax.psum(
                    jnp.where(owned, a, jnp.zeros((), a.dtype)), "node")

            # Stored-layout broadcast (the masked psum is exact on bitcast
            # i32 halves: exactly one node contributes per slot), then a
            # logical view for the dense transition below.
            base = logical_view(jax.tree.map(bcast, rep))

            # Latest request parameters across nodes: max over write
            # stamps (ties broken by node index), then a masked psum
            # selects the winner's aux row — the aggregated request proto
            # of global.go:99-112.
            stamp = aux[AUX["stamp"]]
            key = jnp.where(
                stamp > 0, stamp * n_nodes + my.astype(I64), jnp.int64(-1)
            )
            win = jnp.max(gather_rows(key), axis=0)
            mine = (key == win) & (win >= 0)
            params = lax.psum(jnp.where(mine[None, :], aux, 0), "node")
            havep = win >= 0

            # Forwarded GLOBAL hits get DRAIN_OVER_LIMIT forced
            # (gubernator.go:510-512); RESET_REMAINING applies iff queued
            # this window (stale RESET bits in aux must not re-fire).
            base_behavior = jnp.where(
                havep, params[AUX["behavior"]], 0).astype(I32)
            base_behavior = base_behavior & ~jnp.int32(
                Behavior.RESET_REMAINING)
            base_behavior = base_behavior | jnp.int32(
                Behavior.DRAIN_OVER_LIMIT)

            def make_req(hits, reset, valid):
                return ReqBatch(
                    slot=jnp.arange(capacity, dtype=I32),
                    known=jnp.ones(capacity, jnp.bool_),
                    hits=hits,
                    limit=jnp.where(havep, params[AUX["limit"]], base.limit),
                    duration=jnp.where(
                        havep, params[AUX["duration"]], base.duration
                    ),
                    algorithm=jnp.where(
                        havep, params[AUX["algorithm"]],
                        base.algorithm.astype(I64)
                    ).astype(I32),
                    behavior=jnp.where(
                        reset > 0,
                        base_behavior | jnp.int32(Behavior.RESET_REMAINING),
                        base_behavior,
                    ),
                    created_at=jnp.where(
                        havep, params[AUX["created_at"]], now),
                    burst=jnp.where(havep, params[AUX["burst"]], base.burst),
                    greg_exp=params[AUX["greg_exp"]],
                    greg_dur=params[AUX["greg_dur"]],
                    valid=valid,
                )

            def apply(st, hits, reset, valid):
                # Dense application: slot i ↔ request i — no gather/
                # scatter, no rank rounds; the whole table updates in one
                # elementwise pass.
                new_state, _ = bucket_transition(
                    now, st, make_req(hits, reset, valid)
                )
                return jax.tree.map(
                    lambda n, b: jnp.where(valid, n, b), new_state, st
                )

            # ACC_TOUCH is sparse-only bookkeeping; the dense exchange
            # moves the three rows it reads.
            acc3 = acc_me[:ACC_TOUCH]
            if strict_sequencing:
                # sendHits, exactly: every node's window is one batch at
                # the authority, applied in node order (all_gather +
                # on-device fold).
                acc_all = gather_rows(acc3)  # (n, 3, capacity)

                def fold(d, st):
                    return apply(
                        st,
                        acc_all[d, ACC_HITS],
                        acc_all[d, ACC_RESET],
                        acc_all[d, ACC_COUNT] > 0,
                    )

                merged = lax.fori_loop(0, n_nodes, fold, base)
            else:
                # sendHits as one reduction: cluster-total hits per slot.
                acc = lax.psum(acc3, "node")
                merged = apply(
                    base, acc[ACC_HITS], acc[ACC_RESET], acc[ACC_COUNT] > 0
                )
            return stored_view(merged)

        if not sparse_k:
            merged = dense_recon(None)
            return (
                jax.tree.map(lambda a: a[None], merged),
                jnp.zeros_like(accum_blk),
            )

        # ------------------------------------------------------------------
        # Sparse step: compact → gather envelopes → owner-apply → re-
        # broadcast changed rows.  Compaction is device-local O(capacity)
        # elementwise; everything crossing ICI is O(sparse_k · n).
        # ------------------------------------------------------------------
        K = int(sparse_k)
        K2 = 2 * K
        _, _, wslots, tslots = _sparse_sets(
            acc_me, _make_compact(capacity), K)

        wsl = jnp.clip(wslots, 0, capacity - 1)
        payload = jnp.concatenate([
            wslots.astype(I64)[None],
            acc_me[ACC_HITS][wsl][None],
            acc_me[ACC_RESET][wsl][None],
            acc_me[ACC_COUNT][wsl][None],
            aux[:, wsl],
        ])                                      # (4 + len(AUX_ROWS), K)

        def sparse_recon(_):
            W = gather_rows(payload)            # (n, 13, K)
            sets = gather_rows(jnp.stack([wslots, tslots]))  # (n, 2, K)

            # sendHits at the authority: fold each node's window into MY
            # owned rows, node order (strict semantics; the non-strict
            # psum variant would lose per-window params, so sparse always
            # sequences — window widths are small by construction).
            def fold(d, st):
                slots_d = W[d, 0].astype(I32)
                sl = jnp.clip(slots_d, 0, capacity - 1)
                ok = (slots_d < capacity) & owned[sl] & (W[d, 3] > 0)
                auxd = W[d, 4:]
                havep = auxd[AUX["stamp"]] > 0
                gathered = gather_state(st, sl)
                beh = jnp.where(havep, auxd[AUX["behavior"]], 0).astype(I32)
                beh = beh & ~jnp.int32(Behavior.RESET_REMAINING)
                beh = beh | jnp.int32(Behavior.DRAIN_OVER_LIMIT)
                req = ReqBatch(
                    slot=sl,
                    known=jnp.ones(K, jnp.bool_),
                    hits=W[d, 1],
                    limit=jnp.where(
                        havep, auxd[AUX["limit"]], gathered.limit),
                    duration=jnp.where(
                        havep, auxd[AUX["duration"]], gathered.duration),
                    algorithm=jnp.where(
                        havep, auxd[AUX["algorithm"]],
                        gathered.algorithm.astype(I64)).astype(I32),
                    behavior=jnp.where(
                        W[d, 2] > 0,
                        beh | jnp.int32(Behavior.RESET_REMAINING), beh),
                    created_at=jnp.where(
                        havep, auxd[AUX["created_at"]], now),
                    burst=jnp.where(
                        havep, auxd[AUX["burst"]], gathered.burst),
                    greg_exp=jnp.where(havep, auxd[AUX["greg_exp"]], 0),
                    greg_dur=jnp.where(havep, auxd[AUX["greg_dur"]], 0),
                    valid=ok,
                )
                new_g, _ = bucket_transition(now, gathered, req)
                return scatter_state(
                    st, jnp.where(ok, sl, capacity), new_g)

            st = lax.fori_loop(0, n_nodes, fold, rep)

            # broadcastPeers, sparse: my owned rows that changed (any
            # node's window) or that any node provisionally wrote (its
            # touch set) ship to every replica; receivers scatter them
            # in.  The union derivation is shared with the overflow
            # probe (_mark_touched) so the K2 bound it checked is
            # exactly the set compacted here.
            touched = _mark_touched(capacity, n_nodes, sets)
            bmask = touched & owned
            bslots = _make_compact(capacity)(bmask, K2)
            bsl = jnp.clip(bslots, 0, capacity - 1)
            rows = gather_state(st, bsl)
            BS = gather_rows(bslots)
            BR = jax.tree.map(gather_rows, rows)

            def install(d, st2):
                sl2 = BS[d]
                scat = jnp.where(sl2 < capacity, sl2, capacity)
                return scatter_state(
                    st2, scat, jax.tree.map(lambda a: a[d], BR))

            return lax.fori_loop(0, n_nodes, install, st)

        merged = sparse_recon(None)
        return (
            jax.tree.map(lambda a: a[None], merged),
            jnp.zeros_like(accum_blk),
        )

    state_spec = NODE_LAYOUT.replica_spec()
    return shard_map(
        _recon,
        mesh=mesh,
        in_specs=(state_spec, P("node", None, None), P("node", None, None), P()),
        out_specs=(state_spec, P("node", None, None)),
        check_vma=False,
    )


def make_global_evict_fn(mesh: Mesh):
    """Drop slots on every replica + clear their accumulators/stamps."""
    state_spec = NODE_LAYOUT.replica_spec()

    def _evict(state_blk, aux_blk, accum_blk, slots):
        st = jax.tree.map(lambda a: a[0], state_blk)
        # Zero the whole row, not just in_use: an evicted item is REMOVED
        # (lrucache.go:138-149), and stale don't-care fields would leak
        # into peek()/snapshots when the slot is reborn under the other
        # algorithm (same fix as the local engines' evict).
        from gubernator_tpu.ops.buckets import BucketState as _BS

        st = scatter_state(st, slots, _BS.zeros_logical(slots.shape[0]))
        aux = aux_blk[0].at[AUX["stamp"], slots].set(0, mode="drop")
        acc = accum_blk[0].at[:, slots].set(0, mode="drop")
        return (
            jax.tree.map(lambda a: a[None], st), aux[None], acc[None],
        )

    return shard_map(
        _evict,
        mesh=mesh,
        in_specs=(state_spec, P("node", None, None), P("node", None, None), P()),
        out_specs=(state_spec, P("node", None, None), P("node", None, None)),
        check_vma=False,
    )


class MeshGlobalEngine:
    """Host driver for the replicated GLOBAL table over a device mesh.

    One instance is shared by every service node resident on the mesh (the
    in-process cluster, or the per-host processes of a multi-host mesh);
    each node calls :meth:`process` with its node index, and one driver
    (any of them — calls are internally rate-limited) calls
    :meth:`maybe_reconcile` on the GlobalSyncWait cadence.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity: int = 1 << 16,
        max_batch: int = 1024,
        min_reconcile_ms: int = 0,
        strict_sequencing: bool = True,
        sparse_k: Optional[int] = None,
    ):
        from gubernator_tpu.config import validate_global_mesh_capacity

        validate_global_mesh_capacity(int(capacity))
        self.mesh = mesh if mesh is not None else make_global_mesh()
        self.n_nodes = self.mesh.devices.size
        # Capacity must split evenly into per-node authority slices.
        self.capacity = -(-int(capacity) // self.n_nodes) * self.n_nodes
        self.max_batch = int(max_batch)
        self.min_reconcile_ms = int(min_reconcile_ms)
        # Sparse reconcile envelope: auto-on past the dense envelope's
        # comfortable range (the dense step rewrites every slot on every
        # node; see make_global_reconcile_fn).  Small tables keep the
        # dense step — it is a single fused pass with no compaction
        # bookkeeping and its ICI cost is negligible there.
        if sparse_k is None:
            sparse_k = 4096 if self.capacity > (1 << 16) else 0
        self.sparse_k = min(int(sparse_k), self.capacity)

        row = NODE_LAYOUT.shardings(self.mesh, P("node", None))
        mat = NODE_LAYOUT.shardings(self.mesh, NODE_LAYOUT.mat3())
        self.state: BucketState = jax.tree.map(
            lambda a: jax.device_put(
                jnp.broadcast_to(a, (self.n_nodes,) + a.shape), row
            ),
            BucketState.zeros(self.capacity),
        )
        self.aux = jax.device_put(
            jnp.zeros((self.n_nodes, len(AUX_ROWS), self.capacity), I64), mat
        )
        self.accum = jax.device_put(
            jnp.zeros((self.n_nodes, ACC_ROWS, self.capacity), I64), mat
        )
        self._proc = jax.jit(
            make_global_process_fn(
                self.mesh, self.capacity, self.n_nodes,
                track_touch=bool(self.sparse_k),
            ),
            donate_argnums=(0, 1, 2),
        )
        # The sparse program always sequences per-node windows (its
        # per-window params force it), so when it is enabled the dense
        # overflow fallback must sequence too — otherwise the same
        # traffic would flip semantics on whichever steps happen to
        # overflow the envelope.
        self._recon_dense = jax.jit(
            make_global_reconcile_fn(
                self.mesh, self.capacity, self.n_nodes,
                strict_sequencing or bool(self.sparse_k),
            ),
            donate_argnums=(0, 2),
        )
        if self.sparse_k:
            # The fused step: ONE program computes the overflow probe and
            # the sparse reconcile from a single envelope compaction +
            # gather (the unfused probe/step pair gathered the same sets
            # twice per step; see make_global_sparse_step_fn).
            self._sparse_step = jax.jit(
                make_global_sparse_step_fn(
                    self.mesh, self.capacity, self.n_nodes, self.sparse_k,
                ),
                donate_argnums=(0, 2),
            )
        else:
            self._sparse_step = None
        self.metric_dense_fallbacks = 0
        # Mesh programs launched by reconcile steps: 1 per fused sparse
        # or dense step, 2 when an overflowing step runs the dense
        # fallback after the fused probe.  dispatches/reconciles near
        # 1.0 is the fusion's observable; the bench ladder exports it
        # and scripts/check_bench_regression.py gates on it.
        self.metric_reconcile_dispatches = 0
        self._evict = jax.jit(
            make_global_evict_fn(self.mesh), donate_argnums=(0, 1, 2)
        )
        self.slots = make_slot_map(self.capacity)
        self._last_access = np.zeros(self.capacity, np.int64)
        self._pending: set = set()
        self._tick_count = 0
        self._last_reconcile_ms = 0
        self._reconcile_paused = 0
        self._lock = sanitize.rlock("MeshGlobalEngine._lock")
        self.metric_reconciles = 0
        self._req_sharding = mat
        self._warmup()

    def _warmup(self) -> None:
        m = np.zeros((self.n_nodes, len(REQ_ROWS), self.max_batch), np.int64)
        m[:, REQ_ROW_INDEX["slot"], :] = self.capacity
        self.state, self.aux, self.accum, resp = self._proc(
            self.state, self.aux, self.accum,
            jax.device_put(m, self._req_sharding), jnp.int64(0), jnp.int64(0),
        )
        np.asarray(resp)  # warm the response D2H path (see TickEngine._warmup)
        if self._sparse_step is not None:
            self.state, self.accum, over = self._sparse_step(
                self.state, self.aux, self.accum, jnp.int64(0)
            )
            np.asarray(over)  # warm the probe-bool D2H path
            if self.capacity <= (1 << 20):
                # Big tables leave the dense fallback to compile lazily on
                # the first (rare) overflowing step; warming it would run
                # a full O(capacity·n) pass at startup.
                self.state, self.accum = self._recon_dense(
                    self.state, self.aux, self.accum, jnp.int64(0)
                )
        else:
            self.state, self.accum = self._recon_dense(
                self.state, self.aux, self.accum, jnp.int64(0)
            )
        # Pre-compile the reclaim dead-scan (see TickEngine._warmup).
        from gubernator_tpu.ops.engine import device_dead_mask

        device_dead_mask(
            self.state.in_use[0], slice_field(self.state.expire_at, 0),
            0, self.capacity,
        )
        jax.block_until_ready(self.state)

    # ------------------------------------------------------------------
    # Request path (per node)
    # ------------------------------------------------------------------
    def process(
        self,
        requests: Sequence[RateLimitRequest],
        node_idx: int = 0,
        now: Optional[int] = None,
    ) -> List[RateLimitResponse]:
        """Apply GLOBAL requests that arrived at node ``node_idx``."""
        blocks: List[Sequence[RateLimitRequest]] = [
            [] for _ in range(self.n_nodes)
        ]
        blocks[node_idx] = requests
        return self.process_blocks(blocks, now)[node_idx]

    def process_blocks(
        self,
        blocks: Sequence[Sequence[RateLimitRequest]],
        now: Optional[int] = None,
    ) -> List[List[RateLimitResponse]]:
        """Apply one window of GLOBAL requests, grouped by receiving node.

        Every node's block lands in the same SPMD tick (one program launch
        for the whole mesh); responses mirror the block structure.
        """
        if len(blocks) != self.n_nodes:
            raise ValueError(f"expected {self.n_nodes} blocks, got {len(blocks)}")
        out: List[List[Optional[RateLimitResponse]]] = [
            [None] * len(blk) for blk in blocks
        ]
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            todo = [list(range(len(blk))) for blk in blocks]
            while any(todo):
                left = self._tick_once(blocks, todo, out, now)
                if left == todo:
                    for d, idxs in enumerate(left):
                        for j in idxs:
                            out[d][j] = RateLimitResponse(
                                error="global table full; eviction failed"
                            )
                    break
                todo = left
        return out  # type: ignore[return-value]

    def _tick_once(self, blocks, todo, out, now):
        """Column-vectorized like TickEngine.build_batch: one attribute
        pass per node block, then one fancy-indexed numpy write per
        request-matrix row (the scalar pack_request_col loop was the
        GLOBAL-mesh host bottleneck)."""
        b = self.max_batch
        m = np.zeros((self.n_nodes, len(REQ_ROWS), b), np.int64)
        R = REQ_ROW_INDEX
        m[:, R["slot"], :] = self.capacity
        self._tick_count += 1
        spill = [[] for _ in range(self.n_nodes)]
        packed: List[tuple] = []  # (d, col, j, request, slot, known, ge, gd)
        for d, idxs in enumerate(todo):
            col = 0
            for j in idxs:
                r = blocks[d][j]
                try:
                    ge, gd = resolve_gregorian(r, now)
                except timeutil.GregorianError as e:
                    out[d][j] = RateLimitResponse(error=str(e))
                    continue
                if col >= b:
                    spill[d].append(j)
                    continue
                slot, known = self._resolve(r.hash_key(), now)
                if slot is None:
                    spill[d].append(j)
                    continue
                packed.append((d, col, j, r, slot, known, ge, gd))
                col += 1
        if packed:
            dd_l, cc_l, jj, reqs_l, slot_l, known_l, ge_l, gd_l = zip(*packed)
            dd = np.asarray(dd_l, np.int64)
            cc = np.asarray(cc_l, np.int64)
            pack_request_matrix(
                m, cc, reqs_l, slot_l, known_l, now,
                nodes=dd, greg=(ge_l, gd_l),
            )
            self.state, self.aux, self.accum, resp = self._proc(
                self.state, self.aux, self.accum,
                jax.device_put(m, self._req_sharding),
                jnp.int64(now), jnp.int64(self._tick_count),
            )
            self._pending.clear()
            rm = np.asarray(resp)  # (n_nodes, 5, B)
            status, limit_o, remaining, reset = (
                rm[dd, r, cc].tolist() for r in range(4)
            )
            for t, (d, j) in enumerate(zip(dd_l, jj)):
                out[d][j] = RateLimitResponse(
                    status=status[t], limit=limit_o[t],
                    remaining=remaining[t], reset_time=reset[t],
                )
        return spill

    def _resolve(self, key: str, now: int):
        known = self.slots.get(key) is not None
        slot = self.slots.assign(key)
        if slot is None:
            self._reclaim(now)
            known = self.slots.get(key) is not None
            slot = self.slots.assign(key)
            if slot is None:
                return None, False
        if not known:
            self._pending.add(slot)
        self._last_access[slot] = self._tick_count
        return slot, known

    def _reclaim(self, now: int) -> None:
        """TTL-then-LRU slot reclamation (the shared policy,
        engine.select_reclaim_victims) over the replicated table.

        Authority for expiry is the owner's slice; rather than gather each
        slice, read node 0's replica — correct at reconcile boundaries and
        conservatively stale (never early) between them.
        """
        from gubernator_tpu.ops.engine import (
            device_dead_mask,
            select_reclaim_victims,
        )

        mapped = self.slots.mapped_mask()
        if self._pending:
            mapped[np.fromiter(self._pending, np.int64)] = False
        freed, victims = select_reclaim_victims(
            mapped,
            device_dead_mask(
                self.state.in_use[0], slice_field(self.state.expire_at, 0),
                now, self.capacity,
            ),
            self._last_access,
            self._tick_count,
            max(1, self.capacity // 16),
        )
        self.slots.release_batch(freed)
        if len(victims) == 0:
            return
        self.slots.release_batch(victims)
        from gubernator_tpu.ops.engine import evict_chunked

        def _evict3(bundle, padded):
            st, aux, acc = bundle
            return self._evict(st, aux, acc, padded)

        self.state, self.aux, self.accum = evict_chunked(
            _evict3, (self.state, self.aux, self.accum), victims, self.capacity
        )

    # ------------------------------------------------------------------
    # The collective reconcile (GlobalSyncWait cadence)
    # ------------------------------------------------------------------
    def reconcile(self, now: Optional[int] = None) -> None:
        """One psum + all_gather reconciliation step (see module doc).

        With a sparse envelope configured, the FUSED step computes the
        overflow probe inside the sparse program itself (one envelope
        compaction + gather per step) and returns the bool alongside the
        updated replicas.  An overflowing step applies nothing — its
        scatters are gated off on device, so the returned state/accum
        are the originals — and the host runs the rare dense fallback on
        them (still a host dispatch, not an in-program cond: a cond
        would copy the whole untouched table through the cond output and
        re-impose the O(capacity) cost the sparse step exists to
        remove).
        """
        with self._lock:
            now = now if now is not None else timeutil.now_ms()
            if self._sparse_step is not None:
                self.state, self.accum, over = self._sparse_step(
                    self.state, self.aux, self.accum, jnp.int64(now)
                )
                self.metric_reconcile_dispatches += 1
                if bool(np.asarray(over)):
                    self.metric_dense_fallbacks += 1
                    self.metric_reconcile_dispatches += 1
                    self.state, self.accum = self._recon_dense(
                        self.state, self.aux, self.accum, jnp.int64(now)
                    )
            else:
                self.metric_reconcile_dispatches += 1
                self.state, self.accum = self._recon_dense(
                    self.state, self.aux, self.accum, jnp.int64(now)
                )
            self._pending.clear()
            self._last_reconcile_ms = now
            self.metric_reconciles += 1

    def pause_reconcile(self) -> None:
        """Hold the reconcile cadence (nestable): the reshard coordinator
        quiets the collective plane for its bounded cutover window so
        reconcile programs don't contend with the relayout dispatch on
        the same devices (docs/resharding.md).  Hits keep accumulating —
        a paused cadence defers reconciliation, it never loses it."""
        with self._lock:
            self._reconcile_paused += 1

    def resume_reconcile(self) -> None:
        with self._lock:
            self._reconcile_paused = max(0, self._reconcile_paused - 1)

    def maybe_reconcile(self, now: Optional[int] = None) -> bool:
        """Reconcile unless one ran within ``min_reconcile_ms`` (lets every
        resident node drive the cadence without duplicate work) or the
        cadence is paused for a reshard cutover."""
        if self._reconcile_paused:
            return False
        now = now if now is not None else timeutil.now_ms()
        if now - self._last_reconcile_ms < self.min_reconcile_ms:
            return False
        self.reconcile(now)
        return True

    def cache_size(self) -> int:
        return len(self.slots)

    # Introspection used by tests/benchmarks: per-node view of one key.
    def peek(self, key: str) -> Optional[List[dict]]:
        slot = self.slots.get(key)
        if slot is None:
            return None
        st = {
            name: np_logical(
                slice_field(getattr(self.state, name), (slice(None), slot)),
                name,
            )
            for name in ("remaining", "remaining_f", "status", "in_use", "limit")
        }
        return [
            {
                "remaining": int(st["remaining"][d]),
                "remaining_f": float(st["remaining_f"][d]),
                "status": int(st["status"][d]),
                "in_use": bool(st["in_use"][d]),
                "limit": int(st["limit"][d]),
            }
            for d in range(self.n_nodes)
        ]
