"""Replicated consistent hashing: key → owning peer.

Re-implements the reference's cluster-sharding construction
(``replicated_hash.go:29-119``) with bit-identical hash placement so a
mixed cluster (or a client that precomputes ownership) agrees on owners:

* 512 virtual nodes per peer (``defaultReplicas``),
* replica point ``i`` of a peer = ``fnv1_64(str(i) + md5hex(grpc_address))``,
* key owner = first ring point with ``hash >= fnv1_64(key)``, wrapping.

The TPU-native twist: the ring is a sorted ``numpy`` array, so resolving a
whole request batch is one vectorized ``np.searchsorted`` instead of a
per-key binary-search loop — ownership for a 4k-request tick costs one
array op (the reference walks ``sort.Search`` per key,
``replicated_hash.go:104-119``).

Hash functions are pluggable like ``GUBER_PEER_PICKER_HASH``
(``config.go:429-438``): ``fnv1`` (default) or ``fnv1a``, both 64-bit.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, Generic, List, Optional, Sequence, TypeVar

import numpy as np

from gubernator_tpu.types import PeerInfo

DEFAULT_REPLICAS = 512

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = (1 << 64) - 1


def fnv1_64(data: str) -> int:
    """64-bit FNV-1 (multiply then xor)."""
    h = _FNV_OFFSET
    for b in data.encode():
        h = ((h * _FNV_PRIME) & _MASK) ^ b
    return h


def fnv1a_64(data: str) -> int:
    """64-bit FNV-1a (xor then multiply)."""
    h = _FNV_OFFSET
    for b in data.encode():
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


HASH_FUNCTIONS: Dict[str, Callable[[str], int]] = {
    "fnv1": fnv1_64,
    "fnv1a": fnv1a_64,
}

P = TypeVar("P")  # peer handle type (PeerInfo, PeerClient, ...)


class ReplicatedConsistentHash(Generic[P]):
    """Consistent-hash ring mapping keys to peer handles.

    Peers are identified by their ``grpc_address`` (the reference's
    ``PeerInfo.HashKey()``); the stored handle can be any object exposing
    ``.info`` → :class:`PeerInfo` or a :class:`PeerInfo` itself.
    """

    def __init__(
        self,
        hash_fn: Optional[Callable[[str], int]] = None,
        replicas: int = DEFAULT_REPLICAS,
    ):
        self.hash_fn = hash_fn or fnv1_64
        self.replicas = int(replicas)
        self._peers: Dict[str, P] = {}
        self._ring_hashes = np.zeros(0, np.uint64)
        self._ring_peers: List[P] = []

    @staticmethod
    def _address_of(peer) -> str:
        info = getattr(peer, "info", peer)
        if callable(info):
            info = info()
        return info.grpc_address

    def new(self) -> "ReplicatedConsistentHash[P]":
        """Empty picker with the same configuration (reference New())."""
        return ReplicatedConsistentHash(self.hash_fn, self.replicas)

    def __len__(self) -> int:
        return len(self._peers)

    def peers(self) -> List[P]:
        return list(self._peers.values())

    def get_by_address(self, grpc_address: str) -> Optional[P]:
        return self._peers.get(grpc_address)

    def add(self, peer: P) -> None:
        """Insert a peer's 512 replica points (reference Add(),
        ``replicated_hash.go:78-91``)."""
        addr = self._address_of(peer)
        self._peers[addr] = peer
        md5hex = hashlib.md5(addr.encode()).hexdigest()
        pts = np.fromiter(
            (self.hash_fn(str(i) + md5hex) for i in range(self.replicas)),
            np.uint64,
            count=self.replicas,
        )
        hashes = np.concatenate([self._ring_hashes, pts])
        ring_peers = self._ring_peers + [peer] * self.replicas
        order = np.argsort(hashes, kind="stable")
        self._ring_hashes = hashes[order]
        self._ring_peers = [ring_peers[i] for i in order]

    def get(self, key: str) -> P:
        """Owning peer for one key."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        h = np.uint64(self.hash_fn(key))
        idx = int(np.searchsorted(self._ring_hashes, h, side="left"))
        if idx == len(self._ring_hashes):
            idx = 0
        return self._ring_peers[idx]

    def get_batch(self, keys: Sequence[str]) -> List[P]:
        """Owners for a whole batch: one vectorized searchsorted."""
        if not self._peers:
            raise RuntimeError("unable to pick a peer; pool is empty")
        hs = np.fromiter(
            (self.hash_fn(k) for k in keys), np.uint64, count=len(keys)
        )
        idx = np.searchsorted(self._ring_hashes, hs, side="left")
        idx[idx == len(self._ring_hashes)] = 0
        return [self._ring_peers[i] for i in idx]


class RegionPicker(Generic[P]):
    """Datacenter → ring map (reference ``region_picker.go:29-103``).

    ``get_clients(key)`` returns the owning peer in *every* region — the
    hook MULTI_REGION behavior routes through.
    """

    def __init__(
        self,
        hash_fn: Optional[Callable[[str], int]] = None,
        replicas: int = DEFAULT_REPLICAS,
    ):
        self.hash_fn = hash_fn or fnv1_64
        self.replicas = int(replicas)
        self._regions: Dict[str, ReplicatedConsistentHash[P]] = {}

    def new(self) -> "RegionPicker[P]":
        return RegionPicker(self.hash_fn, self.replicas)

    def pickers(self) -> Dict[str, ReplicatedConsistentHash[P]]:
        return dict(self._regions)

    def regions(self) -> List[str]:
        """Known datacenter names, sorted (deterministic fan-out order
        for the federation exchange)."""
        return sorted(self._regions)

    def add(self, peer: P) -> None:
        info = getattr(peer, "info", peer)
        if callable(info):
            info = info()
        region = self._regions.get(info.datacenter)
        if region is None:
            region = ReplicatedConsistentHash(self.hash_fn, self.replicas)
            self._regions[info.datacenter] = region
        region.add(peer)

    def peers(self) -> List[P]:
        out: List[P] = []
        for region in self._regions.values():
            out.extend(region.peers())
        return out

    def get(self, key: str, datacenter: str = "") -> P:
        region = self._regions.get(datacenter)
        if region is None:
            raise RuntimeError(f"no peers in datacenter {datacenter!r}")
        return region.get(key)

    def get_clients(self, key: str) -> List[P]:
        """The owning peer for ``key`` in every region."""
        return [region.get(key) for region in self._regions.values()]

    def get_by_address(self, grpc_address: str) -> Optional[P]:
        for region in self._regions.values():
            p = region.get_by_address(grpc_address)
            if p is not None:
                return p
        return None
