"""Persistence hooks: Store (write/read-through) and Loader (snapshot).

Mirrors the reference's interface-driven persistence (``store.go:21-78``):

* :class:`Store` — continuous write-through: ``on_change`` fires after every
  bucket mutation with the full item state (algorithms.go:149-153 call
  sites); ``get`` is consulted on cache miss (read-through,
  algorithms.go:45-51); ``remove`` on eviction.
* :class:`Loader` — one-shot: ``load()`` streams items into the engine at
  startup (workers.go:329-413), ``save(items)`` drains the table at
  shutdown (workers.go:451-534).

Items are plain dicts with the engine's SoA field names::

    {key, algorithm, limit, remaining, remaining_f, duration,
     created_at, updated_at, burst, status, expire_at}

(the union of the reference's ``TokenBucketItem``/``LeakyBucketItem`` +
``CacheItem``, store.go:29-43 / cache.go:29-41).

No store implementation ships beyond mocks and a JSONL file loader —
persistence is the embedding user's job, as in the reference (README
"Optional Disk Persistence").
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Protocol

from gubernator_tpu.types import RateLimitRequest


class Store(Protocol):
    """Write-through/read-through hooks (reference store.go:49-65)."""

    def on_change(self, req: RateLimitRequest, item: dict) -> None:
        """Called after every mutation with the full bucket state."""

    def get(self, req: RateLimitRequest) -> Optional[dict]:
        """Called on cache miss; return the persisted item or None."""

    def remove(self, key: str) -> None:
        """Called when an item is evicted from the cache."""


class Loader(Protocol):
    """Startup/shutdown snapshot hooks (reference store.go:69-78)."""

    def load(self) -> Iterable[dict]: ...

    def save(self, items: Iterable[dict]) -> None: ...


class MockStore:
    """Dict-backed Store (reference MockStore, store.go:80-112)."""

    def __init__(self):
        self.data: Dict[str, dict] = {}
        self.called = {"OnChange()": 0, "Get()": 0, "Remove()": 0}

    def on_change(self, req: RateLimitRequest, item: dict) -> None:
        self.called["OnChange()"] += 1
        self.data[item["key"]] = dict(item)

    def get(self, req: RateLimitRequest) -> Optional[dict]:
        self.called["Get()"] += 1
        item = self.data.get(req.hash_key())
        return dict(item) if item is not None else None

    def remove(self, key: str) -> None:
        self.called["Remove()"] += 1
        self.data.pop(key, None)


class MockLoader:
    """List-backed Loader (reference MockLoader, store.go:114-150)."""

    def __init__(self, items: Optional[List[dict]] = None):
        self.contents: List[dict] = list(items or [])
        self.called = {"Load()": 0, "Save()": 0}

    def load(self) -> Iterable[dict]:
        self.called["Load()"] += 1
        return list(self.contents)

    def save(self, items: Iterable[dict]) -> None:
        self.called["Save()"] += 1
        self.contents = list(items)


class FileLoader:
    """JSONL snapshot-to-disk Loader (orbax-style host snapshot of the
    device table; the simplest durable Loader)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Iterable[dict]:
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out

    def save(self, items: Iterable[dict]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for it in items:
                f.write(json.dumps(it) + "\n")
        os.replace(tmp, self.path)
