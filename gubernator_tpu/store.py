"""Persistence hooks: Store (write/read-through) and Loader (snapshot).

Mirrors the reference's interface-driven persistence (``store.go:21-78``):

* :class:`Store` — continuous write-through: ``on_change`` fires after every
  bucket mutation with the full item state (algorithms.go:149-153 call
  sites); ``get`` is consulted on cache miss (read-through,
  algorithms.go:45-51); ``remove`` on eviction.
* :class:`Loader` — one-shot: ``load()`` streams items into the engine at
  startup (workers.go:329-413), ``save(items)`` drains the table at
  shutdown (workers.go:451-534).

Items are plain dicts with the engine's SoA field names::

    {key, algorithm, limit, remaining, remaining_f, duration,
     created_at, updated_at, burst, status, expire_at}

(the union of the reference's ``TokenBucketItem``/``LeakyBucketItem`` +
``CacheItem``, store.go:29-43 / cache.go:29-41).

No store implementation ships beyond mocks and a JSONL file loader —
persistence is the embedding user's job, as in the reference (README
"Optional Disk Persistence").
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Protocol

from gubernator_tpu.types import RateLimitRequest


class Store(Protocol):
    """Write-through/read-through hooks (reference store.go:49-65).

    With tiered bucket state enabled (docs/tiering.md) the Store is also
    the cold tier's **write-behind** sink: when the bounded cold store
    sheds an entry to make room, it calls ``on_change(None, item)`` —
    ``req`` is None because no request drove the flush — so a third
    durability tier can absorb what the host tier drops.  ``remove`` is
    fired when an item leaves the tiered cache entirely: hot-tier
    eviction with no cold tier configured, or cold-tier TTL expiry.

    **Batched extension (optional).**  A store may additionally expose
    ``put_batch(items)`` / ``remove_batch(keys)``; tier dispatchers
    (``ColdStore._flush_shed`` / ``_sink_remove``) feature-detect them
    with ``hasattr`` and fall back to the per-item ``on_change`` /
    ``remove`` loop, so one cold-tier evict sweep costs one sink call
    instead of one Python call per key.  The SSD tier
    (:class:`~gubernator_tpu.tiering.ssd.SsdStore`) implements both,
    plus the columnar ``put_columns(keys, cols, now)`` fast path that
    skips dict materialization entirely."""

    def on_change(self, req: Optional[RateLimitRequest], item: dict) -> None:
        """Called after every mutation with the full bucket state (and
        with ``req=None`` for cold-tier write-behind flushes)."""

    def get(self, req: RateLimitRequest) -> Optional[dict]:
        """Called on cache miss; return the persisted item or None."""

    def remove(self, key: str) -> None:
        """Called when an item is evicted from the cache."""


class BatchStore(Store, Protocol):
    """A Store that also accepts batched writes/removals (see the
    batched-extension note on :class:`Store` — detection is by
    ``hasattr``, this Protocol just names the contract)."""

    def put_batch(self, items: List[dict]) -> None:
        """Absorb one write-behind sweep's items in a single call."""

    def remove_batch(self, keys: List[str]) -> None:
        """Drop a batch of keys in a single call."""


class Loader(Protocol):
    """Startup/shutdown snapshot hooks (reference store.go:69-78)."""

    def load(self) -> Iterable[dict]: ...

    def save(self, items: Iterable[dict]) -> None: ...


class MockStore:
    """Dict-backed Store (reference MockStore, store.go:80-112)."""

    def __init__(self):
        self.data: Dict[str, dict] = {}
        self.called = {"OnChange()": 0, "Get()": 0, "Remove()": 0}

    def on_change(self, req: RateLimitRequest, item: dict) -> None:
        self.called["OnChange()"] += 1
        self.data[item["key"]] = dict(item)

    def get(self, req: RateLimitRequest) -> Optional[dict]:
        self.called["Get()"] += 1
        item = self.data.get(req.hash_key())
        return dict(item) if item is not None else None

    def remove(self, key: str) -> None:
        self.called["Remove()"] += 1
        self.data.pop(key, None)


class MockLoader:
    """List-backed Loader (reference MockLoader, store.go:114-150)."""

    def __init__(self, items: Optional[List[dict]] = None):
        self.contents: List[dict] = list(items or [])
        self.called = {"Load()": 0, "Save()": 0}

    def load(self) -> Iterable[dict]:
        self.called["Load()"] += 1
        return list(self.contents)

    def save(self, items: Iterable[dict]) -> None:
        self.called["Save()"] += 1
        self.contents = list(items)


class FileLoader:
    """JSONL snapshot-to-disk Loader (orbax-style host snapshot of the
    device table; the simplest durable Loader)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Iterable[dict]:
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out

    def save(self, items: Iterable[dict]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for it in items:
                f.write(json.dumps(it) + "\n")
        os.replace(tmp, self.path)


class ColumnLoader(Protocol):
    """Bulk-snapshot Loader (v2): whole-table numpy columns + key blob
    instead of per-item dicts.  The engine detects this protocol and skips
    dict materialization entirely — at 10M items that is seconds instead
    of minutes.  See engine.SNAP_FIELDS for the schema."""

    def load_columns(self) -> Optional[dict]: ...

    def save_columns(self, snap: dict) -> None: ...


class ColumnFileLoader:
    """NPZ columnar snapshot Loader — the durable form of the v2 bulk
    format (and, via load()/save(), also a valid dict Loader for engines
    that don't speak columns)."""

    def __init__(self, path: str):
        self.path = path

    def load_columns(self) -> Optional[dict]:
        import numpy as np

        if not os.path.exists(self.path):
            return None
        with np.load(self.path) as z:
            snap = {k: z[k] for k in z.files}
        snap["key_blob"] = snap["key_blob"].tobytes()
        return snap

    def save_columns(self, snap: dict) -> None:
        import numpy as np

        tmp = self.path + ".tmp.npz"
        enc = dict(snap)
        enc["key_blob"] = np.frombuffer(snap["key_blob"], np.uint8)
        with open(tmp, "wb") as f:
            np.savez(f, **enc)
        os.replace(tmp, self.path)

    # Dict-protocol compatibility (Loader): columnar on disk either way.
    def load(self) -> Iterable[dict]:
        from gubernator_tpu.ops.engine import items_from_snapshot

        snap = self.load_columns()
        return [] if snap is None else items_from_snapshot(snap)

    def save(self, items: Iterable[dict]) -> None:
        from gubernator_tpu.ops.engine import snapshot_from_items

        self.save_columns(snapshot_from_items(list(items)))
