# Build + deploy image for gubernator-tpu (reference: Dockerfile, which
# builds static Go binaries; here the runtime is Python/JAX so the deploy
# image is a slim Python base with the package installed).
#
# The default install runs the CPU backend of XLA — correct everywhere and
# right for development clusters. On TPU hosts, build with
#   --build-arg JAX_EXTRA="jax[tpu]"
# (pulls libtpu; the daemon finds the chips automatically).
FROM python:3.12-slim AS build

ARG JAX_EXTRA=""

# g++ builds the native slotmap (the host-side key→slot table).
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /src
COPY pyproject.toml README.md ./
COPY gubernator_tpu ./gubernator_tpu

RUN make -C gubernator_tpu/native \
    && pip install --no-cache-dir --prefix=/install . ${JAX_EXTRA}

FROM python:3.12-slim

COPY --from=build /install /usr/local

# Container healthcheck probes /v1/HealthCheck on the local daemon
# (reference Dockerfile HEALTHCHECK, cmd/healthcheck). The probe is a
# Python process that imports the package (~2s); the timeout must cover
# that, not just the HTTP round trip.
HEALTHCHECK --interval=10s --timeout=5s --start-period=60s --retries=2 \
    CMD [ "gubernator-tpu-healthcheck" ]

ENTRYPOINT ["gubernator-tpu"]

# HTTP / gRPC / memberlist gossip (reference exposes the same three).
EXPOSE 80
EXPOSE 81
EXPOSE 7946
