"""Autoscaler controller tests (docs/autoscaling.md).

Everything here runs on a :class:`ManualClock` with a fake reshard
executor — no engine builds, no real sleeps.  What these pin is the
guardrail contract: N sustained windows before any action, hysteresis
bands that cannot ping-pong, cooldown / flap-cap / breaker / busy
vetoes counted by reason, dry-run never actuating, and the bounded
decision ring wrapping instead of growing.
"""

import asyncio

import pytest

from gubernator_tpu.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    PolicyConfig,
    SignalSnapshot,
)
from gubernator_tpu.autoscale.controller import ACT, HOLD, VETO
from gubernator_tpu.autoscale.policy import DOWN, UP
from gubernator_tpu.resilience import ManualClock
from gubernator_tpu.utils.metrics import Metrics


def _snap(p99=1.0, queue=0, occ=0.5, shards=2, **kw):
    return SignalSnapshot(
        p99_ms=p99, queue_depth=queue, hot_occupancy=occ, shards=shards,
        **kw,
    )


class _Feed:
    """Scripted sampler: pops queued snapshots, repeats the last one."""

    def __init__(self, *snaps):
        self.snaps = list(snaps)

    def script(self, *snaps):
        """Replace the remaining script (takes effect next sample)."""
        self.snaps = list(snaps)

    def __call__(self):
        if len(self.snaps) > 1:
            return self.snaps.pop(0)
        return self.snaps[0]


class _FakeReshard:
    """Executor double: records targets, scripts outcomes."""

    def __init__(self, outcome="committed"):
        self.calls = []
        self.outcome = outcome

    def __call__(self, target):
        self.calls.append(target)
        if self.outcome == "busy":
            return {"result": "busy"}
        if self.outcome == "raise":
            raise RuntimeError("engine exploded")
        return {"outcome": self.outcome, "from_shards": 0,
                "to_shards": target}


def _scaler(feed, reshard, *, windows=3, dry_run=False, clock=None, **kw):
    clock = clock or ManualClock()
    policy = AutoscalePolicy(PolicyConfig(
        windows=windows, target_p99_ms=5.0, queue_high=100,
        hysteresis=0.5, occupancy_low=0.3, min_shards=1, max_shards=8,
    ))
    scaler = Autoscaler(
        feed, reshard, policy=policy, dry_run=dry_run,
        clock=clock, sleep=clock.sleep, **kw,
    )
    return scaler, clock


async def _steps(scaler, clock, n, dt=10.0):
    out = []
    for _ in range(n):
        clock.advance(dt)
        out.append(await scaler.step())
    return out


def test_single_spike_holds_sustained_pressure_acts():
    """One hot window is noise; N consecutive hot windows are load."""

    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0), _snap(), _snap())
        scaler, clock = _scaler(feed, rs, windows=3)
        # spike, then calm: streak resets, nothing actuates
        d = await _steps(scaler, clock, 3)
        assert [x.action for x in d] == [HOLD, HOLD, HOLD]
        assert rs.calls == []
        # sustained: 3 consecutive hot windows → scale up 2 → 4
        feed.script(_snap(p99=50.0))
        d = await _steps(scaler, clock, 3)
        assert [x.action for x in d] == [HOLD, HOLD, ACT]
        assert d[-1].direction == UP and d[-1].to_shards == 4
        assert rs.calls == [4]

    asyncio.run(run())


def test_queue_depth_alone_triggers_scale_up():
    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap(queue=500))
        scaler, clock = _scaler(feed, rs, windows=2)
        d = await _steps(scaler, clock, 2)
        assert d[-1].action == ACT and d[-1].direction == UP
        assert rs.calls == [4]

    asyncio.run(run())


def test_hysteresis_band_prevents_ping_pong():
    """A p99 between target × hysteresis and target satisfies neither
    band: after a scale-up driven by p99 > 5, a p99 of 4 (under target,
    over the 2.5 down-band) with low occupancy must hold forever — the
    classic ping-pong input."""

    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0))
        scaler, clock = _scaler(feed, rs, windows=2, cooldown_up=0.0,
                                cooldown_down=0.0)
        await _steps(scaler, clock, 2)
        assert rs.calls == [4]
        # in-band: under target (no up), over target×hysteresis (no down)
        feed.script(_snap(p99=4.0, occ=0.05, shards=4))
        d = await _steps(scaler, clock, 20)
        assert all(x.action == HOLD for x in d)
        assert rs.calls == [4]  # no reversal, ever
        # genuinely idle (p99 under the down band too) → scale down
        feed.script(_snap(p99=1.0, occ=0.05, shards=4))
        d = await _steps(scaler, clock, 2)
        assert d[-1].action == ACT and d[-1].direction == DOWN
        assert rs.calls == [4, 2]

    asyncio.run(run())


def test_cooldown_vetoes_counted_then_expire():
    async def run():
        m = Metrics()
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0))
        scaler, clock = _scaler(feed, rs, windows=1, metrics=m,
                                cooldown_up=120.0)
        d = await _steps(scaler, clock, 1)
        assert d[0].action == ACT and rs.calls == [4]
        # inside the 120 s up-cooldown (10 s steps): vetoed by name
        d = await _steps(scaler, clock, 3)
        assert [x.reason for x in d] == ["cooldown_up"] * 3
        assert m.sample("gubernator_tpu_autoscale_vetoes_total",
                        {"reason": "cooldown_up"}) == 3
        # past the cooldown the sustained pressure acts again
        clock.advance(120.0)
        d = await _steps(scaler, clock, 1)
        assert d[0].action == ACT
        assert rs.calls == [4, 4]

    asyncio.run(run())


def test_flap_cap_bounds_transitions_per_rolling_hour():
    async def run():
        m = Metrics()
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0))
        scaler, clock = _scaler(feed, rs, windows=1, metrics=m,
                                cooldown_up=0.0, max_per_hour=2)
        d = await _steps(scaler, clock, 5)
        acts = [x for x in d if x.action == ACT]
        vetoes = [x for x in d if x.action == VETO]
        assert len(acts) == 2 and len(rs.calls) == 2
        assert all(x.reason == "flap_cap" for x in vetoes)
        assert m.sample("gubernator_tpu_autoscale_vetoes_total",
                        {"reason": "flap_cap"}) == 3
        # an hour later the budget refills
        clock.advance(3600.0)
        d = await _steps(scaler, clock, 1)
        assert d[0].action == ACT and len(rs.calls) == 3

    asyncio.run(run())


def test_open_breaker_vetoes_actuation():
    async def run():
        m = Metrics()
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0, breaker_open=True))
        scaler, clock = _scaler(feed, rs, windows=1, metrics=m)
        d = await _steps(scaler, clock, 3)
        assert all(x.action == VETO and x.reason == "breaker_open"
                   for x in d)
        assert rs.calls == []
        assert m.sample("gubernator_tpu_autoscale_vetoes_total",
                        {"reason": "breaker_open"}) == 3

    asyncio.run(run())


def test_reshard_busy_vetoes_before_and_after_the_call():
    """Both busy paths: the sampled coordinator lock (pre-check) and
    the BUSY_RESULT dict from losing the race to the admin endpoint."""

    async def run():
        m = Metrics()
        # pre-check: snapshot says a transition is running
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0, reshard_busy=True))
        scaler, clock = _scaler(feed, rs, windows=1, metrics=m)
        (d,) = await _steps(scaler, clock, 1)
        assert d.action == VETO and d.reason == "reshard_busy"
        assert rs.calls == []
        # post-hoc: the executor answers the coordinator's busy dict
        rs2 = _FakeReshard(outcome="busy")
        feed2 = _Feed(_snap(p99=50.0))
        scaler2, clock2 = _scaler(feed2, rs2, windows=1, metrics=m)
        (d,) = await _steps(scaler2, clock2, 1)
        assert d.action == VETO and d.reason == "reshard_busy"
        assert rs2.calls == [4]  # called, refused, counted
        assert m.sample("gubernator_tpu_autoscale_vetoes_total",
                        {"reason": "reshard_busy"}) == 2

    asyncio.run(run())


def test_dry_run_records_act_but_never_actuates():
    async def run():
        m = Metrics()
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0))
        scaler, clock = _scaler(feed, rs, windows=1, dry_run=True,
                                metrics=m)
        d = await _steps(scaler, clock, 5)
        assert all(x.action == ACT and x.dry_run for x in d)
        assert all(x.outcome == "dry_run" for x in d)
        assert rs.calls == []  # the whole point
        assert scaler.transitions_last_hour() == 0
        assert m.sample("gubernator_tpu_autoscale_transitions_total",
                        {"direction": "up"}) == 0
        assert m.sample("gubernator_tpu_autoscale_decisions_total",
                        {"action": "act"}) == 5

    asyncio.run(run())


def test_executor_failure_is_a_veto_not_a_dead_loop():
    async def run():
        rs = _FakeReshard(outcome="raise")
        feed = _Feed(_snap(p99=50.0))
        scaler, clock = _scaler(feed, rs, windows=1)
        (d,) = await _steps(scaler, clock, 1)
        assert d.action == VETO and d.reason == "reshard_error"

    asyncio.run(run())


def test_frozen_sample_is_skipped_not_counted_as_pressure():
    """Samples taken during a cutover freeze (queue inflated by the
    controller's own transition) must not feed the streaks."""

    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0, frozen=True))
        scaler, clock = _scaler(feed, rs, windows=2)
        d = await _steps(scaler, clock, 10)
        assert all(x.action == HOLD for x in d)
        assert rs.calls == []

    asyncio.run(run())


def test_at_bound_holds_instead_of_acting():
    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0, shards=8))  # already at max_shards
        scaler, clock = _scaler(feed, rs, windows=1)
        (d,) = await _steps(scaler, clock, 1)
        assert d.action == HOLD and d.reason == "at_bound"
        assert rs.calls == []

    asyncio.run(run())


def test_decision_ring_wraps_bounded():
    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap())
        scaler, clock = _scaler(feed, rs, windows=3, ring_size=8)
        await _steps(scaler, clock, 50)
        assert len(scaler.ring) == 8
        state = scaler.debug_state()
        assert len(state["decisions"]) == 8
        # newest entry survives the wrap
        assert state["last_decision"]["ts"] == pytest.approx(
            scaler.ring[-1].ts)

    asyncio.run(run())


def test_supervised_loop_runs_on_injected_clock():
    """start()/stop() with the ManualClock sleep: each loop turn is one
    interval sleep + one step; no wall-clock waits anywhere."""

    async def run():
        rs = _FakeReshard()
        feed = _Feed(_snap(p99=50.0))
        clock = ManualClock()

        async def vsleep(dt):
            # ManualClock.sleep plus one real yield so the test task
            # interleaves with the supervised loop.
            await clock.sleep(dt)
            await asyncio.sleep(0)

        policy = AutoscalePolicy(PolicyConfig(windows=1, target_p99_ms=5.0))
        scaler = Autoscaler(feed, rs, policy=policy, dry_run=False,
                            interval=10.0, clock=clock, sleep=vsleep)
        scaler.start()
        for _ in range(40):
            if rs.calls:
                break
            await asyncio.sleep(0)  # let the loop turn on virtual time
        await scaler.stop()
        assert rs.calls and rs.calls[0] == 4
        assert clock.sleeps and all(s == 10.0 for s in clock.sleeps)

    asyncio.run(run())


def test_policy_target_shards_doubles_halves_and_clamps():
    p = AutoscalePolicy(PolicyConfig(min_shards=2, max_shards=8))
    assert p.target_shards(2, UP) == 4
    assert p.target_shards(8, UP) == 8
    assert p.target_shards(4, DOWN) == 2
    assert p.target_shards(2, DOWN) == 2


def test_config_rejects_overlapping_hysteresis():
    from gubernator_tpu.config import setup_daemon_config

    with pytest.raises(ValueError, match="GUBER_AUTOSCALE_HYSTERESIS"):
        setup_daemon_config(environ={
            "GUBER_AUTOSCALE_HYSTERESIS": "1.0",
        })
    with pytest.raises(ValueError, match="GUBER_AUTOSCALE_MAX_SHARDS"):
        setup_daemon_config(environ={
            "GUBER_AUTOSCALE_MIN_SHARDS": "4",
            "GUBER_AUTOSCALE_MAX_SHARDS": "2",
        })
