"""Property tests: i64-pair and triple-f32 arithmetic vs numpy oracles.

These are the primitives the parts-native bucket transition is built
from (ops/i64pair.py, ops/tfloat.py); pair ops must be bit-exact i64,
triple ops must be >= f64-class precise on the engine's envelope.
"""

import numpy as np
import pytest

from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.ops import tfloat as tf

RNG = np.random.default_rng(7)


def rand_i64(n, lo=-(2**62), hi=2**62):
    specials = np.array(
        [0, 1, -1, 2**31 - 1, 2**31, -(2**31), 2**32 - 1, 2**32,
         -(2**32), 2**52, -(2**52), 2**62 - 1, -(2**62),
         1_700_000_000_000, 3_600_000],
        np.int64,
    )
    vals = RNG.integers(lo, hi, n - len(specials), dtype=np.int64)
    return np.concatenate([specials, vals])


class TestPair:
    def setup_method(self, _):
        self.a = rand_i64(512)
        self.b = rand_i64(512)[::-1].copy()
        self.pa = p64.from_np(self.a)
        self.pb = p64.from_np(self.b)

    def test_roundtrip(self):
        np.testing.assert_array_equal(p64.to_np(self.pa), self.a)

    def test_add_sub_neg(self):
        np.testing.assert_array_equal(
            p64.to_np(p64.add(self.pa, self.pb)), self.a + self.b)
        np.testing.assert_array_equal(
            p64.to_np(p64.sub(self.pa, self.pb)), self.a - self.b)
        np.testing.assert_array_equal(p64.to_np(p64.neg(self.pa)), -self.a)

    def test_mul_wraps(self):
        np.testing.assert_array_equal(
            p64.to_np(p64.mul(self.pa, self.pb)),
            (self.a * self.b))  # numpy int64 mul wraps two's-complement

    def test_compares(self):
        for name, op in [("lt", np.less), ("le", np.less_equal),
                         ("gt", np.greater), ("ge", np.greater_equal),
                         ("eq", np.equal), ("ne", np.not_equal)]:
            got = np.asarray(getattr(p64, name)(self.pa, self.pb))
            np.testing.assert_array_equal(got, op(self.a, self.b), err_msg=name)

    def test_minmax_select(self):
        np.testing.assert_array_equal(
            p64.to_np(p64.max_(self.pa, self.pb)), np.maximum(self.a, self.b))
        np.testing.assert_array_equal(
            p64.to_np(p64.min_(self.pa, self.pb)), np.minimum(self.a, self.b))
        c = self.a > 0
        np.testing.assert_array_equal(
            p64.to_np(p64.select(c, self.pa, self.pb)),
            np.where(c, self.a, self.b))

    def test_shr(self):
        for n in (0, 1, 24, 31, 32, 48, 63):
            np.testing.assert_array_equal(
                p64.to_np(p64.shr(self.pa, n)), self.a >> n, err_msg=str(n))

    def test_from_i32_const(self):
        x = RNG.integers(-(2**31), 2**31, 64, dtype=np.int64)
        np.testing.assert_array_equal(
            p64.to_np(p64.from_i32(x.astype(np.int32))), x)
        np.testing.assert_array_equal(
            p64.to_np(p64.const(-(5 << 40), np.zeros(4, np.int32))),
            np.full(4, -(5 << 40)))


class TestTriple:
    def test_pair_roundtrip_exact(self):
        v = rand_i64(512, -(2**62), 2**62)
        t = tf.from_pair(p64.from_np(v))
        np.testing.assert_array_equal(tf.to_np(t), v.astype(np.float64))
        back = p64.to_np(tf.floor_to_pair(t))
        np.testing.assert_array_equal(back, v)

    def test_add_precision(self):
        # drip accumulation shape: integer counts + small fractions
        a = RNG.uniform(-1e12, 1e12, 512)
        b = RNG.uniform(-1e3, 1e3, 512)
        got = tf.to_np(tf.add(tf.from_np(a), tf.from_np(b)))
        want = a + b
        # ~60-bit precision: within a couple of f64 ulps (XLA's own TPU
        # f64 emulation is a float32 pair, ~49 bits — far looser).
        np.testing.assert_allclose(got, want, rtol=5e-16)

    def test_div_exact_when_representable(self):
        # golden-suite rates: duration / limit with exact quotients
        dur = np.array([30_000, 60_000, 1_000, 5_000, 3_600_000] * 8,
                       np.float64)
        lim = np.array([10, 10, 4, 5, 1000] * 8, np.float64)
        got = tf.to_np(tf.div(tf.from_np(dur), tf.from_np(lim)))
        np.testing.assert_array_equal(got, dur / lim)

    def test_div_precision_random(self):
        a = RNG.uniform(1, 1e15, 512)
        b = RNG.uniform(1, 1e9, 512)
        got = tf.to_np(tf.div(tf.from_np(a), tf.from_np(b)))
        np.testing.assert_allclose(got, a / b, rtol=5e-16)

    def test_floor(self):
        x = np.concatenate([
            RNG.uniform(-1e9, 1e9, 500),
            np.array([0.0, -0.0, 0.5, -0.5, 1.0, -1.0, 2**40 + 0.5,
                      -(2**40) - 0.5, 3.9999999, -3.0000001, 1e-300, 7.0,
                      # within half an f32 ulp of an integer: the raw
                      # per-part fraction sum misrounds without the
                      # compare-verified correction step
                      4.0 - 1e-9, -4.0 + 1e-9, 4.0 + 1e-9, -4.0 - 1e-9,
                      1e6 - 1e-7, -(1e6 - 1e-7)]),
        ])
        got = p64.to_np(tf.floor_to_pair(tf.from_np(x)))
        np.testing.assert_array_equal(got, np.floor(x).astype(np.int64))

    def test_compares(self):
        a = RNG.uniform(-100, 100, 512)
        b = np.where(RNG.random(512) < 0.3, a, RNG.uniform(-100, 100, 512))
        ta, tb = tf.from_np(a), tf.from_np(b)
        np.testing.assert_array_equal(np.asarray(tf.ge(ta, tb)), a >= b)
        np.testing.assert_array_equal(np.asarray(tf.gt(ta, tb)), a > b)
        np.testing.assert_array_equal(np.asarray(tf.ge_zero(ta)), a >= 0)
        np.testing.assert_array_equal(np.asarray(tf.gt_zero(ta)), a > 0)

    def test_compare_pair(self):
        a = RNG.uniform(-1e6, 1e6, 512)
        v = RNG.integers(-(10**6), 10**6, 512, dtype=np.int64)
        ta = tf.from_np(a)
        pv = p64.from_np(v)
        np.testing.assert_array_equal(
            np.asarray(tf.ge_pair(ta, pv)), a >= v.astype(np.float64))
        np.testing.assert_array_equal(
            np.asarray(tf.gt_pair(ta, pv)), a > v.astype(np.float64))

    def test_mul_f(self):
        a = RNG.uniform(-1e9, 1e9, 512)
        f = RNG.uniform(-1e3, 1e3, 512).astype(np.float32)
        got = tf.to_np(tf.mul_f(tf.from_np(a), f))
        want = a * f.astype(np.float64)
        np.testing.assert_allclose(got, want, rtol=1e-15)

    def test_leaky_drip_scenario(self):
        # 10 tokens / 30s -> rate 3000 ms/token; drip accumulation must
        # stay integer-exact over many steps (the golden sequences).
        rate = tf.div(tf.from_np(np.full(8, 30_000.0)),
                      tf.from_np(np.full(8, 10.0)))
        rem = tf.from_np(np.full(8, 7.0))
        for elapsed in (3000.0, 6000.0, 1500.0, 4500.0):
            leak = tf.div(tf.from_np(np.full(8, elapsed)), rate)
            rem = tf.add(rem, leak)
        np.testing.assert_array_equal(
            tf.to_np(rem), np.full(8, 7 + (3000 + 6000 + 1500 + 4500) / 3000))
