"""Algorithm-zoo tests (docs/algorithms.md): golden semantics for the
sliding-window, GCRA, and concurrency transitions through the real
engine, seeded parity fuzz against the scalar references, the
one-dispatch pin for mixed-policy batches, and the mesh's zero-retrace
pin across changing algorithm mixes.
"""

import jax
import numpy as np
import pytest

from gubernator_tpu.algos import reference
from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status

NOW = 1_700_000_000_000  # divisible by 1000: window-aligned golden math


def req(key, alg, hits=1, limit=10, duration=1000, burst=0, behavior=0,
        created_at=None):
    return RateLimitRequest(
        name="zoo", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=alg, behavior=behavior, burst=burst,
        created_at=created_at,
    )


# Module-scoped, and the same geometry other suite files compile (tier-1
# runs near the driver budget — docs/algorithms.md tests must be
# near-free): every test uses its own keys, so sharing the engine is safe.
@pytest.fixture(scope="module")
def eng():
    return TickEngine(capacity=512, max_batch=64)


def one(eng, r, now):
    return eng.process([r], now=now)[0]


# ----------------------------------------------------------------------
# Golden semantics
# ----------------------------------------------------------------------
def test_sliding_window_weighted_carry(eng):
    SW = Algorithm.SLIDING_WINDOW
    # Fill the first window.
    r = one(eng, req("sw", SW, hits=10, created_at=NOW), now=NOW)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    assert r.reset_time == NOW + 1000  # current window's end
    r = one(eng, req("sw", SW, hits=1, created_at=NOW), now=NOW)
    assert r.status == Status.OVER_LIMIT
    # One window later the old count carries at full weight...
    t1 = NOW + 1000
    r = one(eng, req("sw", SW, hits=1, created_at=t1), now=t1)
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 0)
    # ...and fades linearly: halfway through, 10*500//1000 = 5 weighted
    # prior hits leave room for exactly 5 more.
    t2 = NOW + 1500
    r = one(eng, req("sw", SW, hits=5, created_at=t2), now=t2)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    r = one(eng, req("sw", SW, hits=1, created_at=t2), now=t2)
    assert r.status == Status.OVER_LIMIT


def test_sliding_window_drain_and_negative_hits(eng):
    SW = Algorithm.SLIDING_WINDOW
    r = one(eng, req("swd", SW, hits=12, behavior=Behavior.DRAIN_OVER_LIMIT,
                     created_at=NOW), now=NOW)
    # Rejected, but the residual 10-hit budget burns (drain semantics).
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 0)
    r = one(eng, req("swd", SW, hits=1, created_at=NOW), now=NOW)
    assert r.status == Status.OVER_LIMIT
    # Negative hits return budget, clamped at the window floor.
    r = one(eng, req("swd", SW, hits=-3, created_at=NOW), now=NOW)
    assert r.remaining == 3


def test_gcra_burst_then_smooth_refill(eng):
    G = Algorithm.GCRA
    # limit=10/1000ms -> emission interval T=100ms, tau=900ms: a full
    # burst conforms exactly once...
    r = one(eng, req("g", G, hits=10, created_at=NOW), now=NOW)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    r = one(eng, req("g", G, hits=1, created_at=NOW), now=NOW)
    assert r.status == Status.OVER_LIMIT
    # ...and reset_time is the exact earliest-conform instant: one T
    # after the burst, precisely one slot has drained.
    assert r.reset_time == NOW + 100
    t1 = NOW + 100
    r = one(eng, req("g", G, hits=1, created_at=t1), now=t1)
    assert r.status == Status.UNDER_LIMIT
    r = one(eng, req("g", G, hits=1, created_at=t1), now=t1)
    assert r.status == Status.OVER_LIMIT


def test_gcra_burst_one_disables_bursting(eng):
    G = Algorithm.GCRA
    # burst=1 -> tau=0: strictly one hit per emission interval.
    r = one(eng, req("gb", G, hits=1, burst=1, created_at=NOW), now=NOW)
    assert r.status == Status.UNDER_LIMIT
    r = one(eng, req("gb", G, hits=1, burst=1, created_at=NOW), now=NOW)
    assert r.status == Status.OVER_LIMIT
    t1 = NOW + 100
    r = one(eng, req("gb", G, hits=1, burst=1, created_at=t1), now=t1)
    assert r.status == Status.UNDER_LIMIT


def test_concurrency_acquire_release_clamp(eng):
    C = Algorithm.CONCURRENCY
    r = one(eng, req("c", C, hits=3, limit=5, created_at=NOW), now=NOW)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 2)
    # All-or-nothing: 3 > 2 free slots rejects without partial acquire.
    r = one(eng, req("c", C, hits=3, limit=5, created_at=NOW), now=NOW)
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 2)
    r = one(eng, req("c", C, hits=-1, limit=5, created_at=NOW), now=NOW)
    assert r.remaining == 3
    # Double-release clamps at limit — releases can't mint capacity.
    r = one(eng, req("c", C, hits=-10, limit=5, created_at=NOW), now=NOW)
    assert r.remaining == 5


def test_concurrency_ttl_reclaims_leaked_slots(eng):
    C = Algorithm.CONCURRENCY
    r = one(eng, req("cl", C, hits=5, limit=5, duration=1000,
                     created_at=NOW), now=NOW)
    assert r.remaining == 0
    # The holder dies without releasing; past the lease TTL the bucket
    # expires and all five slots return.
    t1 = NOW + 1001
    r = one(eng, req("cl", C, hits=1, limit=5, duration=1000,
                     created_at=t1), now=t1)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 4)


def test_concurrency_limit_rebase_preserves_in_flight(eng):
    C = Algorithm.CONCURRENCY
    one(eng, req("cr", C, hits=2, limit=5, created_at=NOW), now=NOW)
    # Raising the limit re-bases free slots by the delta: 2 stay
    # in flight, 3+5 are free.
    r = one(eng, req("cr", C, hits=0, limit=10, created_at=NOW), now=NOW)
    assert r.remaining == 8


def test_reset_remaining_restarts_zoo_bucket(eng):
    G = Algorithm.GCRA
    one(eng, req("rr", G, hits=10, created_at=NOW), now=NOW)
    r = one(eng, req("rr", G, hits=1, behavior=Behavior.RESET_REMAINING,
                     created_at=NOW), now=NOW)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 9)


def test_algorithm_switch_restarts_bucket(eng):
    one(eng, req("sw2", Algorithm.TOKEN_BUCKET, hits=5, created_at=NOW),
        now=NOW)
    # Same key, different algorithm: the stored-algorithm existence check
    # fails and the bucket restarts as a fresh GCRA.
    r = one(eng, req("sw2", Algorithm.GCRA, hits=1, created_at=NOW),
            now=NOW)
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 9)


# ----------------------------------------------------------------------
# Parity fuzz vs the scalar references
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [1, 2])
def test_fuzz_engine_matches_references(seed):
    """Randomized mixed traffic — all five algorithms, duplicates,
    negative hits, queries, RESET/DRAIN, parameter churn, time
    advancement — with every zoo-lane decision compared ``==`` against
    the scalar references replaying the same stream."""
    rng = np.random.default_rng(seed)
    eng = TickEngine(capacity=512, max_batch=64)
    now = NOW
    model = {}

    def ref_apply(r, t):
        alg = int(r.algorithm)
        if alg < int(Algorithm.SLIDING_WINDOW):
            return None  # token/leaky parity is test_fuzz_parity's job
        ns, resp = reference.transition(
            model.get(r.unique_key),
            dict(hits=r.hits, limit=r.limit, duration=r.duration,
                 algorithm=alg, behavior=int(r.behavior), burst=r.burst,
                 created_at=r.created_at),
            t,
        )
        model[r.unique_key] = ns
        return (resp["status"], resp["remaining"], resp["reset_time"])

    for step in range(25):
        now += int(rng.choice([0, 50, 400, 2_000, 61_000]))
        reqs = []
        for _ in range(48):
            alg = int(rng.integers(0, 5))
            behavior = 0
            if rng.random() < 0.15:
                behavior = int(rng.choice(
                    [Behavior.RESET_REMAINING, Behavior.DRAIN_OVER_LIMIT]
                ))
            # Keys pinned per algorithm so the host model never needs
            # token/leaky state (algorithm switches are covered above).
            reqs.append(req(
                f"k{int(rng.integers(0, 40))}-a{alg}", alg,
                hits=int(rng.choice([0, 1, 1, 2, 5, -1, -3])),
                limit=int(rng.choice([3, 10, 100])),
                duration=int(rng.choice([1_000, 5_000, 60_000])),
                burst=int(rng.choice([0, 2, 20])),
                behavior=behavior, created_at=now,
            ))
        got = eng.process(reqs, now=now)
        for r, g in zip(reqs, got):
            want = ref_apply(r, now)
            if want is None:
                continue
            assert (int(g.status), int(g.remaining),
                    int(g.reset_time)) == want, (
                f"seed {seed} step {step} key {r.unique_key} "
                f"hits {r.hits} behavior {r.behavior}"
            )


# ----------------------------------------------------------------------
# One dispatch for mixed-policy batches
# ----------------------------------------------------------------------
def test_mixed_five_algorithm_batch_is_one_dispatch(eng):
    """A batch mixing all five algorithms — zoo duplicates included —
    runs exactly ONE device tick program (docs/algorithms.md): the
    per-lane algorithm fold replaces per-policy sub-batches."""
    calls = []
    saved = {n: getattr(eng, n) for n in ("_tick32", "_tick32m", "_tick")}
    for name, fn in saved.items():
        def wrap(fn, name=name):
            def run(*a, **kw):
                calls.append(name)
                return fn(*a, **kw)
            return run
        setattr(eng, name, wrap(fn))

    try:
        # Unique mixed batch: one lane per algorithm.
        reqs = [req(f"u{a}", a, created_at=NOW) for a in range(5)]
        eng.process(reqs, now=NOW)
        assert len(calls) == 1

        # Mixed batch WITH zoo duplicates (fold-exempt — they ride size-1
        # units of the same program, never a second dispatch).
        calls.clear()
        reqs = [req(f"d{a}", a, created_at=NOW)
                for a in [0, 1, 2, 2, 3, 3, 4, 4, 4]]
        eng.process(reqs, now=NOW)
        assert len(calls) == 1
    finally:
        for name, fn in saved.items():  # the fixture outlives this test
            setattr(eng, name, fn)
    # Duplicate zoo lanes applied sequentially: 3 acquires landed.
    r = one(eng, req("d4", Algorithm.CONCURRENCY, hits=0, created_at=NOW),
            now=NOW)
    assert r.remaining == 7


# ----------------------------------------------------------------------
# Mesh: parity + zero retraces across mixed-policy shapes
# ----------------------------------------------------------------------
def test_mesh_mixed_algos_parity_and_no_retrace():
    from gubernator_tpu.parallel.mesh_engine import MeshTickEngine, make_mesh

    mesh_eng = MeshTickEngine(
        mesh=make_mesh(jax.devices()), local_capacity=128, max_batch=64,
    )
    ref_eng = TickEngine(capacity=512, max_batch=64)
    rng = np.random.default_rng(3)

    def batch(algs):
        return [
            req(f"m{i % 24}-a{a}", a,
                hits=int(rng.choice([0, 1, 2, -1])), created_at=None)
            for i, a in enumerate(algs)
        ]

    # Warm every program variant with an all-five mix — a unique window
    # (parts program) plus a duplicate-bearing one (merge walker), so
    # the snapshot below covers both serving programs...
    warm = batch([i % 5 for i in range(48)])
    assert [
        (r.status, r.remaining) for r in mesh_eng.process(warm, now=NOW)
    ] == [
        (r.status, r.remaining) for r in ref_eng.process(warm, now=NOW)
    ]
    dup = batch([i % 5 for i in range(24)] * 2)
    assert [
        (r.status, r.remaining) for r in mesh_eng.process(dup, now=NOW)
    ] == [
        (r.status, r.remaining) for r in ref_eng.process(dup, now=NOW)
    ]
    traces = dict(mesh_eng.ops.trace_counts)

    # ...then vary the algorithm mix per window: decisions stay
    # bit-identical to the single-chip replay and nothing retraces
    # (the mix is data, not program shape).
    mixes = [[2] * 48, [0, 3] * 24, [4] * 48, [1, 2, 3, 4] * 12,
             [int(a) for a in rng.integers(0, 5, 48)]]
    for i, algs in enumerate(mixes):
        b = batch(algs)
        now = NOW + 1 + i
        got = mesh_eng.process(b, now=now)
        want = ref_eng.process(b, now=now)
        assert [(r.status, r.remaining, r.reset_time) for r in got] == \
               [(r.status, r.remaining, r.reset_time) for r in want]
    assert dict(mesh_eng.ops.trace_counts) == traces


# ----------------------------------------------------------------------
# Edge validation: out-of-range algorithm is a per-item error
# ----------------------------------------------------------------------
def test_columns_from_pb_rejects_unknown_algorithm():
    from gubernator_tpu.pb import gubernator_pb2 as pb
    from gubernator_tpu.transport.convert import columns_from_pb

    ms = [
        pb.RateLimitReq(name="a", unique_key="k", hits=1, algorithm=7),
        pb.RateLimitReq(name="a", unique_key="k2", hits=1,
                        algorithm=int(Algorithm.CONCURRENCY)),
        # Empty-key errors keep precedence over the algorithm check.
        pb.RateLimitReq(name="a", unique_key="", algorithm=9),
    ]
    cols, errors, special = columns_from_pb(ms)
    assert "invalid algorithm '7'" in errors[0]
    assert 1 not in errors
    assert errors[2] == "field 'unique_key' cannot be empty"


def test_instance_rejects_unknown_algorithm_per_item():
    """The object path answers an out-of-range algorithm with an
    error-in-item (the reference's convention) and still serves the
    rest of the batch; accepted items feed the per-algorithm counter."""
    import asyncio

    from gubernator_tpu.service.instance import InstanceConfig, V1Instance

    async def run():
        inst = await V1Instance.create(
            InstanceConfig(cache_size=256, tpu_max_batch=64)
        )
        try:
            reqs = [
                req("ok", Algorithm.GCRA, created_at=NOW),
                req("bad", 7, created_at=NOW),
                req("ok2", Algorithm.SLIDING_WINDOW, created_at=NOW),
            ]
            out = await inst.get_rate_limits(reqs)
            assert out[0].status == Status.UNDER_LIMIT and not out[0].error
            assert "invalid algorithm '7'" in out[1].error
            assert out[2].status == Status.UNDER_LIMIT and not out[2].error
            m = inst.metrics
            assert m.sample("gubernator_tpu_algorithm_requests_total",
                            {"algorithm": "gcra"}) == 1.0
            assert m.sample("gubernator_tpu_algorithm_requests_total",
                            {"algorithm": "sliding_window"}) == 1.0
            assert m.sample("gubernator_check_error_counter_total",
                            {"error": "Invalid request"}) == 1.0
        finally:
            await inst.close()

    asyncio.run(run())
