"""Differential tests for the fused Pallas tick (interpret mode on CPU):
fused kernel vs the unfused parts program vs the merge-capable x64
program, on randomized unique-slot batches over a populated row table.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.engine import (
    REQ32_INDEX, REQ32_ROWS, _jitted_tick, pack_request_matrix32)
from gubernator_tpu.ops.rowtable import RowState
from gubernator_tpu.ops.tick32 import make_tick32_fn, make_tick32_rows_fn
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

NOW = 1_700_000_000_000
CAP = 2048


def make_plain(cap):
    """Unfused oracle via the two-program split: stacking the response
    inside the jit hands XLA:CPU a concatenate-rooted fusion it executes
    as a per-element tree walk (minutes per test — see
    ops/tick32.make_tick32_rows_fn); the eager stack is its own tiny
    program."""
    inner = jax.jit(make_tick32_rows_fn(cap, "row"))

    def f(state, m, now):
        s, rows = inner(state, m, now)
        return s, jnp.stack(rows)

    return f


def build_batch(rng, b, n, with_behaviors=True):
    """Sorted unique-slot compact request matrix with n live rows."""
    m = np.zeros((REQ32_ROWS, b), np.int32)
    m[REQ32_INDEX["slot"]] = CAP
    slots = np.sort(rng.choice(CAP, n, replace=False))
    reqs = []
    for i in range(n):
        behavior = Behavior(0)
        if with_behaviors:
            p = rng.random()
            if p < 0.15:
                behavior = Behavior.RESET_REMAINING
            elif p < 0.3:
                behavior = Behavior.DRAIN_OVER_LIMIT
        reqs.append(RateLimitRequest(
            name="f", unique_key=f"k{slots[i]}",
            hits=int(rng.choice([0, 1, 2, 5, -3, 10**11])),
            limit=int(rng.choice([3, 10, 1000, 1 << 34])),
            duration=int(rng.choice([1_000, 30_000, 3_600_000])),
            algorithm=Algorithm(int(rng.integers(0, 2))),
            behavior=behavior,
            burst=int(rng.choice([0, 5, 2000])),
            created_at=NOW - int(rng.choice([0, 500, 3_000, 61_000])),
        ))
    pack_request_matrix32(
        m, np.arange(n), reqs, slots,
        rng.random(n) < 0.8, NOW)
    return m


def populate(rng, tick, state, b, rounds=3):
    """Run a few prior ticks so gathered states are non-trivial."""
    for k in range(rounds):
        m = build_batch(rng, b, b // 2, with_behaviors=False)
        state, _ = tick(state, jnp.asarray(m), jnp.int64(NOW - 10_000 + k))
    return state


# Small chunks force the double-buffered pipelined path (nc >= 2)
# without production-width batches.  Mosaic (real TPU) requires the
# chunk to be lane-aligned (128); interpret mode keeps 32 so the
# Python-stepped DMA loop stays seconds, not minutes.
SMALL_CHUNK = 128 if jax.default_backend() == "tpu" else 32

# The fused kernels share the row table's DMA-ring machinery; on jax
# builds whose Pallas interpreter can't lower it these tests would fail
# on the emulator, not the kernels (see rowtable.interpret_supported).
from gubernator_tpu.ops import rowtable  # noqa: E402

pytestmark = pytest.mark.skipif(
    not rowtable.interpret_supported(),
    reason="Pallas interpret mode cannot lower the row kernels on this "
           "jax build",
)


@pytest.mark.parametrize("seed,mult", [(1, 4), (2, 8)])
def test_fused_matches_unfused(seed, mult):
    """nc = 4/8 chunks exercises the double-buffered pipelined path."""
    from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

    b = SMALL_CHUNK * mult
    rng = np.random.default_rng(seed)
    fused = jax.jit(make_fused_tick_fn(CAP, chunk=SMALL_CHUNK))
    plain = make_plain(CAP)

    state0 = jax.tree.map(jnp.asarray, RowState.zeros(CAP))
    state0 = populate(rng, plain, state0, b)

    m = build_batch(rng, b, int(rng.integers(1, b)))
    now = jnp.int64(NOW)

    s_f, r_f = fused(state0, jnp.asarray(m), now)
    s_p, r_p = plain(state0, jnp.asarray(m), now)

    n = int((np.asarray(m[REQ32_INDEX["slot"]]) < CAP).sum())
    np.testing.assert_array_equal(
        np.asarray(r_f)[:, :n], np.asarray(r_p)[:, :n])
    np.testing.assert_array_equal(
        np.asarray(s_f.table), np.asarray(s_p.table))


def test_fused_matches_merge_program_on_unique():
    """The x64 merge-capable program and the fused kernel agree on a
    unique-slot batch (the dispatch boundary in engine.submit_columns)."""
    from gubernator_tpu.ops.fusedtick import make_fused_tick_fn

    rng = np.random.default_rng(7)
    b = 4 * SMALL_CHUNK
    fused = jax.jit(make_fused_tick_fn(CAP, chunk=SMALL_CHUNK))
    legacy = _jitted_tick(CAP, "row", sorted_input=True,
                          compact_resp=True, compact_req=True)

    state0 = jax.tree.map(jnp.asarray, RowState.zeros(CAP))
    plain = make_plain(CAP)
    state0 = populate(rng, plain, state0, b)

    m = build_batch(rng, b, 100)
    now = jnp.int64(NOW)
    s_f, r_f = fused(state0, jnp.asarray(m), now)
    s_l, r_l = legacy(state0, jnp.asarray(m), now)

    np.testing.assert_array_equal(
        np.asarray(r_f)[:, :100], np.asarray(r_l)[:, :100])
    mat_f = np.asarray(s_f.table)
    mat_l = np.asarray(s_l.table)
    # the merge program's padding lanes scatter to the guard row too;
    # compare only real slots
    np.testing.assert_array_equal(mat_f[:CAP], mat_l[:CAP])


def test_fused_single_chunk_width():
    """b < chunk size exercises the nc == 1 path."""
    rng = np.random.default_rng(9)
    b = 128
    fused = jax.jit(make_tick32_fn(CAP, "row", fused=True))
    plain = make_plain(CAP)
    state0 = jax.tree.map(jnp.asarray, RowState.zeros(CAP))
    m = build_batch(rng, b, 100)
    now = jnp.int64(NOW)
    s_f, r_f = fused(state0, jnp.asarray(m), now)
    s_p, r_p = plain(state0, jnp.asarray(m), now)
    np.testing.assert_array_equal(
        np.asarray(r_f)[:, :100], np.asarray(r_p)[:, :100])
    np.testing.assert_array_equal(
        np.asarray(s_f.table), np.asarray(s_p.table))


@pytest.mark.parametrize("case", ["full", "odd", "tiny", "empty"])
def test_ragged_fused_matches_plain_on_extent(case):
    """The ragged Pallas kernel, walking only ``[start, start + count)``
    of a flat global-slot batch, matches the plain program run on the
    localized extent alone — and leaves every off-extent response lane
    exactly zero (the cross-shard gather is a psum).

    ``odd`` picks an unaligned start and an odd chunk count (the
    phantom-chunk even-rounding path); ``tiny`` is a sub-chunk extent
    (nc_live == 1 rounds to 2); ``empty`` skips the pipeline entirely.
    """
    from gubernator_tpu.ops.raggedtick import make_fused_ragged_tick_fn

    b = 4 * SMALL_CHUNK
    start, count = {
        "full": (0, b),
        "odd": (37, 3 * SMALL_CHUNK - 5),
        "tiny": (5, 7),
        "empty": (50, 0),
    }[case]
    lo = CAP  # this shard's slot base in a 3-shard global slot space

    rng = np.random.default_rng(31)
    ragged = jax.jit(make_fused_ragged_tick_fn(CAP, chunk=SMALL_CHUNK))
    plain = make_plain(CAP)

    state0 = jax.tree.map(jnp.asarray, RowState.zeros(CAP))
    state0 = populate(rng, plain, state0, b)

    # Local batch (live rows at columns [0, count)), rolled so the live
    # block sits at [start, start + count): the oracle input.  The
    # global matrix rebases the extent's slots by +lo and plants live
    # FOREIGN rows on both sides — other shards' slots with nonzero
    # hits — which the kernel must skip purely by lane index.
    m_oracle = np.roll(build_batch(rng, b, count), start, axis=1)
    m_glob = m_oracle.copy()
    m_glob[REQ32_INDEX["slot"], start:start + count] += lo
    if start:
        m_glob[REQ32_INDEX["slot"], :start] = np.sort(
            rng.choice(lo, start, replace=False))
        m_glob[REQ32_INDEX["valid"], :start] = 1
        m_glob[REQ32_INDEX["hits"], :start] = 999
    tail = b - start - count
    if tail:
        m_glob[REQ32_INDEX["slot"], start + count:] = (
            lo + CAP + np.arange(tail))
        m_glob[REQ32_INDEX["valid"], start + count:] = 1
        m_glob[REQ32_INDEX["hits"], start + count:] = 999

    now = jnp.int64(NOW)
    s_f, r_f = ragged(state0, jnp.asarray(m_glob),
                      np.int32(start), np.int32(count), np.int32(lo), now)
    s_p, r_p = plain(state0, jnp.asarray(m_oracle), now)

    r_f = np.asarray(r_f)
    np.testing.assert_array_equal(
        r_f[:, start:start + count],
        np.asarray(r_p)[:, start:start + count])
    off = np.ones(b, bool)
    off[start:start + count] = False
    assert (r_f[:, off] == 0).all()
    # the guard row collects masked-lane scatters on both paths; compare
    # only real slots
    np.testing.assert_array_equal(
        np.asarray(s_f.table)[:CAP], np.asarray(s_p.table)[:CAP])


def test_fused_merged_matches_xla_merged():
    """The fused merged kernel (count fold in-register, 15-row resp) and
    the XLA merged rows program agree on state and every output row."""
    from gubernator_tpu.ops.fusedtick import make_fused_merged_tick_fn
    from gubernator_tpu.ops.tick32 import make_merged_tick32_rows_fn

    rng = np.random.default_rng(21)
    b = 4 * SMALL_CHUNK
    fused = jax.jit(make_fused_merged_tick_fn(CAP, chunk=SMALL_CHUNK))
    inner = jax.jit(make_merged_tick32_rows_fn(CAP, "row"))

    def plain(state, mhead, count, now):
        s, rows = inner(state, mhead, count, now)
        return s, jnp.stack(rows)

    state0 = jax.tree.map(jnp.asarray, RowState.zeros(CAP))
    state0 = populate(rng, make_plain(CAP), state0, b)

    m = build_batch(rng, b, 100)
    count = np.ones(b, np.int32)
    live = np.asarray(m[REQ32_INDEX["slot"]]) < CAP
    count[live] = rng.integers(1, 9, int(live.sum()))
    now = jnp.int64(NOW)

    s_f, r_f = fused(state0, jnp.asarray(m), jnp.asarray(count), now)
    s_p, r_p = plain(state0, jnp.asarray(m), jnp.asarray(count), now)

    n = int(live.sum())
    # Fused output is the row-major (U, 24) block; rows 0-14 transpose to
    # the XLA program's 15 rows, 15-22 echo the request params.
    r_f = np.asarray(r_f)
    np.testing.assert_array_equal(r_f[:n, :15].T, np.asarray(r_p)[:, :n])
    from gubernator_tpu.ops.engine import REQ32_INDEX as R

    echo_rows = [R["hits"], R["hits"] + 1, R["limit"], R["limit"] + 1,
                 R["created_at"], R["created_at"] + 1, R["algorithm"],
                 R["behavior"]]
    np.testing.assert_array_equal(
        r_f[:n, 15:23].T, np.asarray(m)[echo_rows][:, :n])
    np.testing.assert_array_equal(
        np.asarray(s_f.table), np.asarray(s_p.table))
