"""Service-stack functional tests: daemon, routing, forwarding, gateway.

The behavioral spec comes from the reference's functional_test.go (run
against an in-process cluster, cluster/cluster.go); these tests exercise
the same surfaces over real loopback gRPC.
"""

import asyncio

import pytest

from gubernator_tpu.cluster import Cluster
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    Status,
)

@pytest.fixture(scope="module")
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def cluster(event_loop):
    c = event_loop.run_until_complete(Cluster.start(3))
    yield c
    event_loop.run_until_complete(c.stop())


def req(name="test", key="k", hits=1, limit=5, duration=60_000, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=duration, **kw
    )


async def test_single_daemon_token_bucket(cluster):
    client = cluster.daemons[0].client()
    out = await client.get_rate_limits([req(key="single")])
    assert out[0].error == ""
    assert out[0].status == Status.UNDER_LIMIT
    assert out[0].limit == 5
    assert out[0].remaining == 4
    out = await client.get_rate_limits([req(key="single", hits=4)])
    assert out[0].remaining == 0
    out = await client.get_rate_limits([req(key="single", hits=1)])
    assert out[0].status == Status.OVER_LIMIT
    await client.close()


async def test_forwarding_owner_state_shared(cluster):
    """Hitting the same key via different daemons must share one bucket."""
    owner = cluster.find_owning_daemon("fwd", "shared")
    non_owner = cluster.list_non_owning_daemons("fwd", "shared")[0]
    c1 = owner.client()
    c2 = non_owner.client()
    out = await c1.get_rate_limits([req(name="fwd", key="shared", limit=10)])
    assert out[0].error == ""
    assert out[0].remaining == 9
    out = await c2.get_rate_limits([req(name="fwd", key="shared", limit=10)])
    assert out[0].error == ""
    assert out[0].remaining == 8
    # Forwarded response carries the owner's address in metadata.
    assert out[0].metadata.get("owner") == owner.conf.grpc_listen_address
    await c1.close()
    await c2.close()


async def test_batch_order_preserved(cluster):
    """Responses must line up with requests across batch sizes
    (functional_test.go:1638-1686 order-stability contract)."""
    client = cluster.daemons[0].client()
    for size in (1, 7, 64, 250):
        reqs = [
            req(name="order", key=f"key-{i}", hits=0, limit=100 + i)
            for i in range(size)
        ]
        out = await client.get_rate_limits(reqs)
        assert len(out) == size
        for i, r in enumerate(out):
            assert r.error == ""
            assert r.limit == 100 + i, f"size={size} idx={i}"
    await client.close()


async def test_batch_too_large_rejected(cluster):
    import grpc

    client = cluster.daemons[0].client()
    reqs = [req(key=f"big-{i}") for i in range(1001)]
    with pytest.raises(grpc.aio.AioRpcError) as exc:
        await client.get_rate_limits(reqs)
    assert exc.value.code() == grpc.StatusCode.OUT_OF_RANGE
    await client.close()


async def test_missing_fields(cluster):
    """Per-item validation errors; RPC still succeeds
    (functional_test.go:896 missing-field table)."""
    client = cluster.daemons[0].client()
    out = await client.get_rate_limits(
        [
            RateLimitRequest(name="test", unique_key="", hits=1, limit=10,
                             duration=1000),
            RateLimitRequest(name="", unique_key="akey", hits=1, limit=10,
                             duration=1000),
            req(key="ok"),
        ]
    )
    assert "unique_key" in out[0].error
    assert "namespace" in out[1].error
    assert out[2].error == ""
    await client.close()


async def test_health_check(cluster):
    client = cluster.daemons[0].client()
    h = await client.health_check()
    assert h.status == "healthy"
    assert h.peer_count == 3
    await client.close()


async def test_leaky_bucket_over_grpc(cluster):
    client = cluster.daemons[0].client()
    out = await client.get_rate_limits(
        [req(name="leaky", key="lk", hits=5, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET)]
    )
    assert out[0].error == ""
    assert out[0].remaining == 5
    await client.close()


async def test_global_behavior_reconciles():
    """GLOBAL: non-owner answers locally; hits flow to the owner and the
    owner broadcasts authoritative state back (global.go protocol)."""
    behaviors = BehaviorConfig(global_sync_wait=0.05, batch_wait=0.002)
    c = await Cluster.start(3, behaviors=behaviors)
    try:
        name, key = "global", "gk"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        client = non_owner.client()
        g = req(name=name, key=key, hits=2, limit=100,
                behavior=Behavior.GLOBAL)
        out = await client.get_rate_limits([g])
        assert out[0].error == ""
        assert out[0].remaining == 98  # local answer
        assert out[0].metadata.get("owner") == owner.conf.grpc_listen_address

        # Metrics are the oracle, not sleeps (functional_test.go:2184-2276):
        # the non-owner must flush its hit batch to the owner, and the owner
        # must complete a broadcast — both observed only after the RPCs land.
        await c.wait_for_update(c.daemons.index(non_owner))
        await c.wait_for_broadcast(c.daemons.index(owner))
        await client.close()

        async def owner_saw_hits():
            while True:
                o = owner.client()
                resp = await o.get_rate_limits(
                    [req(name=name, key=key, hits=0, limit=100,
                         behavior=Behavior.GLOBAL)]
                )
                await o.close()
                if resp[0].remaining == 98:
                    return
                await asyncio.sleep(0.02)

        await asyncio.wait_for(owner_saw_hits(), timeout=5.0)

        # The broadcast reached the third daemon (neither owner nor hitter).
        # Still a bounded poll: _broadcast observes its metric even if one
        # peer push failed (it retries on the next interval), so the metric
        # alone doesn't prove THIS peer got the state.
        third = [d for d in c.daemons if d is not owner and d is not non_owner][0]

        async def third_synced():
            while True:
                t = third.client()
                resp = await t.get_rate_limits(
                    [req(name=name, key=key, hits=0, limit=100,
                         behavior=Behavior.GLOBAL)]
                )
                await t.close()
                if resp[0].remaining == 98:
                    return
                await asyncio.sleep(0.02)

        await asyncio.wait_for(third_synced(), timeout=5.0)
    finally:
        await c.stop()


async def test_global_hits_apply_locally_when_owner():
    """Hits queued for a key this node turns out to own must still land
    (the reference forwards to whatever GetPeer resolves, global.go:153-168;
    dropping them loses accounting for good)."""
    from gubernator_tpu.service.instance import InstanceConfig, V1Instance

    behaviors = BehaviorConfig(global_sync_wait=0.02, batch_wait=0.001)
    inst = await V1Instance.create(
        InstanceConfig(behaviors=behaviors, cache_size=256)
    )
    try:
        r = req(name="gl", key="own", hits=3, limit=10,
                behavior=Behavior.GLOBAL)
        inst.global_mgr.queue_hit(r)

        async def settled():
            while True:
                out = await inst.apply_local(
                    [req(name="gl", key="own", hits=0, limit=10)]
                )
                if out[0].remaining == 7:
                    return
                await asyncio.sleep(0.01)

        await asyncio.wait_for(settled(), timeout=5)
    finally:
        await inst.close()


async def test_http_gateway_snake_case():
    """JSON gateway with snake_case fields (daemon.go:245-261 parity)."""
    import aiohttp

    c = await Cluster.start(1, http_gateway=True)
    try:
        addr = c.daemons[0].conf.http_listen_address
        async with aiohttp.ClientSession() as s:
            body = {
                "requests": [
                    {
                        "name": "http",
                        "unique_key": "hk",
                        "hits": "1",
                        "limit": "10",
                        "duration": "60000",
                    }
                ]
            }
            async with s.post(
                f"http://{addr}/v1/GetRateLimits", json=body
            ) as resp:
                assert resp.status == 200
                out = await resp.json()
            item = out["responses"][0]
            assert item["limit"] == "10"
            assert item["remaining"] == "9"
            assert "reset_time" in item
            async with s.get(f"http://{addr}/v1/HealthCheck") as resp:
                health = await resp.json()
            assert health["status"] == "healthy"
            async with s.get(f"http://{addr}/metrics") as resp:
                text = await resp.text()
            assert "gubernator_grpc_request_counts" in text
            assert "gubernator_cache_size" in text
    finally:
        await c.stop()


async def _wait_replica(daemon, name, key, limit, want_remaining,
                        timeout=5.0):
    """Poll one daemon's GLOBAL replica until it reports ``want_remaining``.

    The broadcast metric alone can't prove delivery to a *specific* peer
    (push failures are swallowed and retried next interval), so state
    assertions poll the replica itself."""
    async def poll():
        while True:
            cl = daemon.client()
            r = (await cl.get_rate_limits(
                [req(name=name, key=key, hits=0, limit=limit,
                     duration=6_000_000, behavior=Behavior.GLOBAL)]
            ))[0]
            await cl.close()
            if r.remaining == want_remaining:
                return
            await asyncio.sleep(0.02)

    await asyncio.wait_for(poll(), timeout=timeout)


async def test_global_peer_over_limit():
    """Non-owner replica drains to OVER_LIMIT through owner broadcasts
    (functional_test.go:1093 TestGlobalRateLimitsPeerOverLimit)."""
    behaviors = BehaviorConfig(global_sync_wait=0.05, batch_wait=0.002)
    c = await Cluster.start(3, behaviors=behaviors)
    try:
        name, key = "global-over", "pk"
        peer = c.list_non_owning_daemons(name, key)[0]
        client = peer.client()

        async def send_hit(hits, want_status, want_remaining):
            r = (await client.get_rate_limits(
                [req(name=name, key=key, hits=hits, limit=2,
                     duration=300_000, behavior=Behavior.GLOBAL)]
            ))[0]
            assert r.error == ""
            assert (r.status, r.remaining) == (want_status, want_remaining), r

        await send_hit(1, Status.UNDER_LIMIT, 1)
        await send_hit(1, Status.UNDER_LIMIT, 0)
        # Wait for the authoritative drained state to land on THIS peer
        # (broadcasts may split across windows and pushes may retry).
        await _wait_replica(peer, name, key, 2, 0)
        await send_hit(1, Status.OVER_LIMIT, 0)
        await send_hit(1, Status.OVER_LIMIT, 0)
        await client.close()
    finally:
        await c.stop()


async def test_global_negative_hits():
    """Negative GLOBAL hits credit tokens back across the cluster
    (functional_test.go:1204 TestGlobalNegativeHits)."""
    behaviors = BehaviorConfig(global_sync_wait=0.05, batch_wait=0.002)
    c = await Cluster.start(4, behaviors=behaviors)
    try:
        name, key = "global-neg", "nk"
        peers = c.list_non_owning_daemons(name, key)

        async def send_hit(daemon, hits, want_remaining):
            cl = daemon.client()
            r = (await cl.get_rate_limits(
                [req(name=name, key=key, hits=hits, limit=2,
                     duration=6_000_000, behavior=Behavior.GLOBAL)]
            ))[0]
            await cl.close()
            assert r.error == ""
            assert r.status == Status.UNDER_LIMIT
            assert r.remaining == want_remaining, (hits, r)

        # Negative hit on an empty bucket: remaining grows past the limit.
        await send_hit(peers[0], -1, 3)
        # Wait for the credit to replicate to the NEXT peer we'll hit —
        # the broadcast metric can't prove per-peer delivery.
        await _wait_replica(peers[1], name, key, 2, 3)
        # That peer sees the credited 3, credits one more.
        await send_hit(peers[1], -1, 4)
        await _wait_replica(peers[2], name, key, 2, 4)
        # A third peer can spend all 4 credits at once.
        await send_hit(peers[2], 4, 0)
        await _wait_replica(peers[0], name, key, 2, 0)
        # Query reflects the drained state everywhere.
        await send_hit(peers[0], 0, 0)
    finally:
        await c.stop()


async def test_forward_retry_exhaustion_and_self_upgrade():
    """The ≤5-retry forward loop (gubernator.go:311-391): a dead owner
    exhausts retries into the reference's "peers that are not connected"
    error; once ownership re-resolves to this node, the retry self-
    upgrades to local handling instead of forwarding."""
    c = await Cluster.start(2)
    try:
        d_owner = c.find_owning_daemon("retrytest", "rk")
        d_other = next(d for d in c.daemons if d is not d_owner)

        # Kill the owner: forwards now fail UNAVAILABLE and re-resolution
        # keeps returning the same dead peer.
        await d_owner.close()
        out = await d_other.instance.get_rate_limits(
            [req(name="retrytest", key="rk")]
        )
        assert "not connected" in out[0].error
        assert d_other.metrics.registry.get_sample_value(
            "gubernator_batch_send_retries_total"
        ) >= 5

        # Self-upgrade: ownership moves to the surviving node; the retry
        # path must answer locally (attempts != 0 and peer.is_owner).
        dead_peer = d_other.instance.get_peer("retrytest_rk")
        from gubernator_tpu.config import PeerInfo

        d_other.set_peers(
            [PeerInfo(grpc_address=d_other.advertise_address)]
        )
        resp = await d_other.instance._async_request(
            dead_peer, req(name="retrytest", key="rk"), "retrytest_rk"
        )
        assert resp.error == ""
        assert resp.remaining == 4
    finally:
        await c.stop()


def test_columns_fast_path_matches_object_path():
    """The wire→columns fast path must answer exactly like the object
    path, and flip off the moment the instance stops being standalone."""
    import asyncio

    import numpy as np

    from gubernator_tpu.ops.reqcols import ReqColumns
    from gubernator_tpu.service.instance import InstanceConfig, V1Instance
    from gubernator_tpu.types import PeerInfo, RateLimitRequest

    async def run():
        conf = InstanceConfig(cache_size=256, tpu_max_batch=64)
        inst = await V1Instance.create(conf)
        assert inst.columns_fast_path_ok()
        reqs = [
            RateLimitRequest(name="fp", unique_key=str(i % 5), hits=1,
                             limit=9, duration=60_000)
            for i in range(20)
        ]
        obj = await inst.get_rate_limits(reqs)
        mat, errors = await inst.get_rate_limits_columns(
            ReqColumns.from_requests(reqs)
        )
        assert not errors
        # Second pass over the same keys: columns observed object ticks.
        assert mat[2].tolist() == [r.remaining - 4 for r in obj]

        # Clustered instance: fast path must disable.
        inst.set_peers([PeerInfo(grpc_address="10.0.0.1:81")])
        assert not inst.columns_fast_path_ok()
        await inst.close()

    asyncio.run(run())


def test_columns_from_pb_validation_and_special():
    from gubernator_tpu.pb import gubernator_pb2 as pb
    from gubernator_tpu.transport.convert import columns_from_pb
    from gubernator_tpu.types import Behavior

    ms = [
        pb.RateLimitReq(name="a", unique_key="k", hits=1, limit=5,
                        duration=1000),
        pb.RateLimitReq(name="", unique_key="k2", hits=1),
        pb.RateLimitReq(name="b", unique_key="", hits=1),
    ]
    cols, errors, special = columns_from_pb(ms)
    assert not special
    assert errors == {
        1: "field 'namespace' cannot be empty",
        2: "field 'unique_key' cannot be empty",
    }
    assert cols.key_bytes(0) == b"a_k"

    ms2 = [pb.RateLimitReq(name="g", unique_key="k", hits=1,
                           behavior=int(Behavior.GLOBAL))]
    _, _, special = columns_from_pb(ms2)
    assert special
