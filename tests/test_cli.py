"""CLI smoke tests (reference cmd/gubernator/main_test.go:26 pattern):
spawn the daemon binary, wait for "Ready", probe it, shut down cleanly."""

import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest


def _env(**extra):
    env = dict(os.environ)
    # Hermetic: no tunneled-TPU plugin, cpu platform, tiny engine.
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(
        GUBER_GRPC_ADDRESS="127.0.0.1:19981",
        GUBER_HTTP_ADDRESS="127.0.0.1:19980",
        GUBER_CACHE_SIZE="1024",
        GUBER_TPU_MAX_BATCH="128",
        GUBER_PEER_DISCOVERY_TYPE="none",
    )
    env.update(extra)
    return env


@pytest.mark.slow
def test_daemon_main_boots_and_serves():
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.daemon_main"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=_env(),
        text=True,
    )
    try:
        # Wait for the readiness marker (compile happens at startup).
        deadline = time.time() + 120
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "Ready" in line:
                break
            assert proc.poll() is None, proc.stderr.read()
        assert "Ready" in line

        with urllib.request.urlopen(
            "http://127.0.0.1:19980/v1/HealthCheck", timeout=5
        ) as resp:
            assert b"healthy" in resp.read()

        # The healthcheck probe binary exits 0 against a healthy daemon.
        probe = subprocess.run(
            [sys.executable, "-m", "gubernator_tpu.cmd.healthcheck"],
            env=_env(GUBER_HTTP_ADDRESS="127.0.0.1:19980"),
            capture_output=True,
            timeout=30,
        )
        assert probe.returncode == 0, probe.stderr

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
