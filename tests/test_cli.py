"""CLI smoke tests (reference cmd/gubernator/main_test.go:26 pattern):
spawn the daemon binary, wait for "Ready", probe it, shut down cleanly."""

import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest


def _env(**extra):
    env = dict(os.environ)
    # Hermetic: no tunneled-TPU plugin, cpu platform, tiny engine.
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(
        GUBER_GRPC_ADDRESS="127.0.0.1:19981",
        GUBER_HTTP_ADDRESS="127.0.0.1:19980",
        GUBER_CACHE_SIZE="1024",
        GUBER_TPU_MAX_BATCH="128",
        GUBER_PEER_DISCOVERY_TYPE="none",
    )
    env.update(extra)
    return env


async def test_cli_load_generator_reports_stats(capsys):
    """The load generator drives a live daemon and reports ok/over/err
    counts (reference cmd/gubernator-cli/main.go)."""
    import argparse

    from gubernator_tpu.cmd import cli
    from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
    from gubernator_tpu.transport.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=1024)
    d = await spawn_daemon(conf)
    try:
        args = argparse.Namespace(
            address=d.advertise_address,
            limits=20,
            requests=200,
            concurrency=16,
            timeout=5.0,
        )
        # Deterministic key/limit pool: with this seed some buckets have
        # small limits and 200 requests over 20 keys must exhaust them —
        # proving OVER_LIMIT responses are counted as such, not as errors.
        import random

        random.seed(7)
        await cli.run(args)
    finally:
        await d.close()
    out = capsys.readouterr().out
    assert "200 requests" in out
    assert "errors=0" in out
    import re

    over = int(re.search(r"over_limit=(\d+)", out).group(1))
    assert over > 0


def test_healthcheck_exits_2_when_daemon_absent(monkeypatch, capsys):
    from gubernator_tpu.cmd import healthcheck

    monkeypatch.setenv("GUBER_HTTP_ADDRESS", "127.0.0.1:1")  # nothing listens
    assert healthcheck.main() == 2
    assert "healthcheck failed" in capsys.readouterr().err


def test_healthcheck_exits_2_on_unhealthy_body(monkeypatch, capsys):
    import json as _json
    import io
    import urllib.request

    from gubernator_tpu.cmd import healthcheck

    def fake_urlopen(url, timeout=0):
        class R(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        return R(_json.dumps(
            {"status": "unhealthy", "message": "1 peer error"}
        ).encode())

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    assert healthcheck.main() == 2
    assert "unhealthy" in capsys.readouterr().err


@pytest.mark.slow
def test_cluster_main_boots_six_instances():
    """cluster_main brings up the fixed-port 6-node dev cluster and serves
    on every node (reference cmd/gubernator-cluster/main.go)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.cluster_main"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=_env(),
        text=True,
    )
    try:
        deadline = time.time() + 180
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "Ready" in line:
                break
            assert proc.poll() is None, proc.stderr.read()
        assert "Ready" in line

        # Every instance answers its health endpoint on the fixed ports.
        for port in range(10090, 10096):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/HealthCheck", timeout=5
            ) as resp:
                assert b"healthy" in resp.read()

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@pytest.mark.slow
def test_daemon_main_boots_and_serves():
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.daemon_main"],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=_env(),
        text=True,
    )
    try:
        # Wait for the readiness marker (compile happens at startup).
        deadline = time.time() + 120
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "Ready" in line:
                break
            assert proc.poll() is None, proc.stderr.read()
        assert "Ready" in line

        with urllib.request.urlopen(
            "http://127.0.0.1:19980/v1/HealthCheck", timeout=5
        ) as resp:
            assert b"healthy" in resp.read()

        # The healthcheck probe binary exits 0 against a healthy daemon.
        probe = subprocess.run(
            [sys.executable, "-m", "gubernator_tpu.cmd.healthcheck"],
            env=_env(GUBER_HTTP_ADDRESS="127.0.0.1:19980"),
            capture_output=True,
            timeout=30,
        )
        assert probe.returncode == 0, probe.stderr

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_compile_cache_configured_by_default(tmp_path):
    """The device bootstrap (gubernator_tpu.jaxinit, imported by every
    jax-using module) enables the persistent XLA compile cache unless
    disabled; daemon restarts must not re-pay tick compiles.  The bare
    package import stays jax-free by design — the probe imports the
    bootstrap the way any device module does."""

    def cache_env(**extra):
        env = _env(HOME=str(tmp_path), **extra)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        return env

    out = subprocess.run(
        [sys.executable, "-c",
         "import jax, gubernator_tpu.jaxinit;"
         "print(jax.config.jax_compilation_cache_dir or '')"],
        env=cache_env(), capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert ".cache/gubernator-tpu/xla" in out.stdout

    out = subprocess.run(
        [sys.executable, "-c",
         "import jax, gubernator_tpu.jaxinit;"
         "print(repr(jax.config.jax_compilation_cache_dir))"],
        env=cache_env(GUBER_COMPILE_CACHE_DIR="off"),
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "None"
