"""Store/Loader persistence hook tests.

Modeled on the reference's store_test.go: TestLoader (:76) proves load-at-
startup / save-at-shutdown; TestStore (:127) proves read-through on miss
and write-through on every mutation.
"""

import pytest

from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.store import FileLoader, MockLoader, MockStore
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status

NOW = 1_700_000_000_000


def req(key="k", hits=1, limit=5, duration=60_000, **kw):
    return RateLimitRequest(
        name="store_test", unique_key=key, hits=hits, limit=limit,
        duration=duration, **kw,
    )


def test_store_write_through_and_read_through():
    store = MockStore()
    eng = TickEngine(capacity=256, max_batch=64, store=store)
    out = eng.process([req(hits=2)], now=NOW)[0]
    assert out.remaining == 3
    assert store.called["Get()"] == 1  # miss consults the store
    # Write-through fired with the post-tick state.
    assert store.called["OnChange()"] == 1
    item = store.data["store_test_k"]
    assert item["remaining"] == 3
    assert item["algorithm"] == Algorithm.TOKEN_BUCKET
    assert item["expire_at"] == NOW + 60_000

    # Fresh engine, same store: miss reads through and continues the bucket.
    eng2 = TickEngine(capacity=256, max_batch=64, store=store)
    out = eng2.process([req(hits=1)], now=NOW + 1)[0]
    assert store.called["Get()"] == 2
    assert out.remaining == 2  # 5 - 2 (persisted) - 1

    # Unknown key: store consulted, returns None, new bucket.
    out = eng2.process([req(key="other", hits=1)], now=NOW + 1)[0]
    assert out.remaining == 4
    assert store.called["Get()"] == 3


def test_store_leaky_read_through_preserves_float_remaining():
    store = MockStore()
    eng = TickEngine(capacity=256, max_batch=64, store=store)
    eng.process(
        [req(hits=3, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET)],
        now=NOW,
    )
    item = store.data["store_test_k"]
    assert item["remaining_f"] == 7.0
    eng2 = TickEngine(capacity=256, max_batch=64, store=store)
    out = eng2.process(
        [req(hits=0, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET)],
        now=NOW,
    )[0]
    assert out.remaining == 7


def test_loader_roundtrip(tmp_path):
    loader = MockLoader()
    eng = TickEngine(capacity=256, max_batch=64)
    eng.process([req(hits=2), req(key="k2", hits=1, limit=9)], now=NOW)
    loader.save(eng.export_items())
    assert loader.called["Save()"] == 1
    assert len(loader.contents) == 2

    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_items(list(loader.load()), now=NOW)
    out = eng2.process([req(hits=0)], now=NOW)[0]
    assert out.remaining == 3
    out = eng2.process([req(key="k2", hits=0, limit=9)], now=NOW)[0]
    assert out.remaining == 8


def test_file_loader(tmp_path):
    path = str(tmp_path / "snapshot.jsonl")
    loader = FileLoader(path)
    eng = TickEngine(capacity=256, max_batch=64)
    eng.process([req(hits=4)], now=NOW)
    loader.save(eng.export_items())

    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_items(list(loader.load()), now=NOW)
    out = eng2.process([req(hits=0)], now=NOW)[0]
    assert out.remaining == 1


async def test_loader_with_mesh_engine():
    """Loader restore/save must work on the sharded engine too (it crashed
    with AttributeError before MeshTickEngine grew load/export_items)."""
    from gubernator_tpu.service.instance import InstanceConfig, V1Instance

    loader = MockLoader()
    inst = await V1Instance.create(
        InstanceConfig(cache_size=512, tpu_mesh_shards=2, loader=loader)
    )
    try:
        out = await inst.get_rate_limits([req(key="mesh-loader", hits=2)])
        assert out[0].remaining == 3
    finally:
        await inst.close()
    assert loader.called["Save()"] == 1
    assert len(loader.contents) == 1
    assert loader.contents[0]["remaining"] == 3


def test_store_with_mesh_shards_rejected():
    """Store write/read-through has no sharded path yet: combining it with
    tpu_mesh_shards > 1 must fail loudly, not silently drop persistence."""
    from gubernator_tpu.service.instance import InstanceConfig, _make_engine
    from gubernator_tpu.store import MockStore

    conf = InstanceConfig(store=MockStore(), tpu_mesh_shards=2, cache_size=256)
    with pytest.raises(ValueError, match="Store"):
        _make_engine(conf)


def test_loader_drops_expired_items():
    eng = TickEngine(capacity=256, max_batch=64)
    eng.process([req(hits=1, duration=1000)], now=NOW)
    items = eng.export_items()
    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_items(items, now=NOW + 10_000)  # past expire_at
    assert eng2.cache_size() == 0
