"""Store/Loader persistence hook tests.

Modeled on the reference's store_test.go: TestLoader (:76) proves load-at-
startup / save-at-shutdown; TestStore (:127) proves read-through on miss
and write-through on every mutation.
"""

import pytest

from gubernator_tpu.ops import rowtable
from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.store import FileLoader, MockLoader, MockStore
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status

NOW = 1_700_000_000_000


def req(key="k", hits=1, limit=5, duration=60_000, **kw):
    return RateLimitRequest(
        name="store_test", unique_key=key, hits=hits, limit=limit,
        duration=duration, **kw,
    )


def test_store_write_through_and_read_through():
    store = MockStore()
    eng = TickEngine(capacity=256, max_batch=64, store=store)
    out = eng.process([req(hits=2)], now=NOW)[0]
    assert out.remaining == 3
    assert store.called["Get()"] == 1  # miss consults the store
    # Write-through fired with the post-tick state.
    assert store.called["OnChange()"] == 1
    item = store.data["store_test_k"]
    assert item["remaining"] == 3
    assert item["algorithm"] == Algorithm.TOKEN_BUCKET
    assert item["expire_at"] == NOW + 60_000

    # Fresh engine, same store: miss reads through and continues the bucket.
    eng2 = TickEngine(capacity=256, max_batch=64, store=store)
    out = eng2.process([req(hits=1)], now=NOW + 1)[0]
    assert store.called["Get()"] == 2
    assert out.remaining == 2  # 5 - 2 (persisted) - 1

    # Unknown key: store consulted, returns None, new bucket.
    out = eng2.process([req(key="other", hits=1)], now=NOW + 1)[0]
    assert out.remaining == 4
    assert store.called["Get()"] == 3


def test_store_leaky_read_through_preserves_float_remaining():
    store = MockStore()
    eng = TickEngine(capacity=256, max_batch=64, store=store)
    eng.process(
        [req(hits=3, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET)],
        now=NOW,
    )
    item = store.data["store_test_k"]
    assert item["remaining_f"] == 7.0
    eng2 = TickEngine(capacity=256, max_batch=64, store=store)
    out = eng2.process(
        [req(hits=0, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET)],
        now=NOW,
    )[0]
    assert out.remaining == 7


def test_loader_roundtrip(tmp_path):
    loader = MockLoader()
    eng = TickEngine(capacity=256, max_batch=64)
    eng.process([req(hits=2), req(key="k2", hits=1, limit=9)], now=NOW)
    loader.save(eng.export_items())
    assert loader.called["Save()"] == 1
    assert len(loader.contents) == 2

    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_items(list(loader.load()), now=NOW)
    out = eng2.process([req(hits=0)], now=NOW)[0]
    assert out.remaining == 3
    out = eng2.process([req(key="k2", hits=0, limit=9)], now=NOW)[0]
    assert out.remaining == 8


def test_file_loader(tmp_path):
    path = str(tmp_path / "snapshot.jsonl")
    loader = FileLoader(path)
    eng = TickEngine(capacity=256, max_batch=64)
    eng.process([req(hits=4)], now=NOW)
    loader.save(eng.export_items())

    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_items(list(loader.load()), now=NOW)
    out = eng2.process([req(hits=0)], now=NOW)[0]
    assert out.remaining == 1


async def test_loader_with_mesh_engine():
    """Loader restore/save must work on the sharded engine too (it crashed
    with AttributeError before MeshTickEngine grew load/export_items)."""
    from gubernator_tpu.service.instance import InstanceConfig, V1Instance

    loader = MockLoader()
    inst = await V1Instance.create(
        InstanceConfig(cache_size=512, tpu_mesh_shards=2, loader=loader)
    )
    try:
        out = await inst.get_rate_limits([req(key="mesh-loader", hits=2)])
        assert out[0].remaining == 3
    finally:
        await inst.close()
    assert loader.called["Save()"] == 1
    assert len(loader.contents) == 1
    assert loader.contents[0]["remaining"] == 3


def test_store_with_mesh_shards_supported():
    """Store write/read-through works on the sharded engine (per-shard
    blocked readback/restore; the round-2 guard that refused this combo
    is gone)."""
    from gubernator_tpu.service.instance import InstanceConfig, _make_engine
    from gubernator_tpu.store import MockStore

    store = MockStore()
    conf = InstanceConfig(store=store, tpu_mesh_shards=2, cache_size=256)
    eng = _make_engine(conf)
    eng.process([req(hits=3)], now=NOW)
    assert store.called["OnChange()"] == 1
    assert store.data["store_test_k"]["remaining"] == 2


def test_loader_drops_expired_items():
    eng = TickEngine(capacity=256, max_batch=64)
    eng.process([req(hits=1, duration=1000)], now=NOW)
    items = eng.export_items()
    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_items(items, now=NOW + 10_000)  # past expire_at
    assert eng2.cache_size() == 0


def test_columnar_snapshot_roundtrip(tmp_path):
    """export_columns/load_columns + ColumnFileLoader: bulk path matches
    the dict path item for item."""
    from gubernator_tpu.ops.engine import TickEngine, items_from_snapshot
    from gubernator_tpu.store import ColumnFileLoader

    eng = TickEngine(capacity=256, max_batch=64)
    eng.process(
        [req(key=f"c{i}", hits=2, limit=9) for i in range(40)]
        + [req(key="leaky", hits=3, limit=8, algorithm=1)],
        now=NOW,
    )
    snap = eng.export_columns()
    items = {it["key"]: it for it in eng.export_items()}
    assert len(items) == 41
    assert {it["key"] for it in items_from_snapshot(snap)} == set(items)

    path = str(tmp_path / "snap.npz")
    loader = ColumnFileLoader(path)
    loader.save_columns(snap)
    back = loader.load_columns()
    eng2 = TickEngine(capacity=256, max_batch=64)
    eng2.load_columns(back, now=NOW + 1)
    out = eng2.process([req(key="c3", hits=0, limit=9)], now=NOW + 1)[0]
    assert out.remaining == 7  # 9 - 2 from before the snapshot
    out = eng2.process([req(key="leaky", hits=0, limit=8, algorithm=1)],
                       now=NOW + 1)[0]
    assert out.remaining == 5

    # Dict-protocol view of the same file agrees.
    assert {it["key"] for it in loader.load()} == set(items)


def test_load_columns_drops_expired_and_dedups(tmp_path):
    from gubernator_tpu.ops.engine import SNAP_FIELDS, TickEngine
    import numpy as np

    eng = TickEngine(capacity=64, max_batch=32)
    keys = [b"store_test_live", b"store_test_dead", b"store_test_live"]  # dup: last wins
    offsets = np.zeros(4, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    snap = {"key_blob": b"".join(keys), "key_offsets": offsets}
    base = dict(
        algorithm=0, limit=10, remaining=5, remaining_f=0.0,
        duration=60_000, created_at=NOW, updated_at=NOW, burst=10,
        status=0,
    )
    for f in SNAP_FIELDS:
        if f == "expire_at":
            snap[f] = np.asarray([NOW + 60_000, NOW - 1, NOW + 60_000])
        else:
            dt = np.float64 if f == "remaining_f" else np.int64
            snap[f] = np.asarray(
                [base[f], base[f], 3 if f == "remaining" else base[f]], dt
            )
    eng.load_columns(snap, now=NOW)
    assert eng.cache_size() == 1
    out = eng.process([req(key="live", hits=0, limit=10)], now=NOW)[0]
    assert out.remaining == 3  # the LAST duplicate's remaining


@pytest.mark.parametrize("layout", [
    "columns",
    pytest.param("row", marks=pytest.mark.skipif(
        not rowtable.interpret_supported(),
        reason="Pallas interpret mode cannot lower the row kernels on "
               "this jax build")),
])
def test_slim_export_probe_regimes(monkeypatch, layout):
    """The schema-specialized export (engine.export_columns) drops hi
    words a device probe proves redundant; this exercises all three
    per-chunk regimes — hi == sign extension (small values), hi constant
    (epoch-ms columns), hi varying (must transfer) — plus negative
    remainings, the leaky f64 triple, and the multi-chunk path."""
    import numpy as np

    from gubernator_tpu.ops import engine as E

    monkeypatch.setattr(E, "SNAP_CHUNK", 16)  # force several chunks
    eng = E.TickEngine(capacity=256, max_batch=64, table_layout=layout)
    reqs = []
    for i in range(40):
        reqs.append(req(key=f"big{i}", hits=3, limit=(1 << 34) + i,
                        duration=60_000))
    # negative remaining: hits overdraft via DRAIN_OVER_LIMIT
    from gubernator_tpu.types import Behavior

    reqs.append(req(key="drained", hits=9, limit=5,
                    behavior=Behavior.DRAIN_OVER_LIMIT))
    reqs.append(req(key="leaky", hits=3, limit=7, algorithm=1))
    eng.process(reqs, now=NOW)

    snap = eng.export_columns()
    stats = eng.last_export_stats
    assert stats["items"] == 42
    # limits straddle 2^34 (hi word needed) but the epoch-ms columns'
    # hi is constant and the remaining column is sign-extended — the
    # transfer must be well under the full 80 B/slot schema.
    assert 0 < stats["d2h_bytes"] < 42 * 80
    by_key = {it["key"]: it for it in E.items_from_snapshot(snap)}
    # The per-item dict export is the oracle: every field of every item
    # must survive the probe/selection/decoding path bit-for-bit.
    oracle = {it["key"]: it for it in eng.export_items()}
    assert set(by_key) == set(oracle)
    for k, it in oracle.items():
        for f, v in it.items():
            assert by_key[k][f] == v, (k, f, by_key[k][f], v)
    assert by_key["store_test_big7"]["limit"] == (1 << 34) + 7
    assert by_key["store_test_big7"]["remaining"] == (1 << 34) + 7 - 3

    eng2 = E.TickEngine(capacity=256, max_batch=64, table_layout=layout)
    eng2.load_columns(snap, now=NOW + 1)
    out = eng2.process([req(key="big7", hits=0, limit=(1 << 34) + 7)],
                       now=NOW + 1)[0]
    assert out.remaining == (1 << 34) + 7 - 3
