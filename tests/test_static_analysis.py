"""guberlint (gubernator_tpu/analysis): rule fixtures, suppression and
baseline mechanics, and the repo-wide zero-findings gate.

Deliberately jax-free: the linter is pure stdlib and these tests import
only ``gubernator_tpu.analysis`` (the package root imports no jax — a
subprocess test below pins that property so it can't regress silently).
Everything here is AST walking over tiny fixture projects; the whole
file runs in a couple of seconds.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from gubernator_tpu.analysis import (
    RULES,
    load_baseline,
    load_project,
    run_project,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# Fixture projects
# ----------------------------------------------------------------------
MINI_CONFIG = """\
ENV_REGISTRY = {
    "GUBER_GOOD_KNOB": "a registered knob",
    "GUBER_OTHER_KNOB": "another registered knob",
}
"""

MINI_CONF = "# GUBER_GOOD_KNOB=1\n# GUBER_OTHER_KNOB=2\n"


def make_project(tmp_path, files, config=MINI_CONFIG, conf=MINI_CONF,
                 prometheus=None, metrics=None):
    """Write a minimal lintable project: pkg/config.py + example.conf
    boilerplate plus the given {relpath: source} fixture files."""
    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "utils" / "__init__.py").write_text("")
    (pkg / "config.py").write_text(config)
    (tmp_path / "example.conf").write_text(conf)
    (tmp_path / "docs").mkdir()
    if prometheus is not None:
        (tmp_path / "docs" / "prometheus.md").write_text(prometheus)
    if metrics is not None:
        (pkg / "utils" / "metrics.py").write_text(metrics)
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return load_project(str(tmp_path), "pkg")


def findings(tmp_path, files, rule, **kw):
    proj = make_project(tmp_path, files, **kw)
    return [f for f in run_project(proj, rule_ids=[rule]).findings]


# ----------------------------------------------------------------------
# G001 — hot-path device sync
# ----------------------------------------------------------------------
G001_POS = """\
from pkg.utils.hotpath import hot_path
import numpy as np
import jax

@hot_path
def dispatch(self, state, resp):
    a = np.asarray(resp)          # D2H
    b = resp.item()               # D2H
    jax.device_get(resp)          # D2H
    state.block_until_ready()     # sync
    c = float(resp)               # scalar materialization
    return a, b, c
"""


def test_g001_flags_sync_primitives_in_hot_path(tmp_path):
    out = findings(tmp_path, {"mod.py": G001_POS}, "G001")
    assert len(out) == 5
    assert {f.rule for f in out} == {"G001"}
    msgs = " ".join(f.message for f in out)
    for tok in ("np.asarray", ".item()", "jax.device_get",
                "block_until_ready", "float()"):
        assert tok in msgs


def test_g001_flags_blocking_file_syscalls_in_hot_path(tmp_path):
    src = """\
    import os
    import mmap
    from pkg.utils.hotpath import hot_path

    @hot_path
    def take_batch(self, path):
        f = open(path, "rb")              # storage stall
        fd = os.open(path, os.O_RDONLY)   # storage stall
        os.fsync(fd)                      # storage stall
        m = mmap.mmap(fd, 0)              # storage stall
        return f, m

    def writer_loop(path):
        return open(path, "ab")           # unmarked: fine
    """
    out = findings(tmp_path, {"mod.py": src}, "G001")
    assert len(out) == 4
    msgs = " ".join(f.message for f in out)
    for tok in ("open()", "os.open()", "os.fsync()", "mmap.mmap()"):
        assert tok in msgs
    assert "blocking syscall" in out[0].message


def test_g001_ignores_unmarked_and_nested_and_jnp(tmp_path):
    src = """\
    import numpy as np
    import jax.numpy as jnp
    from pkg.utils.hotpath import hot_path

    def cold(resp):
        return np.asarray(resp)      # unmarked: fine

    @hot_path
    def dispatch(state, m):
        state = tick(state, jnp.asarray(m))   # H2D: fine

        def finish():                 # deferred callback: not checked
            return np.asarray(state)

        return state, finish
    """
    assert findings(tmp_path, {"mod.py": src}, "G001") == []


def test_g001_suppression_with_reason(tmp_path):
    src = """\
    import numpy as np
    from pkg.utils.hotpath import hot_path

    @hot_path
    def dispatch(sel):
        # guber: allow-G001(sel is host numpy)
        return np.asarray(sel)
    """
    proj = make_project(tmp_path, {"mod.py": src})
    res = run_project(proj, rule_ids=["G001"])
    assert res.findings == []
    assert res.suppressed == 1


def test_g001_empty_reason_does_not_suppress(tmp_path):
    src = """\
    import numpy as np
    from pkg.utils.hotpath import hot_path

    @hot_path
    def dispatch(sel):
        return np.asarray(sel)  # guber: allow-G001()
    """
    res = run_project(make_project(tmp_path, {"mod.py": src}),
                      rule_ids=["G001"])
    assert len(res.findings) == 1 and res.suppressed == 0


# ----------------------------------------------------------------------
# G002 — blocking under lock / blocking in async
# ----------------------------------------------------------------------
def test_g002_await_under_threading_lock(tmp_path):
    src = """\
    import asyncio

    class W:
        async def flush(self):
            with self._write_lock:
                await asyncio.sleep(1)
    """
    out = findings(tmp_path, {"mod.py": src}, "G002")
    assert len(out) == 1 and "held lock" in out[0].message


def test_g002_blocking_calls_in_async(tmp_path):
    src = """\
    import os
    import time

    async def loop(self):
        time.sleep(0.1)
        os.fsync(3)
        f = open("/tmp/x")
    """
    out = findings(tmp_path, {"mod.py": src}, "G002")
    assert len(out) == 3
    msgs = " ".join(f.message for f in out)
    assert "time.sleep" in msgs and "os.fsync" in msgs and "open" in msgs


def test_g002_negative_cases(tmp_path):
    src = """\
    import asyncio
    import time

    def sync_writer(self):
        with self._write_lock:
            time.sleep(0.1)       # sync fn: allowed (runs in executor)

    async def good(self):
        async with self._alock:   # asyncio lock: fine to await under
            await asyncio.sleep(0)
        await asyncio.get_running_loop().run_in_executor(
            None, self.flush)     # blocking work via executor

        def thunk():
            open("/tmp/x")        # nested sync def: runs elsewhere
        return thunk
    """
    assert findings(tmp_path, {"mod.py": src}, "G002") == []


# ----------------------------------------------------------------------
# G003 — fire-and-forget tasks
# ----------------------------------------------------------------------
def test_g003_flags_discarded_handles(tmp_path):
    src = """\
    import asyncio

    def spawn(loop, coro):
        asyncio.create_task(coro())
        asyncio.ensure_future(coro())
        loop.create_task(coro())
        _ = asyncio.create_task(coro())
    """
    out = findings(tmp_path, {"mod.py": src}, "G003")
    assert len(out) == 4
    assert all("fire-and-forget" in f.message for f in out)


def test_g003_negative_cases(tmp_path):
    src = """\
    import asyncio

    async def ok(loop, coro, tasks):
        t = asyncio.create_task(coro())
        tasks.add(t)
        t.add_done_callback(tasks.discard)
        await asyncio.ensure_future(coro())
        return asyncio.ensure_future(coro())
    """
    assert findings(tmp_path, {"mod.py": src}, "G003") == []


# ----------------------------------------------------------------------
# G004 — env discipline
# ----------------------------------------------------------------------
def test_g004_direct_environ_read_outside_config(tmp_path):
    src = """\
    import os
    A = os.environ.get("GUBER_GOOD_KNOB")
    B = os.getenv("GUBER_OTHER_KNOB", "4")
    C = os.environ["GUBER_GOOD_KNOB"]
    """
    out = findings(tmp_path, {"mod.py": src}, "G004")
    assert len(out) == 3
    assert all("bypasses the config registry" in f.message for f in out)


def test_g004_unregistered_name_and_conf_sync(tmp_path):
    src = 'KNOB = "GUBER_NOT_REGISTERED"\n'
    conf = "# GUBER_GOOD_KNOB=1\n# GUBER_STALE_DOC=1\n"
    out = findings(tmp_path, {"mod.py": src}, "G004", conf=conf)
    msgs = " | ".join(f.message for f in out)
    assert "GUBER_NOT_REGISTERED" in msgs       # mentioned, unregistered
    assert "GUBER_OTHER_KNOB is registered but not documented" in msgs
    assert "GUBER_STALE_DOC" in msgs            # documented, unregistered
    assert len(out) == 3


def test_g004_env_writes_and_prefix_families_ok(tmp_path):
    src = """\
    import os
    os.environ["GUBER_GOOD_KNOB"] = "1"     # write: allowed
    DOC = "set any GUBER_FAULT_ knob"        # prefix mention: allowed
    """
    assert findings(tmp_path, {"mod.py": src}, "G004") == []


def test_g004_missing_registry_is_a_finding(tmp_path):
    out = findings(tmp_path, {"mod.py": "X = 1\n"}, "G004",
                   config="OTHER = 1\n")
    assert len(out) == 1 and "ENV_REGISTRY" in out[0].message


# ----------------------------------------------------------------------
# G005 — metric registry sync
# ----------------------------------------------------------------------
METRICS_SRC = """\
from prometheus_client import Counter, Gauge

class M:
    def __init__(self, reg):
        self.a = Counter("gubernator_alpha", "doc", registry=reg)
        self.b = Gauge("gubernator_beta", "doc", registry=reg)
"""

PROM_DOC = """\
# Metrics

| Metric | Type |
| ------ | ---- |
| `gubernator_alpha` | Counter |
| `gubernator_beta` | Gauge |

Prose may cite `gubernator_alpha_total` without a finding.
"""


def test_g005_in_sync(tmp_path):
    assert findings(tmp_path, {}, "G005", metrics=METRICS_SRC,
                    prometheus=PROM_DOC) == []


def test_g005_both_directions_and_duplicates(tmp_path):
    metrics = METRICS_SRC + """\

def extra(reg):
    from prometheus_client import Counter
    return (Counter("gubernator_alpha", "dup", registry=reg),
            Counter("gubernator_undocumented", "doc", registry=reg))
"""
    doc = PROM_DOC + "| `gubernator_ghost` | Counter |\n"
    out = findings(tmp_path, {}, "G005", metrics=metrics, prometheus=doc)
    msgs = " ".join(f.message for f in out)
    assert "duplicate metric family gubernator_alpha" in msgs
    assert "gubernator_undocumented" in msgs
    assert "gubernator_ghost" in msgs
    assert len(out) == 3


# ----------------------------------------------------------------------
# G006 — trace purity
# ----------------------------------------------------------------------
def test_g006_impure_calls_and_branches(tmp_path):
    src = """\
    import os
    import time
    import jax

    @jax.jit
    def decorated(x):
        t = time.time()
        if x > 0:
            x = x + 1
        return x + t

    def by_name(state, n):
        d = os.environ.get("GUBER_GOOD_KNOB")
        return state

    f = jax.jit(by_name, donate_argnums=(0,))
    g = jax.jit(lambda rows: rows + time.monotonic())
    """
    out = findings(tmp_path, {"mod.py": src}, "G006")
    msgs = " ".join(f.message for f in out)
    assert "time.time()" in msgs
    assert "Python-level branch" in msgs
    assert "os.environ" in msgs
    assert "time.monotonic()" in msgs
    assert len(out) == 4


def test_g006_static_metadata_branches_ok(tmp_path):
    src = """\
    import time
    import jax

    @jax.jit
    def ok(x, w):
        if x.shape[0] > 2:
            pass
        if w is None:
            pass
        if len(x.shape) == 2:
            pass
        return x

    def untraced(x):
        return time.time()       # never jitted: fine
    """
    assert findings(tmp_path, {"mod.py": src}, "G006") == []


def test_g006_shard_map_and_partial(tmp_path):
    src = """\
    import functools
    import jax
    from jax.experimental.shard_map import shard_map

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state):
        print("tracing")
        return state

    def body(x):
        import random
        return x * random.random()

    s = shard_map(body, mesh=None, in_specs=None, out_specs=None)
    """
    out = findings(tmp_path, {"mod.py": src}, "G006")
    msgs = " ".join(f.message for f in out)
    assert "print()" in msgs and "random.random()" in msgs
    assert len(out) == 2


def test_g006_sees_ragged_tick_wrappers():
    """The sharded engine's ragged tick wrappers are traced through
    shard_map by NAME — pin that G006's traced-function discovery still
    sees them (renaming or inlining them would silently drop the
    trace-purity guard from the serving path's hottest programs)."""
    import ast

    from gubernator_tpu.analysis.rules import _traced_functions

    path = os.path.join(
        REPO_ROOT, "gubernator_tpu", "parallel", "mesh_engine.py")
    with open(path) as f:
        tree = ast.parse(f.read())
    traced = {
        fn.name for fn, _ in _traced_functions(tree)
        if hasattr(fn, "name")
    }
    assert {"_tick_ragged", "_tick32_ragged"} <= traced


# ----------------------------------------------------------------------
# Suppression + baseline mechanics
# ----------------------------------------------------------------------
def test_suppression_line_above_and_wrong_rule(tmp_path):
    src = """\
    import asyncio

    def f(coro):
        # guber: allow-G003(intentional detach, probe result unused)
        asyncio.create_task(coro())
        # guber: allow-G001(wrong rule id)
        asyncio.create_task(coro())
    """
    res = run_project(make_project(tmp_path, {"mod.py": src}),
                      rule_ids=["G003"])
    assert len(res.findings) == 1
    assert res.suppressed == 1


def test_suppression_in_string_literal_does_not_count(tmp_path):
    src = '''\
    import asyncio

    DOC = "# guber: allow-G003(not a comment)"
    def f(coro):
        asyncio.create_task(coro())
    '''
    res = run_project(make_project(tmp_path, {"mod.py": src}),
                      rule_ids=["G003"])
    assert len(res.findings) == 1


def test_baseline_roundtrip_and_line_drift(tmp_path):
    src = "import asyncio\n\ndef f(c):\n    asyncio.create_task(c())\n"
    proj = make_project(tmp_path, {"mod.py": src})
    res = run_project(proj, rule_ids=["G003"])
    assert len(res.findings) == 1
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, proj, res.findings)
    data = json.load(open(bl_path))
    assert data["findings"][0]["rule"] == "G003"
    assert "reason" in data["findings"][0]

    # Same code → baselined out.
    res2 = run_project(proj, load_baseline(bl_path), rule_ids=["G003"])
    assert res2.findings == [] and res2.baselined == 1

    # Lines shift above the finding → fingerprint still matches.
    shifted = "import asyncio\n\nX = 1\nY = 2\n\ndef f(c):\n" \
              "    asyncio.create_task(c())\n"
    proj3 = make_project(tmp_path / "v2", {"mod.py": shifted})
    res3 = run_project(proj3, load_baseline(bl_path), rule_ids=["G003"])
    assert res3.findings == [] and res3.baselined == 1

    # A DIFFERENT offending line is not covered by the old entry.
    other = "import asyncio\n\ndef f(c):\n    asyncio.ensure_future(c())\n"
    proj4 = make_project(tmp_path / "v3", {"mod.py": other})
    res4 = run_project(proj4, load_baseline(bl_path), rule_ids=["G003"])
    assert len(res4.findings) == 1


def test_baseline_count_caps_repeated_findings(tmp_path):
    one = "import asyncio\n\ndef f(c):\n    asyncio.create_task(c())\n"
    two = ("import asyncio\n\ndef f(c):\n    asyncio.create_task(c())\n"
           "\ndef g(c):\n    asyncio.create_task(c())\n")
    proj1 = make_project(tmp_path, {"mod.py": one})
    res1 = run_project(proj1, rule_ids=["G003"])
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, proj1, res1.findings)
    # The second copy of the same offending line is NOT grandfathered.
    proj2 = make_project(tmp_path / "v2", {"mod.py": two})
    res2 = run_project(proj2, load_baseline(bl_path), rule_ids=["G003"])
    assert len(res2.findings) == 1 and res2.baselined == 1


# ----------------------------------------------------------------------
# The real repo: the permanent gate
# ----------------------------------------------------------------------
def test_repo_has_zero_unsuppressed_findings():
    proj = load_project(REPO_ROOT, "gubernator_tpu")
    assert len(proj.files) > 50  # sanity: the walk found the package
    baseline = load_baseline(
        os.path.join(REPO_ROOT, ".guberlint-baseline.json"))
    res = run_project(proj, baseline)
    assert res.findings == [], "\n" + "\n".join(
        f.render() for f in res.findings)


def test_repo_hot_path_markers_present():
    """G001 only guards what's marked — pin the serving-path coverage so
    removing a decorator (which would silently disable the rule there)
    fails loudly."""
    proj = load_project(REPO_ROOT, "gubernator_tpu")
    expected = {
        # lease_window is the quota-lease column scatter (docs/leases.md
        # — distinct from _lease_matrix's staging-slab lease): one
        # batched dispatch per grant/sync window on the serving path.
        # pack_wide_rows/pack_cols_req32/join_i32_pair are the host-side
        # column packers the call graph proves reachable from submit —
        # transitive G001 guards their bodies, so they carry the marker.
        "gubernator_tpu/ops/engine.py": [
            "_build_cols", "_lease_matrix", "_promote_misses",
            "submit_columns", "submit_cols", "submit", "lease_window",
            "pack_wide_rows", "pack_cols_req32", "join_i32_pair"],
        # The sharded serving path: resolve + the ragged flat dispatch
        # (the ONE serving format) run per serving window.
        # _dispatch_relayout/_cutover are the reshard transition's
        # bounded window (docs/resharding.md): every serving window is
        # frozen behind them, so G001 keeps them sync- and I/O-free.
        "gubernator_tpu/parallel/mesh_engine.py": [
            "submit_columns", "submit_cols", "submit",
            "_gregorian_cols", "_resolve_columns",
            "_resolve_columns_locked", "_account_misses",
            "_dispatch_ragged",
            "_dispatch_relayout", "_cutover"],
        "gubernator_tpu/service/tickloop.py": ["_run", "_flush"],
        # Overload control plane (docs/overload.md): queue admission,
        # window pops, and limiter feedback all run per serving window.
        "gubernator_tpu/admission/queue.py": ["push", "pop_window"],
        "gubernator_tpu/admission/limiter.py": ["record"],
        # Zero-copy ingest edge: the wire decode/encode and the arena
        # lease (plus its bounded-fallback accounting) run once per
        # serving window too.
        "gubernator_tpu/ops/reqcols.py": ["lease", "try_fallback"],
        "gubernator_tpu/transport/fastwire.py": ["parse_req",
                                                 "encode_resp"],
        # Telemetry plane (docs/observability.md): the flight recorder's
        # record path runs inside every instrumented serving window.
        "gubernator_tpu/utils/flightrec.py": ["begin", "note", "finish"],
        # Multi-process edge (docs/edge.md): the SPSC slab handoff and
        # the owner's drain both run once per published window — G001's
        # sync/file-syscall arms must keep them lock- and I/O-free.
        "gubernator_tpu/edge/shmring.py": ["publish", "pop_published"],
        "gubernator_tpu/edge/plane.py": ["_drain_once"],
        # SSD tier (docs/tiering.md): demote staging and the miss-path
        # batched lookup run on the dispatch thread — the file-syscall
        # arm of G001 keeps slab I/O on the background writer.
        "gubernator_tpu/tiering/ssd.py": ["put_columns", "take_batch"],
        # Algorithm zoo (docs/algorithms.md): the N-way policy fold and
        # each per-lane transition run inside every device tick — G001
        # keeps them sync-free, G006 keeps them retrace-free.
        "gubernator_tpu/algos/table.py": ["zoo_transitions"],
        "gubernator_tpu/algos/sliding_window.py": ["transition"],
        "gubernator_tpu/algos/gcra.py": ["transition"],
        "gubernator_tpu/algos/concurrency.py": ["transition"],
        # The branchless zoo mask runs inside submit's packing path.
        "gubernator_tpu/algos/__init__.py": ["invalid_algorithm_mask"],
    }
    for path, names in expected.items():
        text = proj.by_path[path].text
        for name in names:
            assert (
                f"@hot_path\n    def {name}(" in text
                or f"@hot_path\ndef {name}(" in text
            ), f"{path}: {name} lost its @hot_path marker"


def test_all_ten_rules_registered():
    assert sorted(RULES) == ["G001", "G002", "G003", "G004", "G005",
                             "G006", "G007", "G008", "G009", "G010"]
    for r in RULES.values():
        assert r.title and r.description and r.fix_hint


# ----------------------------------------------------------------------
# CLI + the no-jax property
# ----------------------------------------------------------------------
def test_cli_exits_zero_on_repo_and_imports_no_jax():
    out = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "from gubernator_tpu.analysis.__main__ import main\n"
         "rc = main(['--root', sys.argv[1]])\n"
         "assert 'jax' not in sys.modules, 'linter imported jax'\n"
         "sys.exit(rc)\n",
         REPO_ROOT],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
    )
    assert out.returncode == 0, out.stdout + out.stderr


def test_cli_exits_nonzero_on_injected_finding(tmp_path):
    make_project(tmp_path, {
        "bad.py": "import asyncio\n\ndef f(c):\n"
                  "    asyncio.create_task(c())\n"
    })
    out = subprocess.run(
        [sys.executable, "-m", "gubernator_tpu.analysis",
         "--root", str(tmp_path), "--package", "pkg"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
    )
    assert out.returncode == 1, out.stdout + out.stderr
    assert "G003" in out.stdout


@pytest.mark.parametrize("rule", ["G001", "G002", "G003", "G004",
                                  "G005", "G006", "G007", "G008",
                                  "G009", "G010"])
def test_each_rule_fixture_fails_the_cli(tmp_path, rule):
    """Acceptance: injecting any rule's positive fixture into a clean
    project makes the CLI exit nonzero."""
    fixture = {
        "G001": G001_POS,
        "G002": "async def f(self):\n    import time\n"
                "    time.sleep(1)\n",
        "G003": "import asyncio\n\ndef f(c):\n"
                "    asyncio.create_task(c())\n",
        "G004": "import os\nX = os.environ.get('GUBER_GOOD_KNOB')\n",
        "G005": None,
        "G006": "import jax, time\n\n@jax.jit\ndef f(x):\n"
                "    return x + time.time()\n",
        "G007": "import threading, time\n\nclass S:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "    def f(self):\n"
                "        with self._lock:\n"
                "            time.sleep(1)\n",
        "G008": "import threading\n\nclass P:\n"
                "    def __init__(self):\n"
                "        self._lock1 = threading.Lock()\n"
                "        self._lock2 = threading.Lock()\n"
                "    def ab(self):\n"
                "        with self._lock1:\n"
                "            with self._lock2:\n"
                "                pass\n"
                "    def ba(self):\n"
                "        with self._lock2:\n"
                "            with self._lock1:\n"
                "                pass\n",
        "G009": "import threading\n\nclass C:\n"
                "    def __init__(self):\n"
                "        self.n = 0\n"
                "        self._t = threading.Thread(target=self._run)\n"
                "    def _run(self):\n"
                "        self.n += 1\n"
                "    def read(self):\n"
                "        return self.n\n",
        "G010": "class Req:\n    deadline: float = 0.0\n\n\n"
                "def spawn_supervised(factory):\n    return factory\n\n\n"
                "class M:\n"
                "    def __init__(self):\n"
                "        self._q = {}\n"
                "        spawn_supervised(self._loop)\n"
                "    async def _loop(self):\n"
                "        self._q.clear()\n"
                "    def put(self, r: Req):\n"
                "        self._q[0] = r\n",
    }[rule]
    files = {"bad.py": fixture} if fixture else {}
    kw = {}
    if rule == "G005":
        kw = {"metrics": METRICS_SRC,
              "prometheus": PROM_DOC + "| `gubernator_ghost` | C |\n"}
    proj = make_project(tmp_path, files, **kw)
    res = run_project(proj, rule_ids=[rule])
    assert res.findings, f"{rule} fixture produced no findings"
