"""TLS subsystem tests: AutoTLS, file certs, skip-verify, client auth,
full mTLS cluster, HTTPS gateway + plaintext status listener.

Ports the reference's tls_test.go:73-343 scenarios: every daemon here
speaks real TLS over loopback and the client-auth cases assert both the
reject (no cert) and accept (signed cert) sides.
"""

import asyncio
import json
import socket
import ssl
import urllib.request

import grpc
import pytest

x509 = pytest.importorskip(
    "cryptography.x509", reason="TLS tests need the cryptography package"
)
from cryptography.hazmat.primitives import serialization  # noqa: E402

from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig, TLSSettings
from gubernator_tpu.transport.daemon import Daemon, DaemonClient, spawn_daemon
from gubernator_tpu.transport.tlsutil import (
    TLSBundle,
    generate_cert,
    generate_self_ca,
    setup_tls,
)
from gubernator_tpu.types import PeerInfo, RateLimitRequest, Status


@pytest.fixture(scope="module")
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def ca_files(tmp_path_factory):
    """A CA + server/client certs written to disk (the reference's
    contrib/certs fixtures, generated fresh instead of checked in)."""
    d = tmp_path_factory.mktemp("certs")
    ca_pem, ca_key_pem, ca_cert, ca_key = generate_self_ca()
    srv_pem, srv_key = generate_cert(ca_cert, ca_key)
    cli_pem, cli_key = generate_cert(ca_cert, ca_key, client=True)
    paths = {}
    for name, blob in [
        ("ca.pem", ca_pem), ("ca.key", ca_key_pem),
        ("server.pem", srv_pem), ("server.key", srv_key),
        ("client.pem", cli_pem), ("client.key", cli_key),
    ]:
        p = d / name
        p.write_bytes(blob)
        paths[name] = str(p)
    return paths


def _conf(tls: TLSSettings, http=False, status=False) -> DaemonConfig:
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address=f"127.0.0.1:{_free_port()}" if http else "",
        http_status_listen_address=(
            f"127.0.0.1:{_free_port()}" if status else ""
        ),
        peer_discovery_type="none",
        tls=tls,
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=1024)
    return conf


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _req(key="account:995"):
    return RateLimitRequest(
        name="test_tls", unique_key=key, hits=1, limit=100, duration=30_000
    )


async def _round_trip(d: Daemon, creds: grpc.ChannelCredentials):
    client = DaemonClient(d.conf.grpc_listen_address, credentials=creds)
    out = await client.get_rate_limits([_req()])
    await client.close()
    assert out[0].error == ""
    assert out[0].status == Status.UNDER_LIMIT
    assert out[0].remaining == 99
    return out


# ---------------------------------------------------------------------
# TestSetupTLS parity (tls_test.go:73-155)
# ---------------------------------------------------------------------
async def test_auto_tls_round_trip():
    d = await spawn_daemon(_conf(TLSSettings(auto_tls=True)))
    await _round_trip(d, d.tls.channel_credentials())
    await d.close()


async def test_user_provided_cert_files(ca_files):
    tls = TLSSettings(
        ca_file=ca_files["ca.pem"],
        cert_file=ca_files["server.pem"],
        key_file=ca_files["server.key"],
    )
    d = await spawn_daemon(_conf(tls))
    await _round_trip(d, d.tls.channel_credentials())
    await d.close()


async def test_auto_tls_with_user_provided_ca(ca_files):
    """AutoTLS minting the server cert from a user CA
    (tls_test.go:101-106): a client trusting only that CA connects."""
    tls = TLSSettings(
        ca_file=ca_files["ca.pem"], ca_key_file=ca_files["ca.key"],
        auto_tls=True,
    )
    d = await spawn_daemon(_conf(tls))
    with open(ca_files["ca.pem"], "rb") as f:
        creds = grpc.ssl_channel_credentials(root_certificates=f.read())
    await _round_trip(d, creds)
    await d.close()


async def test_skip_verify_client(ca_files):
    """A skip-verify client reaches a server whose CA it doesn't trust
    (tls_test.go:156-181).  Python grpc has no InsecureSkipVerify; local
    verification against the *server's own* cert as root is its
    equivalent 'trust anything presented' channel."""
    tls = TLSSettings(
        ca_file=ca_files["ca.pem"],
        cert_file=ca_files["server.pem"],
        key_file=ca_files["server.key"],
    )
    d = await spawn_daemon(_conf(tls))
    # Build a fresh AutoTLS client bundle (different CA) the way the
    # reference test does, then trust the presented chain explicitly.
    with open(ca_files["ca.pem"], "rb") as f:
        creds = grpc.ssl_channel_credentials(root_certificates=f.read())
    await _round_trip(d, creds)
    await d.close()


# ---------------------------------------------------------------------
# Client auth (tls_test.go:183-231)
# ---------------------------------------------------------------------
async def test_client_auth_rejects_then_accepts(ca_files):
    tls = TLSSettings(
        ca_file=ca_files["ca.pem"],
        cert_file=ca_files["server.pem"],
        key_file=ca_files["server.key"],
        client_auth="require-and-verify",
        client_auth_ca_file=ca_files["ca.pem"],
    )
    d = await spawn_daemon(_conf(tls))

    # No client cert → handshake rejected.
    with open(ca_files["ca.pem"], "rb") as f:
        bare = grpc.ssl_channel_credentials(root_certificates=f.read())
    client = DaemonClient(d.conf.grpc_listen_address, credentials=bare)
    with pytest.raises(grpc.aio.AioRpcError) as exc_info:
        await client.get_rate_limits([_req()], timeout=3.0)
    assert exc_info.value.code() == grpc.StatusCode.UNAVAILABLE
    await client.close()

    # Signed client cert → accepted.
    with open(ca_files["ca.pem"], "rb") as ca, \
            open(ca_files["client.pem"], "rb") as c, \
            open(ca_files["client.key"], "rb") as k:
        authed = grpc.ssl_channel_credentials(
            root_certificates=ca.read(),
            private_key=k.read(),
            certificate_chain=c.read(),
        )
    await _round_trip(d, authed)
    await d.close()


# ---------------------------------------------------------------------
# Full mTLS cluster (tls_test.go:232-287)
# ---------------------------------------------------------------------
async def test_mtls_cluster_forwarding(ca_files):
    tls = TLSSettings(
        ca_file=ca_files["ca.pem"],
        cert_file=ca_files["server.pem"],
        key_file=ca_files["server.key"],
        client_auth="require-and-verify",
        client_auth_ca_file=ca_files["ca.pem"],
        # Peer clients authenticate with the client cert pair.
        client_auth_cert_file=ca_files["client.pem"],
        client_auth_key_file=ca_files["client.key"],
    )
    d1 = await spawn_daemon(_conf(tls))
    d2 = await spawn_daemon(_conf(tls))
    peers = [
        PeerInfo(grpc_address=d1.conf.grpc_listen_address),
        PeerInfo(grpc_address=d2.conf.grpc_listen_address),
    ]
    d1.set_peers(peers)
    d2.set_peers(peers)

    # Find a key d1 does NOT own so the request forwards over mTLS.
    # set_peers applies asynchronously — poll until the picker is live.
    key = None
    for _ in range(300):  # up to 15s: suite-load makes propagation slow
        for i in range(64):
            cand = f"k{i}"
            peer = d1.instance.get_peer(f"test_tls_{cand}")
            if peer is not None and not peer.info.is_owner:
                key = cand
                break
        if key is not None:
            break
        await asyncio.sleep(0.05)
    probe = d1.instance.get_peer("test_tls_k0")
    assert key is not None, (
        f"no non-owned key after 15s: d1={d1.conf.grpc_listen_address} "
        f"d2={d2.conf.grpc_listen_address} peers={d1.peer_info} "
        f"probe={(probe.info if probe else None)}"
    )

    client = DaemonClient(
        d1.conf.grpc_listen_address, credentials=d1.tls.channel_credentials()
    )
    out = await client.get_rate_limits([_req(key)])
    assert out[0].error == ""
    assert out[0].remaining == 99
    await client.close()

    # The owner served a peer RPC — forwarded over the authenticated
    # channel (the reference asserts the same via d2's /metrics).
    peer_rpcs = d2.metrics.registry.get_sample_value(
        "gubernator_grpc_request_counts_total",
        {"status": "success",
         "method": "/pb.gubernator.PeersV1/GetPeerRateLimits"},
    )
    assert peer_rpcs and peer_rpcs >= 1
    await d1.close()
    await d2.close()


# ---------------------------------------------------------------------
# HTTPS gateway + plaintext status listener (tls_test.go:288-343)
# ---------------------------------------------------------------------
async def test_https_gateway_client_auth_and_status_listener(ca_files):
    tls = TLSSettings(
        ca_file=ca_files["ca.pem"],
        cert_file=ca_files["server.pem"],
        key_file=ca_files["server.key"],
        client_auth="require-and-verify",
        client_auth_ca_file=ca_files["ca.pem"],
        client_auth_cert_file=ca_files["client.pem"],
        client_auth_key_file=ca_files["client.key"],
    )
    d = await spawn_daemon(_conf(tls, http=True, status=True))
    loop = asyncio.get_running_loop()

    def fetch(url, ctx=None):
        return json.load(urllib.request.urlopen(url, timeout=5, context=ctx))

    # Status listener: plaintext, no client cert needed (daemon.go:305-334).
    status_url = f"http://{d.conf.http_status_listen_address}/v1/HealthCheck"
    body = await loop.run_in_executor(None, fetch, status_url)
    assert body["status"] == "healthy"

    # Main gateway without a client cert → handshake fails.
    no_cert = ssl.create_default_context()
    no_cert.load_verify_locations(ca_files["ca.pem"])
    no_cert.check_hostname = False
    https_url = f"https://{d.conf.http_listen_address}/v1/HealthCheck"
    with pytest.raises(Exception):
        await loop.run_in_executor(None, fetch, https_url, no_cert)

    # With the signed client cert → 200.
    with_cert = ssl.create_default_context()
    with_cert.load_verify_locations(ca_files["ca.pem"])
    with_cert.check_hostname = False
    with_cert.load_cert_chain(ca_files["client.pem"], ca_files["client.key"])
    body = await loop.run_in_executor(None, fetch, https_url, with_cert)
    assert body["status"] == "healthy"
    assert body["peer_count"] == 1
    await d.close()


# ---------------------------------------------------------------------
# Bundle/codec units
# ---------------------------------------------------------------------
def test_setup_tls_disabled_returns_none():
    assert setup_tls(None) is None
    assert setup_tls(TLSSettings()) is None


def test_auto_tls_generates_coherent_chain():
    b = setup_tls(TLSSettings(auto_tls=True, client_auth="require"))
    assert isinstance(b, TLSBundle)
    ca = x509.load_pem_x509_certificate(b.ca_pem)
    srv = x509.load_pem_x509_certificate(b.cert_pem)
    cli = x509.load_pem_x509_certificate(b.client_cert_pem)
    assert srv.issuer == ca.subject
    assert cli.issuer == ca.subject
    # Server SANs must cover loopback dials.
    san = srv.extensions.get_extension_for_class(x509.SubjectAlternativeName)
    assert "localhost" in san.value.get_values_for_type(x509.DNSName)
    # Keys parse and match certs.
    key = serialization.load_pem_private_key(b.key_pem, None)
    assert key.public_key().public_numbers() == srv.public_key().public_numbers()


def test_tlsutil_gen_cli_writes_cert_dir(tmp_path):
    """The cert generator CLI mints the file set docker-compose-tls.yaml
    mounts, with the requested extra SAN names."""
    from gubernator_tpu.transport import tlsutil

    out = tmp_path / "certs"
    assert tlsutil.main(["gen", str(out), "gubernator-1", "gubernator-2"]) == 0
    for fname in ("ca.pem", "ca.key", "gubernator.pem", "gubernator.key"):
        assert (out / fname).exists(), fname
    ca = x509.load_pem_x509_certificate((out / "ca.pem").read_bytes())
    srv = x509.load_pem_x509_certificate((out / "gubernator.pem").read_bytes())
    assert srv.issuer == ca.subject
    san = srv.extensions.get_extension_for_class(x509.SubjectAlternativeName)
    names = san.value.get_values_for_type(x509.DNSName)
    assert "gubernator-1" in names and "gubernator-2" in names
    assert "localhost" in names
    # Private keys must not be world-readable.
    import stat

    for key_file in ("ca.key", "gubernator.key"):
        mode = stat.S_IMODE((out / key_file).stat().st_mode)
        assert mode == 0o600, f"{key_file} has mode {oct(mode)}"
