"""Native C++ slotmap: behavior parity against the pure-Python SlotMap."""

import numpy as np
import pytest

from gubernator_tpu.ops.engine import SlotMap

native = pytest.importorskip("gubernator_tpu.native")
if native.load_library() is None:
    pytest.skip("native slotmap library unavailable", allow_module_level=True)

from gubernator_tpu.native import NativeSlotMap  # noqa: E402


@pytest.fixture(params=["python", "native"])
def sm(request):
    if request.param == "python":
        return SlotMap(256)
    return NativeSlotMap(256)


def test_assign_get_release_roundtrip(sm):
    s = sm.assign("a")
    assert s is not None
    assert sm.get("a") == s
    assert sm.assign("a") == s  # idempotent
    assert sm.key_of(s) == "a"
    assert len(sm) == 1
    sm.release(s)
    assert sm.get("a") is None
    assert sm.key_of(s) is None
    assert len(sm) == 0


def test_fills_to_capacity_and_reuses_released(sm):
    slots = [sm.assign(f"k{i}") for i in range(256)]
    assert None not in slots
    assert len(set(slots)) == 256
    assert sm.assign("overflow") is None
    sm.release(sm.get("k0"))
    assert sm.assign("overflow") is not None


def test_mapped_mask(sm):
    for i in range(10):
        sm.assign(f"k{i}")
    mask = sm.mapped_mask()
    assert mask.sum() == 10
    sm.release(sm.get("k0"))
    assert sm.mapped_mask().sum() == 9


def test_resolve_batch_matches_single_ops(sm):
    keys = [f"batch-{i % 50}".encode() for i in range(100)]
    slots, known = sm.resolve_batch(keys)
    assert (slots >= 0).all()
    # First 50 are fresh, second 50 are repeats mapping to the same slots.
    assert known[:50].sum() == 0
    assert known[50:].sum() == 50
    assert (slots[:50] == slots[50:]).all()
    for i in range(50):
        assert sm.get(f"batch-{i}") == slots[i]


def test_resolve_batch_full_table_returns_minus_one(sm):
    keys = [f"full-{i}".encode() for i in range(300)]
    slots, known = sm.resolve_batch(keys)
    assert (slots[:256] >= 0).all()
    assert (slots[256:] == -1).all()


def test_native_tombstone_rehash_stays_correct():
    """Churn far past capacity to exercise tombstone cleanup."""
    sm = NativeSlotMap(64)
    for round_ in range(200):
        keys = [f"r{round_}-{i}" for i in range(64)]
        for k in keys:
            assert sm.assign(k) is not None
        assert len(sm) == 64
        for k in keys:
            s = sm.get(k)
            assert s is not None and sm.key_of(s) == k
            sm.release(s)
        assert len(sm) == 0
