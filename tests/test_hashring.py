"""Consistent-hash picker tests (reference replicated_hash_test.go model)."""

import numpy as np
import pytest

from gubernator_tpu.parallel.hashring import (
    RegionPicker,
    ReplicatedConsistentHash,
    fnv1_64,
    fnv1a_64,
)
from gubernator_tpu.types import PeerInfo


def peers(n, dc=""):
    return [
        PeerInfo(grpc_address=f"10.0.0.{i}:81", http_address=f"10.0.0.{i}:80",
                 datacenter=dc)
        for i in range(n)
    ]


def test_fnv_vectors():
    # Published FNV-1 / FNV-1a 64-bit test vectors.
    assert fnv1_64("") == 0xCBF29CE484222325
    assert fnv1_64("a") == 0xAF63BD4C8601B7BE
    assert fnv1_64("foobar") == 0x340D8765A4DDA9C2
    assert fnv1a_64("a") == 0xAF63DC4C8601EC8C
    assert fnv1a_64("foobar") == 0x85944171F73967E8


@pytest.mark.parametrize(
    "hash_fn,expected",
    [
        (fnv1_64, {"a.svc.local": 2948, "b.svc.local": 3592, "c.svc.local": 3460}),
        (fnv1a_64, {"a.svc.local": 3110, "b.svc.local": 3856, "c.svc.local": 3034}),
    ],
)
def test_distribution_golden_vs_reference(hash_fn, expected):
    """EXACT distribution parity with the Go reference's pinned goldens
    (replicated_hash_test.go:56-100): same hosts, same 10k IPv4-string
    keys, same per-host counts ⇒ ring construction and lookup are
    bit-identical across implementations."""
    ring = ReplicatedConsistentHash(hash_fn)
    for h in ["a.svc.local", "b.svc.local", "c.svc.local"]:
        ring.add(PeerInfo(grpc_address=h))
    keys = [f"192.168.{i >> 8}.{i & 255}" for i in range(10_000)]
    counts = {h: 0 for h in expected}
    for owner in ring.get_batch(keys):
        counts[owner.grpc_address] += 1
    assert counts == expected


def test_batch_matches_single():
    ring = ReplicatedConsistentHash()
    for p in peers(7):
        ring.add(p)
    keys = [f"acct_{i}" for i in range(500)]
    batch = ring.get_batch(keys)
    for k, owner in zip(keys, batch):
        assert ring.get(k) is owner


def test_stability_under_membership_change():
    """Adding one peer must move only ~1/(n+1) of the keys."""
    ring = ReplicatedConsistentHash()
    for p in peers(9):
        ring.add(p)
    keys = [f"user_{i}" for i in range(5000)]
    before = {k: o.grpc_address for k, o in zip(keys, ring.get_batch(keys))}
    ring.add(PeerInfo(grpc_address="10.0.0.99:81"))
    after = {k: o.grpc_address for k, o in zip(keys, ring.get_batch(keys))}
    moved = sum(1 for k in keys if before[k] != after[k])
    assert moved / len(keys) < 0.25  # ~10% expected, generous bound


def test_deterministic_across_instances():
    """Two independently-built rings with the same peers agree on every
    owner — the property cross-node routing correctness rests on."""
    a = ReplicatedConsistentHash()
    b = ReplicatedConsistentHash()
    ps = peers(5)
    for p in ps:
        a.add(p)
    for p in reversed(ps):  # insertion order must not matter
        b.add(p)
    keys = [f"k{i}" for i in range(1000)]
    assert [o.grpc_address for o in a.get_batch(keys)] == [
        o.grpc_address for o in b.get_batch(keys)
    ]


def test_empty_pool_raises():
    with pytest.raises(RuntimeError, match="pool is empty"):
        ReplicatedConsistentHash().get("k")


def test_region_picker_returns_owner_per_region():
    rp = RegionPicker()
    for p in peers(3, dc="dc-a") + [
        PeerInfo(grpc_address=f"10.1.0.{i}:81", datacenter="dc-b")
        for i in range(3)
    ]:
        rp.add(p)
    owners = rp.get_clients("some_key")
    assert len(owners) == 2
    dcs = {o.datacenter for o in owners}
    assert dcs == {"dc-a", "dc-b"}
    assert rp.get_by_address("10.1.0.1:81") is not None
    assert len(rp.peers()) == 6
