"""Discovery pool tests: gossip membership, etcd, DNS, and k8s pools.

The reference exercises its pools against real infra in CI
(memberlist/etcd containers); here each pool runs against in-process
equivalents: the gossip pool against its own peers on loopback, the
etcd pool against a stub speaking the v3 JSON gateway (the surface
etcd.go drives), the k8s pool against a stub API server, and the DNS
pool against the system resolver on ``localhost``.
"""

import asyncio
import base64
import contextlib
import json

import pytest
from aiohttp import web

from gubernator_tpu.discovery import etcdpool
from gubernator_tpu.discovery.dnspool import DNSPool
from gubernator_tpu.discovery.etcdpool import EtcdPool
from gubernator_tpu.discovery.gossip import MemberlistPool
from gubernator_tpu.discovery.k8spool import K8sPool
from gubernator_tpu.types import PeerInfo


async def wait_until(predicate, timeout=8.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition not met before timeout")
        await asyncio.sleep(interval)


# ---------------------------------------------------------------------
# Gossip (memberlist equivalent)
# ---------------------------------------------------------------------
def _free_addr():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return addr


def _gossip_node(addr, seeds, updates, interval=0.05):
    return MemberlistPool(
        bind_address=addr,
        known_nodes=seeds,
        info=PeerInfo(grpc_address=f"grpc-{addr}"),
        on_update=updates.append,
        gossip_interval=interval,
        suspect_after=3,
    )


async def test_gossip_three_node_join_death_and_leave():
    addrs = [_free_addr() for _ in range(3)]
    updates = [[] for _ in range(3)]
    pools = [
        _gossip_node(a, [addrs[0]] if i else [], updates[i])
        for i, a in enumerate(addrs)
    ]
    for p in pools:
        await p.start()
    try:
        # Transitive join: node 2 learns node 1 via the shared seed.
        await wait_until(
            lambda: all(u and len(u[-1]) == 3 for u in updates)
        )
        peers = {p.grpc_address for p in updates[0][-1]}
        assert peers == {f"grpc-{a}" for a in addrs}

        # Hard-kill node 2 (no graceful leave): failure detection must mark
        # it dead after suspect_after failed probes.
        pools[2]._task.cancel()
        pools[2]._server.close()
        await pools[2]._server.wait_closed()
        await wait_until(lambda: len(updates[0][-1]) == 2)
        assert f"grpc-{addrs[2]}" not in {
            p.grpc_address for p in updates[0][-1]
        }

        # Graceful leave: node 1 announces its own death on close.
        await pools[1].close()
        await wait_until(lambda: len(updates[0][-1]) == 1)
    finally:
        for p in (pools[0],):
            await p.close()


async def test_gossip_swim_refutation():
    """A falsely-accused node re-asserts itself with a higher incarnation
    (SWIM refutation, the memberlist behavior gossip.py:81-86 mirrors)."""
    a_addr, b_addr = _free_addr(), _free_addr()
    a_updates, b_updates = [], []
    a = _gossip_node(a_addr, [], a_updates)
    b = _gossip_node(b_addr, [a_addr], b_updates)
    await a.start()
    await b.start()
    try:
        await wait_until(
            lambda: b_updates and len(b_updates[-1]) == 2
            and a_updates and len(a_updates[-1]) == 2
        )
        # B wrongly believes A is dead (same incarnation: dead beats alive).
        rec = b._members[a_addr]
        rec["alive"] = False
        b._emit()
        assert len(b_updates[-1]) == 1
        # Gossip reaches A; A refutes; B relearns A alive.
        await wait_until(lambda: len(b_updates[-1]) == 2)
        assert b._members[a_addr]["alive"]
        assert (
            b._members[a_addr]["incarnation"]
            > rec["incarnation"] - 1
        )
    finally:
        await a.close()
        await b.close()


# ---------------------------------------------------------------------
# etcd pool against a stub v3 JSON gateway
# ---------------------------------------------------------------------
class EtcdStub:
    """In-memory etcd v3 gateway: leases + kv under one prefix."""

    def __init__(self):
        self.kv = {}          # key(bytes-str) -> (value b64, lease_id)
        self.leases = set()
        self.next_lease = 100
        self.fail_keepalive_once = False
        self.puts = 0

    def app(self):
        app = web.Application()
        app.router.add_post("/v3/lease/grant", self.lease_grant)
        app.router.add_post("/v3/lease/keepalive", self.lease_keepalive)
        app.router.add_post("/v3/lease/revoke", self.lease_revoke)
        app.router.add_post("/v3/kv/put", self.kv_put)
        app.router.add_post("/v3/kv/range", self.kv_range)
        app.router.add_post("/v3/kv/deleterange", self.kv_delete)
        return app

    async def lease_grant(self, req):
        self.next_lease += 1
        self.leases.add(self.next_lease)
        return web.json_response({"ID": str(self.next_lease), "TTL": "30"})

    async def lease_keepalive(self, req):
        body = await req.json()
        lease = int(body["ID"])
        if self.fail_keepalive_once or lease not in self.leases:
            self.fail_keepalive_once = False
            # Lease gone: etcd reports TTL 0 and the key vanishes.
            self.leases.discard(lease)
            self.kv = {k: v for k, v in self.kv.items() if v[1] != lease}
            return web.json_response({"result": {"TTL": "0"}})
        return web.json_response({"result": {"TTL": "30"}})

    async def lease_revoke(self, req):
        body = await req.json()
        self.leases.discard(int(body["ID"]))
        return web.json_response({})

    async def kv_put(self, req):
        body = await req.json()
        self.kv[body["key"]] = (body["value"], int(body.get("lease", 0)))
        self.puts += 1
        return web.json_response({})

    async def kv_range(self, req):
        body = await req.json()
        lo = base64.b64decode(body["key"])
        hi = base64.b64decode(body["range_end"])
        kvs = [
            {"key": k, "value": v}
            for k, (v, _lease) in self.kv.items()
            if lo <= base64.b64decode(k) < hi
        ]
        return web.json_response({"kvs": kvs})

    async def kv_delete(self, req):
        body = await req.json()
        self.kv.pop(body["key"], None)
        return web.json_response({})


@contextlib.asynccontextmanager
async def serve(app):
    """Run an aiohttp app on an ephemeral port inside the test's loop
    (async fixtures need pytest-asyncio, which the image doesn't ship)."""
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    try:
        yield f"127.0.0.1:{port}"
    finally:
        await runner.cleanup()


async def test_etcd_register_watch_and_close():
    stub = EtcdStub()
    async with serve(stub.app()) as endpoint:
        await _etcd_register_watch_and_close(stub, endpoint)


async def _etcd_register_watch_and_close(stub, endpoint):
    updates = []
    pool = EtcdPool(
        endpoints=[endpoint],
        key_prefix="/guber/peers/",
        info=PeerInfo(grpc_address="10.0.0.1:81", http_address="10.0.0.1:80"),
        on_update=updates.append,
        poll_interval=0.05,
    )
    await pool.start()
    try:
        await wait_until(lambda: updates)
        assert updates[-1] == [
            PeerInfo(grpc_address="10.0.0.1:81", http_address="10.0.0.1:80")
        ]
        # A second node appears in the prefix → emitted.
        key = base64.b64encode(b"/guber/peers/10.0.0.2:81").decode()
        val = base64.b64encode(
            json.dumps({"grpc_address": "10.0.0.2:81"}).encode()
        ).decode()
        stub.kv[key] = (val, 0)
        await wait_until(lambda: len(updates[-1]) == 2)
    finally:
        await pool.close()
    # Close deleted our key and revoked the lease (etcd.go shutdown).
    assert all(b"10.0.0.1" not in base64.b64decode(k) for k in stub.kv)
    assert not stub.leases


async def test_etcd_lease_loss_triggers_reregister(monkeypatch):
    stub = EtcdStub()
    # Shrink the keepalive cadence (LEASE_TTL_S/3 sleeps) for the test.
    monkeypatch.setattr(etcdpool, "LEASE_TTL_S", 0.3)
    async with serve(stub.app()) as endpoint:
        await _etcd_lease_loss(stub, endpoint)


async def _etcd_lease_loss(stub, endpoint):
    updates = []
    pool = EtcdPool(
        endpoints=[endpoint],
        key_prefix="/guber/peers/",
        info=PeerInfo(grpc_address="10.0.0.1:81"),
        on_update=updates.append,
        poll_interval=0.05,
    )
    await pool.start()
    try:
        await wait_until(lambda: stub.puts >= 1)
        first_lease = pool._lease_id
        stub.fail_keepalive_once = True  # lease dies server-side
        # The pool must notice (TTL=0) and re-register under a new lease.
        await wait_until(lambda: stub.puts >= 2 and pool._lease_id != first_lease)
        assert pool._lease_id in stub.leases
        # And the key is back despite the lease loss having dropped it.
        await wait_until(
            lambda: updates and updates[-1]
            and updates[-1][0].grpc_address == "10.0.0.1:81"
        )
    finally:
        await pool.close()


# ---------------------------------------------------------------------
# DNS pool (system resolver, localhost)
# ---------------------------------------------------------------------
async def test_dns_pool_resolves_and_emits_once():
    updates = []
    pool = DNSPool(
        fqdn="localhost",
        grpc_port=1051,
        http_port=1050,
        on_update=updates.append,
        poll_interval=0.05,
    )
    await pool.start()
    try:
        await wait_until(lambda: updates)
        addrs = {p.grpc_address for p in updates[-1]}
        assert "127.0.0.1:1051" in addrs
        assert all(p.http_address.endswith(":1050") for p in updates[-1])
        # Stable records → no duplicate emissions across repolls.
        await asyncio.sleep(0.3)
        assert len(updates) == 1
    finally:
        await pool.close()


def test_dns_pool_requires_fqdn():
    with pytest.raises(ValueError):
        DNSPool(fqdn="", grpc_port=1, http_port=1, on_update=lambda p: None)


# ---------------------------------------------------------------------
# k8s pool against a stub API server
# ---------------------------------------------------------------------
class K8sStub:
    def __init__(self):
        self.endpoints_ips = ["10.1.0.1", "10.1.0.2"]
        self.pods = [
            {"status": {"phase": "Running", "podIP": "10.1.0.1",
                        "conditions": [{"type": "Ready", "status": "True"}]}},
            {"status": {"phase": "Running", "podIP": "10.1.0.9",
                        "conditions": [{"type": "Ready", "status": "False"}]}},
            {"status": {"phase": "Pending", "podIP": "10.1.0.8",
                        "conditions": [{"type": "Ready", "status": "True"}]}},
        ]
        self.selector_seen = None

    def app(self):
        app = web.Application()
        app.router.add_get(
            "/api/v1/namespaces/{ns}/endpoints", self.endpoints
        )
        app.router.add_get("/api/v1/namespaces/{ns}/pods", self.list_pods)
        return app

    async def endpoints(self, req):
        self.selector_seen = req.query.get("labelSelector")
        return web.json_response({
            "items": [{
                "subsets": [{
                    "addresses": [{"ip": ip} for ip in self.endpoints_ips]
                }]
            }]
        })

    async def list_pods(self, req):
        return web.json_response({"items": self.pods})


async def test_k8s_endpoints_mechanism():
    stub = K8sStub()
    async with serve(stub.app()) as endpoint:
        await _k8s_endpoints(stub, f"http://{endpoint}")


async def _k8s_endpoints(stub, base):
    updates = []
    pool = K8sPool(
        namespace="default",
        selector="app=gubernator",
        pod_ip="10.1.0.1",
        pod_port="1051",
        on_update=updates.append,
        mechanism="endpoints",
        poll_interval=0.05,
        api_server=base,
    )
    await pool.start()
    try:
        await wait_until(lambda: updates)
        assert {p.grpc_address for p in updates[-1]} == {
            "10.1.0.1:1051", "10.1.0.2:1051"
        }
        assert stub.selector_seen == "app=gubernator"
        # Membership change → one new emission.
        stub.endpoints_ips.append("10.1.0.3")
        await wait_until(lambda: len(updates[-1]) == 3)
    finally:
        await pool.close()


async def test_k8s_pods_mechanism_filters_not_ready():
    stub = K8sStub()
    async with serve(stub.app()) as endpoint:
        await _k8s_pods(stub, f"http://{endpoint}")


async def _k8s_pods(stub, base):
    updates = []
    pool = K8sPool(
        namespace="default",
        selector="app=gubernator",
        pod_ip="10.1.0.1",
        pod_port="1051",
        on_update=updates.append,
        mechanism="pods",
        poll_interval=0.05,
        api_server=base,
    )
    await pool.start()
    try:
        await wait_until(lambda: updates)
        # Only the Running+Ready pod appears.
        assert [p.grpc_address for p in updates[-1]] == ["10.1.0.1:1051"]
    finally:
        await pool.close()


def test_k8s_rejects_unknown_mechanism():
    with pytest.raises(ValueError):
        K8sPool(
            namespace="d", selector="s", pod_ip="", pod_port="1",
            on_update=lambda p: None, mechanism="nope",
        )
