"""Runtime sanitizers (utils/sanitize.py): the zero-cost-off contract,
the lock-order DAG's inversion assert (with both stacks), condition
wait release/reacquire mirroring, and the shm ring SPSC single-writer
pins — including the wired hooks in edge/shmring.py.

jax-free: sanitize imports only config; shmring imports numpy/reqcols.
"""

from __future__ import annotations

import threading

import pytest

from gubernator_tpu.utils import sanitize
from gubernator_tpu.utils.sanitize import (
    LockOrderTracker,
    LockOrderViolation,
    SingleWriterViolation,
    SlabStateSanitizer,
)


@pytest.fixture
def tracker():
    """Fresh process-wide edge set per test (the module tracker is
    shared state by design)."""
    sanitize.TRACKER.reset()
    yield sanitize.TRACKER
    sanitize.TRACKER.reset()


# ----------------------------------------------------------------------
# The zero-cost-off contract
# ----------------------------------------------------------------------
def test_off_mode_returns_bare_stdlib_primitives():
    assert type(sanitize.lock("x")) is type(threading.Lock())
    assert type(sanitize.rlock("x")) is type(threading.RLock())
    assert type(sanitize.condition("x")) is threading.Condition
    assert sanitize.ring_sanitizer("r") is None


def test_on_mode_returns_tracked_wrappers():
    lk = sanitize.lock("x", enabled=True)
    assert type(lk) is not type(threading.Lock())
    assert sanitize.ring_sanitizer("r", enabled=True) is not None


# ----------------------------------------------------------------------
# Lock-order DAG
# ----------------------------------------------------------------------
def test_inversion_asserts_with_both_stacks(tracker):
    la = sanitize.lock("A", enabled=True)
    lb = sanitize.lock("B", enabled=True)
    with la:
        with lb:
            pass
    with pytest.raises(LockOrderViolation) as ei:
        with lb:
            with la:
                pass
    msg = str(ei.value)
    assert "stack that recorded" in msg       # the A -> B acquisition
    assert "acquiring 'A' now" in msg         # the inverting acquisition
    assert "test_sanitize" in msg             # real stacks, both of them
    # The violating inner lock was released on the way out — the
    # process is not wedged behind a lock nobody will release.
    assert la.acquire(blocking=False)
    la.release()


def test_three_lock_cycle_detected(tracker):
    a = sanitize.lock("A3", enabled=True)
    b = sanitize.lock("B3", enabled=True)
    c = sanitize.lock("C3", enabled=True)
    with a, b:
        pass
    with b, c:
        pass
    with pytest.raises(LockOrderViolation):
        with c, a:
            pass


def test_consistent_order_and_reentrant_rlock_are_clean(tracker):
    a = sanitize.lock("Ok1", enabled=True)
    b = sanitize.lock("Ok2", enabled=True)
    r = sanitize.rlock("OkR", enabled=True)
    for _ in range(3):
        with a:
            with b:
                with r:
                    with r:   # reentrant: no self-edge, no violation
                        pass
    assert tracker.held() == []


def test_inversion_across_threads_is_caught(tracker):
    """The DAG is process-wide: thread 1 records A -> B, thread 2's
    B -> A nesting asserts even though neither thread deadlocks alone."""
    a = sanitize.lock("XT1", enabled=True)
    b = sanitize.lock("XT2", enabled=True)
    def t1():
        with a:
            with b:
                pass
    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with pytest.raises(LockOrderViolation):
        with b:
            with a:
                pass


def test_condition_wait_mirrors_release_reacquire(tracker):
    """A waiter parked in cond.wait() must not hold the cond's slot in
    the order DAG — acquiring another lock from a second thread while
    the waiter is parked records no cond -> lock edge."""
    cond = sanitize.condition("CondM", enabled=True)
    other = sanitize.lock("OtherM", enabled=True)
    woke = threading.Event()

    def waiter():
        with cond:
            cond.wait(timeout=5)
            woke.set()

    th = threading.Thread(target=waiter)
    th.start()
    with other:
        pass
    with cond:
        cond.notify_all()
    th.join()
    assert woke.is_set()
    # wait()'s reacquire restored the held-stack bookkeeping: the
    # waiter thread exited its with-block without underflow, and no
    # CondM edge involving OtherM exists.
    assert not any("OtherM" in k for k in tracker._edges)


# ----------------------------------------------------------------------
# SPSC slab-state sanitizer
# ----------------------------------------------------------------------
def test_slab_roles_pin_to_first_thread():
    s = SlabStateSanitizer("ring")
    s.note_publish(0)
    errs = []

    def other():
        try:
            s.note_publish(1)
        except SingleWriterViolation as e:
            errs.append(e)

    th = threading.Thread(target=other)
    th.start()
    th.join()
    assert len(errs) == 1 and "producer" in str(errs[0])
    # Same thread keeps publishing fine; the consumer role pins
    # independently.
    s.note_publish(2)
    s.note_pop(2)


def test_slab_free_legality_by_prior_state():
    s = SlabStateSanitizer("ring")
    s.note_publish(0)
    s.note_pop(0)
    s.note_free(0, was_published=False)            # leased: the contract
    with pytest.raises(SingleWriterViolation):
        s.note_free(1, was_published=True)         # published, never popped
    s.note_free(2, was_published=False)            # stale post-reset: ok


def test_slab_reset_clears_pins_and_leases():
    s = SlabStateSanitizer("ring")
    s.note_publish(0)
    s.note_pop(0)
    s.note_reset()
    done = []

    def new_producer():
        s.note_publish(1)
        done.append(True)

    th = threading.Thread(target=new_producer)
    th.start()
    th.join()
    assert done  # respawn re-legitimizes a new producer thread
    # The pre-reset lease is gone: freeing it now relies on prior state.
    s.note_free(0, was_published=False)


# ----------------------------------------------------------------------
# Wired hooks in edge/shmring.py
# ----------------------------------------------------------------------
def test_shmring_hooks_enforce_discipline(monkeypatch):
    from gubernator_tpu.edge import shmring

    monkeypatch.setattr(sanitize, "_ENABLED", True)
    seg = shmring.EdgeSegment(None, max_batch=4, slabs=2, depth=2,
                              create=True)
    try:
        ring = shmring.RequestRing(seg)
        assert ring._san is not None
        idx = ring.try_claim()
        ring.publish(idx, seqno=1, rows=1, blob_len=0, deadline_ns=0,
                     decode_ns=0, generation=1)
        popped = ring.pop_published()
        assert popped is not None and popped[0] == idx
        ring.free(idx)                      # leased -> FREE: the contract

        idx2 = ring.try_claim()
        ring.publish(idx2, seqno=2, rows=1, blob_len=0, deadline_ns=0,
                     decode_ns=0, generation=1)
        with pytest.raises(SingleWriterViolation):
            ring.free(idx2)                 # PUBLISHED, never popped
        ring.reset()
        ring.free(idx2)                     # stale release post-reset: ok
        ring.detach()
    finally:
        seg.close()
        seg.unlink()


def test_shmring_off_mode_has_no_sanitizer():
    from gubernator_tpu.edge import shmring

    seg = shmring.EdgeSegment(None, max_batch=4, slabs=2, depth=2,
                              create=True)
    try:
        ring = shmring.RequestRing(seg)
        resp = shmring.ResponseRing(seg)
        assert ring._san is None and resp._san is None
        ring.detach()
        resp.detach()
    finally:
        seg.close()
        seg.unlink()
