"""Seeded randomized differential fuzz: row vs column layouts.

The hand-written parity suite (test_rowtable.py) covers chosen
scenarios; this drives both layouts through the same randomized mixed
workload — algorithms, behaviors, duplicates, queries, negative hits,
limit/duration churn, time advancement, TTL expiry and eviction
pressure — and requires bit-identical responses and exports at every
step.  Deterministic seeds keep failures reproducible.
"""

import numpy as np
import pytest

from gubernator_tpu.ops import rowtable
from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

# The row half of the parity pair runs the Pallas DMA-ring kernels; on
# jax builds whose interpreter can't lower them this would fail on the
# emulator, not the engine (see rowtable.interpret_supported).
pytestmark = pytest.mark.skipif(
    not rowtable.interpret_supported(),
    reason="Pallas interpret mode cannot lower the row kernels on this "
           "jax build",
)

BEHAVIOR_POOL = [
    Behavior.BATCHING,
    Behavior.NO_BATCHING,
    Behavior.RESET_REMAINING,
    Behavior.DRAIN_OVER_LIMIT,
]


def random_request(rng, keyspace):
    key = f"k{rng.integers(0, keyspace)}"
    # All five algorithms, zoo included (docs/algorithms.md) — keys are
    # shared across draws, so algorithm-switch restarts fuzz too.
    algorithm = Algorithm(int(rng.integers(0, 5)))
    behavior = Behavior(0)
    if rng.random() < 0.25:
        behavior = BEHAVIOR_POOL[rng.integers(0, len(BEHAVIOR_POOL))]
    hits = int(rng.choice([0, 1, 1, 1, 2, 5, -1, 100]))
    # Limit/duration drawn from a small pool so a key sees parameter
    # changes over its lifetime (the limit-delta / duration-change and
    # algorithm-switch reference flows).
    limit = int(rng.choice([3, 10, 100]))
    duration = int(rng.choice([1_000, 5_000, 60_000]))
    burst = int(rng.choice([0, limit, limit * 2]))
    return RateLimitRequest(
        name="fuzz", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algorithm, behavior=behavior,
        burst=burst,
    )


def snap(resp):
    return [(r.status, r.limit, r.remaining, r.reset_time, r.error)
            for r in resp]


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzz_layout_parity(seed):
    rng = np.random.default_rng(seed)
    col = TickEngine(capacity=96, max_batch=64, table_layout="columns")
    row = TickEngine(capacity=96, max_batch=64, table_layout="row")
    now = 1_700_000_000_000
    for step in range(40):
        # keyspace > capacity so eviction/reclaim runs under pressure
        batch = [random_request(rng, keyspace=160)
                 for _ in range(int(rng.integers(1, 48)))]
        a = col.process(batch, now=now)
        b = row.process(batch, now=now)
        assert snap(a) == snap(b), f"seed {seed} step {step}"
        now += int(rng.choice([0, 50, 400, 2_000, 61_000]))
    assert col.cache_size() == row.cache_size()
    ea = sorted(col.export_items(), key=lambda d: d["key"])
    eb = sorted(row.export_items(), key=lambda d: d["key"])
    assert ea == eb
