"""Tracing: span lifecycle, W3C TraceContext codec, and cross-peer
propagation through a live cluster.

The reference piggybacks trace context on ``RateLimitReq.Metadata``
(metadata_carrier.go:19-38, injected at peer_client.go:140-141/359-360,
extracted at gubernator.go:502-504) so a forwarded request's owner-side
work reports into the caller's trace.  The cluster test here proves the
same end to end: a traced client call through a non-owner daemon produces
owner-side spans with the client's trace id.
"""

import asyncio

import pytest

from gubernator_tpu.cluster import Cluster
from gubernator_tpu.types import Behavior, RateLimitRequest
from gubernator_tpu.utils import tracing
from gubernator_tpu.utils.tracing import InMemoryExporter, SpanContext, Tracer


# ---------------------------------------------------------------------
# Unit: codec + span tree
# ---------------------------------------------------------------------
def test_traceparent_round_trip():
    t = Tracer()
    exp = InMemoryExporter()
    t.exporters.append(exp)
    carrier = {}
    with t.span("root") as root:
        t.inject(carrier)
    ctx = t.extract(carrier)
    assert ctx is not None
    assert ctx.trace_id == root.trace_id
    assert ctx.span_id == root.span_id
    assert ctx.sampled


@pytest.mark.parametrize(
    "bad",
    [
        "",
        "garbage",
        "00-abc-def-01",                                     # wrong lengths
        "00-" + "0" * 32 + "-" + "1234567890abcdef" + "-01",  # zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",            # zero span id
        "ff-" + "1" * 32 + "-" + "1234567890abcdef" + "-01",  # version ff
        "00-" + "G" * 32 + "-" + "1234567890abcdef" + "-01",  # non-hex
    ],
)
def test_traceparent_malformed_rejected(bad):
    assert Tracer.extract({"traceparent": bad}) is None


def test_span_nesting_and_export():
    t = Tracer()
    exp = InMemoryExporter()
    t.exporters.append(exp)
    with t.span("outer") as outer:
        with t.span("inner", {"k": "v"}) as inner:
            assert t.current_span() is inner
        assert t.current_span() is outer
    assert t.current_span() is None
    names = [s.name for s in exp.spans]
    assert names == ["inner", "outer"]  # inner finishes first
    inner_s, outer_s = exp.spans
    assert inner_s.trace_id == outer_s.trace_id
    assert inner_s.parent_span_id == outer_s.span_id
    assert inner_s.attributes["k"] == "v"
    assert inner_s.duration_ms >= 0


def test_remote_parent_continues_trace():
    t = Tracer()
    remote = SpanContext("ab" * 16, "cd" * 8)
    with t.span("server", parent=remote) as s:
        assert s.trace_id == remote.trace_id
        assert s.parent_span_id == remote.span_id


def test_detached_spans_do_not_become_current():
    t = Tracer()
    exp = InMemoryExporter()
    t.exporters.append(exp)
    remote = SpanContext("12" * 16, "34" * 8)
    s = t.start_detached("batch-item", parent=remote)
    assert t.current_span() is None
    t.finish(s)
    assert exp.spans[0].trace_id == remote.trace_id


def test_sampling_off_propagates_but_records_nothing():
    t = Tracer(ratio=0.0)
    exp = InMemoryExporter()
    t.exporters.append(exp)
    carrier = {}
    with t.span("unsampled") as s:
        assert not s.context.sampled
        t.inject(carrier)
    assert len(exp.spans) == 0
    # Context still crosses the wire, flags=00 (W3C requires propagation).
    ctx = t.extract(carrier)
    assert ctx is not None and not ctx.sampled


def test_exception_recorded():
    t = Tracer()
    exp = InMemoryExporter()
    t.exporters.append(exp)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("nope")
    assert "ValueError: nope" in exp.spans[0].error


# ---------------------------------------------------------------------
# Cluster: trace id survives a forwarded request
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def event_loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


@pytest.fixture(scope="module")
def cluster(event_loop):
    c = event_loop.run_until_complete(Cluster.start(3))
    yield c
    event_loop.run_until_complete(c.stop())


@pytest.fixture()
def exporter():
    exp = InMemoryExporter()
    tracing.add_exporter(exp)
    yield exp
    tracing.remove_exporter(exp)


async def test_trace_id_survives_forwarding(cluster, exporter):
    """Client span → non-owner daemon → owner daemon: every hop's spans
    carry the client's trace id (the in-process cluster shares one
    exporter, so both daemons' spans land in it)."""
    non_owner = cluster.list_non_owning_daemons("traced", "tk")[0]
    client = non_owner.client()
    with tracing.span("client.call") as client_span:
        out = await client.get_rate_limits(
            [RateLimitRequest(name="traced", unique_key="tk", hits=1,
                              limit=5, duration=60_000)]
        )
    assert out[0].error == ""
    await client.close()

    trace = exporter.by_trace(client_span.trace_id)
    names = {s.name for s in trace}
    # Non-owner side: server RPC span + the forward span.
    assert "grpc.recv.pb.gubernator.V1.GetRateLimits" in names
    assert "V1Instance.asyncRequest" in names
    # Owner side: the peer handler continued the trace from the request
    # metadata (gubernator.go:502-504 parity).
    assert "PeersV1.GetPeerRateLimit" in names
    peer_span = next(s for s in trace if s.name == "PeersV1.GetPeerRateLimit")
    assert peer_span.attributes["ratelimit.key"] == "tk"


async def test_no_batching_forward_also_propagates(cluster, exporter):
    non_owner = cluster.list_non_owning_daemons("traced-nb", "tk2")[0]
    client = non_owner.client()
    with tracing.span("client.call.nb") as client_span:
        out = await client.get_rate_limits(
            [RateLimitRequest(name="traced-nb", unique_key="tk2", hits=1,
                              limit=5, duration=60_000,
                              behavior=Behavior.NO_BATCHING)]
        )
    assert out[0].error == ""
    await client.close()
    names = {s.name for s in exporter.by_trace(client_span.trace_id)}
    assert "PeersV1.GetPeerRateLimit" in names


async def test_untraced_request_starts_fresh_traces(cluster, exporter):
    """No client context → server spans are roots (no parent leakage)."""
    d = cluster.daemons[0]
    client = d.client()
    out = await client.get_rate_limits(
        [RateLimitRequest(name="untraced", unique_key="u1", hits=1,
                          limit=5, duration=60_000)]
    )
    assert out[0].error == ""
    await client.close()
    rpc_spans = exporter.by_name("grpc.recv.pb.gubernator.V1.GetRateLimits")
    assert rpc_spans, "server RPC span missing"
    assert all(s.parent_span_id is None for s in rpc_spans)


def test_traceparent_future_version_with_trailing_fields_accepted():
    # W3C forward compatibility: higher versions may append fields; parse
    # the first four and ignore the rest.  Version 00 allows no tail.
    tid, sid = "1" * 32, "1234567890abcdef"
    assert Tracer.extract(
        {"traceparent": f"01-{tid}-{sid}-01-extradata"}
    ) == SpanContext(tid, sid, 1)
    assert Tracer.extract({"traceparent": f"00-{tid}-{sid}-01-extra"}) is None


# ---------------------------------------------------------------------
# Flight recorder (docs/observability.md): stage accounting on a
# virtual clock — no daemon, no device, no wall-clock sleeps.
# ---------------------------------------------------------------------
def test_flight_recorder_stage_accounting():
    from gubernator_tpu.resilience.clock import ManualClock
    from gubernator_tpu.utils import flightrec

    clk = ManualClock(start=100.0)
    rec = flightrec.FlightRecorder(windows=4, clock=clk)
    seen = []
    rec.observer = lambda stage, s: seen.append((stage, round(s, 6)))

    # decode happens before any window exists; it folds into the next
    # begin().  encode trails the last finished window.
    rec.edge("decode", 0.001)
    wid = rec.begin(width=8, depth=2)
    assert rec.active() == wid
    rec.note(wid, "lease", 0.0005)
    rec.note(wid, "pack", 0.002)
    rec.note(wid, "h2d", 0.003)
    rec.end_dispatch(wid)
    assert rec.active() is None
    rec.note(wid, "tick", 0.004)
    rec.note(wid, "resolve", 0.001)
    rec.finish(wid)
    rec.edge("encode", 0.0015)

    recs = rec.recent()
    assert len(recs) == 1
    r = recs[0]
    assert r["window"] == wid and r["width"] == 8 and r["queue_depth"] == 2
    assert r["wall"] == 100.0  # stamped from the injected clock
    assert r["stages_ms"]["decode"] == 1.0   # folded-forward edge
    assert r["stages_ms"]["encode"] == 1.5   # attached-backward edge
    assert r["stages_ms"]["pack"] == 2.0
    assert r["total_ms"] == pytest.approx(13.0)
    # finish() pushed every nonzero stage through the observer, and the
    # encode edge reported directly.
    assert ("pack", 0.002) in seen and ("encode", 0.0015) in seen

    pcts = rec.stage_percentiles()
    assert pcts["h2d"] == {"p50_ms": 3.0, "p99_ms": 3.0}
    assert pcts["decode"]["p50_ms"] == 1.0


def test_flight_recorder_ring_wrap_and_staleness():
    from gubernator_tpu.resilience.clock import ManualClock
    from gubernator_tpu.utils import flightrec

    clk = ManualClock()
    rec = flightrec.FlightRecorder(windows=4, clock=clk)
    wids = []
    for i in range(10):
        w = rec.begin(width=1, depth=0)
        rec.note(w, "pack", 0.001 * (i + 1))
        rec.finish(w)
        clk.advance(1.0)
        wids.append(w)
    # Only the last `windows` records survive the wrap.
    recs = rec.recent()
    assert [r["window"] for r in recs] == wids[-4:]
    # Notes against an evicted window are dropped, not misattributed.
    rec.note(wids[0], "pack", 99.0)
    assert all(r["stages_ms"]["pack"] < 90_000 for r in rec.recent())
    # recent(n) bounds the tail.
    assert [r["window"] for r in rec.recent(2)] == wids[-2:]


def test_flight_recorder_slow_window_watchdog_split():
    from gubernator_tpu.utils import flightrec

    rec = flightrec.FlightRecorder(windows=8, slow_threshold_s=0.005)
    fast = rec.begin(width=1, depth=0)
    rec.note(fast, "pack", 0.001)
    rec.finish(fast)
    slow = rec.begin(width=4, depth=1)
    rec.note(slow, "tick", 0.010)
    rec.finish(slow)

    assert rec.slow_total == 1
    dumps = rec.drain_slow()
    assert [d["window"] for d in dumps] == [slow]
    assert dumps[0]["stages_ms"]["tick"] == 10.0
    assert dumps[0]["width"] == 4
    assert rec.drain_slow() == []  # drained exactly once


def test_flight_recorder_global_slot():
    from gubernator_tpu.utils import flightrec

    assert flightrec.get() is None and not flightrec.enabled()
    rec = flightrec.FlightRecorder(windows=2)
    flightrec.install(rec)
    try:
        assert flightrec.get() is rec and flightrec.enabled()
    finally:
        flightrec.uninstall()
    assert flightrec.get() is None
