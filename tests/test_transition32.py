"""Differential test: transition32 (parts-native) vs bucket_transition
(the jax_enable_x64 oracle) across every branch of the decision tree.

Integer outputs (status, remaining, reset_time, over_limit, and every
integer state field) must match EXACTLY.  The leaky float remaining
matches exactly when rates are exactly representable (all golden-suite
shapes; the generator draws (duration, limit) pairs with exact
quotients) — at non-representable rates f64 and the ~70-bit triple can
legitimately round a drip boundary differently (double rounding), which
is checked separately as a consistency property, not exact equality.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gubernator_tpu.ops import i64pair as p64
from gubernator_tpu.ops import tfloat as tf
from gubernator_tpu.ops.buckets import (
    BucketState, ReqBatch, bucket_transition)
from gubernator_tpu.ops.transition32 import (
    PReq, PResp, PState, transition32)
from gubernator_tpu.types import Algorithm, Behavior

NOW = 1_700_000_000_000


def gen_batch(rng, n):
    """Random state+request pairs exercising every branch combination."""
    # exact-quotient (duration, limit) pool: rate = d/l representable
    dl = [(30_000, 10), (60_000, 1000), (1_000, 4), (4_096, 1 << 12),
          (3_600_000, 1000), (5_000, 5), (1_000, 1), (0, 10)]
    d_l = [dl[i] for i in rng.integers(0, len(dl), n)]
    duration = np.array([d for d, _ in d_l], np.int64)
    limit = np.array([l for _, l in d_l], np.int64)

    hits = rng.choice([0, 1, 2, 5, 100, -1, -50, 10**12], n)
    algo = rng.integers(0, 2, n).astype(np.int64)
    behavior = np.zeros(n, np.int64)
    pick = rng.random(n)
    behavior[pick < 0.2] = int(Behavior.RESET_REMAINING)
    behavior[(pick >= 0.2) & (pick < 0.35)] = int(Behavior.DRAIN_OVER_LIMIT)
    greg = (pick >= 0.35) & (pick < 0.45)
    behavior[greg] |= int(Behavior.DURATION_IS_GREGORIAN)
    burst = rng.choice([0, 5, 20, 10**6], n)

    known = rng.random(n) < 0.8
    in_use = rng.random(n) < 0.85
    s_algo = np.where(rng.random(n) < 0.7, algo, 1 - algo).astype(np.int64)
    s_limit = np.where(rng.random(n) < 0.6, limit,
                       rng.choice([1, 7, 2000, 10**13], n))
    s_duration = np.where(rng.random(n) < 0.6, duration,
                          rng.choice([500, 2_000, 120_000], n))
    s_remaining = rng.integers(0, 30, n).astype(np.int64)
    s_remaining[rng.random(n) < 0.2] = 0
    # drip-accumulated float remainders: integer + k/8 fractions (exact)
    s_rem_f = (rng.integers(0, 25, n) + rng.integers(0, 8, n) / 8.0)
    s_created = NOW - rng.integers(0, 120_000, n)
    s_updated = NOW - rng.integers(-5_000, 120_000, n)
    s_burst = np.where(rng.random(n) < 0.6, np.where(burst == 0, limit, burst),
                       rng.choice([3, 50], n))
    s_status = (rng.random(n) < 0.2).astype(np.int64)
    s_expire = NOW + rng.choice([-10_000, -1, 0, 1, 60_000], n)
    created = NOW - rng.choice([0, 0, 0, 1_000, 3_000, 61_000, -500], n)
    greg_exp = np.where(greg, NOW + rng.choice([500, 3_600_000], n), 0)
    greg_dur = np.where(greg, rng.choice([3_600_000, 86_400_000], n), 0)

    state = dict(
        algorithm=s_algo, limit=s_limit, remaining=s_remaining,
        remaining_f=s_rem_f, duration=s_duration, created_at=s_created,
        updated_at=s_updated, burst=s_burst, status=s_status,
        expire_at=s_expire, in_use=in_use,
    )
    req = dict(
        slot=np.arange(n, dtype=np.int64), known=known, hits=hits,
        limit=limit, duration=duration, algorithm=algo, behavior=behavior,
        created_at=created, burst=burst, greg_exp=greg_exp,
        greg_dur=greg_dur, valid=np.ones(n, bool),
    )
    return state, req


def to_oracle(state, req):
    s = BucketState(
        algorithm=jnp.asarray(state["algorithm"], jnp.int32),
        limit=jnp.asarray(state["limit"]),
        remaining=jnp.asarray(state["remaining"]),
        remaining_f=jnp.asarray(state["remaining_f"], jnp.float64),
        duration=jnp.asarray(state["duration"]),
        created_at=jnp.asarray(state["created_at"]),
        updated_at=jnp.asarray(state["updated_at"]),
        burst=jnp.asarray(state["burst"]),
        status=jnp.asarray(state["status"], jnp.int32),
        expire_at=jnp.asarray(state["expire_at"]),
        in_use=jnp.asarray(state["in_use"]),
        # Zoo columns (PR 16): token/leaky lanes never read them.
        tat=jnp.zeros_like(jnp.asarray(state["expire_at"])),
        prev_count=jnp.zeros_like(jnp.asarray(state["expire_at"])),
    )
    r = ReqBatch(
        slot=jnp.asarray(req["slot"], jnp.int32),
        known=jnp.asarray(req["known"]),
        hits=jnp.asarray(req["hits"]),
        limit=jnp.asarray(req["limit"]),
        duration=jnp.asarray(req["duration"]),
        algorithm=jnp.asarray(req["algorithm"], jnp.int32),
        behavior=jnp.asarray(req["behavior"], jnp.int32),
        created_at=jnp.asarray(req["created_at"]),
        burst=jnp.asarray(req["burst"]),
        greg_exp=jnp.asarray(req["greg_exp"]),
        greg_dur=jnp.asarray(req["greg_dur"]),
        valid=jnp.asarray(req["valid"]),
    )
    return s, r


def to_parts(state, req):
    s = PState(
        algorithm=jnp.asarray(state["algorithm"], jnp.int32),
        limit=p64.from_np(state["limit"]),
        remaining=p64.from_np(state["remaining"]),
        remaining_f=tf.from_np(state["remaining_f"]),
        duration=p64.from_np(state["duration"]),
        created_at=p64.from_np(state["created_at"]),
        updated_at=p64.from_np(state["updated_at"]),
        burst=p64.from_np(state["burst"]),
        status=jnp.asarray(state["status"], jnp.int32),
        expire_at=p64.from_np(state["expire_at"]),
        in_use=jnp.asarray(state["in_use"]),
        # Zoo columns (PR 16): token/leaky lanes never read them.
        tat=p64.from_np(np.zeros_like(state["expire_at"])),
        prev_count=p64.from_np(np.zeros_like(state["expire_at"])),
    )
    r = PReq(
        slot=jnp.asarray(req["slot"], jnp.int32),
        known=jnp.asarray(req["known"]),
        hits=p64.from_np(req["hits"]),
        limit=p64.from_np(req["limit"]),
        duration=p64.from_np(req["duration"]),
        algorithm=jnp.asarray(req["algorithm"], jnp.int32),
        behavior=jnp.asarray(req["behavior"], jnp.int32),
        created_at=p64.from_np(req["created_at"]),
        burst=p64.from_np(req["burst"]),
        greg_exp=p64.from_np(req["greg_exp"]),
        greg_dur=p64.from_np(req["greg_dur"]),
        valid=jnp.asarray(req["valid"]),
    )
    return s, r


@pytest.mark.parametrize("seed", [11, 12, 13, 14])
def test_differential_vs_x64_oracle(seed):
    rng = np.random.default_rng(seed)
    state, req = gen_batch(rng, 2048)

    os_, or_ = to_oracle(state, req)
    want_state, want_resp = jax.jit(bucket_transition)(
        jnp.int64(NOW), os_, or_)

    ps, pr = to_parts(state, req)
    got_state, got_resp = jax.jit(transition32)(
        p64.from_np(np.int64(NOW)), ps, pr)

    # responses: exact
    np.testing.assert_array_equal(
        np.asarray(got_resp.status), np.asarray(want_resp.status))
    np.testing.assert_array_equal(
        p64.to_np(got_resp.remaining), np.asarray(want_resp.remaining))
    np.testing.assert_array_equal(
        p64.to_np(got_resp.reset_time), np.asarray(want_resp.reset_time))
    np.testing.assert_array_equal(
        np.asarray(got_resp.over_limit), np.asarray(want_resp.over_limit))

    # new state: integer fields exact
    for f in ("limit", "remaining", "duration", "created_at",
              "updated_at", "burst", "expire_at"):
        np.testing.assert_array_equal(
            p64.to_np(getattr(got_state, f)),
            np.asarray(getattr(want_state, f)), err_msg=f)
    for f in ("algorithm", "status", "in_use"):
        np.testing.assert_array_equal(
            np.asarray(getattr(got_state, f)),
            np.asarray(getattr(want_state, f)), err_msg=f)
    # float remaining: the triple carries MORE precision than f64, so at
    # inexact leak quotients (elapsed/rate with a repeating expansion)
    # the stored value can sit a few f64-ulps from the CPU-f64 oracle —
    # the same drift class the previous on-TPU x64 emulation (a ~49-bit
    # float32 pair) already had vs CPU f64.  Integer-visible outputs
    # above are exact.
    np.testing.assert_allclose(
        tf.to_np(got_state.remaining_f),
        np.asarray(want_state.remaining_f), rtol=1e-14, atol=1e-12)


def test_rough_rate_consistency():
    """Non-representable rates (duration/limit with repeating binary
    expansion): exact f64 equality is not guaranteed at drip boundaries,
    but the parts path must keep its own invariants: response remaining
    == floor(stored remaining_f) for under-limit leaky decisions, and
    status consistent with remaining."""
    rng = np.random.default_rng(99)
    n = 1024
    state, req = gen_batch(rng, n)
    req["duration"] = rng.choice([1000, 900, 1234], n)
    req["limit"] = rng.choice([3, 7, 11, 13], n)
    req["algorithm"] = np.ones(n, np.int64)  # leaky
    state["algorithm"] = np.ones(n, np.int64)

    ps, pr = to_parts(state, req)
    got_state, got_resp = jax.jit(transition32)(
        p64.from_np(np.int64(NOW)), ps, pr)

    rem = p64.to_np(got_resp.remaining)
    stored = tf.to_np(got_state.remaining_f)
    status = np.asarray(got_resp.status)
    over = np.asarray(got_resp.over_limit)
    behavior = req["behavior"]
    drain = (behavior & int(Behavior.DRAIN_OVER_LIMIT)) != 0
    hits = req["hits"]

    # every decision: stored float remaining is finite and >= 0 unless
    # negative hits pushed it up; response remaining never negative for
    # positive-hit traffic
    assert np.isfinite(stored).all()
    pos = hits > 0
    assert (rem[pos] >= 0).all()
    # over_limit implies OVER status
    np.testing.assert_array_equal(status[over] != 0, over[over])
    # DRAIN over-limit zeroes response remaining
    assert (rem[over & drain & pos] == 0).all()


def test_preq_from_compact_roundtrip():
    from gubernator_tpu.ops.engine import (
        REQ32_ROWS, pack_request_matrix32)
    from gubernator_tpu.ops.transition32 import preq_from_compact
    from gubernator_tpu.types import RateLimitRequest

    reqs = [
        RateLimitRequest(
            name="t", unique_key=f"k{i}", hits=(-1) ** i * (i + 1) * 10**i,
            limit=(1 << 33) + i, duration=60_000 + i,
            algorithm=Algorithm(i % 2), behavior=Behavior(0),
            burst=i * 7, created_at=NOW + i)
        for i in range(8)
    ]
    m32 = np.zeros((REQ32_ROWS, 8), np.int32)
    pack_request_matrix32(
        m32, np.arange(8), reqs, np.arange(8), np.ones(8, bool), NOW)
    pr = preq_from_compact(jnp.asarray(m32))
    np.testing.assert_array_equal(
        p64.to_np(pr.hits), [r.hits for r in reqs])
    np.testing.assert_array_equal(
        p64.to_np(pr.limit), [r.limit for r in reqs])
    np.testing.assert_array_equal(
        p64.to_np(pr.created_at), [r.created_at for r in reqs])
    np.testing.assert_array_equal(np.asarray(pr.slot), np.arange(8))


def test_matrix_adapters_roundtrip():
    from gubernator_tpu.ops.rowtable import ROW_USED, logical_to_matrix
    from gubernator_tpu.ops.transition32 import (
        pstate_from_matrix, pstate_to_matrix)

    rng = np.random.default_rng(5)
    state, _ = gen_batch(rng, 256)
    os_, _ = to_oracle(state, gen_batch(rng, 256)[1])
    mat = jax.jit(logical_to_matrix)(os_)

    ps = pstate_from_matrix(mat)
    np.testing.assert_array_equal(p64.to_np(ps.limit), state["limit"])
    np.testing.assert_array_equal(
        tf.to_np(ps.remaining_f), state["remaining_f"])
    back = jax.jit(pstate_to_matrix)(ps)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(mat))
