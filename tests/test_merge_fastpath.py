"""Thundering-herd fast path: merged duplicate application must be bit-exact
with the sequential rank rounds.

The reference's headline scenario is many clients hammering one key
(docs/architecture.md, benchmark_test.go:122-147).  The tick kernel merges
uniform duplicate groups into closed-form prefix arithmetic
(engine._apply_merged_followers); these tests prove the merged kernel and
the pure rank-round kernel (merge_uniform=False) produce identical
responses *and* identical final table state across the branch space:
under/over, exact remainder, DRAIN_OVER_LIMIT, persisted status, mixed
groups (fallback), leaky herds (fraction preservation, exact-zero, drain),
RESET_REMAINING (never merged).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gubernator_tpu.ops.buckets import BucketState, get_slot, set_slot
from gubernator_tpu.ops.engine import REQ_ROW_INDEX, REQ_ROWS, make_tick_fn
from gubernator_tpu.types import Algorithm, Behavior, Status

CAP = 256


# Module-scoped jitted kernels: jax.jit caches per (function, shapes), and
# make_tick_fn returns a fresh closure per call — building them once lets
# every same-shape batch across the suite reuse one compiled program.
FAST = jax.jit(make_tick_fn(CAP, merge_uniform=True))
SLOW = jax.jit(make_tick_fn(CAP, merge_uniform=False))


def run_both(m: np.ndarray, state: BucketState | None = None, now: int = 1_000):
    """Run one packed batch through the merged and unmerged kernels."""
    if state is None:
        state = BucketState.zeros(CAP)
    st_f, r_f = FAST(state, jnp.asarray(m), jnp.int64(now))
    st_s, r_s = SLOW(state, jnp.asarray(m), jnp.int64(now))
    return (st_f, np.asarray(r_f)), (st_s, np.asarray(r_s))


def assert_identical(fast, slow):
    (st_f, r_f), (st_s, r_s) = fast, slow
    np.testing.assert_array_equal(r_f, r_s, err_msg="responses diverge")
    for name in BucketState._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_f, name)),
            np.asarray(getattr(st_s, name)),
            err_msg=f"state.{name} diverges",
        )


def packed(rows, b=None):
    """rows: list of dicts of REQ_ROWS fields; padding aims out of bounds."""
    b = b or len(rows)
    m = np.zeros((len(REQ_ROWS), b), np.int64)
    m[REQ_ROW_INDEX["slot"]] = CAP
    for c, r in enumerate(rows):
        for k, v in r.items():
            m[REQ_ROW_INDEX[k], c] = v
        m[REQ_ROW_INDEX["valid"], c] = 1
    return m


def uniform_rows(n, slot=3, hits=1, limit=10, behavior=0, known_head=0,
                 duration=60_000, created_at=1_000, algorithm=0, burst=0):
    rows = []
    for i in range(n):
        rows.append(dict(
            slot=slot, known=(1 if i else known_head), hits=hits, limit=limit,
            duration=duration, algorithm=algorithm, behavior=behavior,
            created_at=created_at, burst=burst,
        ))
    return rows


def test_herd_fresh_key_drains_then_over():
    m = packed(uniform_rows(64, hits=1, limit=10))
    f, s = run_both(m)
    assert_identical(f, s)
    # Sanity against the spec, not just self-consistency:
    r = f[1]
    status, _, remaining = r[0], r[1], r[2]
    assert list(remaining[:10]) == list(range(9, -1, -1))
    assert (status[:10] == Status.UNDER_LIMIT).all()
    assert (status[10:64] == Status.OVER_LIMIT).all()
    assert int(get_slot(f[0], "remaining", 3)) == 0
    # At-zero branch persisted OVER into the stored item (algorithms.go:162-169).
    assert int(get_slot(f[0], "status", 3)) == Status.OVER_LIMIT


def test_herd_nondivisible_no_drain_keeps_remainder():
    # hits=3 into limit=10: 7,4,1 under, then over-ask forever; remaining
    # parks at 1 and stored status never flips (over-ask isn't persisted).
    m = packed(uniform_rows(32, hits=3, limit=10))
    f, s = run_both(m)
    assert_identical(f, s)
    r = f[1]
    assert list(r[2][:3]) == [7, 4, 1]
    assert (r[0][3:32] == Status.OVER_LIMIT).all()
    assert (r[2][3:32] == 1).all()
    assert int(get_slot(f[0], "remaining", 3)) == 1
    assert int(get_slot(f[0], "status", 3)) == Status.UNDER_LIMIT


def test_herd_nondivisible_drain_zeroes():
    m = packed(uniform_rows(32, hits=3, limit=10,
                            behavior=Behavior.DRAIN_OVER_LIMIT))
    f, s = run_both(m)
    assert_identical(f, s)
    r = f[1]
    assert list(r[2][:3]) == [7, 4, 1]
    assert (r[2][3:32] == 0).all()
    assert int(get_slot(f[0], "remaining", 3)) == 0
    # Drain → at-zero from rank q+2 on → OVER persisted.
    assert int(get_slot(f[0], "status", 3)) == Status.OVER_LIMIT


def test_herd_on_existing_bucket_with_persisted_over():
    # Stored status OVER with remaining bumped back up (limit-delta path):
    # follower responses must echo the *persisted* status while under.
    st = BucketState.zeros(CAP)
    st = set_slot(st, 3, algorithm=0, limit=10, remaining=5,
                  duration=60_000, created_at=500, status=int(Status.OVER_LIMIT),
                  expire_at=60_500, in_use=True)
    m = packed(uniform_rows(8, hits=1, limit=10, known_head=1))
    f, s = run_both(m, state=st)
    assert_identical(f, s)
    assert (f[1][0][:5] == Status.OVER_LIMIT).all()  # echo of stored status


def test_mixed_hits_group_falls_back_identically():
    rows = uniform_rows(16, hits=2, limit=20)
    rows[7]["hits"] = 5  # one non-uniform member → whole group sequential
    m = packed(rows)
    f, s = run_both(m)
    assert_identical(f, s)


def test_reset_and_query_groups_never_merge_wrongly():
    rows = (
        uniform_rows(8, slot=2, hits=1, limit=10,
                     behavior=Behavior.RESET_REMAINING)
        + uniform_rows(8, slot=4, hits=0, limit=10)  # queries
    )
    m = packed(rows)
    f, s = run_both(m)
    assert_identical(f, s)


def test_leaky_herd_fresh_key_drains_then_over():
    m = packed(uniform_rows(
        64, hits=1, limit=10, algorithm=Algorithm.LEAKY_BUCKET))
    f, s = run_both(m)
    assert_identical(f, s)
    r = f[1]
    # burst defaults to limit; head takes 1, followers drain the rest.
    assert list(r[2][:10]) == list(range(9, -1, -1))
    assert (r[0][:10] == Status.UNDER_LIMIT).all()
    assert (r[0][10:64] == Status.OVER_LIMIT).all()
    assert float(get_slot(f[0], "remaining_f", 3)) == 0.0


def test_leaky_herd_preserves_fraction_through_decrements():
    # A stored fractional remaining (mid-drip) must survive integer
    # decrements bit-exactly — the closed form subtracts from the float,
    # not the truncation.
    st = BucketState.zeros(CAP)
    st = set_slot(st, 3, algorithm=int(Algorithm.LEAKY_BUCKET), limit=10,
                  remaining_f=7.625, duration=60_000, burst=10,
                  updated_at=1_000, expire_at=61_000, in_use=True)
    m = packed(uniform_rows(4, hits=2, limit=10, known_head=1,
                            algorithm=Algorithm.LEAKY_BUCKET))
    f, s = run_both(m, state=st)
    assert_identical(f, s)
    # 7.625 → head 5.625 → followers 3.625, 1.625, then over-ask parks it.
    assert float(get_slot(f[0], "remaining_f", 3)) == 1.625


def test_leaky_herd_exact_remainder_zeroes_float():
    # algorithms.go:392-397: the exact-remainder branch sets the *float*
    # remaining to exactly 0.0, dropping any fraction.
    st = BucketState.zeros(CAP)
    st = set_slot(st, 3, algorithm=int(Algorithm.LEAKY_BUCKET), limit=10,
                  remaining_f=6.5, duration=60_000, burst=10,
                  updated_at=1_000, expire_at=61_000, in_use=True)
    m = packed(uniform_rows(8, hits=2, limit=10, known_head=1,
                            algorithm=Algorithm.LEAKY_BUCKET))
    f, s = run_both(m, state=st)
    assert_identical(f, s)
    assert float(get_slot(f[0], "remaining_f", 3)) == 0.0


def test_leaky_herd_drain_zeroes_and_at_zero_reset_time():
    # Non-divisible remainder + DRAIN_OVER_LIMIT: the first over-ask zeroes
    # the float; later followers take the at-zero branch, whose reset_time
    # is computed from zero remaining, not the parked remainder.
    m = packed(uniform_rows(32, hits=3, limit=10,
                            algorithm=Algorithm.LEAKY_BUCKET,
                            behavior=Behavior.DRAIN_OVER_LIMIT))
    f, s = run_both(m)
    assert_identical(f, s)
    assert float(get_slot(f[0], "remaining_f", 3)) == 0.0


def test_leaky_herd_zero_remaining_keeps_fraction():
    # trunc(remaining)=0 with a live fraction: every follower is at-zero
    # and the fraction must survive (no exact/drain step ever fires).
    st = BucketState.zeros(CAP)
    st = set_slot(st, 3, algorithm=int(Algorithm.LEAKY_BUCKET), limit=10,
                  remaining_f=0.875, duration=60_000, burst=10,
                  updated_at=1_000, expire_at=61_000, in_use=True)
    m = packed(uniform_rows(6, hits=2, limit=10, known_head=1,
                            algorithm=Algorithm.LEAKY_BUCKET))
    f, s = run_both(m, state=st)
    assert_identical(f, s)
    assert float(get_slot(f[0], "remaining_f", 3)) == 0.875


def test_leaky_herd_4096_one_key():
    n = 4096
    m = packed(uniform_rows(n, hits=1, limit=100,
                            algorithm=Algorithm.LEAKY_BUCKET), b=n)
    f, s = run_both(m)
    assert_identical(f, s)
    r = f[1]
    assert (r[0][:100] == Status.UNDER_LIMIT).all()
    assert (r[0][100:n] == Status.OVER_LIMIT).all()


def test_negative_hits_group_falls_back():
    m = packed(uniform_rows(8, hits=-2, limit=10))
    f, s = run_both(m)
    assert_identical(f, s)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_randomized_parity(seed):
    rng = np.random.default_rng(seed)
    rows = []
    # ~20 slot groups, random sizes/params; some groups uniform, some mixed,
    # some leaky, some with behaviors; shuffled into one batch.
    for g in range(20):
        slot = int(rng.integers(0, 40))
        size = int(rng.integers(1, 12))
        uniform = rng.random() < 0.6
        base = dict(
            slot=slot,
            hits=int(rng.integers(0, 6)),
            limit=int(rng.integers(1, 12)),
            duration=60_000,
            algorithm=int(rng.random() < 0.2),
            behavior=int(rng.choice(
                [0, 0, 0, Behavior.DRAIN_OVER_LIMIT, Behavior.RESET_REMAINING]
            )),
            created_at=1_000,
            burst=0,
        )
        for i in range(size):
            r = dict(base)
            if not uniform and i and rng.random() < 0.5:
                r["hits"] = int(rng.integers(0, 6))
            r["known"] = 0  # first occurrence per slot fixed below
            rows.append(r)
    rng.shuffle(rows)
    seen = set()
    for r in rows:
        r["known"] = 1 if r["slot"] in seen else 0
        seen.add(r["slot"])
    m = packed(rows, b=256)
    f, s = run_both(m)
    assert_identical(f, s)


def test_herd_4096_one_key_matches_and_is_single_round():
    # The benchmark_test.go:122-147 scenario at full batch width: correctness
    # here, speed in bench.py.
    n = 4096
    m = packed(uniform_rows(n, hits=1, limit=100), b=n)
    f, s = run_both(m)
    assert_identical(f, s)
    r = f[1]
    assert (r[0][:100] == Status.UNDER_LIMIT).all()
    assert (r[0][100:n] == Status.OVER_LIMIT).all()
