"""Shared test helpers: a TickEngine wrapper with a controllable clock.

Plays the role of the reference's `clock.Freeze/Advance` (holster clock)
used throughout functional_test.go.
"""

from __future__ import annotations

from typing import List

from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse


class Sim:
    """Single-node engine with frozen, manually-advanced time."""

    def __init__(self, capacity: int = 1024, max_batch: int = 64, now: int = 1_700_000_000_000):
        self.engine = TickEngine(capacity=capacity, max_batch=max_batch)
        self.now = now

    def advance(self, ms: int) -> None:
        self.now += ms

    def hit(self, **kw) -> RateLimitResponse:
        return self.batch([RateLimitRequest(**kw)])[0]

    def batch(self, reqs: List[RateLimitRequest]) -> List[RateLimitResponse]:
        return self.engine.process(reqs, now=self.now)
