"""Client helper surface (reference client.go + python client parity)."""

import time

import pytest

from gubernator_tpu import client
from gubernator_tpu.types import PeerInfo
from gubernator_tpu.utils import timeutil


def test_duration_constants():
    assert client.SECOND == 1000 and client.MINUTE == 60_000


def test_timestamp_converters():
    assert client.to_timestamp(1.5) == 1500
    now = timeutil.now_ms()
    assert abs(client.from_timestamp(now - 2000) - 2.0) < 0.1
    assert client.from_unix_milliseconds(1500) == 1.5


def test_sleep_until_reset_blocks_until_reset():
    t0 = time.perf_counter()
    client.sleep_until_reset(timeutil.now_ms() + 120)
    assert time.perf_counter() - t0 >= 0.1
    # Past reset: returns immediately.
    t0 = time.perf_counter()
    client.sleep_until_reset(timeutil.now_ms() - 5000)
    assert time.perf_counter() - t0 < 0.05


async def test_asleep_until_reset():
    t0 = time.perf_counter()
    await client.asleep_until_reset(timeutil.now_ms() + 120)
    assert time.perf_counter() - t0 >= 0.1


def test_random_helpers():
    peers = [PeerInfo(grpc_address=f"h{i}:81") for i in range(5)]
    assert client.random_peer(peers) in peers
    s = client.random_string(24)
    assert len(s) == 24 and s.isalnum()


def test_dial_v1_rejects_empty():
    with pytest.raises(ValueError):
        client.dial_v1("")


async def test_dial_v1_roundtrip():
    from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
    from gubernator_tpu.transport.daemon import spawn_daemon
    from gubernator_tpu.types import RateLimitRequest

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        c = client.dial_v1(d.advertise_address)
        out = await c.get_rate_limits([RateLimitRequest(
            name="svc", unique_key="k", hits=1, limit=10, duration=60_000)])
        assert out[0].remaining == 9
        await client.asleep_until_reset(
            min(out[0].reset_time, timeutil.now_ms() + 50)
        )
        await c.close()
    finally:
        await d.close()
