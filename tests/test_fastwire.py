"""Native wire codec tests: byte parity with the protobuf library.

The serving fast path (transport/fastwire.py + native/wirecodec.cc)
replaces protobuf message objects on the wire↔columns boundary; these
tests prove the replacement is invisible — same columns as
``convert.columns_from_pb``, same bytes as ``SerializeToString()``,
lossless roundtrips — including the awkward cases (negative int64
varints, empty names, explicit created_at=0, metadata presence, unknown
fields from a future schema).
"""

import numpy as np
import pytest

from gubernator_tpu.ops.reqcols import CREATED_UNSET
from gubernator_tpu.pb import gubernator_pb2 as pb
from gubernator_tpu.transport import convert, fastwire

pytestmark = pytest.mark.skipif(
    fastwire.load() is None, reason="native wire codec unavailable"
)


def _req_bytes(reqs):
    return pb.GetRateLimitsReq(requests=reqs).SerializeToString()


def _parity(reqs):
    data = _req_bytes(reqs)
    out = fastwire.parse_req(data)
    assert out is not None
    cols, errors, special = out
    ref_cols, ref_errors, ref_special = convert.columns_from_pb(
        pb.GetRateLimitsReq.FromString(data).requests
    )
    assert errors == ref_errors
    assert special == ref_special
    # parse_req blobs are buffer views (zero-copy decode); compare bytes.
    assert bytes(cols.key_blob) == bytes(ref_cols.key_blob)
    np.testing.assert_array_equal(cols.key_offsets, ref_cols.key_offsets)
    for f in ("hits", "limit", "duration", "algorithm", "behavior",
              "created_at", "burst"):
        np.testing.assert_array_equal(
            getattr(cols, f), getattr(ref_cols, f), err_msg=f
        )
    return cols, errors, special


def test_parse_req_basic_parity():
    reqs = [
        pb.RateLimitReq(name=f"svc{i % 3}", unique_key=f"key{i}",
                        hits=1 + i, limit=10 ** 6, duration=3_600_000)
        for i in range(257)
    ]
    cols, errors, special = _parity(reqs)
    assert not errors and not special
    assert cols.name_len is not None
    assert cols.name_len[0] == len("svc0")


def test_parse_req_edge_values():
    reqs = [
        pb.RateLimitReq(name="n", unique_key="k", hits=-3,  # 10-byte varint
                        limit=2 ** 62, duration=1, burst=7),
        pb.RateLimitReq(name="n", unique_key="k2", created_at=0),
        pb.RateLimitReq(name="n", unique_key="k3", created_at=123456789),
        pb.RateLimitReq(name="Ω≈", unique_key="ключ", hits=1),  # UTF-8
    ]
    cols, errors, special = _parity(reqs)
    # explicit created_at=0 means "server stamps now" (columns_from_pb
    # parity); the nonzero one survives.
    assert cols.created_at[1] == CREATED_UNSET
    assert cols.created_at[2] == 123456789


def test_parse_req_errors_and_special():
    reqs = [
        pb.RateLimitReq(name="", unique_key="k"),
        pb.RateLimitReq(name="n", unique_key=""),
        pb.RateLimitReq(name="ok", unique_key="ok", behavior=2),  # GLOBAL
    ]
    cols, errors, special = _parity(reqs)
    assert 0 in errors and 1 in errors
    assert special


def test_parse_req_rejects_unknown_algorithm():
    # Out-of-range algorithm values must not fall through the kernels'
    # branchless dispatch as token-bucket (docs/algorithms.md); empty-
    # key errors keep precedence, and all five valid values pass.
    reqs = [
        pb.RateLimitReq(name="n", unique_key="k", hits=1, algorithm=7),
        pb.RateLimitReq(name="n", unique_key="", algorithm=9),
    ] + [
        pb.RateLimitReq(name="n", unique_key=f"ok{a}", hits=1, algorithm=a)
        for a in range(5)
    ]
    cols, errors, special = _parity(reqs)
    assert "invalid algorithm '7'" in errors[0]
    assert errors[1] == "field 'unique_key' cannot be empty"
    assert set(errors) == {0, 1}


def test_parse_req_metadata_presence():
    r = pb.RateLimitReq(name="n", unique_key="k")
    r.metadata["trace"] = "abc"
    cols, errors, special = _parity([r])
    assert special


def test_parse_req_unknown_fields_skipped():
    # A future-schema message: append an unknown varint field (200) and an
    # unknown length-delimited field (201) to a valid RateLimitReq.
    inner = pb.RateLimitReq(name="n", unique_key="k", hits=5)

    def varint(v):
        out = b""
        while True:
            if v < 0x80:
                return out + bytes([v])
            out += bytes([(v & 0x7F) | 0x80])
            v >>= 7

    raw_inner = (
        inner.SerializeToString()
        + varint((200 << 3) | 0) + varint(42)
        + varint((201 << 3) | 2) + varint(3) + b"xyz"
    )
    data = varint((1 << 3) | 2) + varint(len(raw_inner)) + raw_inner
    out = fastwire.parse_req(data)
    assert out is not None
    cols, errors, special = out
    assert len(cols) == 1 and cols.hits[0] == 5 and not errors


def test_parse_req_malformed_returns_none():
    assert fastwire.parse_req(b"\x0a\xff\xff\xff\xff\xff") is None


def test_encode_req_roundtrip():
    reqs = [
        pb.RateLimitReq(name=f"name{i}", unique_key=f"uk{i}", hits=i,
                        limit=5 * i, duration=1000 + i, algorithm=i % 2,
                        behavior=0, burst=i % 7)
        for i in range(64)
    ]
    reqs[3].created_at = 777
    reqs[4].hits = -1
    data = _req_bytes(reqs)
    cols, _, _ = fastwire.parse_req(data)
    enc = fastwire.encode_req(cols)
    assert enc is not None
    back = pb.GetRateLimitsReq.FromString(enc)
    assert len(back.requests) == len(reqs)
    for a, b in zip(reqs, back.requests):
        for f in ("name", "unique_key", "hits", "limit", "duration",
                  "algorithm", "behavior", "burst"):
            assert getattr(a, f) == getattr(b, f), f
        assert a.HasField("created_at") == b.HasField("created_at")
        assert a.created_at == b.created_at


def test_encode_req_from_requests_bridge():
    from gubernator_tpu.ops.reqcols import ReqColumns
    from gubernator_tpu.types import RateLimitRequest

    cols = ReqColumns.from_requests([
        RateLimitRequest(name="a", unique_key="b", hits=2, limit=9,
                         duration=100),
        RateLimitRequest(name="c_d", unique_key="e_f", hits=1, limit=1,
                         duration=1, created_at=55),
    ])
    enc = fastwire.encode_req(cols)
    back = pb.GetRateLimitsReq.FromString(enc)
    assert back.requests[0].name == "a"
    assert back.requests[1].unique_key == "e_f"  # '_' in parts survives
    assert back.requests[1].created_at == 55


def test_encode_resp_byte_parity():
    rng = np.random.default_rng(11)
    n = 500
    mat = np.zeros((5, n), np.int64)
    mat[0] = rng.integers(0, 2, n)
    mat[1] = rng.integers(0, 2 ** 40, n)
    mat[2] = rng.integers(-5, 2 ** 40, n)  # negatives: 10-byte varints
    mat[3] = rng.integers(0, 2 ** 45, n)
    ref = pb.GetRateLimitsResp(responses=[
        pb.RateLimitResp(
            status=int(mat[0, i]), limit=int(mat[1, i]),
            remaining=int(mat[2, i]), reset_time=int(mat[3, i]),
        )
        for i in range(n)
    ]).SerializeToString()
    assert fastwire.encode_resp(mat) == ref
    # and the numpy fallback agrees too
    from gubernator_tpu.transport.wire import encode_get_rate_limits_resp

    assert encode_get_rate_limits_resp(mat) == ref


def test_encode_resp_worst_case_cap():
    """All four fields negative — every varint takes its full 10 bytes,
    so each item costs the worst-case 46 B (44 B payload + 2 B item
    header).  The old `8 + 44 * n` budget under-sized exactly this
    matrix and leaned on the retry path; the corrected cap must fit it
    first try and still match protobuf byte-for-byte."""
    n = 64
    mat = np.full((5, n), -1, np.int64)
    mat[4] = 0  # error row: no special strings
    ref = pb.GetRateLimitsResp(responses=[
        pb.RateLimitResp(status=-1, limit=-1, remaining=-1, reset_time=-1)
        for _ in range(n)
    ]).SerializeToString()
    assert fastwire.encode_resp(mat) == ref


def test_parse_resp_roundtrip_and_special():
    mat = np.array(
        [[0, 1], [10, 20], [5, -2], [111, 222], [0, 1]], np.int64
    )
    m, special = fastwire.parse_resp(fastwire.encode_resp(mat))
    np.testing.assert_array_equal(m, mat[:4])
    assert not special.any()
    raw = pb.GetRateLimitsResp(responses=[
        pb.RateLimitResp(status=1, error="table full"),
        pb.RateLimitResp(limit=5),
    ]).SerializeToString()
    m2, sp2 = fastwire.parse_resp(raw)
    assert sp2[0] and not sp2[1]
    assert m2[0, 0] == 1 and m2[1, 1] == 5


def test_empty_batches():
    cols, errors, special = fastwire.parse_req(b"")
    assert len(cols) == 0 and not errors and not special
    assert fastwire.encode_resp(np.zeros((5, 0), np.int64)) == b""
    m, sp = fastwire.parse_resp(b"")
    assert m.shape == (4, 0) and len(sp) == 0


def test_peer_raw_wire_end_to_end():
    """GetPeerRateLimits over raw bytes: the peer edge shares the public
    edge's wire shapes, so the codec serves relayed batches too (the
    daemon processes them as owner regardless of ring state)."""
    import asyncio

    import grpc as grpc_mod

    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.transport.daemon import spawn_daemon

    async def run():
        conf = DaemonConfig(
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="",
            peer_discovery_type="none",
        )
        d = await spawn_daemon(conf)
        channel = grpc_mod.aio.insecure_channel(d.conf.grpc_listen_address)
        raw_peer = channel.unary_unary(
            "/pb.gubernator.PeersV1/GetPeerRateLimits",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        try:
            # The codec path must actually be live, or this test would
            # pass vacuously (codec bytes == protobuf bytes by design).
            assert d.instance.peer_columns_fast_path_ok()
            reqs = [
                pb.RateLimitReq(name="pw", unique_key=f"k{i}", hits=1,
                                limit=9, duration=60_000)
                for i in range(6)
            ]
            data = pb.GetRateLimitsReq(requests=reqs).SerializeToString()
            out = await raw_peer(data, timeout=30.0)
            mat, special = fastwire.parse_resp(out)
            assert mat.shape == (4, 6) and not special.any()
            assert (mat[1] == 9).all() and (mat[2] == 8).all()
            # Object-path parity through the real stub.
            from gubernator_tpu.transport.grpc_api import PeersV1Stub
            from gubernator_tpu.pb import peers_pb2 as ppb

            stub = PeersV1Stub(channel)
            resp = await stub.GetPeerRateLimits(
                ppb.GetPeerRateLimitsReq(requests=reqs), timeout=30.0
            )
            assert [r.remaining for r in resp.rate_limits] == [7] * 6
        finally:
            await channel.close()
            await d.close()

    asyncio.run(run())


def test_columnar_client_end_to_end():
    """Raw-bytes gRPC path: columnar client → native codec both ways →
    same decisions the object API returns (standalone daemon)."""
    import asyncio

    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.ops.reqcols import ReqColumns
    from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
    from gubernator_tpu.types import RateLimitRequest, Status

    async def run():
        conf = DaemonConfig(
            grpc_listen_address="127.0.0.1:0",
            http_listen_address="",
            peer_discovery_type="none",
        )
        d = await spawn_daemon(conf)
        client = DaemonClient(d.advertise_address)
        try:
            assert d.instance.columns_fast_path_ok()
            reqs = [
                RateLimitRequest(name="fw", unique_key=f"k{i}", hits=1,
                                 limit=3, duration=60_000)
                for i in range(8)
            ] * 2  # duplicates: second half decrements further
            cols = ReqColumns.from_requests(reqs)
            mat, errors = await client.get_rate_limits_columns(
                cols, timeout=30.0
            )
            assert not errors
            assert mat.shape == (4, 16)
            assert (mat[1] == 3).all()
            assert (mat[2][:8] == 2).all()      # first hit: remaining 2
            assert (mat[2][8:] == 1).all()      # duplicate: remaining 1
            # Object API against the same daemon agrees on the next hit.
            out = await client.get_rate_limits(reqs[:8], timeout=30.0)
            assert all(r.remaining == 0 for r in out)
            assert all(r.status == Status.UNDER_LIMIT for r in out)
            # One more drains it past the limit.
            out = await client.get_rate_limits(reqs[:8], timeout=30.0)
            assert all(r.status == Status.OVER_LIMIT for r in out)
            # Malformed bytes: INVALID_ARGUMENT, not UNKNOWN (the
            # pass-through deserializer moved parsing into the handler).
            import grpc

            try:
                await client._raw_get_rate_limits(
                    b"\x0a\xff\xff\xff\xff\xff", timeout=10.0
                )
                raise AssertionError("malformed request should fail")
            except grpc.aio.AioRpcError as e:
                assert e.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            await client.close()
            await d.close()

    asyncio.run(run())


# ----------------------------------------------------------------------
# Zero-copy ingest arena: decode-into-slab parity + lease mechanics
# ----------------------------------------------------------------------
def _rand_reqs(rng, n):
    """Randomized request batch spanning the codec's edge cases:
    negative/huge varints, explicit created_at=0, absent fields,
    UTF-8 keys."""
    reqs = []
    for i in range(n):
        kw = {}
        if rng.random() < 0.5:
            kw["hits"] = int(rng.integers(-(2**40), 2**40))
        if rng.random() < 0.5:
            kw["limit"] = int(rng.integers(0, 2**62))
        if rng.random() < 0.5:
            kw["duration"] = int(rng.integers(-(2**31), 2**31))
        if rng.random() < 0.3:
            kw["burst"] = int(rng.integers(0, 2**31))
        if rng.random() < 0.3:
            kw["algorithm"] = int(rng.integers(0, 2))
        if rng.random() < 0.3:
            # Any behavior bits except GLOBAL (2): GLOBAL flips the
            # special flag, which is its own (covered) route.
            kw["behavior"] = int(rng.choice([1, 4, 8, 16]))
        if rng.random() < 0.3:
            kw["created_at"] = int(rng.integers(0, 2**50))
        name = rng.choice(["svc", "s" * int(rng.integers(1, 40)), "Ω≈"])
        reqs.append(pb.RateLimitReq(
            name=name, unique_key=f"k{i}-{rng.integers(0, 10**9)}", **kw
        ))
    return reqs


def test_arena_decode_fuzz_parity():
    """Fuzzed wire batches must decode into arena slabs identically to
    both the plain decode and the protobuf object path — the zero-copy
    ingest pipeline changes allocation, never values."""
    from gubernator_tpu.ops.reqcols import ColumnArena

    rng = np.random.default_rng(11)
    arena = ColumnArena(512, slabs=3)
    for trial in range(6):
        reqs = _rand_reqs(rng, int(rng.integers(1, 400)))
        data = _req_bytes(reqs)
        plain = fastwire.parse_req(data)
        slab = fastwire.parse_req(data, arena)
        assert plain is not None and slab is not None
        pc, pe, ps = plain
        sc, se, ss = slab
        assert sc.lease is not None, "arena lease was not used"
        assert pe == se and ps == ss
        assert bytes(pc.key_blob) == bytes(sc.key_blob)
        np.testing.assert_array_equal(pc.key_offsets, sc.key_offsets)
        for f in ("hits", "limit", "duration", "algorithm", "behavior",
                  "created_at", "burst", "name_len"):
            np.testing.assert_array_equal(
                getattr(pc, f), getattr(sc, f), err_msg=f"{f} trial {trial}"
            )
        # Object-path parity (columns_from_pb is the reference).
        ref_cols, ref_errors, ref_special = convert.columns_from_pb(
            pb.GetRateLimitsReq.FromString(data).requests
        )
        assert se == ref_errors and ss == ref_special
        assert bytes(sc.key_blob) == bytes(ref_cols.key_blob)
        for f in ("hits", "limit", "duration", "algorithm", "behavior",
                  "created_at", "burst"):
            np.testing.assert_array_equal(
                getattr(sc, f), getattr(ref_cols, f), err_msg=f
            )
        sc.release()
        sc.release()  # idempotent
    assert arena.in_use() == 0


def test_arena_exhaustion_and_oversize_fall_back():
    """The arena is a bounded fast path: all-slabs-busy and oversized
    batches fall back to plain allocation, never fail or block."""
    from gubernator_tpu.ops.reqcols import ColumnArena

    arena = ColumnArena(8, slabs=2)
    small = _req_bytes(_rand_reqs(np.random.default_rng(0), 4))
    big = _req_bytes(_rand_reqs(np.random.default_rng(1), 64))
    a = fastwire.parse_req(small, arena)[0]
    b = fastwire.parse_req(small, arena)[0]
    assert a.lease is not None and b.lease is not None
    c = fastwire.parse_req(small, arena)[0]  # both slabs busy
    assert c.lease is None
    np.testing.assert_array_equal(a.hits, c.hits)
    d = fastwire.parse_req(big, arena)[0]    # wider than the slab
    assert d.lease is None
    assert arena.metric_misses == 2
    a.release()
    e = fastwire.parse_req(small, arena)[0]  # the slab recycled
    assert e.lease is not None
    np.testing.assert_array_equal(e.hits, b.hits)


def test_arena_slab_reuse_does_not_alias_live_columns():
    """A released slab's next decode must not disturb a still-held
    fallback batch, and two live leases never alias each other."""
    from gubernator_tpu.ops.reqcols import ColumnArena

    arena = ColumnArena(64, slabs=2)
    rng = np.random.default_rng(5)
    d1 = _req_bytes(_rand_reqs(rng, 16))
    d2 = _req_bytes(_rand_reqs(rng, 16))
    c1 = fastwire.parse_req(d1, arena)[0]
    h1 = c1.hits.copy()
    c2 = fastwire.parse_req(d2, arena)[0]
    np.testing.assert_array_equal(c1.hits, h1)  # second lease: no alias
    c1.release()
    c3 = fastwire.parse_req(d2, arena)[0]       # reuses c1's slab
    np.testing.assert_array_equal(c3.hits, c2.hits)
