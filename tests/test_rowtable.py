"""Row-layout table tests: Pallas kernels (interpret mode on CPU) and
row-vs-column engine parity.

The row layout (ops/rowtable.py) is the TPU production path; on the CPU
test backend its kernels run in Pallas interpret mode, so everything here
checks semantics, and the TPU bench checks speed.
"""

import numpy as np
import pytest

from gubernator_tpu.ops import rowtable
from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.ops.rowtable import (
    FIELD_OFFSETS,
    ROW_W,
    RowState,
    gather_rows,
    scatter_rows,
)
from gubernator_tpu.store import MockStore
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status

import jax.numpy as jnp

# Interpret-mode emulation of the DMA-ring kernels is version-sensitive
# (see rowtable.interpret_supported); on jax builds whose interpreter
# can't lower them these tests would fail on the emulator, not the
# kernels — real-TPU runs (GUBER_TEST_TPU=1) always execute them.
pytestmark = pytest.mark.skipif(
    not rowtable.interpret_supported(),
    reason="Pallas interpret mode cannot lower the row kernels on this "
           "jax build",
)


def req(key="k", hits=1, limit=10, duration=60_000, **kw):
    return RateLimitRequest(
        name="t", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=kw.pop("algorithm", Algorithm.TOKEN_BUCKET), **kw,
    )


# ----------------------------------------------------------------------
# Kernel correctness (interpret mode)
# ----------------------------------------------------------------------
def test_scatter_gather_round_trip():
    cap, b = 256, 32
    rng = np.random.default_rng(7)
    slots = np.sort(rng.permutation(cap)[:b]).astype(np.int32)
    rows = rng.integers(0, 1 << 30, (b, ROW_W)).astype(np.int32)
    table = jnp.zeros((cap + 1, ROW_W), jnp.int32)

    out = np.asarray(scatter_rows(table, jnp.asarray(slots), jnp.asarray(rows)))
    ref = np.zeros((cap + 1, ROW_W), np.int32)
    ref[slots] = rows
    assert np.array_equal(out, ref)

    got = np.asarray(gather_rows(jnp.asarray(out), jnp.asarray(slots)))
    assert np.array_equal(got, rows)


def test_scatter_guard_row_absorbs_masked_lanes():
    cap = 64
    table = jnp.zeros((cap + 1, ROW_W), jnp.int32)
    slots = jnp.asarray(np.array([3, cap, cap, 7], np.int32))
    rows = jnp.asarray(np.full((4, ROW_W), 9, np.int32))
    out = np.asarray(scatter_rows(table, slots, rows))
    assert (out[3] == 9).all() and (out[7] == 9).all()
    # nothing besides rows 3, 7 and the guard row was touched
    touched = np.zeros(cap + 1, bool)
    touched[[3, 7, cap]] = True
    assert (out[~touched] == 0).all()


def test_logical_matrix_round_trip():
    from gubernator_tpu.ops.buckets import BucketState

    b = 8
    rows = BucketState(
        algorithm=jnp.arange(b, dtype=jnp.int32) % 2,
        limit=jnp.asarray(np.arange(b) * (1 << 40) + 5, jnp.int64),
        remaining=jnp.asarray(np.arange(b) - 3, jnp.int64),
        remaining_f=jnp.asarray(np.linspace(-2.5, 1e12, b), jnp.float64),
        duration=jnp.full(b, 60_000, jnp.int64),
        created_at=jnp.full(b, 1_700_000_000_123, jnp.int64),
        updated_at=jnp.full(b, 1_700_000_000_456, jnp.int64),
        burst=jnp.full(b, 7, jnp.int64),
        status=jnp.ones(b, jnp.int32),
        expire_at=jnp.full(b, 1_700_000_060_000, jnp.int64),
        in_use=jnp.asarray(np.arange(b) % 2 == 0),
    )
    m = rowtable.logical_to_matrix(rows)
    back = rowtable.matrix_to_logical(m)
    for f in rows._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(back, f)), np.asarray(getattr(rows, f)), err_msg=f
        )


# ----------------------------------------------------------------------
# Engine parity: row layout must be observably identical to columns
# ----------------------------------------------------------------------
def make_engines(**kw):
    return (
        TickEngine(capacity=64, max_batch=64, table_layout="columns", **kw),
        TickEngine(capacity=64, max_batch=64, table_layout="row", **kw),
    )


def run_parity(batches, now0=1_700_000_000_000, **engine_kw):
    col, row = make_engines(**engine_kw)
    assert row.layout == "row" and col.layout == "columns"
    now = now0
    for batch in batches:
        a = col.process(batch, now=now)
        b = row.process(batch, now=now)
        assert [
            (r.status, r.limit, r.remaining, r.reset_time, r.error) for r in a
        ] == [
            (r.status, r.limit, r.remaining, r.reset_time, r.error) for r in b
        ]
        now += 1_000
    return col, row


def test_engine_parity_token_and_leaky():
    run_parity([
        [req(key=f"k{i}", hits=2, limit=5) for i in range(8)],
        [req(key=f"k{i}", hits=2, limit=5) for i in range(8)],
        [req(key=f"k{i}", hits=2, limit=5) for i in range(8)],  # over limit
        [req(key=f"l{i}", hits=1, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET) for i in range(8)],
        [req(key=f"l{i}", hits=3, limit=10, duration=10_000,
             algorithm=Algorithm.LEAKY_BUCKET) for i in range(8)],
    ])


def test_engine_parity_duplicates_and_behaviors():
    run_parity([
        # thundering herd: one key many times (merge fast path)
        [req(key="hot", hits=1, limit=10) for _ in range(32)],
        # mixed-parameter duplicates (rank-round fallback)
        [req(key="hot", hits=1, limit=10 + (i % 2)) for i in range(8)],
        # queries + RESET_REMAINING + DRAIN_OVER_LIMIT + negative hits
        [
            req(key="hot", hits=0, limit=10),
            req(key="hot", hits=-2, limit=10),
            req(key="hot", hits=1, limit=10,
                behavior=Behavior.RESET_REMAINING),
            req(key="hot", hits=100, limit=10,
                behavior=Behavior.DRAIN_OVER_LIMIT),
        ],
    ])


def test_engine_parity_eviction_pressure():
    # capacity 64 engines; 3 generations of 60 distinct short-TTL keys
    # force TTL reclaim and LRU eviction on both layouts.
    gens = [
        [req(key=f"g{g}-{i}", hits=1, limit=3, duration=1_500)
         for i in range(60)]
        for g in range(3)
    ]
    col, row = run_parity(
        [gens[0], gens[1], gens[2]],
    )
    assert col.cache_size() == row.cache_size()


def test_engine_parity_store_write_through():
    col_store, row_store = MockStore(), MockStore()
    col = TickEngine(capacity=64, max_batch=64, table_layout="columns",
                     store=col_store)
    row = TickEngine(capacity=64, max_batch=64, table_layout="row",
                     store=row_store)
    now = 1_700_000_000_000
    batch = [req(key=f"k{i}", hits=1, limit=5) for i in range(4)]
    assert [r.remaining for r in col.process(batch, now=now)] == \
           [r.remaining for r in row.process(batch, now=now)]
    assert sorted(col_store.data) == sorted(row_store.data)
    for k in col_store.data:
        assert col_store.data[k] == row_store.data[k], k


def test_engine_parity_snapshot_and_globals():
    from gubernator_tpu.types import GlobalUpdate, RateLimitResponse

    col, row = run_parity([
        [req(key=f"k{i}", hits=1, limit=9, duration=120_000) for i in range(6)],
    ])
    a = sorted(col.export_items(), key=lambda d: d["key"])
    b = sorted(row.export_items(), key=lambda d: d["key"])
    assert a == b

    upd = [
        GlobalUpdate(
            key="t_gk",
            algorithm=Algorithm.TOKEN_BUCKET,
            duration=60_000,
            created_at=1_700_000_000_000,
            status=RateLimitResponse(
                status=Status.UNDER_LIMIT, limit=50, remaining=44,
                reset_time=1_700_000_060_000,
            ),
        )
    ]
    col.install_globals(upd, now=1_700_000_001_000)
    row.install_globals(upd, now=1_700_000_001_000)
    a = sorted(col.export_items(), key=lambda d: d["key"])
    b = sorted(row.export_items(), key=lambda d: d["key"])
    assert a == b

    # load_items round trip into fresh row engine
    fresh = TickEngine(capacity=64, max_batch=64, table_layout="row")
    fresh.load_items(a, now=1_700_000_001_500)
    c = sorted(fresh.export_items(), key=lambda d: d["key"])
    assert [d["key"] for d in c] == [d["key"] for d in a]
