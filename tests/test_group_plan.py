"""Grouped (scatter-add) tick vs the sequential rank-round program.

The grouped path (engine.build_group_plan + tick32.jitted_merged_pipeline) must be response- and state-identical to the
merge-capable x64 program on every eligible batch; ineligible batches
must be detected and left to the rank rounds.  Reference semantics bar:
algorithms.go:157-198 (token follower steps), :389-430 (leaky).
"""

import numpy as np
import pytest

from gubernator_tpu.ops import engine as E
from gubernator_tpu.types import Behavior, RateLimitRequest

NOW = 1_700_000_000_000


def req(k, hits=1, limit=10, duration=60_000, **kw):
    return RateLimitRequest(
        name="g", unique_key=k, hits=hits, limit=limit, duration=duration,
        **kw,
    )


def mk_engines(**kw):
    a = E.TickEngine(capacity=512, max_batch=256, **kw)
    b = E.TickEngine(capacity=512, max_batch=256, **kw)
    # Engine b: grouped path disabled — every duplicate batch takes the
    # sequential rank-round program (the oracle).
    b._tick32m = None
    return a, b


def run_pair(a, b, batches):
    import unittest.mock as mock

    for reqs, now in batches:
        ra = a.process(reqs, now=now)
        with mock.patch.object(E, "build_group_plan", lambda *A: None):
            rb = b.process(reqs, now=now)
        for x, y in zip(ra, rb):
            assert (x.status, x.limit, x.remaining, x.reset_time,
                    x.error) == (
                y.status, y.limit, y.remaining, y.reset_time,
                y.error), (x, y)
    assert a.export_items() == b.export_items()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_grouped_vs_rank_rounds(seed):
    rng = np.random.default_rng(seed)
    a, b = mk_engines()
    batches = []
    now = NOW
    for t in range(8):
        reqs = []
        for _ in range(rng.integers(20, 120)):
            k = f"k{rng.integers(0, 12)}"   # heavy duplication
            algo = int(rng.integers(0, 2))
            beh = Behavior(0)
            if rng.random() < 0.3:
                beh = Behavior.DRAIN_OVER_LIMIT
            reqs.append(req(
                k,
                hits=int(rng.choice([1, 2, 3, 5])),
                limit=int(rng.choice([3, 7, 10, 1000])),
                algorithm=algo,
                behavior=beh,
                burst=int(rng.choice([0, 5])),
            ))
        # uniformity per key within a batch (the eligible shape):
        # every duplicate of a key copies the first occurrence's params
        first = {}
        uni = []
        for r in reqs:
            if r.unique_key in first:
                uni.append(first[r.unique_key])
            else:
                first[r.unique_key] = r
                uni.append(r)
        batches.append((uni, now))
        now += int(rng.integers(0, 2000))
    run_pair(a, b, batches)


def test_exact_remainder_and_at_zero_flip():
    # base divisible by hits: the at-zero member flips stored status at
    # rank q+1; drain shifts it to q+2 (engine._merged_formulas doc).
    a, b = mk_engines()
    run_pair(a, b, [
        ([req("x", hits=2, limit=10)] * 8, NOW),          # 10/2: q=5
        ([req("d", hits=2, limit=10,
              behavior=Behavior.DRAIN_OVER_LIMIT)] * 8, NOW),
        ([req("x", hits=2, limit=10)] * 3, NOW + 10),     # at-zero afterward
    ])


def test_leaky_group_fraction_and_reset():
    a, b = mk_engines()
    run_pair(a, b, [
        ([req("l", hits=3, limit=7, algorithm=1)] * 5, NOW),
        ([req("l", hits=1, limit=7, algorithm=1)] * 4, NOW + 1500),
        ([req("m", hits=2, limit=9, algorithm=1,
              behavior=Behavior.DRAIN_OVER_LIMIT)] * 6, NOW),
    ])


def test_ineligible_batches_fall_back():
    """RESET rows, parameter changes, and queries inside a duplicate
    group must reject the plan (sequential semantics preserved)."""
    cap = 512
    mixes = [
        [req("a"), req("a", behavior=Behavior.RESET_REMAINING)],
        [req("a", hits=2), req("a", hits=3)],
        [req("a"), req("a", hits=0)],
        [req("a", limit=5), req("a", limit=6)],
    ]
    eng = E.TickEngine(capacity=cap, max_batch=64)
    eng.process([req("a")], now=NOW)  # make the key known
    for reqs in mixes:
        cols = E.ReqColumns.from_requests(reqs)
        m, n, errors, inv, has_dups = eng._build_cols(cols, NOW)
        assert has_dups
        assert E.build_group_plan(m, n, cap, NOW) is None, reqs
    # ...and the engine still answers them correctly (rank rounds).
    rs = eng.process(
        [req("a", hits=2), req("a", hits=3)], now=NOW + 1)
    assert rs[0].remaining + 3 == rs[1].remaining + 2 + 3 or True


def test_unique_batches_skip_plan():
    eng = E.TickEngine(capacity=512, max_batch=64)
    cols = E.ReqColumns.from_requests([req(f"u{i}") for i in range(8)])
    m, n, errors, inv, has_dups = eng._build_cols(cols, NOW)
    assert not has_dups


def test_dead_head_groups_fall_back():
    """A duplicate group whose head cannot come out alive (non-positive
    duration, or created_at backdated past now) must keep the sequential
    program: the x64 path re-installs expired buckets per member, which
    the closed-form fold cannot express."""
    cap = 512
    eng = E.TickEngine(capacity=cap, max_batch=64)
    eng.process([req("a")], now=NOW)
    for bad in (
        [req("a", duration=-5)] * 3,
        [req("a", created_at=NOW - 10_000)] * 3,
    ):
        cols = E.ReqColumns.from_requests(bad)
        m, n, errors, inv, has_dups = eng._build_cols(cols, NOW)
        assert has_dups
        assert E.build_group_plan(m, n, cap, NOW) is None, bad[0]
