"""Overload control plane unit tests (docs/overload.md).

Everything deadline-driven runs on :class:`ManualClock` — no wall-clock
sleeps anywhere near the shed decisions.  The TickLoop tests inject the
clock for *deadline math only* (the batch window stays on real time, so
the dispatch thread never wedges on a frozen clock) and use stub
engines, so the whole file is device-free and near-instant.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from gubernator_tpu.admission import (
    CLASS_CLIENT,
    CLASS_PEER,
    POLICY_FAIL_CLOSED,
    POLICY_FAIL_OPEN,
    SHED_EXPIRED_MSG,
    SHED_SHUTDOWN_MSG,
    AdmissionConfig,
    AdmissionQueue,
    AimdLimiter,
    BudgetExhaustedError,
    QueueItem,
    batch_deadline,
    budget_header_value,
    deadline_from_header,
)
from gubernator_tpu.resilience.clock import ManualClock
from gubernator_tpu.service.tickloop import TickLoop
from gubernator_tpu.types import (
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    Status,
)


def _item(n=1, deadline=None, klass=CLASS_CLIENT, kind="obj", payload=None):
    if payload is None:
        payload = [
            RateLimitRequest(name="a", unique_key=str(i), hits=1,
                             limit=100, duration=60_000)
            for i in range(n)
        ]
    return QueueItem(kind, payload, n, Future(), deadline, klass)


# ----------------------------------------------------------------------
# Deadline helpers
# ----------------------------------------------------------------------

def test_budget_header_round_trip_reanchors():
    # Sender at now=100 with 250ms left; receiver at its own now=7.
    hdr = budget_header_value(100.25, now=100.0)
    assert hdr == "250"
    d = deadline_from_header(hdr, now=7.0)
    assert d == pytest.approx(7.25)


def test_budget_header_spent_renders_zero_not_negative():
    assert budget_header_value(99.0, now=100.0) == "0"
    # A zero budget re-anchors to "already expired", not "no deadline".
    d = deadline_from_header("0", now=5.0)
    assert d == 5.0


def test_malformed_budget_header_never_fails_the_request():
    assert deadline_from_header(None, now=1.0) is None
    assert deadline_from_header("nope", now=1.0) is None
    assert deadline_from_header("-5", now=1.0) is None


def test_batch_deadline_is_earliest_member():
    rs = [
        RateLimitRequest(name="a", unique_key="1"),
        RateLimitRequest(name="a", unique_key="2", deadline=9.0),
        RateLimitRequest(name="a", unique_key="3", deadline=4.0),
    ]
    assert batch_deadline(rs) == 4.0
    assert batch_deadline(rs[:1]) is None


# ----------------------------------------------------------------------
# AdmissionQueue
# ----------------------------------------------------------------------

def test_queue_peer_class_drains_before_client():
    q = AdmissionQueue(limit=100)
    a = _item(klass=CLASS_CLIENT)
    b = _item(klass=CLASS_PEER)
    c = _item(klass=CLASS_CLIENT)
    for it in (a, b, c):
        assert q.push(it) == []
    out = q.pop_window(100)
    assert out == [b, a, c]  # peer first, then client FIFO


def test_queue_overflow_sheds_soonest_expiring_client():
    q = AdmissionQueue(limit=3)
    far = _item(deadline=50.0)
    soon = _item(deadline=10.0)
    none = _item(deadline=None)  # deadline-less ranks last
    assert q.push(far) == []
    assert q.push(soon) == []
    assert q.push(none) == []
    newcomer = _item(deadline=40.0)
    shed = q.push(newcomer)
    assert shed == [soon]
    assert q.requests == 3
    assert newcomer in q.pop_window(100)


def test_queue_client_arrival_never_evicts_peer_work():
    q = AdmissionQueue(limit=2)
    p1 = _item(klass=CLASS_PEER)
    p2 = _item(klass=CLASS_PEER)
    assert q.push(p1) == []
    assert q.push(p2) == []
    client = _item(klass=CLASS_CLIENT, deadline=1.0)
    shed = q.push(client)
    assert shed == [client]  # the arrival sheds itself
    assert q.pop_window(100) == [p1, p2]


def test_queue_peer_arrival_may_evict_peer_when_no_client_queued():
    q = AdmissionQueue(limit=2)
    p1 = _item(klass=CLASS_PEER, deadline=5.0)
    p2 = _item(klass=CLASS_PEER, deadline=1.0)
    assert q.push(p1) == []
    assert q.push(p2) == []
    p3 = _item(klass=CLASS_PEER, deadline=9.0)
    assert q.push(p3) == [p2]


def test_queue_oversized_item_admitted_when_empty_and_popped():
    q = AdmissionQueue(limit=4)
    big = _item(n=10)
    assert q.push(big) == []  # never deadlocks a legal batch
    assert q.pop_window(4) == [big]  # always at least one item
    assert q.requests == 0


def test_queue_pop_window_respects_request_bound():
    q = AdmissionQueue(limit=100)
    items = [_item(n=3) for _ in range(4)]
    for it in items:
        q.push(it)
    out = q.pop_window(7)
    assert out == items[:2]  # 3+3 fits, +3 would exceed 7
    assert q.requests == 6


# ----------------------------------------------------------------------
# AIMD limiter
# ----------------------------------------------------------------------

def test_limiter_disabled_at_zero_target():
    lim = AimdLimiter(0.0, max_limit=1000)
    assert not lim.enabled
    for _ in range(100):
        lim.record(1e9)
    assert lim.window_limit == 1000  # untouched


def test_limiter_backs_off_multiplicatively_then_recovers():
    lim = AimdLimiter(10.0, max_limit=1000, adjust_every=4)
    assert lim.window_limit == 1000  # starts wide open
    for _ in range(4):
        lim.record(50.0)  # p99 over target
    assert lim.window_limit == 800
    assert lim.metric_decreases == 1
    for _ in range(4):
        lim.record(50.0)
    assert lim.window_limit == 640
    # Healthy windows: additive recovery, one step per adjustment.
    for _ in range(4):
        lim.record(1.0)
    assert lim.window_limit == 640 + lim.step
    assert lim.metric_increases == 1


def test_limiter_converges_within_bounds():
    lim = AimdLimiter(10.0, max_limit=1000, adjust_every=4)
    for _ in range(200):
        lim.record(50.0)
    assert lim.window_limit == lim.min_limit == max(1, 1000 // 32)
    for _ in range(100_000 // 4):
        lim.record(1.0)
    assert lim.window_limit == 1000  # clamped at max


# ----------------------------------------------------------------------
# AdmissionConfig
# ----------------------------------------------------------------------

def test_admission_config_from_env(monkeypatch):
    monkeypatch.setenv("GUBER_REQUEST_TIMEOUT", "2s")
    monkeypatch.setenv("GUBER_TARGET_P99_MS", "7.5")
    monkeypatch.setenv("GUBER_PENDING_LIMIT", "123")
    monkeypatch.setenv("GUBER_SHED_POLICY", "fail-closed")
    c = AdmissionConfig.from_env()
    assert c.request_timeout == 2.0
    assert c.target_p99_ms == 7.5
    assert c.pending_limit == 123
    assert c.shed_policy == POLICY_FAIL_CLOSED
    assert c.effective_pending_limit(1000) == 123


def test_admission_config_junk_falls_back(monkeypatch):
    monkeypatch.setenv("GUBER_REQUEST_TIMEOUT", "soon")
    monkeypatch.setenv("GUBER_TARGET_P99_MS", "fast")
    monkeypatch.setenv("GUBER_PENDING_LIMIT", "many")
    monkeypatch.setenv("GUBER_SHED_POLICY", "fail-sideways")
    c = AdmissionConfig.from_env()
    assert c.request_timeout == 30.0
    assert c.target_p99_ms == 0.0
    assert c.pending_limit == 0
    assert c.shed_policy == POLICY_FAIL_OPEN
    assert c.effective_pending_limit(1000) == 8000  # auto: 8x window


# ----------------------------------------------------------------------
# TickLoop admission behavior (stub engines, ManualClock deadlines)
# ----------------------------------------------------------------------

class _StubBatch:
    def __init__(self, reqs):
        self._reqs = reqs

    def handles(self):
        return []

    def responses(self):
        return [
            RateLimitResponse(
                status=Status.UNDER_LIMIT, limit=r.limit,
                remaining=r.limit - r.hits,
            )
            for r in self._reqs
        ]


class _StubEngine:
    """Counts submissions; optionally blocks inside submit so tests can
    deterministically fill the admission queue behind a busy device."""

    def __init__(self, gate: threading.Event = None):
        self.batches = []
        self.gate = gate
        self.entered = threading.Event()

    def submit(self, reqs):
        self.entered.set()
        if self.gate is not None:
            self.gate.wait(timeout=10)
        self.batches.append(list(reqs))
        return _StubBatch(reqs)


def _reqs(n, limit=100):
    return [
        RateLimitRequest(name="t", unique_key=str(i), hits=1, limit=limit,
                         duration=60_000, created_at=1_000)
        for i in range(n)
    ]


def test_tickloop_sheds_expired_before_pack():
    clk = ManualClock(start=100.0)
    eng = _StubEngine()
    loop = TickLoop(eng, admission=AdmissionConfig(), clock=clk)
    try:
        fut = loop.submit(_reqs(3), deadline=99.0)  # already past
        out = fut.result(timeout=5)
        assert len(out) == 3
        assert all(r.error == SHED_EXPIRED_MSG for r in out)
        assert eng.batches == []  # never reached the device
        assert loop.metric_shed_admission["expired"] == 3
        assert loop.metric_expired_served == 0  # the gated invariant
    finally:
        loop.close()


def test_tickloop_mixed_window_serves_live_sheds_dead():
    clk = ManualClock(start=100.0)
    eng = _StubEngine()
    loop = TickLoop(eng, admission=AdmissionConfig(), clock=clk)
    try:
        dead = loop.submit(_reqs(2), deadline=50.0)
        live = loop.submit(_reqs(1), deadline=200.0)
        assert [r.error for r in dead.result(timeout=5)] == (
            [SHED_EXPIRED_MSG] * 2)
        out = live.result(timeout=5)
        assert out[0].error == "" and out[0].status == Status.UNDER_LIMIT
        assert sum(len(b) for b in eng.batches) == 1
        assert loop.metric_expired_served == 0
    finally:
        loop.close()


def test_tickloop_deadline_none_is_never_shed():
    clk = ManualClock(start=1e9)  # absurdly late clock
    eng = _StubEngine()
    loop = TickLoop(eng, admission=AdmissionConfig(), clock=clk)
    try:
        out = loop.submit(_reqs(2)).result(timeout=5)
        assert all(r.error == "" for r in out)
        assert loop.metric_shed_admission == {}
    finally:
        loop.close()


def _overflow_shed(policy):
    """Wedge the engine on a gate, overfill the bounded queue, and
    return the overflow victim's answered responses."""
    gate = threading.Event()
    eng = _StubEngine(gate=gate)
    adm = AdmissionConfig(pending_limit=2, shed_policy=policy)
    loop = TickLoop(eng, admission=adm)
    try:
        first = loop.submit(_reqs(1))  # dispatch thread blocks in submit
        assert eng.entered.wait(timeout=5)
        victim = loop.submit(_reqs(2), deadline=time.monotonic() + 5.0)
        # Overflow: the queued victim (soonest deadline) is answered
        # synchronously in the caller's thread — no timing involved.
        survivor = loop.submit(_reqs(2), deadline=time.monotonic() + 50.0)
        out = victim.result(timeout=1)
        gate.set()
        assert survivor.result(timeout=5)
        assert first.result(timeout=5)
        assert loop.metric_shed_admission["overflow"] == 2
        return out
    finally:
        gate.set()
        loop.close()


def test_tickloop_overflow_fail_open_answers_under_limit():
    out = _overflow_shed(POLICY_FAIL_OPEN)
    assert all(r.status == Status.UNDER_LIMIT for r in out)
    assert all(r.remaining == r.limit == 100 for r in out)
    assert all(r.error == "" for r in out)


def test_tickloop_overflow_fail_closed_answers_over_limit():
    out = _overflow_shed(POLICY_FAIL_CLOSED)
    assert all(r.status == Status.OVER_LIMIT for r in out)
    assert all(r.remaining == 0 for r in out)
    assert all(r.limit == 100 for r in out)


def test_tickloop_policy_matrix_shapes():
    class _Cols:
        limit = np.array([10, 20], np.int64)
        created_at = np.array([100, 100], np.int64)
        duration = np.array([5, 5], np.int64)

    loop = TickLoop(_StubEngine(), admission=AdmissionConfig(
        shed_policy=POLICY_FAIL_CLOSED))
    try:
        mat = loop._policy_matrix(_Cols(), 2)
        assert mat.shape == (5, 2)
        assert (mat[0] == int(Status.OVER_LIMIT)).all()
        assert (mat[2] == 0).all() and (mat[4] == 1).all()
        assert (mat[1] == [10, 20]).all() and (mat[3] == 105).all()
        loop.shed_policy = POLICY_FAIL_OPEN
        mat = loop._policy_matrix(_Cols(), 2)
        assert (mat[0] == 0).all() and (mat[2] == [10, 20]).all()
        assert (mat[4] == 0).all()
    finally:
        loop.close()


def test_tickloop_wedged_close_answers_queued_with_retriable_shed():
    """Satellite: close() on a wedged dispatch thread must answer every
    queued future with a retriable shed status, not abandon them behind
    the old fixed join timeout."""
    gate = threading.Event()
    eng = _StubEngine(gate=gate)
    loop = TickLoop(eng, admission=AdmissionConfig(pending_limit=100))
    stuck = None
    try:
        first = loop.submit(_reqs(1))
        assert eng.entered.wait(timeout=5)
        stuck = loop.submit(_reqs(3))  # queued behind the wedged window
        # Make close() take the wedged branch immediately instead of
        # burning the real 5s join timeout.
        real_join = loop._thread.join
        loop._thread.join = lambda timeout=None: None
        loop.close()
        out = stuck.result(timeout=1)
        assert [r.error for r in out] == [SHED_SHUTDOWN_MSG] * 3
        assert loop.metric_shed_admission["shutdown"] == 3
    finally:
        # Unwedge so the real threads exit; first window still resolves.
        gate.set()
        if stuck is not None:
            loop._thread.join = real_join
        loop._thread.join(timeout=5)
        assert first.result(timeout=5)


def test_tickloop_limiter_narrows_admitted_window():
    clk = ManualClock(start=0.0)
    eng = _StubEngine()
    adm = AdmissionConfig(target_p99_ms=5.0)
    loop = TickLoop(eng, batch_limit=100, admission=adm, clock=clk)
    try:
        assert loop.limiter.enabled
        # Saturation evidence recorded out-of-band (as _metrics_sync
        # would): the next window must be admitted narrower.
        for _ in range(loop.limiter.adjust_every):
            loop.limiter.record(50.0)
        assert loop.limiter.window_limit == 80
        out = loop.submit(_reqs(5)).result(timeout=5)
        assert len(out) == 5
    finally:
        loop.close()


# ----------------------------------------------------------------------
# PeerClient budget propagation
# ----------------------------------------------------------------------

def _peer_client(clk):
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.service.peer_client import PeerClient

    return PeerClient(
        PeerInfo(grpc_address="127.0.0.1:1"),
        behaviors=BehaviorConfig(batch_timeout=0.5),
        clock=clk,
    )


def test_rpc_budget_no_deadline_uses_batch_timeout():
    pc = _peer_client(ManualClock(start=10.0))
    timeout, hdr = pc.rpc_budget(_reqs(2))
    assert timeout == 0.5 and hdr is None


def test_rpc_budget_forwards_remaining_not_original():
    clk = ManualClock(start=10.0)
    pc = _peer_client(clk)
    rs = _reqs(2)
    rs[0].deadline = 10.3  # 300ms left
    timeout, hdr = pc.rpc_budget(rs)
    assert timeout == pytest.approx(0.3)
    assert hdr == "300"
    clk.advance(0.2)  # budget drains as time passes
    timeout, hdr = pc.rpc_budget(rs)
    assert timeout == pytest.approx(0.1)
    assert hdr == "100"


def test_rpc_budget_floor_and_cap():
    clk = ManualClock(start=0.0)
    pc = _peer_client(clk)
    rs = _reqs(1)
    rs[0].deadline = 0.001  # 1ms left: floored, one real wire attempt
    timeout, hdr = pc.rpc_budget(rs)
    assert timeout == pc.timeout_floor == pytest.approx(0.05)
    assert hdr == "1"  # the header still tells the peer the truth
    rs[0].deadline = 60.0  # huge budget: capped at batch_timeout
    timeout, hdr = pc.rpc_budget(rs)
    assert timeout == 0.5
    assert hdr == "60000"


def test_rpc_budget_spent_raises_before_the_wire():
    clk = ManualClock(start=100.0)
    pc = _peer_client(clk)
    rs = _reqs(1)
    rs[0].deadline = 99.0
    with pytest.raises(BudgetExhaustedError):
        pc.rpc_budget(rs)


# ----------------------------------------------------------------------
# Edge deadline derivation + arena fallback budget
# ----------------------------------------------------------------------

def test_edge_deadline_precedence():
    from gubernator_tpu.transport.daemon import _edge_deadline

    class _Ctx:
        def __init__(self, md=(), rem=None):
            self._md = md
            self._rem = rem

        def invocation_metadata(self):
            return self._md

        def time_remaining(self):
            return self._rem

    t0 = time.monotonic()
    # Header wins over the gRPC context deadline.
    d = _edge_deadline(
        _Ctx(md=(("guber-deadline-ms", "250"),), rem=9.0), 30.0)
    assert d is not None and 0.2 <= d - t0 <= 0.3
    # No header: the context deadline.
    d = _edge_deadline(_Ctx(rem=2.0), 30.0)
    assert d is not None and 1.9 <= d - time.monotonic() + 0.1 <= 2.1
    # Neither: the configured default budget.
    d = _edge_deadline(_Ctx(), 30.0)
    assert d is not None and d - time.monotonic() > 29.0
    # Malformed header falls through to the next source, never errors.
    d = _edge_deadline(_Ctx(md=(("guber-deadline-ms", "junk"),)), 0.0)
    assert d is None  # default 0 = no deadline


def test_arena_fallback_budget_is_per_window():
    from gubernator_tpu.ops.reqcols import ColumnArena

    arena = ColumnArena(max_batch=8, slabs=1, fallback_limit=2)
    lease = arena.lease(4, 64)
    assert lease is not None
    # Slab busy: fits-but-unleasable → budgeted fallbacks, then shed.
    assert arena.fits(4, 64)
    assert arena.lease(4, 64) is None
    assert arena.try_fallback()
    assert arena.try_fallback()
    assert not arena.try_fallback()  # budget spent
    assert arena.metric_fallbacks == 2
    lease.release()  # window completed: budget resets
    lease2 = arena.lease(4, 64)
    assert arena.try_fallback()
    lease2.release()
