"""Unit tests for the fault-tolerant peer path (docs/resilience.md):
circuit breaker transitions, decorrelated-jitter backoff, the fault
injector, the crash supervisor, PeerClient shutdown drain, GLOBAL queue
gauges / bounded redelivery, the breaker-quorum health rule, and the
forward path's ownership re-resolution.

Everything here runs on virtual time (ManualClock) or sub-second asyncio
windows — no real sleeps longer than the supervisor's 10 ms restart delay.
"""

import asyncio
import time

import grpc
import pytest

from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.resilience import (
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
    DecorrelatedJitterBackoff,
    FaultInjector,
    ManualClock,
    ResilienceConfig,
    spawn_supervised,
)
from gubernator_tpu.resilience.faults import rpc_error
from gubernator_tpu.service.global_manager import GlobalManager
from gubernator_tpu.service.instance import InstanceConfig, V1Instance
from gubernator_tpu.service.peer_client import PeerClient
from gubernator_tpu.types import (
    Behavior,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
)
from gubernator_tpu.utils.metrics import Metrics


def req(name="res", key="k", hits=1, limit=10, duration=60_000, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, **kw
    )


# ---------------------------------------------------------------------------
# Backoff
# ---------------------------------------------------------------------------
def test_backoff_bounds_and_determinism():
    import random

    b1 = DecorrelatedJitterBackoff(0.01, 0.5, rng=random.Random(42))
    b2 = DecorrelatedJitterBackoff(0.01, 0.5, rng=random.Random(42))
    seq1 = [b1.next() for _ in range(20)]
    seq2 = [b2.next() for _ in range(20)]
    assert seq1 == seq2  # seeded → replayable
    assert all(0.01 <= d <= 0.5 for d in seq1)
    # The walk grows well past the base (expected growth ~2x per step,
    # though jitter can shrink it on any single draw).
    assert max(seq1) > 0.05
    b1.reset()
    assert b1.next() <= 0.03  # back near base: uniform(base, base*3)


def test_backoff_rejects_bad_params():
    with pytest.raises(ValueError):
        DecorrelatedJitterBackoff(0, 1.0)
    with pytest.raises(ValueError):
        DecorrelatedJitterBackoff(1.0, 0.5)


# ---------------------------------------------------------------------------
# Circuit breaker (virtual clock; no sleeps)
# ---------------------------------------------------------------------------
def make_breaker(clk, **kw):
    kw.setdefault("min_requests", 4)
    kw.setdefault("failure_threshold", 0.5)
    kw.setdefault("window", 10.0)
    kw.setdefault("open_for", 1.0)
    kw.setdefault("open_cap", 4.0)
    return CircuitBreaker(clock=clk, **kw)


def test_breaker_opens_on_failure_rate():
    clk = ManualClock()
    transitions = []
    b = make_breaker(clk, on_transition=lambda o, n: transitions.append((o, n)))
    # Below the volume floor: 3 failures don't trip.
    for _ in range(3):
        b.record_failure()
    assert b.state is BreakerState.CLOSED
    b.record_failure()  # 4th: rate 100% >= 50% and volume floor met
    assert b.state is BreakerState.OPEN
    assert not b.allow()
    assert b.is_open()
    assert transitions == [(BreakerState.CLOSED, BreakerState.OPEN)]


def test_breaker_mixed_window_respects_threshold():
    clk = ManualClock()
    b = make_breaker(clk, min_requests=4, failure_threshold=0.5)
    # 3 successes, 2 failures → rate 0.4 < 0.5: stays closed.
    for _ in range(3):
        b.record_success()
    for _ in range(2):
        b.record_failure()
    assert b.state is BreakerState.CLOSED
    b.record_failure()  # 3/6 = 0.5: trips
    assert b.state is BreakerState.OPEN


def test_breaker_half_open_probe_success_closes():
    clk = ManualClock()
    b = make_breaker(clk)
    for _ in range(4):
        b.record_failure()
    assert not b.allow()
    clk.advance(5.0)  # past any open duration (cap 4.0)
    assert b.state is BreakerState.HALF_OPEN
    assert b.allow()       # the single probe slot
    assert not b.allow()   # concurrent requests still fail fast
    b.record_success()
    assert b.state is BreakerState.CLOSED
    assert b.allow()


def test_breaker_probe_failure_reopens_with_backoff():
    import random

    clk = ManualClock()
    b = make_breaker(clk, rng=random.Random(7))
    for _ in range(4):
        b.record_failure()
    first_open = b._open_until - clk.now()
    clk.advance(5.0)
    assert b.allow()       # probe
    b.record_failure()     # probe fails
    assert b.state is BreakerState.OPEN
    second_open = b._open_until - clk.now()
    # Decorrelated jitter: the draw range starts at base both times, but
    # the open duration stays within [base, cap] and the breaker is OPEN
    # again without needing another volume window.
    assert 1.0 <= second_open <= 4.0
    assert 1.0 <= first_open <= 4.0


def test_breaker_window_ages_out_failures():
    clk = ManualClock()
    b = make_breaker(clk, window=10.0)
    for _ in range(3):
        b.record_failure()
    clk.advance(11.0)  # the old failures fall out of the window
    b.record_failure()
    # Only 1 sample in-window: under the volume floor, stays closed.
    assert b.state is BreakerState.CLOSED


def test_breaker_disabled_never_trips():
    clk = ManualClock()
    b = make_breaker(clk, enabled=False)
    for _ in range(50):
        b.record_failure()
    assert b.allow()
    assert not b.is_open()


def test_breaker_force_open():
    clk = ManualClock()
    b = make_breaker(clk)
    b.force_open(60.0)
    assert b.is_open()
    clk.advance(30.0)
    assert b.is_open()
    clk.advance(31.0)
    assert b.state is BreakerState.HALF_OPEN


# ---------------------------------------------------------------------------
# Fault injector
# ---------------------------------------------------------------------------
async def test_fault_injector_partition_error_drop():
    inj = FaultInjector(seed=3)
    inj.set_fault("p1", partition=True)
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await inj.before_rpc("p1", "GetPeerRateLimits")
    assert e.value.code() == grpc.StatusCode.UNAVAILABLE
    # Other peers unaffected.
    await inj.before_rpc("p2", "GetPeerRateLimits")

    inj.set_fault("p2", drop_rate=1.0)
    with pytest.raises(grpc.aio.AioRpcError) as e:
        await inj.before_rpc("p2", "UpdatePeerGlobals")
    assert e.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert inj.injected[("p1", "error")] == 1
    assert inj.injected[("p2", "drop")] == 1

    inj.clear("p1")
    await inj.before_rpc("p1", "GetPeerRateLimits")  # schedule removed


async def test_fault_injector_seeded_error_rate_replays():
    async def draw(seed):
        inj = FaultInjector(seed=seed)
        inj.set_fault("*", error_rate=0.5)
        outcomes = []
        for _ in range(32):
            try:
                await inj.before_rpc("px", "GetPeerRateLimits")
                outcomes.append(0)
            except grpc.aio.AioRpcError:
                outcomes.append(1)
        return outcomes

    a = await draw(11)
    b = await draw(11)
    c = await draw(12)
    assert a == b          # same seed → same schedule
    assert 0 < sum(a) < 32  # actually probabilistic
    assert a != c


async def test_fault_injector_delay_uses_virtual_clock():
    clk = ManualClock()
    inj = FaultInjector(seed=0, clock=clk, sleep=clk.sleep)
    inj.set_fault("p1", delay=0.25)
    await inj.before_rpc("p1", "GetPeerRateLimits")
    assert clk.sleeps == [0.25]  # no real wall-clock sleep happened
    assert clk.now() == 0.25


async def test_fault_injector_method_filter():
    inj = FaultInjector()
    inj.set_fault("p1", partition=True, methods=("UpdatePeerGlobals",))
    await inj.before_rpc("p1", "GetPeerRateLimits")  # not matched
    with pytest.raises(grpc.aio.AioRpcError):
        await inj.before_rpc("p1", "UpdatePeerGlobals")


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------
async def test_supervisor_restarts_crashed_loop():
    metrics = Metrics()
    ran = []
    done = asyncio.Event()

    async def loop_body():
        ran.append(1)
        if len(ran) < 3:
            raise RuntimeError("boom")
        done.set()

    t = spawn_supervised(
        loop_body, name="t", metrics=metrics, loop_label="test_loop",
        restart_delay=0.001,
    )
    await asyncio.wait_for(done.wait(), 2)
    await t
    assert len(ran) == 3
    assert metrics.sample(
        "gubernator_loop_restarts_total", {"loop": "test_loop"}
    ) == 2


async def test_supervisor_stops_when_owner_closed():
    stop = []

    async def loop_body():
        raise RuntimeError("boom")

    t = spawn_supervised(
        loop_body, name="t", should_restart=lambda: not stop,
        restart_delay=0.001,
    )
    stop.append(1)
    await asyncio.wait_for(t, 2)  # returns instead of restarting forever


# ---------------------------------------------------------------------------
# PeerClient shutdown drain (satellite: no hung futures)
# ---------------------------------------------------------------------------
async def test_peer_client_drains_requests_enqueued_after_sentinel():
    client = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
    q = client._ensure_batch_loop()
    fut = asyncio.get_running_loop().create_future()
    # Interleaving under test: the shutdown sentinel lands first, a
    # straggler request right after — before the batch loop task runs.
    q.put_nowait(None)
    q.put_nowait((req(), fut))
    with pytest.raises(RuntimeError, match="shut down"):
        await asyncio.wait_for(fut, 2)
    await client.shutdown()


async def test_peer_client_rejects_after_closed():
    client = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
    await client.shutdown()
    with pytest.raises(RuntimeError, match="shut down"):
        await client.get_peer_rate_limit(req())


async def test_peer_client_breaker_open_fails_fast_without_dial():
    client = PeerClient(PeerInfo(grpc_address="127.0.0.1:1"))
    client.breaker.force_open(60.0)
    with pytest.raises(BreakerOpenError):
        await client.get_peer_rate_limit(req())
    with pytest.raises(BreakerOpenError):
        await client.get_peer_rate_limits([req()])
    with pytest.raises(BreakerOpenError):
        await client.update_peer_globals([])
    assert client._channel is None  # fail fast means no dial at all
    assert any("circuit breaker open" in m for m in client.get_last_err())
    await client.shutdown()


# ---------------------------------------------------------------------------
# GlobalManager: gauges + bounded redelivery (satellites)
# ---------------------------------------------------------------------------
class FailingPeer:
    """Peer stub whose RPCs always fail UNAVAILABLE."""

    def __init__(self, addr="10.0.0.9:81"):
        self.info = PeerInfo(grpc_address=addr)
        self.calls = 0
        self.breaker = CircuitBreaker(name=addr)

    async def get_peer_rate_limits(self, reqs):
        self.calls += 1
        raise rpc_error(grpc.StatusCode.UNAVAILABLE, "down")

    async def update_peer_globals(self, updates):
        self.calls += 1
        raise rpc_error(grpc.StatusCode.UNAVAILABLE, "down")


class FakeInstance:
    """Just enough V1Instance surface for a GlobalManager."""

    def __init__(self, peer):
        self.peer = peer

    def get_peer(self, key):
        return self.peer

    def get_peer_list(self):
        return [self.peer]

    async def get_peer_rate_limits(self, reqs):
        return [RateLimitResponse() for _ in reqs]

    async def apply_local(self, reqs):
        return [RateLimitResponse(limit=r.limit, remaining=r.limit)
                for r in reqs]


async def test_global_send_queue_gauge_tracks_requeued_hits():
    metrics = Metrics()
    peer = FailingPeer()
    mgr = GlobalManager(
        FakeInstance(peer),
        BehaviorConfig(global_sync_wait=0.01),
        metrics,
        resilience=ResilienceConfig(redelivery_limit=100),
    )
    try:
        for i in range(3):
            mgr.queue_hit(req(key=f"g-{i}", behavior=Behavior.GLOBAL))
        # Wait until at least one flush failed and requeued.
        for _ in range(200):
            if metrics.sample("gubernator_global_redelivered_hits_total") > 0:
                break
            await asyncio.sleep(0.01)
        assert peer.calls >= 1
        # The gauge must reflect the requeued keys, not a hardcoded 0.
        assert metrics.sample("gubernator_global_send_queue_length") == \
            len(mgr._hits) > 0
        assert metrics.sample("gubernator_global_dropped_hits_total") == 0
    finally:
        await mgr.close()


async def test_redelivery_buffer_bounded_and_drops_counted():
    metrics = Metrics()
    peer = FailingPeer()
    mgr = GlobalManager(
        FakeInstance(peer),
        BehaviorConfig(global_sync_wait=0.01),
        metrics,
        resilience=ResilienceConfig(redelivery_limit=4),
    )
    try:
        for i in range(10):
            mgr.queue_hit(req(key=f"b-{i}", behavior=Behavior.GLOBAL))
        for _ in range(200):
            if metrics.sample("gubernator_global_dropped_hits_total") > 0:
                break
            await asyncio.sleep(0.01)
        # 10 distinct keys flushed into a failing peer with cap 4: the
        # buffer holds at most 4, the rest are dropped AND counted.
        assert len(mgr._hits) <= 4
        assert metrics.sample("gubernator_global_dropped_hits_total") >= 6
        assert metrics.sample("gubernator_global_send_queue_length") == \
            len(mgr._hits)
    finally:
        await mgr.close()


async def test_queued_hit_sheds_caller_deadline():
    """The queued flush copy must NOT inherit the caller's admission
    budget: the client was already answered locally, so nobody is
    waiting on the flush.  A copy that kept the deadline would make
    every redelivery raise BudgetExhausted once an owner outage outlives
    the budget — the buffered hits could then never land (the breaker
    never even gets a probe), silently breaking zero-loss heal."""
    mgr = GlobalManager(
        FakeInstance(FailingPeer()),
        BehaviorConfig(global_sync_wait=60.0),  # no flush during the test
        Metrics(),
        resilience=ResilienceConfig(redelivery_limit=100),
    )
    try:
        r = req(key="dl", behavior=Behavior.GLOBAL)
        r.deadline = time.monotonic() - 1.0  # budget already spent
        mgr.queue_hit(r)
        (queued,) = mgr._hits.values()
        assert queued.deadline is None
        assert queued.hits == 1
        # Aggregation onto the shed copy must not resurrect a deadline.
        mgr.queue_hit(req(key="dl", behavior=Behavior.GLOBAL))
        (queued,) = mgr._hits.values()
        assert queued.deadline is None and queued.hits == 2
    finally:
        await mgr.close()


async def test_broadcast_requeues_failed_updates():
    metrics = Metrics()
    peer = FailingPeer()
    mgr = GlobalManager(
        FakeInstance(peer),
        BehaviorConfig(global_sync_wait=0.01),
        metrics,
        resilience=ResilienceConfig(redelivery_limit=100),
    )
    try:
        mgr.queue_update(req(key="u-1", behavior=Behavior.GLOBAL))
        for _ in range(200):
            if metrics.sample(
                "gubernator_global_redelivered_broadcasts_total"
            ) > 0:
                break
            await asyncio.sleep(0.01)
        assert metrics.sample(
            "gubernator_global_redelivered_broadcasts_total") >= 1
        assert "u-1" in {r.unique_key for r in mgr._updates.values()}
        assert metrics.sample("gubernator_global_queue_length") == \
            len(mgr._updates)
    finally:
        await mgr.close()


async def test_hits_loop_crash_restarts_and_keeps_flushing():
    """A crashed hits loop must restart (counted) and keep reconciling."""
    inst = await V1Instance.create(
        InstanceConfig(
            behaviors=BehaviorConfig(global_sync_wait=0.01, batch_wait=0.001),
            cache_size=256,
        )
    )
    try:
        orig = inst.global_mgr._send_hits
        state = {"n": 0}

        async def flaky(hits):
            state["n"] += 1
            if state["n"] == 1:
                raise RuntimeError("chaos: flush crashed")
            await orig(hits)

        inst.global_mgr._send_hits = flaky
        inst.global_mgr.queue_hit(
            req(name="crash", key="c1", hits=1, behavior=Behavior.GLOBAL)
        )
        for _ in range(300):
            if inst.metrics.sample(
                "gubernator_loop_restarts_total", {"loop": "global_hits"}
            ) >= 1:
                break
            await asyncio.sleep(0.01)
        assert inst.metrics.sample(
            "gubernator_loop_restarts_total", {"loop": "global_hits"}
        ) >= 1
        # The restarted loop still reconciles: a new hit lands locally
        # (standalone instance → apply_self path).
        inst.global_mgr.queue_hit(
            req(name="crash", key="c2", hits=3, limit=10,
                behavior=Behavior.GLOBAL)
        )

        async def settled():
            while True:
                out = await inst.apply_local(
                    [req(name="crash", key="c2", hits=0, limit=10)]
                )
                if out[0].remaining == 7:
                    return
                await asyncio.sleep(0.01)

        await asyncio.wait_for(settled(), timeout=5)
        for t in inst.global_mgr._tasks:
            assert not t.done()
    finally:
        await inst.close()


# ---------------------------------------------------------------------------
# Health: breaker quorum rule (satellite)
# ---------------------------------------------------------------------------
async def test_health_unhealthy_when_majority_breakers_open():
    inst = await V1Instance.create(InstanceConfig(cache_size=256))
    try:
        inst.set_peers([
            PeerInfo(grpc_address=f"10.0.0.{i}:81") for i in range(1, 4)
        ])
        assert inst.health_check().status == "healthy"
        peers = inst.get_peer_list()
        peers[0].breaker.force_open(60.0)
        # 1/3 open: still healthy (not a majority).
        assert inst.health_check().status == "healthy"
        peers[1].breaker.force_open(60.0)
        h = inst.health_check()
        assert h.status == "unhealthy"
        assert "open circuit breakers" in h.message
    finally:
        await inst.close()


async def test_healthz_returns_503_on_open_breaker_majority():
    import aiohttp

    from gubernator_tpu.cluster import Cluster

    c = await Cluster.start(1, http_gateway=True)
    try:
        addr = c.daemons[0].conf.http_listen_address
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://{addr}/healthz") as resp:
                assert resp.status == 200
                assert (await resp.json())["status"] == "healthy"
            # The single daemon's only local peer is itself: one open
            # breaker is a majority.
            c.daemons[0].instance.get_peer_list()[0].breaker.force_open(60.0)
            async with s.get(f"http://{addr}/healthz") as resp:
                assert resp.status == 503
                body = await resp.json()
                assert body["status"] == "unhealthy"
                assert "open circuit breakers" in body["message"]
    finally:
        await c.stop()


# ---------------------------------------------------------------------------
# Forward path: ownership re-resolution (satellite)
# ---------------------------------------------------------------------------
class ScriptedPeer:
    """Peer whose get_peer_rate_limit follows a scripted outcome list."""

    def __init__(self, addr, outcomes):
        self.info = PeerInfo(grpc_address=addr)
        self.outcomes = list(outcomes)
        self.received = []
        self.breaker = CircuitBreaker(name=addr)

    async def get_peer_rate_limit(self, r):
        out = self.outcomes.pop(0)
        if isinstance(out, Exception):
            raise out
        self.received.append(r)
        return out


async def test_forward_reresolution_lands_hit_exactly_once():
    """DEADLINE_EXCEEDED from the old owner + a fresh get_peer returning
    the new owner must land the hit exactly once on the new owner."""
    inst = await V1Instance.create(
        InstanceConfig(
            behaviors=BehaviorConfig(batch_wait=0.001), cache_size=256,
        )
    )
    try:
        old = ScriptedPeer("10.0.0.1:81", [
            rpc_error(grpc.StatusCode.DEADLINE_EXCEEDED, "old owner hung"),
        ])
        new = ScriptedPeer("10.0.0.2:81", [
            RateLimitResponse(limit=10, remaining=9),
        ])
        inst.get_peer = lambda key: new  # ownership moved by re-resolution
        r = req(name="move", key="mk")
        resp = await inst._async_request(old, r, r.hash_key())
        assert resp.error == ""
        assert resp.metadata.get("owner") == "10.0.0.2:81"
        # Exactly one landing: the old owner never recorded the hit, the
        # new owner saw it exactly once, and exactly one retry happened.
        assert old.received == []
        assert not old.outcomes and not new.outcomes
        assert len(new.received) == 1
        assert new.received[0].hits == 1
        assert inst.metrics.sample(
            "gubernator_batch_send_retries_total") == 1
    finally:
        await inst.close()


async def test_forward_retries_use_backoff_and_give_up():
    """Exhausted retries surface the reference's 'not connected' error;
    every retry waited a decorrelated-jitter delay (patched here to count
    instead of sleep)."""
    inst = await V1Instance.create(
        InstanceConfig(
            behaviors=BehaviorConfig(batch_wait=0.001),
            cache_size=256,
            resilience=ResilienceConfig(
                forward_max_attempts=3,
                forward_backoff_base=0.001,
                forward_backoff_cap=0.004,
            ),
        )
    )
    try:
        boom = rpc_error(grpc.StatusCode.UNAVAILABLE, "down")
        dead = ScriptedPeer("10.0.0.1:81", [boom] * 10)
        inst.get_peer = lambda key: dead
        r = req(name="dead", key="dk")
        resp = await inst._async_request(dead, r, r.hash_key())
        assert "not connected" in resp.error
        assert inst.metrics.sample(
            "gubernator_batch_send_retries_total") == 4  # attempts 1..4
    finally:
        await inst.close()


async def test_forward_global_degrades_to_local_on_open_breaker():
    """An open breaker on the owner must not error a GLOBAL caller: the
    local non-owner answer serves (counted as degraded), and the hit is
    queued for redelivery."""
    inst = await V1Instance.create(
        InstanceConfig(
            behaviors=BehaviorConfig(batch_wait=0.001, global_sync_wait=5.0),
            cache_size=256,
        )
    )
    try:
        owner = ScriptedPeer("10.0.0.1:81", [])
        owner.breaker.force_open(60.0)

        async def open_breaker_rpc(r):
            raise BreakerOpenError("circuit breaker open")

        owner.get_peer_rate_limit = open_breaker_rpc
        r = req(name="deg", key="gk", hits=2, limit=10,
                behavior=Behavior.GLOBAL)
        resp = await inst._async_request(owner, r, r.hash_key())
        assert resp.error == ""
        assert resp.remaining == 8  # answered from local state
        assert resp.metadata.get("degraded") == "true"
        assert inst.metrics.sample("gubernator_degraded_answers_total") == 1
        # The hit sits in the redelivery queue for when the owner recovers.
        assert r.hash_key() in inst.global_mgr._hits
    finally:
        await inst.close()


async def test_owned_tracker_overflow_counted_not_silent(caplog):
    """Owner-side GLOBAL key tracking past GUBER_REDELIVERY_LIMIT must
    never be silent: the excess keys (which will NOT ride a ring-swap
    handoff) are counted under ownership_transfers{result="untracked"}
    and logged — at reshard scale a quietly lossy tracker re-creates the
    ownership-migration bug the handoff machinery exists to prevent."""
    import logging

    metrics = Metrics()
    peer = FailingPeer()
    mgr = GlobalManager(
        FakeInstance(peer),
        BehaviorConfig(global_sync_wait=60.0),
        metrics,
        resilience=ResilienceConfig(redelivery_limit=3),
    )
    try:
        with caplog.at_level(logging.WARNING, logger="gubernator.global"):
            for i in range(5):
                mgr.queue_update(
                    req(key=f"ov-{i}", behavior=Behavior.GLOBAL))
        assert len(mgr._owned) == 3                  # bounded
        assert len(mgr._updates) == 5                # broadcast unaffected
        assert metrics.sample(
            "gubernator_tpu_ownership_transfers_total",
            {"result": "untracked"}) == 2
        assert any("ownership tracker full" in r.message
                   for r in caplog.records)
        # A key already tracked keeps updating in place at the cap.
        mgr.queue_update(req(key="ov-0", hits=2, behavior=Behavior.GLOBAL))
        assert len(mgr._owned) == 3
        assert metrics.sample(
            "gubernator_tpu_ownership_transfers_total",
            {"result": "untracked"}) == 2
    finally:
        await mgr.close()
