"""Parity: the parts-native chained-unit-round program vs the x64 oracle.

``tick32.make_sorted_tick32_rows_fn`` is the program the engine runs for
mixed/ineligible duplicate batches (TickEngine ``self._tick``); the x64
``engine.make_tick_fn`` sorted tick is the oracle.  Responses AND final
table state must agree bit-for-bit on adversarial batches: duplicate
groups broken by RESET/DRAIN/parameter changes, query rows (hits=0),
dead heads (negative durations), backdated created_at, fresh vs known
rows, and both algorithms interleaved.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gubernator_tpu.ops.buckets import BucketState
from gubernator_tpu.ops.engine import (
    REQ32_INDEX as R32,
    REQ32_ROWS,
    _jitted_tick,
    pack_wide_rows,
)
from gubernator_tpu.ops.tick32 import jitted_sorted_tick32
from gubernator_tpu.types import Behavior

CAP = 1 << 10
B = 256
NOW = 1_700_000_000_000

ORACLE = _jitted_tick(CAP, "columns", sorted_input=True, compact_resp=True,
                      compact_req=True)
SORTED32 = jitted_sorted_tick32(CAP, "columns")


def _random_batch(rng):
    n = int(rng.integers(50, B))
    hot_n = int(rng.integers(5, min(60, n - 1)))
    slots = np.sort(np.concatenate([
        np.zeros(hot_n, np.int64),           # deep hot group at slot 0
        rng.integers(1, CAP, n - hot_n),     # cold keys (some collide)
    ]))
    m = np.zeros((REQ32_ROWS, B), np.int32)
    m[R32["slot"], :n] = slots
    m[R32["slot"], n:] = CAP
    m[R32["known"], :n] = rng.integers(0, 2, n)
    m[R32["valid"], :n] = 1
    hits = rng.integers(0, 4, n)             # incl. queries
    limit = rng.integers(1, 20, n)
    dur = rng.choice([60_000, 60_000, 60_000, -5], n)   # incl. dead heads
    created = np.full(n, NOW)
    created[rng.random(n) < 0.1] = NOW - 10 ** 9        # backdated
    behavior = rng.choice(
        [0, 0, 0, int(Behavior.RESET_REMAINING),
         int(Behavior.DRAIN_OVER_LIMIT)], n)
    algo = rng.integers(0, 2, n)
    # Duplicates often share params so real units form; the rest break
    # groups into singleton units.
    for i in range(1, n):
        if slots[i] == slots[i - 1] and rng.random() < 0.6:
            hits[i], limit[i] = hits[i - 1], limit[i - 1]
            behavior[i], algo[i] = behavior[i - 1], algo[i - 1]
            dur[i], created[i] = dur[i - 1], created[i - 1]
    m[R32["algorithm"], :n] = algo
    m[R32["behavior"], :n] = behavior
    for name, v in (("hits", hits), ("limit", limit), ("duration", dur),
                    ("created_at", created)):
        full = np.zeros(B, np.int64)
        full[:n] = v
        pack_wide_rows(m, name, full, slice(None))
    return jnp.asarray(m), n


@pytest.mark.parametrize("seed", [5, 17, 99])
def test_sorted32_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    for _ in range(4):
        packed, n = _random_batch(rng)
        s1 = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
        s2 = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
        s1, r1 = ORACLE(s1, packed, jnp.int64(NOW))
        s2, r2 = SORTED32(s2, packed, jnp.int64(NOW))
        np.testing.assert_array_equal(
            np.asarray(r1)[:, :n], np.asarray(r2)[:, :n])
        for a, b, name in zip(
            jax.tree.leaves(s1), jax.tree.leaves(s2),
            [str(i) for i in range(len(jax.tree.leaves(s1)))],
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"state leaf {name}")


def test_sorted32_chains_across_ticks():
    """Sequential ticks through the program keep per-slot state exactly
    in step with the oracle (the chain touches the table, not just the
    responses)."""
    rng = np.random.default_rng(3)
    s1 = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    s2 = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    for t in range(3):
        packed, n = _random_batch(rng)
        s1, r1 = ORACLE(s1, packed, jnp.int64(NOW + t * 1000))
        s2, r2 = SORTED32(s2, packed, jnp.int64(NOW + t * 1000))
        np.testing.assert_array_equal(
            np.asarray(r1)[:, :n], np.asarray(r2)[:, :n])
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trunc_to_pair_negative_rate():
    """Negative leaky rates (negative durations) convert Go-style —
    trunc toward zero, not floor (algorithms.go int64(rate))."""
    from gubernator_tpu.ops import i64pair as p64
    from gubernator_tpu.ops import tfloat as tf

    for v in (-0.357, -5.0, -5.9, 0.9, 5.9, -(2.0 ** 40) - 0.5):
        t = tf.from_f32(jnp.full((4,), np.float32(v)))
        got = p64.to_np(tf.trunc_to_pair(t))[0]
        assert got == int(v), (v, got)
