"""Concurrency stress: exact accounting under parallel load.

The reference leans on Go's race detector plus mutex/channel discipline
(SURVEY §5.2); here safety is by construction (engine lock + event-loop
serialization + rank-ordered device application), so the tests assert the
*observable* invariant instead: with hits=1 requests against a bucket of
limit L, exactly L requests win UNDER_LIMIT no matter how many clients
race — any lost update, double count, or torn read shows up as a wrong
total.
"""

import asyncio
import threading

from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
from gubernator_tpu.types import RateLimitRequest, Status


def _req(key, name="stress", hits=1, limit=100):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit, duration=60_000
    )


def test_engine_threads_exact_accounting():
    """8 threads × 50 calls × 4 hits on one key: exactly limit wins."""
    eng = TickEngine(capacity=1 << 12, max_batch=512)
    limit = 137
    wins = []
    lock = threading.Lock()

    def worker():
        got = 0
        for _ in range(50):
            rs = eng.process([_req("hot", hits=1, limit=limit)] * 4)
            got += sum(1 for r in rs if r.status == Status.UNDER_LIMIT)
        with lock:
            wins.append(got)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(wins) == limit  # 1600 hits total, exactly `limit` admitted


def test_engine_threads_disjoint_keys_no_crosstalk():
    eng = TickEngine(capacity=1 << 12, max_batch=512)

    def worker(tid, out):
        under = 0
        for i in range(40):
            rs = eng.process([_req(f"k{tid}", limit=25)])
            under += rs[0].status == Status.UNDER_LIMIT
        out[tid] = under

    out = {}
    threads = [
        threading.Thread(target=worker, args=(t, out)) for t in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(v == 25 for v in out.values()), out


async def test_service_concurrent_clients_exact_accounting():
    """64 concurrent gRPC clients racing on one bucket through the full
    daemon stack (tick loop batching + duplicate-key serialization)."""
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(
        behaviors=BehaviorConfig(batch_wait=0.002), cache_size=4096
    )
    d = await spawn_daemon(conf)
    limit, n_clients, per_client = 200, 64, 8
    try:
        async def one_client():
            c = DaemonClient(d.advertise_address)
            under = 0
            for _ in range(per_client):
                rs = await c.get_rate_limits([_req("svc-hot", limit=limit)])
                assert rs[0].error == ""
                under += rs[0].status == Status.UNDER_LIMIT
            await c.close()
            return under

        wins = await asyncio.gather(*(one_client() for _ in range(n_clients)))
        assert sum(wins) == limit  # 512 racing hits, exactly 200 admitted
    finally:
        await d.close()


async def test_snapshot_during_traffic_is_consistent():
    """export_items racing live traffic must snapshot a consistent table:
    every racing snapshot restores to a bucket that admits exactly its
    remaining budget, and total admissions across snapshot + replay equal
    the limit."""
    eng = TickEngine(capacity=1 << 12, max_batch=512)
    limit = 300
    stop = threading.Event()
    snaps = []

    def snapshotter():
        while not stop.is_set():
            snaps.append(eng.export_items())

    t = threading.Thread(target=snapshotter)
    t.start()
    try:
        admitted = 0
        # 400 hits > limit: snapshots race both contended and exhausted
        # states of the bucket.
        for _ in range(40):
            rs = eng.process([_req("snap-key", limit=limit)] * 10)
            admitted += sum(1 for r in rs if r.status == Status.UNDER_LIMIT)
    finally:
        stop.set()
        t.join()
    assert admitted == limit
    assert snaps, "snapshotter never ran"

    def drain(snapshot):
        """Restore a snapshot and count how many more hits it admits."""
        e = TickEngine(capacity=1 << 12, max_batch=512)
        e.load_items(snapshot)
        more = 0
        for _ in range(2 * limit // 100):
            rs = e.process([_req("snap-key", limit=limit)] * 100)
            more += sum(1 for r in rs if r.status == Status.UNDER_LIMIT)
        return more

    # A torn export (remaining disagreeing with status, half-written item)
    # breaks the invariant: snapshot-admitted + replayed == limit.
    for snapshot in [s for s in snaps if s][:: max(1, len(snaps) // 3)]:
        item = next(i for i in snapshot if i["key"].endswith("snap-key"))
        snapshot_admitted = limit - item["remaining"]
        assert 0 <= item["remaining"] <= limit
        assert drain(snapshot) == limit - snapshot_admitted

    # The final snapshot restores to an exhausted bucket.
    final = eng.export_items()
    eng2 = TickEngine(capacity=1 << 12, max_batch=512)
    eng2.load_items(final)
    r = eng2.process([_req("snap-key", limit=limit)])[0]
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
