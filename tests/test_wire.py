"""Vectorized response encoder vs message-object serialization."""

import numpy as np
import pytest

from gubernator_tpu.pb import gubernator_pb2 as pb
from gubernator_tpu.transport.wire import encode_get_rate_limits_resp


def oracle(mat):
    return pb.GetRateLimitsResp(responses=[
        pb.RateLimitResp(
            status=int(mat[0, i]), limit=int(mat[1, i]),
            remaining=int(mat[2, i]), reset_time=int(mat[3, i]),
        )
        for i in range(mat.shape[1])
    ]).SerializeToString()


@pytest.mark.parametrize("seed", [0, 1])
def test_matches_message_objects(seed):
    rng = np.random.default_rng(seed)
    n = 257
    mat = np.zeros((5, n), np.int64)
    mat[0] = rng.integers(0, 2, n)                      # status enum
    mat[1] = rng.choice([0, 1, 127, 128, 10**6, 1 << 40, (1 << 62)], n)
    mat[2] = rng.choice([0, 5, -1, -(1 << 40), 10**6], n)  # negatives too
    mat[3] = rng.choice([0, 1_700_000_000_000, 1 << 62], n)
    assert encode_get_rate_limits_resp(mat) == oracle(mat)
    # parse-back sanity
    msg = pb.GetRateLimitsResp.FromString(encode_get_rate_limits_resp(mat))
    assert len(msg.responses) == n
    assert msg.responses[3].remaining == mat[2, 3]


def test_empty_and_single():
    assert encode_get_rate_limits_resp(np.zeros((5, 0), np.int64)) == b""
    mat = np.zeros((5, 1), np.int64)
    assert encode_get_rate_limits_resp(mat) == oracle(mat)  # all defaults
    mat[2] = -9  # negative remaining alone
    assert encode_get_rate_limits_resp(mat) == oracle(mat)
