"""Chaos suite: fault-injected clusters proving the peer path degrades
instead of lying (docs/resilience.md acceptance runs).

Scenarios: one peer at 100% injected RPC failure (breaker opens, GLOBAL
still answers locally, hits redeliver with zero loss on recovery), a peer
killed mid-flush and restarted, and degraded-mode limit enforcement
(DRAIN_OVER_LIMIT preserved).  All runs are seeded, use sub-100ms
breaker/sync windows, and end by asserting no background loop died —
metrics are the oracle (functional_test.go:2184-2276 pattern), never bare
sleeps.
"""

import asyncio

import pytest

from gubernator_tpu.cluster import Cluster
from gubernator_tpu.config import BehaviorConfig, Config, DaemonConfig
from gubernator_tpu.resilience import FaultInjector, ResilienceConfig
from gubernator_tpu.transport.daemon import Daemon
from gubernator_tpu.types import Behavior, RateLimitRequest, Status


def req(name, key, hits=1, limit=1_000_000, duration=3_600_000, **kw):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, behavior=Behavior.GLOBAL, **kw
    )


def fast_chaos_conf():
    behaviors = BehaviorConfig(global_sync_wait=0.02, batch_wait=0.001)
    resilience = ResilienceConfig(
        breaker_open_for=0.05,
        breaker_open_cap=0.1,
        breaker_min_requests=3,
        forward_backoff_base=0.002,
        forward_backoff_cap=0.02,
    )
    return behaviors, resilience


def assert_no_loop_dead(cluster):
    """Acceptance (c): after the run, every background loop — GLOBAL hits,
    broadcast, and each peer's batch loop — is still alive."""
    for d in cluster.daemons:
        for t in d.instance.global_mgr._tasks:
            assert not t.done(), f"dead loop {t.get_name()} on {d.advertise_address}"
        for p in d.instance.get_peer_list():
            if p._batch_task is not None:
                assert not p._batch_task.done(), (
                    f"dead batch loop for {p.info.grpc_address}"
                )


async def poll_consumed(daemon, name, key, want, limit=1_000_000,
                        timeout=10.0):
    """Poll a daemon's local GLOBAL state until ``want`` hits landed."""
    client = daemon.client()

    async def poll():
        while True:
            # Per-RPC deadline above the poll budget: a first-compile
            # stall on a loaded single-core host must surface as a slow
            # poll, not a DEADLINE_EXCEEDED crash out of the helper.
            r = (await client.get_rate_limits(
                [req(name, key, hits=0, limit=limit)], timeout=30.0
            ))[0]
            if limit - r.remaining == want:
                return r
            await asyncio.sleep(0.02)

    try:
        return await asyncio.wait_for(poll(), timeout=timeout)
    finally:
        await client.close()


async def test_chaos_100pct_failure_degrades_then_redelivers():
    """The ISSUE's acceptance run: one peer at 100% injected RPC failure.
    (a) the breaker opens within the configured threshold and GLOBAL
    requests still answer locally; (b) zero hits are lost once the peer
    recovers; (c) no background loop is dead at the end."""
    behaviors, resilience = fast_chaos_conf()
    inj = FaultInjector(seed=7)
    c = await Cluster.start(3, behaviors=behaviors, resilience=resilience,
                            fault_injector=inj)
    try:
        name, key = "chaos", "ck"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        ni = c.daemons.index(non_owner)
        owner_addr = owner.conf.grpc_listen_address
        inj.set_fault(owner_addr, partition=True)

        client = non_owner.client()
        sent = 0
        for _ in range(30):
            out = await client.get_rate_limits([req(name, key)])
            # (a) degraded mode: local answers, never errors.
            assert out[0].error == ""
            assert out[0].status == Status.UNDER_LIMIT
            sent += 1
            await asyncio.sleep(0.005)
        await client.close()

        # (a) the breaker opened (metrics oracle, not sleeps) and flushes
        # were re-enqueued instead of dropped.
        await c.wait_for_metric(
            ni, "gubernator_breaker_transitions_total",
            labels={"peerAddr": owner_addr, "to": "open"}, timeout=30,
        )
        await c.wait_for_metric(
            ni, "gubernator_global_redelivered_hits_total", timeout=30)
        assert c.metric_value(ni, "gubernator_degraded_answers_total") >= 1
        assert c.metric_value(ni, "gubernator_global_dropped_hits_total") == 0

        # Recovery: (b) every hit lands on the owner — zero loss.
        inj.clear()
        await poll_consumed(owner, name, key, sent, timeout=60)
        assert c.metric_value(ni, "gubernator_global_dropped_hits_total") == 0
        # The breaker closed again after a successful probe.  Generous
        # budget: the half-open probe rides the backoff schedule, and the
        # suite shares one CPU core.
        await c.wait_for_metric(
            ni, "gubernator_breaker_transitions_total",
            labels={"peerAddr": owner_addr, "to": "closed"}, timeout=30,
        )
        # (c) nothing died.
        assert_no_loop_dead(c)
    finally:
        await c.stop()


async def test_chaos_drain_over_limit_preserved_in_degraded_mode():
    """Degraded GLOBAL answers still enforce the limit locally, and the
    redelivered hits drain the owner's bucket (DRAIN_OVER_LIMIT is forced
    on the owner's relay path) instead of erroring or going negative."""
    behaviors, resilience = fast_chaos_conf()
    inj = FaultInjector(seed=11)
    c = await Cluster.start(3, behaviors=behaviors, resilience=resilience,
                            fault_injector=inj)
    try:
        name, key = "chaos-drain", "dk"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        inj.set_fault(owner.conf.grpc_listen_address, partition=True)

        client = non_owner.client()
        statuses = []
        for _ in range(7):
            out = await client.get_rate_limits(
                [req(name, key, hits=1, limit=5, duration=300_000)]
            )
            assert out[0].error == ""
            statuses.append(out[0].status)
            await asyncio.sleep(0.005)
        await client.close()
        # Local degraded enforcement: 5 under, then over — the partition
        # never turns the limiter into an allow-all.
        assert statuses[:5] == [Status.UNDER_LIMIT] * 5
        assert statuses[5:] == [Status.OVER_LIMIT] * 2

        inj.clear()
        # All 7 queued hits redeliver; DRAIN_OVER_LIMIT on the owner's
        # relay path pins the bucket at 0 rather than erroring/negative.
        oc = owner.client()

        async def owner_drained():
            while True:
                r = (await oc.get_rate_limits(
                    [req(name, key, hits=0, limit=5, duration=300_000)]
                ))[0]
                if r.remaining == 0:
                    return r
                await asyncio.sleep(0.02)

        await asyncio.wait_for(owner_drained(), timeout=10)
        # One more hit against the drained bucket is OVER_LIMIT (a zero-hit
        # query reports UNDER — nothing was requested).
        oc2 = owner.client()
        r = (await oc2.get_rate_limits(
            [req(name, key, hits=1, limit=5, duration=300_000)]
        ))[0]
        await oc2.close()
        assert r.status == Status.OVER_LIMIT
        assert r.remaining == 0
        assert_no_loop_dead(c)
    finally:
        await c.stop()


async def test_chaos_kill_peer_mid_flush_redelivers_after_restart():
    """A peer that actually dies (daemon closed, not injected) mid-flush:
    hits buffer locally and land once the peer comes back on the same
    address."""
    behaviors, resilience = fast_chaos_conf()
    c = await Cluster.start(2, behaviors=behaviors, resilience=resilience)
    try:
        name, key = "chaos-kill", "kk"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        owner_idx = c.daemons.index(owner)
        ni = c.daemons.index(non_owner)

        # Kill the owner BEFORE any flush can land, then drive traffic:
        # every flush of these hits happens against a dead peer.
        await owner.close()
        client = non_owner.client()
        sent = 0
        for _ in range(20):
            out = await client.get_rate_limits([req(name, key)])
            assert out[0].error == ""
            sent += 1
            await asyncio.sleep(0.005)
        await client.close()
        await c.wait_for_metric(
            ni, "gubernator_global_redelivered_hits_total", timeout=10,
        )

        # Resurrect the owner on its old port; redelivery drains into it.
        owner = await c.restart(owner_idx)
        await poll_consumed(owner, name, key, sent, timeout=60)
        assert c.metric_value(ni, "gubernator_global_dropped_hits_total") == 0
        assert_no_loop_dead(c)
    finally:
        await c.stop()


async def test_chaos_intermittent_errors_recover_without_loss():
    """50% injected error rate (seeded), *asymmetric*: only the
    non-owner → owner direction fails (the directional WAN-style
    schedule); the owner's own outbound broadcasts are clean.  Slower,
    flappier — but the accounting still converges to zero loss and the
    loops survive."""
    behaviors, resilience = fast_chaos_conf()
    inj = FaultInjector(seed=23)
    c = await Cluster.start(2, behaviors=behaviors, resilience=resilience,
                            fault_injector=inj)
    try:
        name, key = "chaos-flap", "fk"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        inj.set_fault(owner.conf.grpc_listen_address,
                      from_peer=non_owner.advertise_address,
                      error_rate=0.5)
        # The reverse direction is untouched: broadcasts owner → non_owner
        # must never be counted against this schedule.
        assert inj.spec_for(
            non_owner.conf.grpc_listen_address,
            from_peer=owner.advertise_address) is None

        client = non_owner.client()
        sent = 0
        for _ in range(25):
            out = await client.get_rate_limits([req(name, key)], timeout=30.0)
            assert out[0].error == ""
            sent += 1
            await asyncio.sleep(0.004)
        await client.close()

        inj.clear()
        # Generous budget: at 50% injected errors the flush can need
        # several backoff rounds, the poll client pays a fresh channel +
        # first-compile on its first RPC, and the suite shares one core.
        await poll_consumed(owner, name, key, sent, timeout=60)
        ni = c.daemons.index(non_owner)
        assert c.metric_value(ni, "gubernator_global_dropped_hits_total") == 0
        assert_no_loop_dead(c)
    finally:
        await c.stop()


async def test_chaos_peer_death_mid_reshard_defined_state():
    """Reshard acceptance run (docs/resharding.md failure matrix): a peer
    dies (100% partition) while a shard transition is requested.  The
    open breaker aborts the transition *before* the cutover — a defined
    state, zero bucket loss, zero double-serves — and admission
    unfreezes so the daemon keeps serving.  Once the peer recovers the
    same transition commits, the full protocol (freeze → drain →
    journal → verify) runs on the live cluster, and the buffered GLOBAL
    hits still redeliver with zero loss."""
    behaviors, resilience = fast_chaos_conf()
    inj = FaultInjector(seed=31)
    c = await Cluster.start(3, behaviors=behaviors, resilience=resilience,
                            fault_injector=inj)
    try:
        name, key = "chaos-reshard", "rk"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        ni = c.daemons.index(non_owner)
        owner_addr = owner.conf.grpc_listen_address
        inj.set_fault(owner_addr, partition=True)

        # Drive GLOBAL traffic into the dead owner until the breaker
        # opens (metrics oracle) — this is the "peer died mid-transfer"
        # precondition the coordinator must observe.
        client = non_owner.client()
        sent = 0
        for _ in range(30):
            out = await client.get_rate_limits([req(name, key)])
            assert out[0].error == ""
            sent += 1
            await asyncio.sleep(0.005)
        await client.close()
        await c.wait_for_metric(
            ni, "gubernator_breaker_transitions_total",
            labels={"peerAddr": owner_addr, "to": "open"}, timeout=30,
        )
        # Pin the breaker open across the abort check: fast_chaos_conf's
        # 50ms open window can slip to HALF_OPEN between the metric wait
        # and the coordinator's breaker_check on a loaded host, and
        # is_open() is False in HALF_OPEN.
        for p in non_owner.instance.get_peer_list():
            if p._info.grpc_address == owner_addr:
                p.breaker.force_open(10.0)

        # The transition aborts on the open breaker, before any state
        # moves: a defined outcome, never an exception.
        res = await non_owner.instance.reshard(2)
        assert res["outcome"] == "aborted"
        assert "breaker" in res["reason"]
        assert res["state_loss"] == 0 and res["double_served"] == 0
        assert c.metric_value(
            ni, "gubernator_tpu_reshard_transitions_total",
            labels={"result": "aborted"},
        ) == 1
        # Admission unfroze: the daemon still answers (degraded, local).
        assert not non_owner.instance.tick_loop.frozen
        client = non_owner.client()
        out = await client.get_rate_limits([req(name, key)])
        assert out[0].error == ""
        sent += 1
        await client.close()

        # Recovery: breaker closes, the same transition commits — the
        # degenerate identity cutover runs the full freeze/drain/verify
        # protocol on this single-chip engine.
        inj.clear()
        await c.wait_for_metric(
            ni, "gubernator_breaker_transitions_total",
            labels={"peerAddr": owner_addr, "to": "closed"}, timeout=30,
        )
        before = non_owner.instance.engine.cache_size()
        res = await non_owner.instance.reshard(2)
        assert res["outcome"] == "committed"
        assert res.get("degenerate") is True
        assert res["state_loss"] == 0 and res["double_served"] == 0
        assert res["live_items"] == before
        assert c.metric_value(
            ni, "gubernator_tpu_reshard_state_loss_total") == 0
        assert c.metric_value(
            ni, "gubernator_tpu_reshard_double_served_total") == 0
        assert c.metric_value(
            ni, "gubernator_tpu_reshard_transitions_total",
            labels={"result": "committed"},
        ) == 1

        # The in-flight GLOBAL state rode through both transitions: every
        # buffered hit redelivers to the recovered owner — zero loss,
        # zero double-serves on the bucket itself.
        await poll_consumed(owner, name, key, sent, timeout=60)
        assert c.metric_value(ni, "gubernator_global_dropped_hits_total") == 0
        assert_no_loop_dead(c)
    finally:
        await c.stop()


def _isolate_regions(inj, c, a="us", b="eu"):
    """Cut every cross-region link with directional schedules — intra-
    region traffic keeps flowing, exactly what a WAN partition does."""
    for da in c.daemons:
        for db in c.daemons:
            if da.conf.data_center == a and db.conf.data_center == b:
                inj.set_fault(db.conf.grpc_listen_address,
                              from_peer=da.advertise_address,
                              partition=True)
                inj.set_fault(da.conf.grpc_listen_address,
                              from_peer=db.advertise_address,
                              partition=True)


async def test_chaos_region_isolation_degrades_heals_zero_loss():
    """The federation acceptance run (docs/federation.md): two regions,
    healthy exchange first, then a full WAN partition (directional
    schedules — intra-region links stay up), bounded degraded serving
    on both sides, then heal.  After the heal both regions converge on
    the union of all hits: ABSOLUTE_ZERO hit loss, no double-counts."""
    behaviors, resilience = fast_chaos_conf()
    inj = FaultInjector(seed=13)
    c = await Cluster.start(
        4, datacenters=["us", "us", "eu", "eu"], behaviors=behaviors,
        resilience=resilience, fault_injector=inj, federation=True,
        federation_interval=0.02,
    )
    try:
        name, key = "chaos-fed", "gk"
        us_owner = c.find_owning_daemon_in_region(name, key, "us")
        eu_owner = c.find_owning_daemon_in_region(name, key, "eu")
        ui, ei = c.daemons.index(us_owner), c.daemons.index(eu_owner)

        def mr_req(hits=1):
            return RateLimitRequest(
                name=name, unique_key=key, hits=hits, limit=1_000_000,
                duration=3_600_000, behavior=Behavior.MULTI_REGION,
            )

        async def drive(daemon, n):
            client = daemon.client()
            for _ in range(n):
                # Generous RPC deadline: four engines JIT their first
                # programs during this test on a shared CPU host.
                out = await client.get_rate_limits([mr_req()], timeout=30.0)
                assert out[0].error == ""
                await asyncio.sleep(0.002)
            await client.close()

        # Healthy path: us hits show up in eu via the envelope stream.
        await drive(us_owner, 5)
        await c.wait_for_metric(
            ei, "gubernator_tpu_federation_envelopes_total",
            labels={"result": "applied"}, timeout=30)
        await poll_consumed(eu_owner, name, key, 5, timeout=60)

        # WAN partition: both regions keep serving, deltas buffer.
        _isolate_regions(inj, c)
        await drive(us_owner, 10)
        await drive(eu_owner, 7)
        # The sender noticed (redelivery attempts on the same envelope)
        # and flags MULTI_REGION answers as degraded.
        await c.wait_for_metric(
            ui, "gubernator_tpu_federation_redeliveries_total", timeout=30)
        await drive(us_owner, 3)
        assert c.metric_value(
            ui, "gubernator_tpu_federation_degraded_answers_total") >= 1
        # Degraded, never down: each region still answers from local
        # state — drift is bounded by staleness × local rate, which the
        # staleness gauge now exports.
        assert c.metric_value(
            ui, "gubernator_tpu_federation_staleness_seconds") > 0

        # Heal: buffered envelopes replay; the receive ledger dedupes
        # redeliveries; both regions converge on the union of all hits.
        inj.clear()
        total = 5 + 10 + 7 + 3
        await poll_consumed(us_owner, name, key, total, timeout=60)
        await poll_consumed(eu_owner, name, key, total, timeout=60)
        # Exactly-once: nothing pending, nothing lost, nothing doubled —
        # poll_consumed above asserted the == (over-admission would
        # overshoot, loss would undershoot).
        for d in (us_owner, eu_owner):
            fed = d.instance.federation
            assert fed is not None
            assert fed.pending_keys() == 0
            assert not fed._task.done()
        assert_no_loop_dead(c)
    finally:
        await c.stop()


def _snapshot_daemon_conf(tmp_path, interval=0.05):
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(
        # 1024 is a capacity the suite already compiles for — new table
        # shapes would pay fresh JIT programs in tier-1.
        cache_size=1024,
        snapshot_dir=str(tmp_path),
        snapshot_interval=interval,
    )
    return conf


def _local_req(key, hits, limit=1_000):
    return RateLimitRequest(
        name="crash", unique_key=key, hits=hits, limit=limit,
        duration=3_600_000,
    )


async def test_chaos_graceful_sigterm_restart_zero_loss(tmp_path):
    """The persistence acceptance run, graceful half: traffic, then the
    SIGTERM path (daemon.close == what the signal handler runs), then a
    restart from the same snapshot directory — every hit must still be
    accounted.  Zero loss, not bounded loss: close writes a final full
    base."""
    d = Daemon(_snapshot_daemon_conf(tmp_path, interval=60))
    await d.start()
    await d.wait_for_connect()
    client = d.client()
    for i in range(12):
        out = await client.get_rate_limits([_local_req(f"g{i}", hits=3)])
        assert out[0].error == ""
    await client.close()
    await d.close()  # graceful drain: readiness flips, final base written

    d2 = Daemon(_snapshot_daemon_conf(tmp_path, interval=60))
    await d2.start()
    await d2.wait_for_connect()
    try:
        assert d2.instance.restore_stats["restored_items"] >= 12
        c2 = d2.client()
        out = await c2.get_rate_limits(
            [_local_req(f"g{i}", hits=0) for i in range(12)]
        )
        await c2.close()
        loss = sum(1 for r in out if 1_000 - r.remaining != 3)
        assert loss == 0
    finally:
        await d2.close()


async def test_chaos_hard_kill_loss_bounded_by_one_delta_interval(tmp_path):
    """Hard kill (no final snapshot): a second daemon restores from the
    same directory while the first still runs — exactly what a kill -9
    leaves on disk.  Hits flushed by the delta loop must all be there;
    total loss is bounded by the traffic of one snapshot interval."""
    d = Daemon(_snapshot_daemon_conf(tmp_path, interval=0.05))
    await d.start()
    await d.wait_for_connect()
    client = d.client()
    n_flushed = 10
    for i in range(n_flushed):
        out = await client.get_rate_limits([_local_req(f"h{i}", hits=2)])
        assert out[0].error == ""
    # Wait until the delta loop has durably persisted the first batch.
    writer = d.instance._snapshot_writer
    deadline = asyncio.get_running_loop().time() + 10
    while writer.metric_items_written < n_flushed:
        assert asyncio.get_running_loop().time() < deadline, "no delta flush"
        await asyncio.sleep(0.02)
    # One more interval's worth of traffic that may or may not flush.
    n_tail = 5
    for i in range(n_tail):
        await client.get_rate_limits([_local_req(f"t{i}", hits=2)])
    await client.close()

    # "Kill": restore from disk NOW, first daemon still running (its
    # final base never happens for this read).
    d2 = Daemon(_snapshot_daemon_conf(tmp_path / "ignored", interval=60))
    d2.conf.config.snapshot_dir = str(tmp_path)
    await d2.start()
    await d2.wait_for_connect()
    try:
        c2 = d2.client()
        out = await c2.get_rate_limits(
            [_local_req(f"h{i}", hits=0) for i in range(n_flushed)]
            + [_local_req(f"t{i}", hits=0) for i in range(n_tail)]
        )
        await c2.close()
        flushed_lost = sum(
            1 for r in out[:n_flushed] if 1_000 - r.remaining != 2
        )
        tail_lost = sum(
            1 for r in out[n_flushed:] if 1_000 - r.remaining != 2
        )
        assert flushed_lost == 0, "fsync'd delta records must survive"
        assert tail_lost <= n_tail  # bounded by one interval's traffic
    finally:
        await d2.close()
        await d.close()


async def test_chaos_forward_path_faults_surface_as_retries():
    """Non-GLOBAL forwards against an injected-faulty owner: drops
    (DEADLINE_EXCEEDED) retry with backoff and eventually exhaust into the
    reference's 'not connected' error — the caller always gets an answer,
    never a hang."""
    behaviors, resilience = fast_chaos_conf()
    inj = FaultInjector(seed=5)
    c = await Cluster.start(2, behaviors=behaviors, resilience=resilience,
                            fault_injector=inj)
    try:
        name, key = "chaos-fwd", "wk"
        owner = c.find_owning_daemon(name, key)
        non_owner = c.list_non_owning_daemons(name, key)[0]
        inj.set_fault(owner.conf.grpc_listen_address, drop_rate=1.0)

        out = await asyncio.wait_for(
            non_owner.instance.get_rate_limits(
                [RateLimitRequest(name=name, unique_key=key, hits=1,
                                  limit=10, duration=60_000)]
            ),
            timeout=10,
        )
        assert "not connected" in out[0].error
        ni = c.daemons.index(non_owner)
        assert c.metric_value(
            ni, "gubernator_batch_send_retries_total"
        ) >= resilience.forward_max_attempts

        # Clear the fault: the next forward works again (breaker probes
        # through half-open within its 50ms open window).
        inj.clear()

        async def forward_recovers():
            while True:
                out = await non_owner.instance.get_rate_limits(
                    [RateLimitRequest(name=name, unique_key=key, hits=1,
                                      limit=10, duration=60_000)]
                )
                if out[0].error == "":
                    return out[0]
                await asyncio.sleep(0.05)

        r = await asyncio.wait_for(forward_recovers(), timeout=10)
        assert r.status == Status.UNDER_LIMIT
        assert_no_loop_dead(c)
    finally:
        await c.stop()


async def test_chaos_overload_spent_budget_sheds_not_hangs(tmp_path):
    """Overload scenario (docs/overload.md): a caller whose propagated
    budget is already spent gets an immediate retriable shed answer —
    the daemon never queues or serves work nobody is waiting for — and
    healthy traffic through the same daemon is untouched."""
    from gubernator_tpu.admission import SHED_EXPIRED_MSG

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(cache_size=1024)
    d = Daemon(conf)
    await d.start()
    await d.wait_for_connect()
    try:
        client = d.client()
        # Zero remaining budget rides guber-deadline-ms: expired on
        # arrival, shed before the device ever sees it.
        out = await client.get_rate_limits(
            [_local_req("ov-dead", hits=1)], budget_ms=0)
        assert out[0].error == SHED_EXPIRED_MSG
        shed = d.instance.tick_loop.metric_shed_admission
        assert shed.get("expired", 0) >= 1
        assert d.instance.tick_loop.metric_expired_served == 0

        # A generous budget and a budget-less request both serve.
        out = await client.get_rate_limits(
            [_local_req("ov-live", hits=1)], budget_ms=30_000)
        assert out[0].error == "" and out[0].status == Status.UNDER_LIMIT
        out = await client.get_rate_limits([_local_req("ov-live", hits=1)])
        assert out[0].error == ""
        assert 1_000 - out[0].remaining == 2  # shed never consumed hits
        await client.close()
    finally:
        await d.close()


# ---------------------------------------------------------------------
# Edge worker SIGKILL (docs/edge.md crash semantics)
# ---------------------------------------------------------------------
def test_chaos_edge_worker_sigkill_respawns_without_double_serve():
    """SIGKILL one edge worker mid-drive.  The supervisor must respawn
    it (fresh process, bumped generation), the in-flight slabs shed
    retriably — counted, never silently dropped — and no acked window
    may ever be double-served.  The respawned life resumes publishing
    into the same segment, so C_WIN_ACKED keeps climbing."""
    import os
    import signal
    import time

    from gubernator_tpu.edge import shmring
    from gubernator_tpu.edge.plane import EdgeConfig, EdgePlane
    from gubernator_tpu.ops.engine import TickEngine
    from gubernator_tpu.service.tickloop import TickLoop
    from gubernator_tpu.transport import fastwire
    from gubernator_tpu.utils.metrics import Metrics

    if fastwire.load() is None:
        pytest.skip("native wire codec not built")

    def wait_for(cond, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return
            time.sleep(0.02)
        raise AssertionError(f"timed out waiting for {what}")

    eng = TickEngine(capacity=1024, max_batch=64)
    loop = TickLoop(eng, batch_limit=64)
    metrics = Metrics()
    plane = EdgePlane(loop, EdgeConfig(
        workers=2, slabs=4, ring_depth=8, max_batch=64, mode="drive",
        drive={"batch": 32, "windows": 0, "keys": 64, "frames": 4},
    ), metrics=metrics)
    try:
        plane.start()
        assert plane.wait_ready(60), "workers never became ready"
        plane.go()
        victim = plane.workers[0]
        pid = victim.proc.pid
        wait_for(
            lambda: plane.counters(0)[shmring.C_WIN_ACKED] > 0,
            30, "worker 0 to ack its first window",
        )
        os.kill(pid, signal.SIGKILL)
        wait_for(
            lambda: victim.proc.pid != pid and victim.proc.is_alive(),
            30, "supervisor respawn",
        )
        acked_at_respawn = int(plane.counters(0)[shmring.C_WIN_ACKED])
        wait_for(
            lambda: plane.counters(0)[shmring.C_WIN_ACKED] > acked_at_respawn,
            30, "respawned worker to make progress",
        )
        tot = plane.totals()
    finally:
        plane.close()
        loop.close()
        eng.close()
    assert tot["restarts"] == 1, tot
    assert tot["double_served"] == 0, tot
    # Zero hit loss for acked windows: every window the workers counted
    # as acked was served exactly once, so acked accounting never
    # exceeds what was published; the crash gap is *accounted* (shed
    # slabs + dropped stale responses), not silent.
    assert tot["windows_acked"] <= tot["windows_published"], tot
    assert victim.generation == 2  # stale in-flight responses can't land
    assert metrics.sample(
        "gubernator_tpu_edge_worker_restarts_total", {"worker": "0"}
    ) == 1
