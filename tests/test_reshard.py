"""Reshard protocol tests: admission freeze, quiesce, the coordinator
state machine (commit / abort / rollback), and the crash journal.

Nearly everything here runs against stub engines or the single-chip
TickLoop — the mesh-engine relayout itself is covered by
test_mesh_engine.py and the reshard_live bench rung.  The ONE mesh
build in this module is the reshard × ragged composition case at the
bottom (a deliberately tiny 8→3→8 engine), because what it pins is the
coordinator-visible outcome: extent offsets recomputed against the new
``cap_to`` keep ``state_loss`` / ``double_served`` at zero under
Zipf-skewed ragged dispatch.
"""

import threading

import pytest

from gubernator_tpu.admission import (
    CLASS_PEER,
    SHED_RESHARD_MSG,
    AdmissionConfig,
)
from gubernator_tpu.parallel.reshard import (
    PHASE_IDLE,
    ReshardCoordinator,
    ReshardError,
)
from gubernator_tpu.persistence import (
    TransitionLog,
    TransitionRecord,
    check_interrupted,
)
from gubernator_tpu.service.tickloop import TickLoop
from gubernator_tpu.types import RateLimitRequest, RateLimitResponse, Status
from gubernator_tpu.utils.metrics import Metrics


class _StubBatch:
    def __init__(self, reqs):
        self._reqs = reqs

    def handles(self):
        return []

    def responses(self):
        return [
            RateLimitResponse(status=Status.UNDER_LIMIT, limit=r.limit,
                              remaining=r.limit - r.hits)
            for r in self._reqs
        ]


class _StubEngine:
    """No-device engine: records batches, carries a fake key census for
    the coordinator's degenerate path + verify phase."""

    def __init__(self, items=()):
        self.batches = []
        self.items = list(items)

    def submit(self, reqs):
        self.batches.append(list(reqs))
        return _StubBatch(reqs)

    def cache_size(self):
        return len(self.items)

    def export_items(self):
        return [dict(it) for it in self.items]


def _reqs(n):
    return [
        RateLimitRequest(name="rs", unique_key=str(i), hits=1, limit=100,
                         duration=60_000, created_at=1_000)
        for i in range(n)
    ]


class _StubLoop:
    """Records the freeze protocol a coordinator drives."""

    def __init__(self, quiesce_ok=True):
        self.calls = []
        self.quiesce_ok = quiesce_ok

    def freeze(self, shed_peers=False):
        self.calls.append(("freeze", shed_peers))

    def unfreeze(self):
        self.calls.append(("unfreeze",))

    def quiesce(self, timeout):
        self.calls.append(("quiesce", timeout))
        return self.quiesce_ok


# ---------------------------------------------------------------------------
# TickLoop freeze / quiesce
# ---------------------------------------------------------------------------
def test_freeze_sheds_clients_retriable_peers_drain():
    """Level-1 freeze: CLIENT windows answer the retriable reshard shed
    without touching the queue; PEER reconcile traffic keeps flowing
    (it must land before the cutover).  Level 2 sheds both; unfreeze
    restores normal service."""
    eng = _StubEngine()
    m = Metrics()
    loop = TickLoop(eng, admission=AdmissionConfig(), metrics=m)
    try:
        loop.freeze()
        out = loop.submit(_reqs(2)).result(timeout=5)
        assert [r.error for r in out] == [SHED_RESHARD_MSG] * 2
        peer_out = loop.submit(_reqs(1), klass=CLASS_PEER).result(timeout=5)
        assert peer_out[0].error == ""
        assert sum(len(b) for b in eng.batches) == 1  # only the peer window
        loop.freeze(shed_peers=True)
        out = loop.submit(_reqs(1), klass=CLASS_PEER).result(timeout=5)
        assert out[0].error == SHED_RESHARD_MSG
        loop.unfreeze()
        out = loop.submit(_reqs(1)).result(timeout=5)
        assert out[0].error == ""
        assert m.sample("gubernator_tpu_admission_shed_total",
                        {"reason": "reshard"}) == 3
        assert loop.metric_shed_admission["reshard"] == 3
    finally:
        loop.close()


def test_freeze_never_downgrades_and_quiesce_idle():
    eng = _StubEngine()
    loop = TickLoop(eng, admission=AdmissionConfig())
    try:
        loop.freeze(shed_peers=True)
        loop.freeze()  # must not downgrade the escalated freeze
        out = loop.submit(_reqs(1), klass=CLASS_PEER).result(timeout=5)
        assert out[0].error == SHED_RESHARD_MSG
        loop.unfreeze()
        loop.submit(_reqs(2)).result(timeout=5)
        assert loop.quiesce(timeout=5.0)  # drained loop is idle
    finally:
        loop.close()


def test_quiesce_times_out_under_stuck_window():
    """A window wedged on the device keeps the loop non-idle: quiesce
    must report False inside its budget instead of hanging (the
    coordinator aborts on that answer)."""
    gate = threading.Event()

    class _GatedEngine(_StubEngine):
        def submit(self, reqs):
            gate.wait(timeout=10)
            return super().submit(reqs)

    eng = _GatedEngine()
    loop = TickLoop(eng, admission=AdmissionConfig())
    try:
        fut = loop.submit(_reqs(1))
        assert not loop.quiesce(timeout=0.2)
        gate.set()
        assert fut.result(timeout=5)[0].error == ""
        assert loop.quiesce(timeout=5.0)
    finally:
        gate.set()
        loop.close()


# ---------------------------------------------------------------------------
# Coordinator state machine
# ---------------------------------------------------------------------------
def _items(n):
    return [{"key": f"it-{i}", "remaining": 5, "expire_at": 1 << 60}
            for i in range(n)]


def test_coordinator_degenerate_commit_and_metrics(tmp_path):
    """Single-chip engines (no native reshard) run the full protocol —
    freeze, drain, journal, verify — around an identity transition; the
    journal holds begin+commit and the metrics record the outcome."""
    eng = _StubEngine(items=_items(7))
    tl = _StubLoop()
    m = Metrics()
    coord = ReshardCoordinator(
        eng, tick_loop=tl, transition_log=TransitionLog(str(tmp_path)),
        metrics=m, freeze_timeout=1.0,
    )
    res = coord.reshard(2)
    assert res["outcome"] == "committed" and res["degenerate"] is True
    assert res["live_items"] == 7
    assert res["state_loss"] == 0 and res["double_served"] == 0
    # Freeze protocol order: level-1 freeze, quiesce, escalate, unfreeze.
    assert tl.calls == [
        ("freeze", False), ("quiesce", 1.0), ("freeze", True),
        ("unfreeze",),
    ]
    recs = TransitionLog(str(tmp_path)).records()
    assert [(r.phase, r.from_shards, r.to_shards) for r in recs] == [
        ("begin", 1, 2), ("commit", 1, 2),
    ]
    assert m.sample("gubernator_tpu_reshard_transitions_total",
                    {"result": "committed"}) == 1
    assert m.sample("gubernator_tpu_reshard_phase") == 0  # back to idle
    assert coord.phase != PHASE_IDLE  # terminal phase retained in status
    assert coord.status()["last"]["outcome"] == "committed"
    # A committed journal is not an interruption.
    assert check_interrupted(TransitionLog(str(tmp_path))) is None


def test_coordinator_drain_timeout_aborts(tmp_path):
    eng = _StubEngine(items=_items(3))
    tl = _StubLoop(quiesce_ok=False)
    m = Metrics()
    coord = ReshardCoordinator(
        eng, tick_loop=tl, transition_log=TransitionLog(str(tmp_path)),
        metrics=m, freeze_timeout=0.1,
    )
    res = coord.reshard(4)
    assert res["outcome"] == "aborted" and "drain timeout" in res["reason"]
    assert ("unfreeze",) in tl.calls           # admission always restored
    assert ("freeze", True) not in tl.calls    # never escalated
    assert TransitionLog(str(tmp_path)).records() == []  # pre-journal abort
    assert m.sample("gubernator_tpu_reshard_transitions_total",
                    {"result": "aborted"}) == 1


def test_coordinator_breaker_abort():
    """An open breaker (mid-transfer peer death) aborts before the
    cutover; admission unfreezes."""
    tl = _StubLoop()
    coord = ReshardCoordinator(
        _StubEngine(items=_items(2)), tick_loop=tl,
        breaker_check=lambda: True,
    )
    res = coord.reshard(3)
    assert res["outcome"] == "aborted" and "breaker" in res["reason"]
    assert tl.calls[-1] == ("unfreeze",)


def test_coordinator_engine_failure_rolls_back(tmp_path):
    """An engine that raises mid-relayout (it restores the old layout
    before raising) lands as an aborted transition with begin+abort in
    the journal — a crash *between* those records is what the startup
    interruption check catches."""

    class _ExplodingEngine(_StubEngine):
        n_shards = 4

        def reshard(self, new_shards):
            raise RuntimeError("device fell over")

    coord = ReshardCoordinator(
        _ExplodingEngine(items=_items(2)), tick_loop=_StubLoop(),
        transition_log=TransitionLog(str(tmp_path)),
    )
    res = coord.reshard(2)
    assert res["outcome"] == "aborted" and "rolled back" in res["reason"]
    recs = TransitionLog(str(tmp_path)).records()
    assert [r.phase for r in recs] == ["begin", "abort"]
    assert check_interrupted(TransitionLog(str(tmp_path))) is None


def test_coordinator_rejects_concurrent_and_bad_target():
    coord = ReshardCoordinator(_StubEngine())
    with pytest.raises(ReshardError):
        coord.reshard(0)
    assert coord._lock.acquire(blocking=False)  # simulate a running one
    try:
        with pytest.raises(ReshardError, match="already running"):
            coord.reshard(2)
    finally:
        coord._lock.release()
    assert coord.reshard(1)["outcome"] == "noop"  # 1 -> 1


def test_coordinator_verify_counts_damage():
    """A lossy/double-resident post-cutover table is counted, never
    silent (the bench rung gates both at ABSOLUTE_ZERO)."""

    class _DamagedEngine(_StubEngine):
        n_shards = 2

        def reshard(self, new_shards):
            return {"live_items": 4}

        def export_items(self):  # 2 unique keys, one resident twice
            return [{"key": "a"}, {"key": "a"}, {"key": "b"}]

    m = Metrics()
    coord = ReshardCoordinator(_DamagedEngine(), metrics=m)
    res = coord.reshard(1)
    assert res["outcome"] == "committed"
    assert res["state_loss"] == 2      # 4 expected, 2 unique survived
    assert res["double_served"] == 1
    assert m.sample("gubernator_tpu_reshard_state_loss_total") == 2
    assert m.sample("gubernator_tpu_reshard_double_served_total") == 1


def test_coordinator_pauses_global_mesh_reconcile():
    class _Pausable:
        def __init__(self):
            self.paused = 0
            self.log = []

        def pause_reconcile(self):
            self.paused += 1
            self.log.append("pause")

        def resume_reconcile(self):
            self.paused -= 1
            self.log.append("resume")

    gm = _Pausable()
    coord = ReshardCoordinator(
        _StubEngine(items=_items(1)), tick_loop=_StubLoop(),
        global_engine=gm,
    )
    assert coord.reshard(2)["outcome"] == "committed"
    assert gm.log == ["pause", "resume"] and gm.paused == 0


# ---------------------------------------------------------------------------
# Transition journal
# ---------------------------------------------------------------------------
def test_transition_log_crash_detection(tmp_path):
    log = TransitionLog(str(tmp_path))
    log.append(TransitionRecord("begin", 8, 4, epoch=1))
    log.append(TransitionRecord("commit", 8, 4, epoch=1))
    log.append(TransitionRecord("begin", 4, 8, epoch=2))  # died here
    rec = check_interrupted(TransitionLog(str(tmp_path)))
    assert rec is not None
    assert (rec.from_shards, rec.to_shards, rec.epoch) == (4, 8, 2)
    # check_interrupted clears the journal: the record matters across
    # exactly one restart.
    assert TransitionLog(str(tmp_path)).records() == []


def test_transition_log_torn_tail_tolerated(tmp_path):
    log = TransitionLog(str(tmp_path))
    log.append(TransitionRecord("begin", 2, 4, epoch=1))
    with open(log.path, "ab") as f:
        f.write(b"\x00garbage-torn-write")
    rec = check_interrupted(TransitionLog(str(tmp_path)))
    assert rec is not None and rec.to_shards == 4


def test_transition_log_disabled_is_noop():
    log = TransitionLog(None)
    log.append(TransitionRecord("begin", 1, 2, epoch=1))
    assert log.records() == []
    assert check_interrupted(log) is None


def test_interrupted_detection_counts_metric():
    m = Metrics()
    coord = ReshardCoordinator(_StubEngine(), metrics=m)
    coord.record_interrupted(TransitionRecord("begin", 8, 4, epoch=3))
    assert m.sample("gubernator_tpu_reshard_transitions_total",
                    {"result": "interrupted"}) == 1


# ---------------------------------------------------------------------------
# Reshard × ragged dispatch composition (the one mesh build here; see
# the module docstring)
# ---------------------------------------------------------------------------
def test_reshard_ragged_zipf_round_trip_zero_loss():
    """8→3→8 through the full coordinator protocol with Zipf-skewed
    traffic served by the ragged dispatch on every layout: the extent
    offsets are recomputed against each layout's ``cap_to``, so
    ``state_loss`` / ``double_served`` / ``parity_errors`` stay 0 and
    decisions keep matching a single-chip replay across both cutovers.
    The overflow canary must never move — skew has no fallback."""
    import jax
    import numpy as np

    from gubernator_tpu.ops.engine import TickEngine
    from gubernator_tpu.parallel.mesh_engine import MeshTickEngine, make_mesh
    from gubernator_tpu.utils import timeutil

    # Wall-clock base: the coordinator's cutover stamps load_items with
    # the real clock, so synthetic epochs would expire every item at
    # the relayout boundary.
    NOW = timeutil.now_ms()
    eng = MeshTickEngine(
        mesh=make_mesh(jax.devices()), local_capacity=16, max_batch=32
    )
    ref = TickEngine(capacity=8 * 16, max_batch=32)
    coord = ReshardCoordinator(eng, verify=True)
    rng = np.random.default_rng(29)

    def zipf_window(width):
        return [
            RateLimitRequest(
                name="zr", unique_key=f"z{int(rng.zipf(1.2)) % 40}",
                hits=1, limit=10_000, duration=3_600_000,
            )
            for _ in range(width)
        ]

    def serve_and_compare(t):
        reqs = zipf_window(int(rng.integers(8, 33)))
        a = eng.process(reqs, now=NOW + t)
        b = ref.process(reqs, now=NOW + t)
        assert [(r.status, r.remaining, r.error) for r in a] == \
               [(r.status, r.remaining, r.error) for r in b]

    for t in range(2):
        serve_and_compare(t)
    for leg, (target, t0) in enumerate([(3, 100), (8, 200)]):
        res = coord.reshard(target)
        assert res["outcome"] == "committed", res
        assert res["to_shards"] == target == eng.n_shards
        assert res["state_loss"] == 0 and res["double_served"] == 0
        assert res["parity_errors"] == 0
        for t in range(2):
            serve_and_compare(t0 + t)
    assert eng.metric_routed_overflows == 0


def test_try_reshard_busy_dict_is_single_source_of_truth():
    """The concurrent-call outcome is one defined dict from the
    coordinator (BUSY_RESULT) — Instance.reshard, /debug/reshard's 409,
    and the autoscaler's reshard_busy veto all consume it instead of
    string-matching the error."""
    from gubernator_tpu.parallel.reshard import BUSY_RESULT

    coord = ReshardCoordinator(_StubEngine(items=_items(1)))
    assert not coord.is_busy()
    assert coord._lock.acquire(blocking=False)  # simulate a running one
    try:
        assert coord.is_busy()
        out = coord.try_reshard(2)
        assert out == BUSY_RESULT
        assert out is not BUSY_RESULT  # a copy; callers can't mutate it
        # the raising wrapper stays the compat surface
        with pytest.raises(ReshardError, match="already running"):
            coord.reshard(2)
    finally:
        coord._lock.release()
    assert not coord.is_busy()
    # bad targets still raise on BOTH entry points — busy is the only
    # non-raising outcome
    with pytest.raises(ReshardError):
        coord.try_reshard(0)
    assert coord.try_reshard(2)["outcome"] == "committed"


def test_coordinator_pauses_federation_sends():
    """Mirror of the global-mesh pause: federation envelope sends stop
    at FREEZE and resume after commit AND after abort (the finally)."""

    class _Pausable:
        def __init__(self):
            self.paused = 0
            self.log = []

        def pause(self):
            self.paused += 1
            self.log.append("pause")

        def resume(self):
            self.paused -= 1
            self.log.append("resume")

    fed = _Pausable()
    coord = ReshardCoordinator(
        _StubEngine(items=_items(1)), tick_loop=_StubLoop(), federation=fed,
    )
    assert coord.reshard(2)["outcome"] == "committed"
    assert fed.log == ["pause", "resume"] and fed.paused == 0

    # abort path: drain timeout — the finally must still resume
    fed2 = _Pausable()
    coord2 = ReshardCoordinator(
        _StubEngine(items=_items(1)), tick_loop=_StubLoop(quiesce_ok=False),
        federation=fed2, freeze_timeout=0.01,
    )
    assert coord2.reshard(2)["outcome"] == "aborted"
    assert fed2.log == ["pause", "resume"] and fed2.paused == 0
