"""Metric-flag collectors + metrics-as-oracle helpers.

The reference registers optional OS/runtime collectors behind
``GUBER_METRIC_FLAGS`` (flags.go:20-57, daemon.go:276-287) and its
distributed tests poll metric counters instead of sleeping
(functional_test.go:2184-2276).  Both surfaces are covered here.
"""

import pytest

from gubernator_tpu.config import (
    FLAG_OS_METRICS,
    FLAG_RUNTIME_METRICS,
    DaemonConfig,
    parse_metric_flags,
)
from gubernator_tpu.utils.metrics import Metrics


def test_parse_metric_flags_reference_names():
    # flags.go:47-52: "os" and "golang" are the two valid names.
    assert parse_metric_flags(["os"]) == FLAG_OS_METRICS
    assert parse_metric_flags(["golang"]) == FLAG_RUNTIME_METRICS
    assert parse_metric_flags(["os", "golang"]) == (
        FLAG_OS_METRICS | FLAG_RUNTIME_METRICS
    )
    # Native aliases for the runtime collector, plus whitespace tolerance.
    assert parse_metric_flags([" python "]) == FLAG_RUNTIME_METRICS
    assert parse_metric_flags([]) == 0


def test_parse_metric_flags_invalid_ignored(caplog):
    # flags.go:53-55: unknown names are logged and skipped, not fatal.
    with caplog.at_level("ERROR", logger="gubernator"):
        assert parse_metric_flags(["bogus", "os"]) == FLAG_OS_METRICS
    assert any("invalid flag" in r.message for r in caplog.records)


def test_flag_collectors_registered():
    m = Metrics()
    m.register_flag_collectors(FLAG_OS_METRICS | FLAG_RUNTIME_METRICS)
    text = m.expose().decode()
    # ProcessCollector under the gubernator namespace (daemon.go:278-281)...
    assert "gubernator_process_cpu_seconds_total" in text
    # ...and the Python-runtime analog of Go's GoCollector.
    assert "python_info" in text
    assert "python_gc_objects_collected_total" in text


def test_no_flag_collectors_by_default():
    text = Metrics().expose().decode()
    assert "process_cpu_seconds_total" not in text
    assert "python_info" not in text


def test_sample_oracle_reads_counters_and_summaries():
    m = Metrics()
    assert m.sample("gubernator_broadcast_duration_count") == 0.0
    m.broadcast_duration.observe(0.25)
    m.broadcast_duration.observe(0.75)
    assert m.sample("gubernator_broadcast_duration_count") == 2.0
    assert m.sample("gubernator_broadcast_duration_sum") == pytest.approx(1.0)
    m.getratelimit_counter.labels(calltype="local").inc()
    assert m.sample(
        "gubernator_getratelimit_counter_total", {"calltype": "local"}
    ) == 1.0


async def test_service_request_populates_catalog_families():
    """One live request drives the engine/cache/func families the catalog
    documents (docs/prometheus.md) — they must not stay at zero."""
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
    from gubernator_tpu.types import RateLimitRequest

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        client = DaemonClient(d.advertise_address)
        reqs = [RateLimitRequest(name="svc", unique_key="k", hits=1,
                                 limit=10, duration=60000)]
        await client.get_rate_limits(reqs)  # miss: installs the bucket
        await client.get_rate_limits(reqs)  # hit
        await client.close()
        m = d.metrics
        assert m.sample("gubernator_cache_access_count_total",
                        {"type": "miss"}) >= 1
        assert m.sample("gubernator_cache_access_count_total",
                        {"type": "hit"}) >= 1
        assert m.sample("gubernator_command_counter_total",
                        {"worker": "0", "method": "GetRateLimits"}) >= 2
        assert m.sample("gubernator_func_duration_count",
                        {"name": "V1Instance.GetRateLimits"}) >= 2
        assert m.sample("gubernator_func_duration_count",
                        {"name": "V1Instance.getLocalRateLimit"}) >= 2
        assert m.sample("gubernator_tpu_tick_batch_size_count") >= 2
    finally:
        await d.close()


async def test_daemon_exposes_flag_collectors():
    """GUBER_METRIC_FLAGS surfaces through the daemon's /metrics page."""
    import aiohttp

    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.transport.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        peer_discovery_type="none",
        metric_flags=parse_metric_flags(["os", "golang"]),
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{d.conf.http_listen_address}/metrics"
            ) as r:
                text = await r.text()
        assert "gubernator_process_cpu_seconds_total" in text
        assert "python_gc_objects_collected_total" in text
    finally:
        await d.close()


async def test_grpc_max_conn_age_env():
    """GUBER_GRPC_MAX_CONN_AGE_SEC parity (config.go:319): default 0 =
    infinity; a positive value serves traffic with age+grace applied."""
    from gubernator_tpu.config import BehaviorConfig, Config, setup_daemon_config
    from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
    from gubernator_tpu.types import RateLimitRequest

    assert setup_daemon_config(environ={}).grpc_max_conn_age_sec == 0
    conf = setup_daemon_config(
        environ={"GUBER_GRPC_MAX_CONN_AGE_SEC": "30"}
    )
    assert conf.grpc_max_conn_age_sec == 30

    # The daemon boots with the option set and serves normally.
    conf.grpc_listen_address = "127.0.0.1:0"
    conf.http_listen_address = ""
    conf.peer_discovery_type = "none"
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        c = DaemonClient(d.advertise_address)
        out = await c.get_rate_limits([RateLimitRequest(
            name="age", unique_key="k", hits=1, limit=5, duration=60_000)])
        assert out[0].remaining == 4
        await c.close()
    finally:
        await d.close()


def test_tiering_families_registered():
    # docs/tiering.md observability table: the tiering counters/gauges
    # exist from construction so dashboards see zeroes, not absences.
    m = Metrics()
    m.cold_demotions.inc(3)
    m.cold_promotions.inc(2)
    m.cold_hits.inc(2)
    m.cold_size.set(1)
    m.hot_occupancy.set(0.5)
    m.shed_requests.inc()
    assert m.sample("gubernator_tpu_cold_demotions_total") == 3
    assert m.sample("gubernator_tpu_cold_promotions_total") == 2
    assert m.sample("gubernator_tpu_cold_hits_total") == 2
    assert m.sample("gubernator_tpu_cold_size") == 1
    assert m.sample("gubernator_tpu_hot_occupancy") == 0.5
    assert m.sample("gubernator_tpu_shed_requests_total") == 1
