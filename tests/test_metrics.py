"""Metric-flag collectors + metrics-as-oracle helpers.

The reference registers optional OS/runtime collectors behind
``GUBER_METRIC_FLAGS`` (flags.go:20-57, daemon.go:276-287) and its
distributed tests poll metric counters instead of sleeping
(functional_test.go:2184-2276).  Both surfaces are covered here.
"""

import pytest

from gubernator_tpu.config import (
    FLAG_OS_METRICS,
    FLAG_RUNTIME_METRICS,
    DaemonConfig,
    parse_metric_flags,
)
from gubernator_tpu.utils.metrics import Metrics


def test_parse_metric_flags_reference_names():
    # flags.go:47-52: "os" and "golang" are the two valid names.
    assert parse_metric_flags(["os"]) == FLAG_OS_METRICS
    assert parse_metric_flags(["golang"]) == FLAG_RUNTIME_METRICS
    assert parse_metric_flags(["os", "golang"]) == (
        FLAG_OS_METRICS | FLAG_RUNTIME_METRICS
    )
    # Native aliases for the runtime collector, plus whitespace tolerance.
    assert parse_metric_flags([" python "]) == FLAG_RUNTIME_METRICS
    assert parse_metric_flags([]) == 0


def test_parse_metric_flags_invalid_ignored(caplog):
    # flags.go:53-55: unknown names are logged and skipped, not fatal.
    with caplog.at_level("ERROR", logger="gubernator"):
        assert parse_metric_flags(["bogus", "os"]) == FLAG_OS_METRICS
    assert any("invalid flag" in r.message for r in caplog.records)


def test_flag_collectors_registered():
    m = Metrics()
    m.register_flag_collectors(FLAG_OS_METRICS | FLAG_RUNTIME_METRICS)
    text = m.expose().decode()
    # ProcessCollector under the gubernator namespace (daemon.go:278-281)...
    assert "gubernator_process_cpu_seconds_total" in text
    # ...and the Python-runtime analog of Go's GoCollector.
    assert "python_info" in text
    assert "python_gc_objects_collected_total" in text


def test_no_flag_collectors_by_default():
    text = Metrics().expose().decode()
    assert "process_cpu_seconds_total" not in text
    assert "python_info" not in text


def test_sample_oracle_reads_counters_and_summaries():
    m = Metrics()
    assert m.sample("gubernator_broadcast_duration_count") == 0.0
    m.broadcast_duration.observe(0.25)
    m.broadcast_duration.observe(0.75)
    assert m.sample("gubernator_broadcast_duration_count") == 2.0
    assert m.sample("gubernator_broadcast_duration_sum") == pytest.approx(1.0)
    m.getratelimit_counter.labels(calltype="local").inc()
    assert m.sample(
        "gubernator_getratelimit_counter_total", {"calltype": "local"}
    ) == 1.0


async def test_service_request_populates_catalog_families():
    """One live request drives the engine/cache/func families the catalog
    documents (docs/prometheus.md) — they must not stay at zero."""
    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
    from gubernator_tpu.types import RateLimitRequest

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        peer_discovery_type="none",
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        client = DaemonClient(d.advertise_address)
        reqs = [RateLimitRequest(name="svc", unique_key="k", hits=1,
                                 limit=10, duration=60000)]
        await client.get_rate_limits(reqs)  # miss: installs the bucket
        await client.get_rate_limits(reqs)  # hit
        await client.close()
        m = d.metrics
        assert m.sample("gubernator_cache_access_count_total",
                        {"type": "miss"}) >= 1
        assert m.sample("gubernator_cache_access_count_total",
                        {"type": "hit"}) >= 1
        assert m.sample("gubernator_command_counter_total",
                        {"worker": "0", "method": "GetRateLimits"}) >= 2
        assert m.sample("gubernator_func_duration_count",
                        {"name": "V1Instance.GetRateLimits"}) >= 2
        assert m.sample("gubernator_func_duration_count",
                        {"name": "V1Instance.getLocalRateLimit"}) >= 2
        assert m.sample("gubernator_tpu_tick_batch_size_count") >= 2
    finally:
        await d.close()


async def test_daemon_exposes_flag_collectors():
    """GUBER_METRIC_FLAGS surfaces through the daemon's /metrics page."""
    import aiohttp

    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.transport.daemon import spawn_daemon

    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        peer_discovery_type="none",
        metric_flags=parse_metric_flags(["os", "golang"]),
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{d.conf.http_listen_address}/metrics"
            ) as r:
                text = await r.text()
        assert "gubernator_process_cpu_seconds_total" in text
        assert "python_gc_objects_collected_total" in text
    finally:
        await d.close()


async def test_grpc_max_conn_age_env():
    """GUBER_GRPC_MAX_CONN_AGE_SEC parity (config.go:319): default 0 =
    infinity; a positive value serves traffic with age+grace applied."""
    from gubernator_tpu.config import BehaviorConfig, Config, setup_daemon_config
    from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
    from gubernator_tpu.types import RateLimitRequest

    assert setup_daemon_config(environ={}).grpc_max_conn_age_sec == 0
    conf = setup_daemon_config(
        environ={"GUBER_GRPC_MAX_CONN_AGE_SEC": "30"}
    )
    assert conf.grpc_max_conn_age_sec == 30

    # The daemon boots with the option set and serves normally.
    conf.grpc_listen_address = "127.0.0.1:0"
    conf.http_listen_address = ""
    conf.peer_discovery_type = "none"
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        c = DaemonClient(d.advertise_address)
        out = await c.get_rate_limits([RateLimitRequest(
            name="age", unique_key="k", hits=1, limit=5, duration=60_000)])
        assert out[0].remaining == 4
        await c.close()
    finally:
        await d.close()


def test_tiering_families_registered():
    # docs/tiering.md observability table: the tiering counters/gauges
    # exist from construction so dashboards see zeroes, not absences.
    m = Metrics()
    m.cold_demotions.inc(3)
    m.cold_promotions.inc(2)
    m.cold_hits.inc(2)
    m.cold_size.set(1)
    m.hot_occupancy.set(0.5)
    m.shed_requests.inc()
    assert m.sample("gubernator_tpu_cold_demotions_total") == 3
    assert m.sample("gubernator_tpu_cold_promotions_total") == 2
    assert m.sample("gubernator_tpu_cold_hits_total") == 2
    assert m.sample("gubernator_tpu_cold_size") == 1
    assert m.sample("gubernator_tpu_hot_occupancy") == 0.5
    assert m.sample("gubernator_tpu_shed_requests_total") == 1


# ---------------------------------------------------------------------
# Telemetry plane (docs/observability.md): lock-light Histogram with
# OpenMetrics exemplars + the daemon's /debug introspection surface.
# ---------------------------------------------------------------------
def test_histogram_exposition_golden_format():
    """The custom-collector Histogram renders the standard Prometheus
    text shape: cumulative _bucket{le=...} rows ending in +Inf, plus
    _count and _sum — and the sample() oracle reads all three."""
    m = Metrics()
    m.stage_duration.labels(stage="pack").observe(0.003)
    m.stage_duration.labels(stage="pack").observe(0.4)
    m.stage_duration.labels(stage="h2d").observe(70.0)  # above top bucket

    text = m.expose().decode()
    assert "# TYPE gubernator_tpu_stage_duration_seconds histogram" in text
    name = "gubernator_tpu_stage_duration_seconds"
    assert m.sample(f"{name}_count", {"stage": "pack"}) == 2
    assert m.sample(f"{name}_sum", {"stage": "pack"}) == pytest.approx(0.403)
    # A 70 s observation lands only in +Inf (buckets top out at ~56 s).
    assert m.sample(f"{name}_bucket", {"stage": "h2d", "le": "+Inf"}) == 1
    assert m.sample(f"{name}_bucket", {"stage": "h2d", "le": "0.0001"}) == 0
    # Bucket counts are cumulative: parse the pack series back out and
    # check monotonicity with the +Inf row equal to _count.  (The text
    # exposition sorts labels, so match on both labels, not an order.)
    pack = [
        float(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith(f"{name}_bucket{{") and 'stage="pack"' in line
    ]
    assert pack == sorted(pack)
    assert pack[-1] == 2.0


def test_histogram_exemplars_link_trace_ids():
    """Observations made inside a span carry its trace id as an
    OpenMetrics exemplar on the bucket that counted them."""
    from gubernator_tpu.utils import tracing
    from gubernator_tpu.utils.metrics import Histogram
    from gubernator_tpu.utils.tracing import InMemoryExporter

    h = Histogram("t_exemplar_seconds", "test family", ["stage"])
    exp = InMemoryExporter()
    tracing.add_exporter(exp)
    try:
        with tracing.span("observe") as span:
            h.labels(stage="pack").observe(0.01)
        tid = span.trace_id
    finally:
        tracing.remove_exporter(exp)

    text = h.openmetrics()
    lines = [ln for ln in text.splitlines() if "trace_id" in ln]
    assert len(lines) == 1
    assert f'# {{trace_id="{tid}"}} 0.01' in lines[0]
    assert "_bucket{" in lines[0] and 'stage="pack"' in lines[0]

    # Tracing off (no exporter installed): no exemplar is captured.
    h2 = Histogram("t_noexemplar_seconds", "test family")
    h2.observe(0.01)
    assert "trace_id" not in h2.openmetrics()


async def test_debug_endpoints_serve_populated_json(monkeypatch):
    """GUBER_DEBUG_ENDPOINTS=1: /debug/pipeline, /debug/state and
    /debug/traces all answer populated JSON on a live daemon after a
    few requests (the issue's acceptance criterion), and the per-method
    gRPC latency histogram saw every call."""
    import aiohttp

    from gubernator_tpu.config import BehaviorConfig, Config
    from gubernator_tpu.transport.daemon import DaemonClient, spawn_daemon
    from gubernator_tpu.types import RateLimitRequest
    from gubernator_tpu.utils import flightrec

    monkeypatch.setenv("GUBER_DEBUG_ENDPOINTS", "1")
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="127.0.0.1:0",
        peer_discovery_type="none",
    )
    conf.config = Config(behaviors=BehaviorConfig(), cache_size=256)
    d = await spawn_daemon(conf)
    try:
        assert flightrec.enabled()
        client = DaemonClient(d.advertise_address)
        reqs = [RateLimitRequest(name="dbg", unique_key=f"k{i}", hits=1,
                                 limit=10, duration=60000) for i in range(4)]
        for _ in range(3):
            await client.get_rate_limits(reqs)
        await client.close()

        base = f"http://{d.conf.http_listen_address}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/pipeline") as r:
                assert r.status == 200
                pipe = await r.json()
            async with s.get(f"{base}/debug/state") as r:
                assert r.status == 200
                state = await r.json()
            async with s.get(f"{base}/debug/traces") as r:
                assert r.status == 200
                traces = await r.json()

        assert pipe["windows"], pipe
        assert set(pipe["windows"][0]["stages_ms"]) == set(flightrec.STAGES)
        assert "pack" in pipe["stage_percentiles"]
        assert state["ready"] is True
        assert state["occupancy"]
        assert "breakers" in state and "redelivery" in state
        assert traces["tracing_enabled"] is True
        assert traces["count"] > 0 and traces["spans"][0]["trace_id"]
        # Satellite: _StatsInterceptor feeds the RPC latency histogram.
        assert d.metrics.sample(
            "gubernator_tpu_grpc_duration_seconds_count",
            {"method": "/pb.gubernator.V1/GetRateLimits"}) >= 3
    finally:
        await d.close()
    assert not flightrec.enabled()  # close() uninstalled the recorder
