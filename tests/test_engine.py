"""Tick-engine tests: duplicate-key sequencing, ordering, eviction, batching.

These cover what the reference gets from worker-pool serialization
(workers.go:19-37) and LRU eviction (lrucache.go:88-149).
"""

import numpy as np
import pytest

from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status
from tests.helpers import Sim


def req(key="k", hits=1, limit=10, duration=60_000, **kw):
    return RateLimitRequest(
        name="t", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=kw.pop("algorithm", Algorithm.TOKEN_BUCKET), **kw,
    )


def test_duplicate_keys_sequential_semantics():
    # Same key three times in one batch must behave like three sequential
    # requests (Go serializes per key via worker ownership).
    s = Sim()
    rs = s.batch([req(hits=4), req(hits=4), req(hits=4)])
    assert [r.remaining for r in rs] == [6, 2, 2]
    assert [r.status for r in rs] == [
        Status.UNDER_LIMIT, Status.UNDER_LIMIT, Status.OVER_LIMIT,
    ]


def test_duplicate_keys_exhaust_exactly():
    s = Sim()
    rs = s.batch([req(hits=1, limit=3) for _ in range(5)])
    assert [r.remaining for r in rs] == [2, 1, 0, 0, 0]
    assert [r.status for r in rs] == [
        Status.UNDER_LIMIT, Status.UNDER_LIMIT, Status.UNDER_LIMIT,
        Status.OVER_LIMIT, Status.OVER_LIMIT,
    ]


def test_mixed_keys_order_preserved():
    s = Sim()
    reqs = [req(key=f"k{i % 3}", hits=1, limit=100) for i in range(9)]
    rs = s.batch(reqs)
    # Each of k0,k1,k2 is hit 3 times; per-key remaining descends 99,98,97.
    for i in range(9):
        assert rs[i].remaining == 99 - i // 3


def test_duplicate_new_key_in_one_batch():
    # First occurrence creates the bucket; later ones must see it.
    s = Sim()
    rs = s.batch([req(key="fresh", hits=10), req(key="fresh", hits=1)])
    assert rs[0].remaining == 0
    assert rs[0].status == Status.UNDER_LIMIT
    assert rs[1].status == Status.OVER_LIMIT


def test_reset_remaining_then_hit_same_batch():
    s = Sim()
    s.batch([req(hits=10)])
    rs = s.batch([
        req(hits=0, behavior=Behavior.RESET_REMAINING),
        req(hits=3),
    ])
    assert rs[0].remaining == 10
    # Reset removed the item; second request creates a fresh bucket.
    assert rs[1].remaining == 7


def test_chunking_beyond_max_batch():
    s = Sim(capacity=2048, max_batch=32)
    reqs = [req(key=f"k{i}", hits=1, limit=5) for i in range(100)]
    rs = s.batch(reqs)
    assert len(rs) == 100
    assert all(r.remaining == 4 for r in rs)


def test_eviction_reclaims_expired():
    s = Sim(capacity=8, max_batch=8)
    for i in range(8):
        s.batch([req(key=f"k{i}", duration=100)])
    s.advance(200)  # all expired
    rs = s.batch([req(key="new0", duration=100)])
    assert rs[0].remaining == 9
    assert s.engine.cache_size() <= 8


def test_eviction_lru_when_nothing_expired():
    s = Sim(capacity=8, max_batch=8)
    for i in range(8):
        s.batch([req(key=f"k{i}", duration=600_000)])
    rs = s.batch([req(key="overflow", duration=600_000)])
    assert rs[0].remaining == 9
    assert s.engine.metric_unexpired_evictions > 0


def test_reclaim_retry_does_not_corrupt_same_batch_slots():
    # A mid-batch reclaim triggered by the table-full retry must not release
    # slots resolved earlier in the SAME batch: fresh misses look unused on
    # device until the tick lands, and an unstamped reclaim would hand their
    # slots to the retried keys (two keys, one bucket).
    s = Sim(capacity=2, max_batch=8)
    s.batch([req(key="old", duration=10)])  # occupies 1 of 2 slots
    s.advance(1000)                          # "old" expires
    rs = s.batch([
        req(key="A", duration=600_000),      # takes the last free slot
        req(key="B", duration=600_000),      # table full → reclaim → retry
    ])
    assert [r.remaining for r in rs] == [9, 9]
    sa, sb = s.engine.slots.get("t_A"), s.engine.slots.get("t_B")
    assert sa is not None and sb is not None and sa != sb
    rs = s.batch([req(key="A"), req(key="B")])
    assert [r.remaining for r in rs] == [8, 8]


def test_snapshot_roundtrip():
    # Loader.Save/Load analog (workers.go:329-534).
    s = Sim()
    s.batch([req(key="a", hits=3), req(key="b", hits=7)])
    items = s.engine.export_items()
    assert len(items) == 2

    s2 = Sim()
    s2.engine.load_items(items, now=s2.now)
    rs = s2.batch([req(key="a", hits=0), req(key="b", hits=0)])
    assert rs[0].remaining == 7
    assert rs[1].remaining == 3


def test_incremental_export_ships_only_touched():
    """dirty_only export after a baseline: only the keys mutated since
    the last export cross, and a delta loads as upserts over the full
    snapshot (store.go:49-65 OnChange trickle analog)."""
    s = Sim()
    s.batch([req(key=f"k{i}", hits=1) for i in range(16)])
    full = s.engine.export_columns()          # baseline; clears dirty
    assert len(full["key_offsets"]) - 1 == 16

    delta0 = s.engine.export_columns(dirty_only=True)
    assert len(delta0["key_offsets"]) - 1 == 0   # nothing touched since

    s.batch([req(key="k3", hits=2), req(key="k7", hits=5)])
    delta = s.engine.export_columns(dirty_only=True)
    keys = {
        delta["key_blob"][
            delta["key_offsets"][i]:delta["key_offsets"][i + 1]
        ].decode()
        for i in range(len(delta["key_offsets"]) - 1)
    }
    assert keys == {"t_k3", "t_k7"}
    assert s.engine.last_export_stats["partial"] is True

    # Baseline + delta reconstructs the touched keys' exact state.
    s2 = Sim()
    s2.engine.load_columns(full, now=s2.now)
    s2.engine.load_columns(delta, now=s2.now)
    rs = s2.batch([req(key="k3", hits=0), req(key="k7", hits=0),
                   req(key="k0", hits=0)])
    assert rs[0].remaining == 7   # 10 - 1 - 2
    assert rs[1].remaining == 4   # 10 - 1 - 5
    assert rs[2].remaining == 9   # baseline only

    # A second delta is empty again (export reset the dirty set).
    assert len(
        s.engine.export_columns(dirty_only=True)["key_offsets"]) - 1 == 0


def _delta_keys(delta):
    off = delta["key_offsets"]
    return {
        delta["key_blob"][off[i]:off[i + 1]].decode()
        for i in range(len(off) - 1)
    }


def test_query_only_tick_exports_empty_delta():
    """Pure queries (hits == 0 on known slots) read bucket state without
    mutating it — a query-only tick must not inflate the next
    dirty_only delta (advisor finding: read-heavy traffic was marking
    every requested slot)."""
    s = Sim()
    s.batch([req(key=f"q{i}", hits=1) for i in range(8)])
    s.engine.export_columns()                  # baseline; clears dirty
    s.batch([req(key=f"q{i}", hits=0) for i in range(8)])  # queries only
    assert len(
        s.engine.export_columns(dirty_only=True)["key_offsets"]) - 1 == 0


def test_mixed_tick_delta_exports_exactly_mutated_slots():
    """A mixed tick's delta carries exactly the mutated slots: hit rows
    and query-created rows, not pure-query rows."""
    s = Sim()
    s.batch([req(key=f"m{i}", hits=1) for i in range(6)])
    s.engine.export_columns()                  # baseline; clears dirty
    s.batch([
        req(key="m1", hits=2),                 # mutates
        req(key="m2", hits=0),                 # pure query: no mark
        req(key="m3", hits=0),                 # pure query: no mark
        req(key="new", hits=0),                # creates the row: marks
        req(key="m4", hits=0,                  # RESET removes: marks
            behavior=Behavior.RESET_REMAINING),
    ])
    delta = s.engine.export_columns(dirty_only=True)
    # m4's RESET removed the bucket (tokenBucket reset semantics), so
    # the slot is dirty but no longer live — it has no row to export.
    assert _delta_keys(delta) == {"t_m1", "t_new"}

    # The delta applies as an upsert over the baseline and reproduces
    # the mutated keys' state, and the untouched query keys keep their
    # baseline state.
    s2 = Sim()
    s2.engine.load_columns(s.engine.export_columns(), now=s2.now)
    rs = s2.batch([req(key="m1", hits=0), req(key="m2", hits=0),
                   req(key="new", hits=0)])
    assert rs[0].remaining == 7   # 10 - 1 - 2
    assert rs[1].remaining == 9   # baseline only
    assert rs[2].remaining == 10  # created by the query tick


def test_empty_batch():
    s = Sim()
    assert s.batch([]) == []


if __name__ == "__main__":
    pytest.main([__file__, "-q"])


def test_reclaim_frees_enough_for_whole_batch():
    """A batch whose misses exceed the capacity//16 reclaim quantum must
    still land: the retry reclaim sizes itself to the batch's need."""
    from gubernator_tpu.ops.engine import TickEngine
    from gubernator_tpu.types import RateLimitRequest

    now = 1_700_000_000_000
    eng = TickEngine(capacity=256, max_batch=128)

    def req(k):
        return RateLimitRequest(name="n", unique_key=k, hits=1, limit=10,
                                duration=3_600_000)

    for start in (0, 128, 256):  # third batch LRU-evicts 128 > 256//16
        rs = eng.process([req(f"c{start + i}") for i in range(128)], now=now)
        assert all(r.error == "" for r in rs)
    assert eng.metric_unexpired_evictions >= 128
    # Evicted state must not resurrect on slot reuse.
    assert eng.process([req("c0")], now=now)[0].remaining == 9


def test_background_reclaim_keeps_table_under_watermark():
    """With bg_reclaim forced on, sustained insert pressure near capacity
    is absorbed by the reclaimer thread: allocations keep succeeding, LRU
    evictions happen, and the sync fallback path stays available."""
    import time

    from gubernator_tpu.ops.engine import TickEngine
    from gubernator_tpu.types import RateLimitRequest

    now = 1_700_000_000_000
    eng = TickEngine(capacity=512, max_batch=64, bg_reclaim=True)
    try:

        def req(k):
            return RateLimitRequest(name="n", unique_key=k, hits=1,
                                    limit=10, duration=3_600_000)

        # Flood with fresh keys well past capacity.
        for start in range(0, 2048, 64):
            rs = eng.process(
                [req(f"f{start + i}") for i in range(64)], now=now
            )
            assert all(r.error == "" for r in rs)
        # Give the reclaimer a beat, then keep inserting: still no errors.
        time.sleep(0.2)
        rs = eng.process([req(f"tail{i}") for i in range(64)], now=now)
        assert all(r.error == "" for r in rs)
        assert eng.metric_unexpired_evictions > 0
        assert eng.cache_size() <= 512
    finally:
        eng.close()


def test_background_reclaim_no_evictions_without_watermark_pressure():
    """The reclaimer only wakes when free slots dip under the watermark
    AND a batch had misses; a table holding above the watermark never
    evicts, however hot the traffic (the reference evicts on insert
    pressure only, lrucache.go:88-103)."""
    import time

    from gubernator_tpu.ops.engine import TickEngine
    from gubernator_tpu.types import RateLimitRequest

    now = 1_700_000_000_000
    # watermark = min(128//8, max(2*64, 2)) = 16 free slots
    eng = TickEngine(capacity=128, max_batch=64, bg_reclaim=True)
    try:

        def req(k):
            return RateLimitRequest(name="n", unique_key=k, hits=1,
                                    limit=1000, duration=3_600_000)

        fill = [req(f"k{i}") for i in range(100)]  # free = 28 > watermark
        eng.process(fill[:64], now=now)
        eng.process(fill[64:], now=now)
        for t in range(5):  # pure hits on a comfortably-full table
            eng.process(fill[:64], now=now + t)
        time.sleep(0.2)
        assert eng.metric_unexpired_evictions == 0
        assert eng._reclaim_thread is None  # never even started
        assert eng.cache_size() == 100
    finally:
        eng.close()
