"""Golden behavior tests for the token-bucket kernel.

Ported from the reference behavioral spec (functional_test.go:160-470 and
algorithms.go:37-257): same sequences, same expected status/remaining, with
time driven explicitly instead of clock.Freeze/Advance.
"""

import pytest

from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status
from tests.helpers import Sim


def tok(name="t", key="k", hits=1, limit=2, duration=5, **kw):
    kw.setdefault("algorithm", Algorithm.TOKEN_BUCKET)
    return dict(
        name=name, unique_key=key, hits=hits, limit=limit, duration=duration, **kw,
    )


def test_token_bucket_basic():
    # functional_test.go:160 TestTokenBucket: limit=2, duration=5ms.
    s = Sim()
    r = s.hit(**tok())
    assert (r.status, r.remaining, r.limit) == (Status.UNDER_LIMIT, 1, 2)
    assert r.reset_time == s.now + 5

    r = s.hit(**tok())
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)

    s.advance(100)  # past the 5ms window -> fresh bucket
    r = s.hit(**tok())
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 1)


def test_token_bucket_over_limit_then_status_persisted():
    s = Sim()
    assert s.hit(**tok(limit=1)).remaining == 0
    r = s.hit(**tok(limit=1))
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    # Status is persisted into the bucket (algorithms.go:162-169): a Hits=0
    # query now reports OVER_LIMIT.
    r = s.hit(**tok(limit=1, hits=0))
    assert r.status == Status.OVER_LIMIT


def test_token_bucket_negative_hits():
    # functional_test.go:296 TestTokenBucketNegativeHits: negative hits add
    # tokens, even beyond the limit.
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=1))
    assert r.remaining == 9
    r = s.hit(**tok(limit=10, duration=60000, hits=-1))
    assert r.remaining == 10
    r = s.hit(**tok(limit=10, duration=60000, hits=-5))
    assert r.remaining == 15
    assert r.status == Status.UNDER_LIMIT


def test_token_bucket_over_ask_does_not_drain():
    # algorithms.go:29-34 note + functional_test.go:434 over-ask semantics:
    # asking more than remaining rejects but leaves the bucket intact.
    s = Sim()
    r = s.hit(**tok(limit=100, duration=60000, hits=1))
    assert r.remaining == 99
    r = s.hit(**tok(limit=100, duration=60000, hits=1000))
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 99)
    r = s.hit(**tok(limit=100, duration=60000, hits=99))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)


def test_token_bucket_drain_over_limit():
    # functional_test.go:368 TestDrainOverLimit: first over-limit event
    # drains remaining to zero.
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=1,
                    behavior=Behavior.DRAIN_OVER_LIMIT))
    assert r.remaining == 9
    r = s.hit(**tok(limit=10, duration=60000, hits=100,
                    behavior=Behavior.DRAIN_OVER_LIMIT))
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 0)
    r = s.hit(**tok(limit=10, duration=60000, hits=1,
                    behavior=Behavior.DRAIN_OVER_LIMIT))
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 0)


def test_token_bucket_first_request_over_limit():
    # algorithms.go:240-248: Hits > Limit on a brand-new bucket returns
    # OVER_LIMIT but remaining stays at Limit.
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=100))
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 10)
    r = s.hit(**tok(limit=10, duration=60000, hits=5))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 5)


def test_token_bucket_exact_remainder():
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=10))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    r = s.hit(**tok(limit=10, duration=60000, hits=1))
    assert r.status == Status.OVER_LIMIT


def test_token_bucket_limit_change():
    # functional_test.go:1343 TestChangeLimit: remaining adjusts by the
    # limit delta (algorithms.go:106-113).
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=3))
    assert r.remaining == 7
    r = s.hit(**tok(limit=20, duration=60000, hits=0))
    assert (r.limit, r.remaining) == (20, 17)
    r = s.hit(**tok(limit=5, duration=60000, hits=0))
    # 17 + (5-20) = 2
    assert (r.limit, r.remaining) == (5, 2)
    r = s.hit(**tok(limit=1, duration=60000, hits=0))
    # 2 + (1-5) = -2 -> clamp 0
    assert (r.limit, r.remaining) == (1, 0)


def test_token_bucket_duration_change_extends_reset():
    s = Sim()
    r = s.hit(**tok(limit=10, duration=1000, hits=1))
    created = s.now
    assert r.reset_time == created + 1000
    s.advance(500)
    r = s.hit(**tok(limit=10, duration=60000, hits=1))
    # expire recomputed from original CreatedAt (algorithms.go:126)
    assert r.reset_time == created + 60000
    assert r.remaining == 8


def test_token_bucket_duration_change_renews_expired():
    # algorithms.go:134-142: new duration that leaves the bucket already
    # expired renews it: CreatedAt=now, Remaining=Limit... but the
    # *response* remaining reflects the pre-renewal snapshot (quirk).
    s = Sim()
    s.hit(**tok(limit=10, duration=100000, hits=4))
    s.advance(5000)
    r = s.hit(**tok(limit=10, duration=1000, hits=1))
    # expire = created + 1000 = now - 4000 <= now -> renew
    assert r.reset_time == s.now + 1000
    assert r.remaining == 9  # refilled to 10 by the renewal, then -1 hit
    r = s.hit(**tok(limit=10, duration=1000, hits=0))
    assert r.remaining == 9


def test_token_bucket_reset_remaining():
    # functional_test.go:1438 TestResetRemaining.
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=10))
    assert r.remaining == 0
    r = s.hit(**tok(limit=10, duration=60000, hits=0,
                    behavior=Behavior.RESET_REMAINING))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 10)
    assert r.reset_time == 0
    r = s.hit(**tok(limit=10, duration=60000, hits=3))
    assert r.remaining == 7


def test_token_bucket_hits_zero_query_creates_item():
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=0))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 10)
    r = s.hit(**tok(limit=10, duration=60000, hits=0))
    assert r.remaining == 10


def test_token_bucket_algorithm_switch_resets():
    # algorithms.go:92-103: switching algorithms resets hit counts.
    s = Sim()
    r = s.hit(**tok(limit=10, duration=60000, hits=4))
    assert r.remaining == 6
    r = s.hit(**tok(limit=10, duration=60000, hits=1,
                    algorithm=Algorithm.LEAKY_BUCKET))
    assert r.remaining == 9  # fresh leaky bucket
    r = s.hit(**tok(limit=10, duration=60000, hits=1,
                    algorithm=Algorithm.TOKEN_BUCKET))
    assert r.remaining == 9  # fresh token bucket again


def test_token_bucket_expire_resets():
    s = Sim()
    s.hit(**tok(limit=2, duration=100, hits=2))
    s.advance(101)
    r = s.hit(**tok(limit=2, duration=100, hits=1))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 1)


def test_token_bucket_gregorian_minutes():
    # functional_test.go:221 TestTokenBucketGregorian, limit 60/minute.
    from gubernator_tpu.utils.timeutil import gregorian_expiration
    from gubernator_tpu.types import GREGORIAN_MINUTES

    s = Sim()
    g = dict(limit=60, duration=GREGORIAN_MINUTES,
             behavior=Behavior.DURATION_IS_GREGORIAN)
    r = s.hit(**tok(hits=1, **g))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 59)
    assert r.reset_time == gregorian_expiration(s.now, GREGORIAN_MINUTES)
    r = s.hit(**tok(hits=1, **g))
    assert r.remaining == 58
    r = s.hit(**tok(hits=58, **g))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    r = s.hit(**tok(hits=1, **g))
    assert (r.status, r.remaining) == (Status.OVER_LIMIT, 0)
    s.advance(61_000)
    r = s.hit(**tok(hits=0, **g))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 60)


def test_token_bucket_gregorian_weeks():
    # The reference rejects weeks with a TODO (interval.go:132); here the
    # interval is implemented as ISO-8601 weeks (Monday 00:00 start).
    from datetime import datetime

    from gubernator_tpu.types import GREGORIAN_WEEKS
    from gubernator_tpu.utils.timeutil import gregorian_expiration

    s = Sim()
    g = dict(limit=10, duration=GREGORIAN_WEEKS,
             behavior=Behavior.DURATION_IS_GREGORIAN)
    r = s.hit(**tok(hits=4, **g))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 6)
    exp = gregorian_expiration(s.now, GREGORIAN_WEEKS)
    assert r.reset_time == exp
    # Interval ends at a Monday midnight in local time (timeutil uses
    # local time like Go's now.Location()).
    end = datetime.fromtimestamp((exp + 1) / 1000)
    assert end.weekday() == 0
    assert (end.hour, end.minute, end.second) == (0, 0, 0)
    # Same week: the bucket persists.
    s.advance(3_600_000)
    r = s.hit(**tok(hits=6, **g))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 0)
    r = s.hit(**tok(hits=1, **g))
    assert r.status == Status.OVER_LIMIT
    # Next week: fresh allowance.
    s.advance(7 * 86_400_000)
    r = s.hit(**tok(hits=1, **g))
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 9)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
