"""Crash-safe persistence suite (docs/persistence.md).

Covers the snapshot store's corruption contract (truncated tails, flipped
CRC bytes, missing manifests all restore to the last good prefix with the
damage counted, never an exception), delta/compaction mechanics, the
supervised writer's loss bounds, and the service-level lifecycle: kill
-and-restore roundtrips preserving leaky-bucket float level and cold-tier
entries, graceful shutdown's zero-loss final base, /readyz vs /healthz
split, tracked peer teardown, and GLOBAL ownership handoff on ring churn.
"""

import asyncio
import os

import numpy as np

from gubernator_tpu.ops.engine import (
    SNAP_FIELDS,
    TickEngine,
    snapshot_from_items,
)
from gubernator_tpu.persistence import (
    SnapshotStore,
    SnapshotWriter,
    decode_snapshot,
    encode_snapshot,
)
from gubernator_tpu.persistence.snapshot import MANIFEST, _delta_name
from gubernator_tpu.service.instance import InstanceConfig, V1Instance
from gubernator_tpu.types import RateLimitRequest

FAR = 4_000_000_000_000  # expire_at far in the future (epoch ms)


def item(key, remaining=50, remaining_f=0.0, algorithm=0, **kw):
    base = dict(
        key=key, algorithm=algorithm, limit=100, remaining=remaining,
        remaining_f=remaining_f, duration=3_600_000, created_at=1_000,
        updated_at=2_000, burst=100, status=0, expire_at=FAR,
    )
    base.update(kw)
    return base


def snap_of(*items_):
    return snapshot_from_items(list(items_))


def restored_map(result):
    """Replay a RestoreResult's snapshots host-side: key → last row."""
    out = {}
    for snap in result.snapshots:
        offs = snap["key_offsets"]
        for j in range(len(offs) - 1):
            key = bytes(snap["key_blob"][offs[j]: offs[j + 1]]).decode()
            out[key] = {f: snap[f][j] for f in SNAP_FIELDS}
    return out


# ----------------------------------------------------------------------
# SnapshotStore unit coverage
# ----------------------------------------------------------------------
def test_payload_roundtrip(tmp_path):
    snap = snap_of(item("a"), item("b", remaining=7, remaining_f=3.25))
    out = decode_snapshot(encode_snapshot(snap))
    assert out["key_blob"] == snap["key_blob"]
    for f in SNAP_FIELDS:
        np.testing.assert_array_equal(out[f], snap[f])


def test_base_plus_deltas_replay_last_wins(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write_base(snap_of(item("a", remaining=90), item("b")))
    store.append_delta(snap_of(item("a", remaining=80)))
    store.append_delta(snap_of(item("a", remaining=70), item("c")))
    store.close()

    result = SnapshotStore(str(tmp_path)).load()
    assert result.corrupt_records == 0
    assert result.delta_records == 2
    m = restored_map(result)
    assert m["a"]["remaining"] == 70     # last delta wins
    assert set(m) == {"a", "b", "c"}


def test_truncated_delta_tail_restores_prefix(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write_base(snap_of(item("a")))
    store.append_delta(snap_of(item("b", remaining=42)))
    store.append_delta(snap_of(item("c")))
    store.close()
    # Kill -9 mid-append: the final record loses its tail.
    path = tmp_path / _delta_name(1)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 11)

    result = SnapshotStore(str(tmp_path)).load()
    assert result.corrupt_records == 1
    m = restored_map(result)
    assert m["b"]["remaining"] == 42     # prefix survives
    assert "c" not in m                  # torn tail dropped, no exception


def test_flipped_crc_byte_stops_at_corruption(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write_base(snap_of(item("a")))
    store.append_delta(snap_of(item("b")))
    store.append_delta(snap_of(item("c")))
    store.close()
    path = tmp_path / _delta_name(1)
    with open(path, "r+b") as f:
        f.seek(30)                        # inside record 1's payload
        b = f.read(1)
        f.seek(30)
        f.write(bytes([b[0] ^ 0xFF]))

    result = SnapshotStore(str(tmp_path)).load()
    assert result.corrupt_records >= 1
    m = restored_map(result)
    assert "a" in m and "b" not in m and "c" not in m


def test_missing_manifest_scans_for_newest_generation(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write_base(snap_of(item("old")))
    store.append_delta(snap_of(item("x")))
    store.write_base(snap_of(item("new"), item("x")))  # generation 2
    store.close()
    os.unlink(tmp_path / MANIFEST)

    result = SnapshotStore(str(tmp_path)).load()
    assert result.manifest_missing
    assert result.generation == 2
    assert set(restored_map(result)) == {"new", "x"}


def test_corrupt_base_falls_back_to_older_generation(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write_base(snap_of(item("g1")))
    store.close()
    # Manifest pointing at a generation whose base is garbage.
    with open(tmp_path / "base-00000009.snap", "wb") as f:
        f.write(b"\x00" * 64)
    with open(tmp_path / MANIFEST, "w") as f:
        f.write('{"generation": 9, "base": "base-00000009.snap", '
                '"delta": "delta-00000009.log"}')

    result = SnapshotStore(str(tmp_path)).load()
    assert set(restored_map(result)) == {"g1"}
    assert result.corrupt_records >= 1


def test_empty_directory_is_a_fresh_start(tmp_path):
    result = SnapshotStore(str(tmp_path)).load()
    assert result.snapshots == []
    assert result.items == 0
    assert result.corrupt_records == 0


def test_compaction_starts_new_generation_and_retires_old(tmp_path):
    eng = TickEngine(capacity=256, max_batch=64)
    try:
        store = SnapshotStore(str(tmp_path))
        writer = SnapshotWriter(eng, store, interval=60, deltas_per_base=3)
        for i in range(3):
            eng.process([RateLimitRequest(
                name="t", unique_key=f"k{i}", hits=1, limit=100,
                duration=3_600_000,
            )])
            writer.flush()
        # Third flush crossed deltas_per_base: compacted into gen+1.
        assert writer.metric_base_writes == 1
        assert store.delta_records == 0
        names = sorted(os.listdir(tmp_path))
        assert "base-00000001.snap" in names
        assert "base-00000000.snap" not in names  # retired
        result = SnapshotStore(str(tmp_path)).load()
        assert set(restored_map(result)) == {"t_k0", "t_k1", "t_k2"}
    finally:
        eng.close()


def test_writer_carries_failed_deltas(tmp_path, monkeypatch):
    eng = TickEngine(capacity=256, max_batch=64)
    try:
        store = SnapshotStore(str(tmp_path))
        writer = SnapshotWriter(eng, store, interval=60, deltas_per_base=99)
        eng.process([RateLimitRequest(
            name="t", unique_key="k", hits=5, limit=100, duration=3_600_000,
        )])

        def boom(snap):
            raise OSError("disk full")

        monkeypatch.setattr(store, "append_delta", boom)
        writer.flush()  # dirty set drained into the carry, not lost
        assert writer.metric_write_failures == 1
        assert len(writer._carry) == 1
        monkeypatch.undo()
        written = writer.flush()
        assert written == 1 and not writer._carry
        m = restored_map(SnapshotStore(str(tmp_path)).load())
        assert m["t_k"]["remaining"] == 95
    finally:
        eng.close()


# ----------------------------------------------------------------------
# Engine roundtrips: hard kill and cold tier
# ----------------------------------------------------------------------
def test_hard_kill_roundtrip_preserves_float_level_and_cold_tier(tmp_path):
    """One tiered engine, one hard kill: the fsync'd delta + base are all
    that survive (no close), and the restore must keep the leaky bucket's
    float level, token counts, AND the cold tier's overflow entries."""
    now = 1_700_000_000_000
    # Table smaller than the working set: load_columns overflows the
    # tail into the cold tier; exports must carry both tiers.
    eng = TickEngine(capacity=128, max_batch=64, cold_capacity=512)
    try:
        n = 200
        eng.load_columns(snap_of(
            *[item(f"k{i}", remaining=100 - (i % 50)) for i in range(n)]
        ), now=now)
        assert eng.cold_size() > 0
        store = SnapshotStore(str(tmp_path))
        store.write_base(eng.export_columns())
        writer = SnapshotWriter(eng, store, interval=60, deltas_per_base=99)
        eng.process([
            RateLimitRequest(name="tok", unique_key="a", hits=7, limit=100,
                             duration=3_600_000),
            RateLimitRequest(name="lk", unique_key="b", hits=5, limit=100,
                             duration=60_000, algorithm=1),
        ], now=now)
        writer.flush()
        # Hard kill: NO final base, no close — the fsync'd records are
        # all that survive.
        store.close()

        result = SnapshotStore(str(tmp_path)).load()
        m = restored_map(result)
        assert len(m) == n + 2                    # cold entries included
        assert m["k7"]["remaining"] == 93

        eng2 = TickEngine(capacity=256, max_batch=64)
        try:
            for snap in result.snapshots:
                eng2.load_columns(snap, now=now + 10)
            out = eng2.process([
                RateLimitRequest(name="tok", unique_key="a", hits=0,
                                 limit=100, duration=3_600_000),
                RateLimitRequest(name="lk", unique_key="b", hits=0,
                                 limit=100, duration=60_000, algorithm=1),
            ], now=now + 10)
            assert out[0].remaining == 93         # token hits survived
            # Leaky level: 5 hits leaked back ~10ms of a 60s/100 drip —
            # remaining is 95, not a fresh 100.
            assert out[1].remaining == 95
        finally:
            eng2.close()
    finally:
        eng.close()


def test_pre_zoo_snapshot_loads_with_zeroed_zoo_columns():
    """Snapshots written before the algorithm zoo carry no tat/
    prev_count columns; they must load with both zero-filled (fresh
    TAT / empty previous window — docs/algorithms.md) while the legacy
    charge survives, and live zoo state must round-trip the persistence
    codec bit-exactly."""
    now = 1_700_000_000_000
    eng = TickEngine(capacity=128, max_batch=64)
    eng.process([
        RateLimitRequest(name="z", unique_key="g", hits=5, limit=10,
                         duration=1_000, algorithm=3, created_at=now),
        RateLimitRequest(name="z", unique_key="t", hits=7, limit=100,
                         duration=3_600_000, created_at=now),
    ], now=now)
    snap = eng.export_columns()
    assert (snap["tat"] != 0).any()           # live GCRA state exported
    # The npz codec carries the zoo columns unchanged.
    rt = decode_snapshot(encode_snapshot(snap))
    np.testing.assert_array_equal(rt["tat"], snap["tat"])
    np.testing.assert_array_equal(rt["prev_count"], snap["prev_count"])

    legacy = {k: v for k, v in snap.items()
              if k not in ("tat", "prev_count")}
    fresh = TickEngine(capacity=128, max_batch=64)
    fresh.load_columns(legacy, now=now)
    snap2 = fresh.export_columns()
    assert (snap2["tat"] == 0).all()
    assert (snap2["prev_count"] == 0).all()
    out = fresh.process([
        RateLimitRequest(name="z", unique_key="t", hits=0, limit=100,
                         duration=3_600_000, created_at=now),
    ], now=now)
    assert out[0].remaining == 93             # legacy charge survived


# ----------------------------------------------------------------------
# Service lifecycle
# ----------------------------------------------------------------------
def _iconf(tmp_path, **kw):
    # 256 matches the suite's most common engine capacity, so the table
    # programs are compile-cache hits instead of fresh shapes.
    kw.setdefault("cache_size", 256)
    kw.setdefault("tpu_platform", "cpu")
    kw.setdefault("snapshot_dir", str(tmp_path))
    kw.setdefault("snapshot_interval", 0.05)
    return InstanceConfig(**kw)


async def test_restore_increments_corrupt_metric_and_serves(tmp_path):
    store = SnapshotStore(str(tmp_path))
    store.write_base(snap_of(item("t_a", remaining=1)))
    store.append_delta(snap_of(item("t_b", remaining=2)))
    store.close()
    path = tmp_path / _delta_name(1)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)

    inst = await V1Instance.create(_iconf(tmp_path, snapshot_interval=60))
    try:
        assert inst.restore_stats["corrupt_records"] == 1
        assert inst.metrics.sample(
            "gubernator_tpu_snapshot_corrupt_records_total") == 1
        out = await inst.get_rate_limits([RateLimitRequest(
            name="t", unique_key="a", hits=0, limit=100, duration=3_600_000,
        )])
        assert out[0].remaining == 1   # prefix state is live
    finally:
        await inst.close()


class _StubEngine:
    """No device work: this test exercises only peer bookkeeping."""

    def cache_size(self):
        return 0

    def close(self):
        pass


async def test_set_peers_tracks_doomed_peer_shutdowns(tmp_path, caplog):
    from gubernator_tpu.types import PeerInfo

    inst = V1Instance(
        InstanceConfig(cache_size=256, tpu_platform="cpu"),
        engine=_StubEngine(),
    )
    try:
        a = PeerInfo(grpc_address="127.0.0.1:1", is_owner=True)
        b = PeerInfo(grpc_address="127.0.0.1:2")
        inst.conf.advertise_address = "127.0.0.1:1"
        inst.set_peers([a, b])
        doomed = inst.local_picker.get_by_address("127.0.0.1:2")

        async def boom():
            raise RuntimeError("teardown exploded")

        doomed.shutdown = boom
        inst.set_peers([a])  # b removed -> tracked shutdown task
        assert inst._peer_shutdown_tasks
        import logging
        with caplog.at_level(logging.WARNING, logger="gubernator.instance"):
            await inst.close()
        # The failure was logged, not swallowed; nothing left pending.
        assert any("shutdown of removed peer" in r.message
                   for r in caplog.records)
        assert not inst._peer_shutdown_tasks
        assert not inst._transfer_tasks
    finally:
        await inst.close()


async def test_ownership_handoff_and_close_drain(tmp_path):
    """One 3-daemon cluster, two acceptance behaviors:

    (1) set_peers ring swap — owned GLOBAL keys whose new owner is a
    different peer get their accumulated state pushed there; the key
    keeps counting, no reset (ownership_transfer_loss == 0).
    (2) graceful drain — hits still buffered at close() land on the
    owner instead of dying with the process (bounded by drain_timeout).
    The 60s sync window guarantees only the handoff push / close-path
    drain can have delivered anything."""
    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.types import Behavior

    c = await Cluster.start(3, behaviors=BehaviorConfig(
        global_sync_wait=60.0, batch_wait=0.001))
    try:
        name, key = "xfer", "xk"

        def greq(hits):
            return RateLimitRequest(
                name=name, unique_key=key, hits=hits, limit=1000,
                duration=3_600_000, behavior=Behavior.GLOBAL,
            )

        owner = c.find_owning_daemon(name, key)
        oi = c.daemons.index(owner)
        sent = 9
        oc = owner.client()
        for _ in range(sent):
            out = await oc.get_rate_limits([greq(1)])
            assert out[0].error == ""
        await oc.close()
        assert owner.instance.global_mgr._owned  # tracked as owned

        # Ring swap: drop the owner from everyone's peer list (it stays
        # alive — a scale-down/ring-churn event, not a crash).
        new_peers = [p for p in c.peers
                     if p.grpc_address != owner.conf.grpc_listen_address]
        for d in c.daemons:
            d.set_peers(new_peers)

        new_owner = owner.instance.get_peer(f"{name}_{key}")
        assert new_owner is not None and not new_owner.info.is_owner

        await c.wait_for_metric(
            oi, "gubernator_tpu_ownership_transfers_total",
            labels={"result": "pushed"}, timeout=10,
        )

        # The new owner answers from the transferred level — no reset.
        nd = next(d for d in c.daemons
                  if d.conf.grpc_listen_address
                  == new_owner.info.grpc_address)
        nc = nd.client()
        r = (await nc.get_rate_limits([greq(0)]))[0]
        assert 1000 - r.remaining == sent          # transfer loss == 0

        # (2) Buffer hits on the OLD owner (now a non-owner for the
        # key) against the new owner; only its graceful close can
        # deliver them inside the 60s sync window.
        oc2 = owner.client()
        for _ in range(4):
            out = await oc2.get_rate_limits([greq(1)])
            assert out[0].error == ""
        await oc2.close()
        assert owner.instance.global_mgr._hits     # still buffered
        await owner.close()                        # graceful drain

        r = (await nc.get_rate_limits([greq(0)]))[0]
        await nc.close()
        assert 1000 - r.remaining == sent + 4      # drain lost nothing
    finally:
        await c.stop()


async def test_readyz_and_healthcheck_ready_probe(tmp_path, monkeypatch,
                                                  capsys):
    """/readyz splits readiness from /healthz liveness, and the probe
    binary's --ready flag follows it (one daemon serves both checks)."""
    import aiohttp

    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.cmd import healthcheck

    c = await Cluster.start(1, http_gateway=True)
    try:
        d = c.daemons[0]
        addr = d.conf.http_listen_address
        monkeypatch.setenv("GUBER_HTTP_ADDRESS", addr)
        monkeypatch.delenv("GUBER_STATUS_HTTP_ADDRESS", raising=False)
        loop = asyncio.get_running_loop()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://{addr}/readyz") as resp:
                assert resp.status == 200
                assert (await resp.json())["ready"] is True
            assert await loop.run_in_executor(
                None, healthcheck.main, ["--ready"]) == 0
            # Drain: readiness drops to 503 while liveness stays 200.
            d._draining = True
            async with s.get(f"http://{addr}/readyz") as resp:
                assert resp.status == 503
                assert (await resp.json())["draining"] is True
            async with s.get(f"http://{addr}/healthz") as resp:
                assert resp.status == 200
            assert await loop.run_in_executor(
                None, healthcheck.main, ["--ready"]) == 2
            assert "draining" in capsys.readouterr().err
            d._draining = False
    finally:
        await c.stop()
