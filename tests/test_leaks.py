"""Thread/task leak checks — the analog of the reference's goleak gate
(go.mod:25, uber-go/goleak wired into the test suite).

A daemon owns background machinery (tick loop, global manager loops,
discovery pools, gRPC server threads); Close() must tear all of it down.
These tests snapshot live threads before a full daemon lifecycle and
assert nothing survives it.
"""

import asyncio
import threading
import time

from gubernator_tpu.config import Config, DaemonConfig
from gubernator_tpu.transport.daemon import Daemon
from gubernator_tpu.types import RateLimitRequest


def _live_threads():
    return {t for t in threading.enumerate() if t.is_alive()}


def _settle(before, timeout=5.0):
    """Wait for thread count to return to the baseline (thread pools wind
    down asynchronously after loop close)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        extra = {
            t for t in _live_threads() - before
            # grpc's internal poller threads are daemonic singletons that
            # persist for the process (shared channel machinery), matching
            # goleak's standard IgnoreTopFunction allowances.
            if not t.daemon
        }
        if not extra:
            return set()
        time.sleep(0.05)
    return extra


async def test_daemon_close_leaves_no_threads():
    before = _live_threads()
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        config=Config(cache_size=1024),
    )
    d = Daemon(conf)
    await d.start()
    # Exercise the stack so worker/tick machinery actually spins up.
    out = await d.instance.get_rate_limits(
        [RateLimitRequest(name="lk", unique_key="k", hits=1, limit=5,
                          duration=10_000)]
    )
    assert out[0].error == ""
    await d.close()
    extra = _settle(before)
    assert not extra, f"threads leaked past Daemon.close(): {extra}"


async def test_daemon_close_cancels_event_loop_tasks():
    conf = DaemonConfig(
        grpc_listen_address="127.0.0.1:0",
        http_listen_address="",
        config=Config(cache_size=1024),
    )
    d = Daemon(conf)
    await d.start()
    await d.close()
    # Drain one scheduler round, then every task spawned by the daemon
    # (tick loop, global manager, discovery) must be finished.
    await asyncio.sleep(0.1)
    leaked = [
        t for t in asyncio.all_tasks()
        if t is not asyncio.current_task() and not t.done()
    ]
    assert not leaked, f"tasks leaked past Daemon.close(): {leaked}"
