"""Multi-region federation unit tests (docs/federation.md).

The envelope merge discipline is the whole correctness story for the
inter-region exchange: commutative additive deltas + per-channel
sequence dedup means any interleaving with any number of redeliveries
converges to the same totals.  These tests fuzz that claim directly,
then cover the edges around it — the wire frames, the MULTI_REGION
edge validation, and the region scoping of transfer_ownership.
"""

import asyncio
import random

import pytest

from gubernator_tpu.federation.envelope import (
    FederationEnvelope,
    FederationRecord,
    ReceiveLedger,
    merge_records,
)
from gubernator_tpu.types import Behavior, PeerInfo, RateLimitRequest


def _rec(key: str, hits: int, behavior: int = 0) -> FederationRecord:
    return FederationRecord(
        name="fed", unique_key=key, hits=hits, limit=1000,
        duration=60_000, behavior=behavior,
    )


# ----------------------------------------------------------------------
# Envelope merge: commutative + idempotent (the tentpole's core claim)
# ----------------------------------------------------------------------
def test_envelope_merge_commutes_and_dedups_fuzz():
    """Random envelope streams from several origins, applied in random
    interleavings with random duplicate redeliveries, must all converge
    to the same per-key totals — the exact sum of every origin's deltas,
    each counted once."""
    rng = random.Random(20260807)
    origins = ["node-a:81", "node-b:81", "node-c:81"]
    keys = [f"k{i}" for i in range(6)]

    # Each origin emits a numbered stream of envelopes (seq from 1, the
    # sender discipline).
    streams = {}
    expected = {k: 0 for k in keys}
    for origin in origins:
        envs = []
        for seq in range(1, rng.randint(4, 9)):
            records = [
                _rec(k, rng.randint(1, 7))
                for k in rng.sample(keys, rng.randint(1, len(keys)))
            ]
            for r in records:
                expected[r.unique_key] += r.hits
            envs.append(FederationEnvelope(
                origin=origin, region="us", seq=seq, records=records))
        streams[origin] = envs

    for trial in range(20):
        # Interleave: in-order per channel (the sender never advances seq
        # without an ack) but arbitrary across channels, with duplicates
        # injected anywhere at or below the already-delivered seq.
        cursors = {o: 0 for o in origins}
        ledger = ReceiveLedger()
        totals = {k: 0 for k in keys}

        def apply(env):
            if not ledger.admit(env):
                return
            for r in env.records:
                totals[r.unique_key] += r.hits

        while any(cursors[o] < len(streams[o]) for o in origins):
            o = rng.choice(origins)
            if cursors[o] < len(streams[o]):
                apply(streams[o][cursors[o]])
                cursors[o] += 1
            # Random redelivery of an already-delivered envelope on a
            # random channel (the lost-ack case).
            if rng.random() < 0.5:
                od = rng.choice(origins)
                if cursors[od]:
                    apply(streams[od][rng.randrange(cursors[od])])

        assert totals == expected, f"trial {trial} diverged"
        for o in origins:
            assert ledger.last(o) == len(streams[o])


def test_record_merge_last_writer_config_and_reset_or():
    a = _rec("k", 3)
    b = _rec("k", 4, behavior=int(Behavior.RESET_REMAINING))
    b.limit = 77
    a.merge(b)
    assert a.hits == 7
    assert a.limit == 77  # config is last-writer-wins
    assert a.behavior & int(Behavior.RESET_REMAINING)  # sticky OR
    a.merge(_rec("k", 1))
    assert a.hits == 8
    assert a.behavior & int(Behavior.RESET_REMAINING)  # never cleared


def test_ledger_failed_apply_admits_retry():
    """mark() is separate from seen() so an apply that dies mid-flight
    leaves the seq unmarked — the sender's retry of the SAME envelope
    must be admitted, not treated as a duplicate."""
    led = ReceiveLedger()
    env = FederationEnvelope(origin="o:1", seq=1, records=[_rec("k", 2)])
    assert not led.seen(env)   # first delivery: apply...
    # ...apply fails; mark() never runs; the retry is admitted:
    assert not led.seen(env)
    led.mark(env)              # retry succeeds
    assert led.seen(env)       # third delivery (lost ack): no-op
    assert led.last("o:1") == 1


def test_ledger_sender_restart_opens_fresh_epoch():
    """A restarted sender reuses its advertise address but numbers a
    fresh stream from seq 1 under a new epoch — the ledger must admit
    it (keying by origin alone would silently drop every envelope of
    the new incarnation as a 'duplicate' of the old one's sequences),
    while a straggler redelivery from the dead incarnation still
    dedups."""
    led = ReceiveLedger()
    old = FederationEnvelope(
        origin="o:1", epoch="boot-1", seq=7, records=[_rec("k", 2)])
    assert led.admit(old)
    assert led.last("o:1", "boot-1") == 7

    reborn = FederationEnvelope(
        origin="o:1", epoch="boot-2", seq=1, records=[_rec("k", 3)])
    assert not led.seen(reborn)   # NOT a duplicate despite seq 1 <= 7
    assert led.admit(reborn)
    assert led.last("o:1", "boot-2") == 1

    # Old-epoch straggler (delayed retry of the dead process): no-op.
    assert led.seen(old)
    # And the new epoch keeps its own ordering.
    assert led.seen(reborn)


def test_merge_records_bounds_distinct_keys_not_hits():
    """A full pending buffer drops NEW keys only — tracked keys always
    absorb their delta, so a long partition loses nothing for keys
    already buffered."""
    into = {}
    merged, dropped = merge_records(
        into, [_rec("a", 1), _rec("b", 1)], limit=2)
    assert (merged, dropped) == (2, 0)
    merged, dropped = merge_records(
        into, [_rec("a", 5), _rec("c", 1)], limit=2)
    assert (merged, dropped) == (1, 1)
    assert into["fed_a"].hits == 6
    assert "fed_c" not in into


# ----------------------------------------------------------------------
# Wire frames (pure-Python struct codecs; transport/fastwire.py)
# ----------------------------------------------------------------------
def test_federation_wire_roundtrip():
    from gubernator_tpu.federation.envelope import FederationAck
    from gubernator_tpu.transport import fastwire

    env = FederationEnvelope(
        origin="10.0.0.1:81", region="eu", epoch="b00t00000001", seq=42,
        records=[
            _rec("k1", 3),
            FederationRecord(name="Ω≈", unique_key="ключ", hits=-2,
                             limit=2 ** 62, duration=1,
                             algorithm=1, behavior=10, burst=7,
                             created_at=123456789),
        ],
    )
    back = fastwire.parse_federation_envelope(
        fastwire.encode_federation_envelope(env))
    assert back == env

    ack = FederationAck(origin="10.0.0.1:81", seq=42, applied=2)
    assert fastwire.parse_federation_ack(
        fastwire.encode_federation_ack(ack)) == ack

    # Malformed frames parse to None, never raise.
    data = fastwire.encode_federation_envelope(env)
    assert fastwire.parse_federation_envelope(b"") is None
    assert fastwire.parse_federation_envelope(b"XXXX" + data[4:]) is None
    assert fastwire.parse_federation_envelope(data[:-1]) is None
    assert fastwire.parse_federation_envelope(data + b"\0") is None
    assert fastwire.parse_federation_ack(data) is None
    assert fastwire.parse_federation_ack(b"GFA1\x01") is None


# ----------------------------------------------------------------------
# MULTI_REGION at the edge
# ----------------------------------------------------------------------
def test_multi_region_is_special_on_both_decode_paths():
    """MULTI_REGION items must route through the object path (where the
    edge validation lives) on the protobuf path and the native wire fast
    path alike."""
    from gubernator_tpu.pb import gubernator_pb2 as pb
    from gubernator_tpu.transport import convert, fastwire

    ms = [pb.RateLimitReq(name="mr", unique_key="k", hits=1,
                          behavior=int(Behavior.MULTI_REGION))]
    _, errors, special = convert.columns_from_pb(ms)
    assert not errors and special

    if fastwire.load() is not None:
        data = pb.GetRateLimitsReq(requests=ms).SerializeToString()
        out = fastwire.parse_req(data)
        assert out is not None
        _, errors, special = out
        assert not errors and special


def test_multi_region_rejected_per_item_without_federation():
    """A node that cannot federate rejects MULTI_REGION items per-item
    (never silently serving region-local answers forever); other items
    in the batch still serve."""
    from gubernator_tpu.service.instance import InstanceConfig, V1Instance

    async def run():
        inst = await V1Instance.create(InstanceConfig(cache_size=256))
        try:
            assert inst.federation is None
            out = await inst.get_rate_limits([
                RateLimitRequest(
                    name="mr", unique_key="k", hits=1, limit=10,
                    duration=60_000, behavior=Behavior.MULTI_REGION),
                RateLimitRequest(
                    name="plain", unique_key="k", hits=1, limit=10,
                    duration=60_000),
            ])
            assert "MULTI_REGION requires" in out[0].error
            assert "GUBER_DATA_CENTER" in out[0].error
            assert out[1].error == "" and out[1].remaining == 9
        finally:
            await inst.close()

    asyncio.run(run())


def test_federation_enabled_requires_data_center():
    from gubernator_tpu.config import setup_daemon_config

    with pytest.raises(ValueError, match="GUBER_DATA_CENTER"):
        setup_daemon_config(environ={"GUBER_FEDERATION_ENABLED": "true"})
    conf = setup_daemon_config(environ={
        "GUBER_FEDERATION_ENABLED": "true",
        "GUBER_DATA_CENTER": "us-east-1",
        "GUBER_FEDERATION_INTERVAL": "250ms",
    })
    assert conf.config.federation_enabled
    assert conf.config.federation_interval == 0.25


def test_federation_batch_limit_capped_at_peer_batch_size():
    """A batch limit over MAX_BATCH_SIZE would build envelopes the
    receiver's peer handler rejects outright — a poison message retried
    forever — so config load refuses it."""
    from gubernator_tpu.config import setup_daemon_config
    from gubernator_tpu.types import MAX_BATCH_SIZE

    with pytest.raises(ValueError, match="GUBER_FEDERATION_BATCH_LIMIT"):
        setup_daemon_config(environ={
            "GUBER_FEDERATION_BATCH_LIMIT": str(MAX_BATCH_SIZE + 1),
        })
    with pytest.raises(ValueError, match="GUBER_FEDERATION_BATCH_LIMIT"):
        setup_daemon_config(environ={"GUBER_FEDERATION_BATCH_LIMIT": "0"})


# ----------------------------------------------------------------------
# FederationManager channel discipline (sender-side unit harness)
# ----------------------------------------------------------------------
class _FakeRemotePeer:
    """In-process stand-in for a remote-region PeerClient."""

    def __init__(self, addr, dc="eu"):
        self.info = PeerInfo(grpc_address=addr, datacenter=dc)
        self.received = []
        self.fail = False
        self.ack_offset = 0   # added to the acked seq (negative = stale)

    async def federation_sync(self, env, timeout=None):
        from gubernator_tpu.federation.envelope import FederationAck

        if self.fail:
            raise RuntimeError("wan down")
        self.received.append(env)
        return FederationAck(
            origin=env.origin, seq=env.seq + self.ack_offset,
            applied=len(env.records))


def _region_picker(peers):
    from gubernator_tpu.parallel.hashring import RegionPicker

    picker = RegionPicker()
    for p in peers:
        picker.add(p)
    return picker


def _fake_instance(peers, home="us"):
    from types import SimpleNamespace

    from gubernator_tpu.resilience import ResilienceConfig

    inst = SimpleNamespace(
        conf=SimpleNamespace(
            data_center=home, advertise_address="self:81",
            federation_interval=60.0, federation_batch_limit=1000,
            federation_timeout=0.5, resilience=ResilienceConfig()),
        region_picker=_region_picker(peers),
        applied=[],
    )

    async def apply(reqs):
        inst.applied.append(list(reqs))

    inst.get_peer_rate_limits = apply
    return inst


def _mr_req(key="k", hits=3):
    return RateLimitRequest(
        name="fed", unique_key=key, hits=hits, limit=100,
        duration=60_000)


def test_manager_stale_ack_is_a_send_failure():
    """ack.seq < env.seq (buggy or mixed-version receiver) must count as
    a failed delivery — backoff, failing flag, degraded — not limbo
    where the envelope retries every interval on a 'healthy' channel."""
    from gubernator_tpu.federation.manager import FederationManager

    async def run():
        peer = _FakeRemotePeer("eu-1:81")
        inst = _fake_instance([peer])
        fed = FederationManager(inst, epoch="boot-1")
        try:
            peer.ack_offset = -1   # acks seq-1: stale
            fed.queue(_mr_req())
            await fed._flush_once(force_retry=True)
            (ch,) = fed._channels.values()
            assert ch.failing and ch.inflight is not None
            assert ch.next_try > 0
            assert fed.is_degraded()
            # A correct ack on the retry clears the channel; the retry
            # carried the SAME envelope (same seq).
            peer.ack_offset = 0
            await fed._flush_once(force_retry=True)
            assert ch.inflight is None and not ch.failing
            assert [e.seq for e in peer.received] == [1, 1]
        finally:
            await fed.close()

    asyncio.run(run())


def test_manager_ring_update_reroutes_inflight_to_new_owner():
    """When the target peer leaves its region's ring mid-flight, the
    channel is dropped (no zombie failing flag holding is_degraded) and
    its records requeue and rehash to the new owner — never retried
    against the dead address forever."""
    from gubernator_tpu.federation.manager import FederationManager

    async def run():
        dead = _FakeRemotePeer("eu-1:81")
        dead.fail = True
        inst = _fake_instance([dead])
        fed = FederationManager(inst, epoch="boot-1")
        try:
            fed.queue(_mr_req(hits=3))
            await fed._flush_once(force_retry=True)
            assert fed.inflight_envelopes() == 1 and fed.is_degraded()

            # Ring churn: the owning peer leaves, an heir joins.
            heir = _FakeRemotePeer("eu-2:81")
            inst.region_picker = _region_picker([heir])
            fed.on_ring_update()
            assert fed._channels == {}
            assert not fed.is_degraded()
            assert fed.pending_keys() == 1

            await fed._flush_once(force_retry=True)
            assert [(e.seq, len(e.records)) for e in heir.received] \
                == [(1, 1)]
            assert heir.received[0].records[0].hits == 3
            assert fed.pending_keys() == 0
            assert fed.inflight_envelopes() == 0
        finally:
            await fed.close()

    asyncio.run(run())


def test_manager_ring_update_mid_send_defers_to_rpc_outcome():
    """Ring churn while an envelope RPC is awaiting must not decide for
    the RPC: a send that still succeeds (graceful drain) is delivered —
    requeueing it would double-count — while a send that fails requeues
    for the new owner."""
    from gubernator_tpu.federation.manager import FederationManager
    from gubernator_tpu.parallel.hashring import RegionPicker

    async def run():
        for outcome, want_pending in (("ok", 0), ("fail", 1)):
            gate = asyncio.Event()

            class _SlowPeer(_FakeRemotePeer):
                async def federation_sync(self, env, timeout=None):
                    await gate.wait()
                    if outcome == "fail":
                        raise RuntimeError("died mid-drain")
                    return await super().federation_sync(env, timeout)

            peer = _SlowPeer("eu-1:81")
            inst = _fake_instance([peer])
            fed = FederationManager(inst, epoch="boot-1")
            try:
                fed.queue(_mr_req())
                task = asyncio.ensure_future(
                    fed._flush_once(force_retry=True))
                while not any(
                        ch.sending for ch in fed._channels.values()):
                    await asyncio.sleep(0)
                inst.region_picker = RegionPicker()  # peer leaves
                fed.on_ring_update()
                assert fed._channels == {}
                assert fed.pending_keys() == 0  # decision deferred
                # While the orphaned RPC is unsettled its address is
                # quarantined: a rejoin must not open a second channel
                # racing the in-flight envelope.
                assert "eu-1:81" in fed._orphans
                inst.region_picker = _region_picker([peer])
                fed.queue(_mr_req("other-key"))
                fed._compact("eu", fed._pending["eu"])
                assert fed._channels == {}
                gate.set()
                await task
                assert fed._orphans == {}
                assert fed.pending_keys() == want_pending + 1, outcome
                assert len(peer.received) == (1 if outcome == "ok" else 0)
            finally:
                await fed.close()

    asyncio.run(run())


def test_manager_channel_seq_survives_drop_and_recreate():
    """A peer that leaves and returns gets a channel that CONTINUES the
    per-address sequence — restarting at 1 would collide with the
    receiver's (origin, epoch) ledger and every envelope would be
    deduplicated away."""
    from gubernator_tpu.federation.manager import FederationManager
    from gubernator_tpu.parallel.hashring import RegionPicker

    async def run():
        peer = _FakeRemotePeer("eu-1:81")
        inst = _fake_instance([peer])
        fed = FederationManager(inst, epoch="boot-1")
        try:
            fed.queue(_mr_req())
            await fed._flush_once(force_retry=True)
            assert [e.seq for e in peer.received] == [1]

            inst.region_picker = RegionPicker()   # region vanishes
            fed.on_ring_update()
            assert fed._channels == {}

            inst.region_picker = _region_picker([peer])  # ...and returns
            fed.queue(_mr_req(hits=1))
            await fed._flush_once(force_retry=True)
            assert [e.seq for e in peer.received] == [1, 2]
            assert all(e.epoch == "boot-1" for e in peer.received)
        finally:
            await fed.close()

    asyncio.run(run())


def test_manager_receive_chunks_oversized_envelope():
    """An envelope over the peer batch limit (mixed-version or
    misconfigured sender) applies in chunks instead of becoming a
    poison message whose apply fails on every redelivery."""
    from gubernator_tpu.federation.manager import FederationManager
    from gubernator_tpu.types import MAX_BATCH_SIZE

    async def run():
        inst = _fake_instance([_FakeRemotePeer("eu-1:81")])
        fed = FederationManager(inst, epoch="boot-1")
        try:
            env = FederationEnvelope(
                origin="o:1", region="eu", epoch="e1", seq=1,
                records=[_rec(f"k{i}", 1)
                         for i in range(MAX_BATCH_SIZE + 5)])
            ack = await fed.receive(env)
            assert ack.seq == 1
            assert ack.applied == MAX_BATCH_SIZE + 5
            assert [len(b) for b in inst.applied] == [MAX_BATCH_SIZE, 5]
            assert fed.ledger.seen(env)
        finally:
            await fed.close()

    asyncio.run(run())


# ----------------------------------------------------------------------
# transfer_ownership stays region-scoped (satellite 3 regression)
# ----------------------------------------------------------------------
def test_transfer_ownership_never_pushes_cross_region():
    """Ring churn handoff resolves new owners through the LOCAL picker
    only: accumulated GLOBAL state must never be installed on a
    remote-region peer via raw UpdatePeerGlobals — remote regions
    converge through the envelope stream (docs/federation.md)."""
    from gubernator_tpu.service.instance import InstanceConfig, V1Instance
    from gubernator_tpu.service.peer_client import PeerClient

    async def run():
        self_addr, us_addr, eu_addr = (
            "127.0.0.1:9101", "127.0.0.1:9102", "127.0.0.1:9103")
        inst = await V1Instance.create(InstanceConfig(
            cache_size=256, data_center="us",
            advertise_address=self_addr))
        pushed = []
        orig = PeerClient.update_peer_globals

        async def spy(self, updates):
            pushed.append((self.info.grpc_address, len(updates)))

        PeerClient.update_peer_globals = spy
        try:
            # Seed owner-side accumulated state while standalone.
            for i in range(24):
                r = RateLimitRequest(
                    name="xfer", unique_key=f"k{i}", hits=2, limit=100,
                    duration=60_000, behavior=Behavior.GLOBAL)
                inst.global_mgr._owned[r.hash_key()] = r

            # Ring churn: a second local peer joins, plus a remote-region
            # peer that MUST stay invisible to the handoff.
            inst.set_peers([
                PeerInfo(grpc_address=self_addr, datacenter="us"),
                PeerInfo(grpc_address=us_addr, datacenter="us"),
                PeerInfo(grpc_address=eu_addr, datacenter="eu"),
            ])
            assert [p.info.grpc_address
                    for p in inst.region_picker.peers()] == [eu_addr]

            moved = await inst.global_mgr.transfer_ownership()
            assert moved > 0  # some keys re-hashed to the new local peer
            assert pushed, "no handoff pushes recorded"
            assert all(addr == us_addr for addr, _ in pushed), pushed
        finally:
            PeerClient.update_peer_globals = orig
            await inst.close()

    asyncio.run(run())


# ----------------------------------------------------------------------
# Reshard × federation interlock (docs/resharding.md)
# ----------------------------------------------------------------------
def test_reshard_pauses_federation_no_envelope_from_half_relayout():
    """Regression for the PR 18 × PR 14 interplay: the coordinator's
    freeze pauses the intra-region GLOBAL reconcile but the federation
    flush loop kept compacting envelopes mid-cutover — an envelope built
    then snapshots half-relayouted owner state and exports it to every
    remote region.  Two-region in-process cluster (home ``us``, fake
    ``eu`` owner peer) on a ManualClock: a flush tick firing while the
    engine is mid-relayout must build and send NOTHING; the first tick
    after commit drains every delta accumulated under the pause."""
    import threading

    from gubernator_tpu.parallel.reshard import ReshardCoordinator
    from gubernator_tpu.resilience import ManualClock
    from gubernator_tpu.federation.manager import FederationManager

    async def run():
        clock = ManualClock()
        peer = _FakeRemotePeer("eu-1:81")
        inst = _fake_instance([peer])
        # ManualClock drives the manager's timestamps; the supervised
        # loop keeps the default sleep (the 60 s interval never fires
        # in-test) and the test drives flush ticks explicitly — same
        # discipline as the channel tests above.
        fed = FederationManager(inst, epoch="boot-1", clock=clock)
        in_cutover = threading.Event()
        release = threading.Event()

        class _HalfRelayoutEngine:
            """Engine whose reshard() parks mid-relayout until released
            — the window where owner state is torn."""

            n_shards = 2

            def cache_size(self):
                return 0

            def export_items(self):
                return []

            def reshard(self, new_shards):
                in_cutover.set()
                assert release.wait(5), "test never released the cutover"
                self.n_shards = new_shards
                return {"live_items": 0}

        coord = ReshardCoordinator(_HalfRelayoutEngine(), federation=fed)
        try:
            loop = asyncio.get_running_loop()
            fut = loop.run_in_executor(None, coord.reshard, 4)
            await loop.run_in_executor(None, in_cutover.wait)
            # Owner-side delta lands mid-relayout.  The explicit tick
            # below is exactly what the supervised loop (and the
            # force_retry final flush on the close path) would run: it
            # must not compact or send a single envelope while the
            # cutover holds the pause.
            fed.queue(_mr_req("k1"))
            await fed._flush_once(force_retry=True)
            assert peer.received == []
            assert not fed._channels, "envelope compacted mid-relayout"
            assert fed.pending_keys() == 1  # delta retained, not lost
            release.set()
            assert (await fut)["outcome"] == "committed"
            # After commit the pause lifts and the same tick drains it.
            await fed._flush_once(force_retry=True)
            assert [e.seq for e in peer.received] == [1]
            assert {r.unique_key for r in peer.received[0].records} == {"k1"}
        finally:
            release.set()
            await fed.close()

    asyncio.run(run())


def test_reshard_abort_resumes_federation_sends():
    """An aborted transition must not leave federation paused forever —
    the coordinator's finally block resumes on every exit path."""
    from gubernator_tpu.parallel.reshard import ReshardCoordinator
    from gubernator_tpu.resilience import ManualClock
    from gubernator_tpu.federation.manager import FederationManager

    async def run():
        clock = ManualClock()
        peer = _FakeRemotePeer("eu-1:81")
        inst = _fake_instance([peer])
        fed = FederationManager(inst, epoch="boot-1", clock=clock)

        class _ExplodingEngine:
            n_shards = 2

            def cache_size(self):
                return 0

            def export_items(self):
                return []

            def reshard(self, new_shards):
                raise RuntimeError("relayout OOM (rolled back)")

        coord = ReshardCoordinator(_ExplodingEngine(), federation=fed)
        try:
            fed.queue(_mr_req("k2"))
            loop = asyncio.get_running_loop()
            out = await loop.run_in_executor(None, coord.reshard, 4)
            assert out["outcome"] == "aborted"
            assert not fed._paused
            await fed._flush_once(force_retry=True)
            assert [e.seq for e in peer.received] == [1]
        finally:
            await fed.close()

    asyncio.run(run())
