"""Multi-chip sharded engine tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np
import pytest

from gubernator_tpu.ops import rowtable
from gubernator_tpu.parallel.mesh_engine import MeshTickEngine, make_mesh
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status

NOW = 1_700_000_000_000


@pytest.fixture(scope="module")
def engine():
    mesh = make_mesh(jax.devices())
    return MeshTickEngine(mesh=mesh, local_capacity=128, max_batch=64)


def req(key, hits=1, limit=10, duration=60_000, **kw):
    return RateLimitRequest(
        name="mesh", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=Algorithm.TOKEN_BUCKET, **kw,
    )


def test_sharded_state_persists_across_ticks(engine):
    reqs = [req(str(i)) for i in range(100)]
    out1 = engine.process(reqs, now=NOW)
    assert [r.remaining for r in out1] == [9] * 100
    out2 = engine.process(reqs, now=NOW + 5)
    assert [r.remaining for r in out2] == [8] * 100


@pytest.mark.skipif(
    len(jax.devices()) < 6,
    reason="needs a >=6-shard mesh: the assertions require ~all of 8 "
           "shards populated and 200 keys exceed a small mesh's summed "
           "128-slot shard capacity (GUBER_TEST_TPU runs single-chip)",
)
def test_keys_spread_across_shards(engine):
    engine.process([req(f"spread-{i}") for i in range(200)], now=NOW)
    per_shard = [len(sm) for sm in engine.slots]
    assert sum(per_shard) >= 200
    assert sum(1 for n in per_shard if n > 0) >= 6  # ~all 8 shards populated


def test_over_limit_on_mesh(engine):
    r = req("exhaust", hits=10, limit=10)
    out = engine.process([r], now=NOW)
    assert out[0].remaining == 0
    out = engine.process([req("exhaust", hits=1, limit=10)], now=NOW + 1)
    assert out[0].status == Status.OVER_LIMIT


def test_reclaim_does_not_release_same_batch_slots():
    """Filling a shard then inserting more keys in ONE batch must not
    release slots assigned earlier in that same batch (pre-tick device
    state is stale for them)."""
    mesh = make_mesh(jax.devices()[:1])
    eng = MeshTickEngine(mesh=mesh, local_capacity=4, max_batch=16)
    # Fill the table with short-TTL keys, let them expire.
    eng.process([req(f"old{i}", duration=10) for i in range(4)], now=NOW)
    # One batch: 4 fresh long-lived keys exhaust the shard, then a straw
    # request forces a SECOND mid-batch reclaim whose view of device
    # in_use/expire_at is stale for the 4 slots just assigned.
    fresh = [req(f"new{i}", limit=10, duration=600_000) for i in range(4)]
    straw = [req("straw", limit=10, duration=600_000)]
    eng.process(fresh + straw, now=NOW + 1000)
    out = eng.process(fresh, now=NOW + 2000)
    # The straw's spill tick may LRU-evict at most one fresh key; the
    # pre-fix bug released every same-batch slot → ALL keys reset (=9).
    rems = sorted(r.remaining for r in out if not r.error)
    assert rems in ([8, 8, 8, 8], [8, 8, 8, 9]), out


def test_spill_chunking_beyond_tick_budget():
    mesh = make_mesh(jax.devices()[:2])
    eng = MeshTickEngine(mesh=mesh, local_capacity=512, max_batch=8)
    reqs = [req(f"spill{i}", limit=100) for i in range(100)]  # >> 2*8
    out = eng.process(reqs, now=NOW)
    assert len(out) == 100
    assert all(r.error == "" and r.remaining == 99 for r in out)


def test_mesh_snapshot_roundtrip():
    """Loader.Save/Load over the sharded table (see TickEngine analog)."""
    mesh = make_mesh(jax.devices())
    e1 = MeshTickEngine(mesh=mesh, local_capacity=64, max_batch=64)
    e1.process([req(f"snap{i}", hits=3, limit=9) for i in range(40)], now=NOW)
    items = e1.export_items()
    assert len(items) == 40
    e2 = MeshTickEngine(mesh=mesh, local_capacity=64, max_batch=64)
    e2.load_items(items, now=NOW)
    out = e2.process(
        [req(f"snap{i}", hits=0, limit=9) for i in range(40)], now=NOW
    )
    assert all(r.remaining == 6 for r in out), out


def test_matches_single_device_engine():
    """The sharded tick must agree with the single-chip engine bit-for-bit
    — including same-tick duplicate keys: both engines sequence same-slot
    requests in arrival order (stable slot sorts on both paths), so even
    duplicate-bearing windows must match decision for decision."""
    from gubernator_tpu.ops.engine import TickEngine

    mesh = make_mesh(jax.devices())
    m_eng = MeshTickEngine(mesh=mesh, local_capacity=64, max_batch=64)
    s_eng = TickEngine(capacity=512, max_batch=256)
    rng = np.random.default_rng(7)
    for t in range(6):
        reqs = [
            RateLimitRequest(
                name="cmp",
                unique_key=str(int(rng.integers(0, 40))),
                hits=int(rng.integers(0, 4)),
                limit=20,
                duration=60_000,
                algorithm=int(rng.integers(0, 2)),
            )
            for _ in range(50)
        ]
        if t < 3:
            # Unique-key windows exercise the parts-native program...
            seen, uniq = set(), []
            for r in reqs:
                k = r.hash_key()
                if k not in seen:
                    seen.add(k)
                    uniq.append(r)
            reqs = uniq
        # ...and the rest keep their duplicates (the merge-capable
        # program, arrival-order sequencing across both engines).
        a = m_eng.process(reqs, now=NOW + t * 1000)
        b = s_eng.process(reqs, now=NOW + t * 1000)
        for x, y in zip(a, b):
            assert (x.status, x.remaining, x.reset_time, x.error) == (
                y.status,
                y.remaining,
                y.reset_time,
                y.error,
            )
    # The routed flat format served every window (no silent fallback).
    assert m_eng.metric_routed_windows == 6
    assert m_eng.metric_routed_overflows == 0


@pytest.mark.skipif(
    not rowtable.interpret_supported(),
    reason="Pallas interpret mode cannot lower the row kernels on this "
           "jax build",
)
def test_mesh_row_layout_matches_columns():
    """The Pallas row layout on the sharded mesh (interpret mode on CPU)
    must agree with the column layout decision for decision."""
    row = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=16, table_layout="row"
    )
    col = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=16,
        table_layout="columns",
    )
    assert row.layout == "row" and col.layout == "columns"
    for t in range(3):
        reqs = [req(f"rl{i}", hits=1, limit=7) for i in range(24)]
        a = row.process(reqs, now=NOW + t)
        b = col.process(reqs, now=NOW + t)
        assert [(r.status, r.remaining, r.reset_time) for r in a] == \
               [(r.status, r.remaining, r.reset_time) for r in b]


@pytest.mark.skipif(
    not rowtable.interpret_supported(),
    reason="Pallas interpret mode cannot lower the row kernels on this "
           "jax build",
)
def test_mesh_row_layout_snapshot_roundtrip():
    eng = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=16, table_layout="row"
    )
    eng.process([req(f"snapr{i}", hits=2, limit=9) for i in range(20)], now=NOW)
    items = eng.export_items()
    assert len(items) == 20
    e2 = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=16, table_layout="row"
    )
    e2.load_items(items, now=NOW + 1)
    out = e2.process([req("snapr3", hits=0, limit=9)], now=NOW + 1)[0]
    assert out.remaining == 7


def test_routing_parity_fuzz_vs_host_ring(engine):
    """Device-derived ownership must agree with the host hash ring for
    every served key: the vectorized CRC-32 route, the scalar
    ``_shard_of`` ring, slotmap residency (exactly one shard), and the
    global-slot derivation (``slot // local_capacity``) — the invariant
    the bench mesh rungs export as ``mesh_routing_parity_errors``."""
    rng = np.random.default_rng(11)
    keys = [
        f"parity-{int(rng.integers(0, 1 << 30))}-{'x' * int(rng.integers(0, 40))}"
        for _ in range(120)
    ]
    reqs = [req(k, limit=1000) for k in keys]
    for s in range(0, len(reqs), 60):
        engine.process(reqs[s:s + 60], now=NOW)
    assert engine.routing_parity_errors(
        [r.hash_key() for r in reqs]) == 0


def test_route_function_parity_shard_counts():
    """The vectorized CRC-32 router must be bit-identical to the scalar
    zlib route at every shard count — including 1, odd, prime, and >8
    (no engine builds: this is pure host routing math)."""
    import zlib

    from gubernator_tpu.native import crc32_batch

    rng = np.random.default_rng(13)
    keys = [b"", b"a", b"name_key", bytes(rng.integers(1, 255, 60).astype(np.uint8))] + [
        f"k{int(rng.integers(0, 1 << 40))}".encode() for _ in range(200)
    ]
    blob = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    crcs = crc32_batch(blob, offsets)
    for n_shards in (1, 2, 3, 5, 7, 8, 13):
        vec = (crcs % np.uint32(n_shards)).astype(np.int64)
        ref = [zlib.crc32(k) % n_shards for k in keys]
        assert vec.tolist() == ref, n_shards


def test_ragged_trace_stability_across_widths(engine):
    """One fixed-shape program per batch capacity: the ragged dispatch
    always uploads a (19, max_batch) slab + offsets, so varying the
    OBSERVED window width must never trace a new program (the routed
    path compiled one per width; a signature drift — e.g. a committed
    device_put where warmup used jnp.asarray — re-traces per tick at
    ~0.6 s each).  The ShardedOps trace counters only increment at
    trace time, so they must stay flat across the full width sweep,
    duplicate-bearing windows included."""
    # Unique window, then a duplicate-bearing window: both programs run.
    engine.process([req(f"tr-{i}") for i in range(20)], now=NOW)
    engine.process(
        [req("tr-dup", hits=1) for _ in range(8)]
        + [req(f"tr-{i}") for i in range(8)],
        now=NOW + 1,
    )
    before = dict(engine.ops.trace_counts)
    assert {"tick_ragged", "tick_unique_ragged"} <= set(before)
    # Width sweep 1 → max_batch (64 on the module engine): every width
    # reuses the two warmed programs.
    for t, width in enumerate((1, 7, 16, 33, 48, engine.max_batch)):
        engine.process(
            [req(f"tw-{t}-{i}") for i in range(width)], now=NOW + 2 + t)
        engine.process(
            [req(f"tw-dup-{t}", hits=1) for _ in range(max(1, width // 2))]
            + [req(f"tw-{t}-{i}") for i in range(width // 2)],
            now=NOW + 20 + t,
        )
    assert dict(engine.ops.trace_counts) == before


def test_ragged_skew_window_no_fallback(engine):
    """The adversarial window the routed path used to overflow on —
    every key hashing to ONE shard — is just another ragged extent now:
    one shard's count is the whole batch, the rest are zero, answers
    are exact, and the pinned-zero overflow canary never moves."""
    shard0 = [
        k for k in (f"ov{i}" for i in range(2000))
        if engine._shard_of(f"mesh_{k}") == 0
    ][:40]
    assert len(shard0) == 40
    over0 = engine.metric_routed_overflows
    out = engine.process([req(k, limit=50) for k in shard0], now=NOW)
    assert all(r.error == "" and r.remaining == 49 for r in out)
    # Second tick on the same skewed window: state persisted on-shard.
    out = engine.process([req(k, limit=50) for k in shard0], now=NOW + 1)
    assert all(r.remaining == 48 for r in out)
    assert engine.metric_routed_overflows == over0 == 0


def test_local_width_knob_warns_deprecated():
    """GUBER_MESH_LOCAL_WIDTH / local_width= is dead — the ragged
    dispatch has no per-shard width.  A non-zero value must emit the
    one-time DeprecationWarning and change nothing else."""
    import gubernator_tpu.parallel.mesh_engine as me

    me._LOCAL_WIDTH_WARNED = False
    mesh = make_mesh(jax.devices()[:1])
    with pytest.warns(DeprecationWarning, match="LOCAL_WIDTH"):
        eng = MeshTickEngine(
            mesh=mesh, local_capacity=16, max_batch=8, local_width=4
        )
    assert not hasattr(eng, "local_width")
    out = eng.process([req("lw", limit=9)], now=NOW)
    assert out[0].remaining == 8
    # One-time: the latch keeps a second deprecated build quiet.
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        me._warn_local_width_deprecated()
    assert not caught


def test_ragged_extent_math_shard_counts():
    """Pure-host extent math at every interesting shard count —
    including 1, odd, prime, and >8 (no engine builds): counts sum to
    the live rows, offsets are their exact cumsum, and each shard's
    extent covers precisely its own rows of a slot-sorted batch."""
    from gubernator_tpu.parallel.partition import RaggedExtents

    rng = np.random.default_rng(17)
    for n_shards in (1, 2, 3, 5, 7, 8, 13):
        spec = RaggedExtents(n_shards, 64)
        sh = rng.integers(0, n_shards, 200)
        ok = rng.random(200) < 0.8
        counts = spec.counts(sh, ok)
        assert counts.sum() == ok.sum(), n_shards
        offs = spec.offsets(counts)
        assert offs[0] == 0 and offs[-1] == ok.sum()
        assert (np.diff(offs) == counts).all(), n_shards
        # Sorting live lanes by shard makes each extent exactly that
        # shard's rows — the invariant the on-device walker relies on
        # (global-slot sort implies shard sort: slot // cap ascends).
        sorted_sh = np.sort(sh[ok])
        for s in range(n_shards):
            ext = sorted_sh[offs[s]:offs[s + 1]]
            assert (ext == s).all(), (n_shards, s)
        # All-dead window: zero counts, all-zero offsets (the warmup
        # shape), never an exception.
        zero = spec.counts(sh, np.zeros(200, bool))
        assert (spec.offsets(zero) == 0).all()


def test_ragged_parity_fuzz_vs_single_chip(engine):
    """Randomized ragged-vs-single-chip decision parity on the module
    engine — skewed key mixes, duplicates, mixed algorithms, and an
    adversarial all-rows-on-one-shard window (the regime that used to
    fall back).  Decisions must match bit-for-bit; the overflow canary
    must never move."""
    from gubernator_tpu.ops.engine import TickEngine

    s_eng = TickEngine(capacity=2048, max_batch=64)
    rng = np.random.default_rng(23)
    over0 = engine.metric_routed_overflows
    windows = []
    for t in range(4):
        windows.append([
            RateLimitRequest(
                name="rf", unique_key=f"z{int(rng.zipf(1.4)) % 30}",
                hits=int(rng.integers(0, 3)), limit=40, duration=60_000,
                algorithm=int(rng.integers(0, 2)),
            )
            for _ in range(int(rng.integers(20, 64)))
        ])
    # Adversarial window: every key owned by one shard.
    hot = [
        k for k in (f"rfhot{i}" for i in range(2000))
        if engine._shard_of(f"rf_{k}") == engine.n_shards - 1
    ][:30]
    windows.append([
        RateLimitRequest(name="rf", unique_key=k, hits=1, limit=40,
                         duration=60_000)
        for k in hot
    ])
    for t, reqs in enumerate(windows):
        a = engine.process(reqs, now=NOW + t * 500)
        b = s_eng.process(reqs, now=NOW + t * 500)
        for x, y in zip(a, b):
            assert (x.status, x.remaining, x.reset_time, x.error) == (
                y.status, y.remaining, y.reset_time, y.error)
    assert engine.metric_routed_overflows == over0 == 0


def test_mesh_store_write_and_read_through():
    """Store on the sharded engine: on_change after every mutation,
    get() consulted on miss, remove() on eviction-by-reset."""
    from gubernator_tpu.store import MockStore

    store = MockStore()
    eng = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=16, store=store
    )
    eng.process([req("st1", hits=2, limit=10)], now=NOW)
    assert store.called["OnChange()"] == 1
    item = store.data["mesh_st1"]
    assert item["remaining"] == 8

    # A fresh engine read-throughs the persisted state on miss.
    eng2 = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=16, store=store
    )
    out = eng2.process([req("st1", hits=1, limit=10)], now=NOW + 1)[0]
    assert out.remaining == 7
    assert store.called["Get()"] >= 1


def test_mesh_store_via_instance_config():
    """The service layer no longer refuses Store + mesh shards."""
    import asyncio

    from gubernator_tpu.service.instance import InstanceConfig, V1Instance
    from gubernator_tpu.store import MockStore

    async def run():
        conf = InstanceConfig(
            cache_size=256, tpu_mesh_shards=2, store=MockStore(),
            tpu_max_batch=16,
        )
        inst = await V1Instance.create(conf)
        out = await inst.get_rate_limits([req("svc1", hits=1, limit=5)])
        assert out[0].remaining == 4
        assert conf.store.called["OnChange()"] >= 1
        await inst.close()

    asyncio.run(run())


def test_mesh_store_read_through_for_spilled_rows():
    """A block-overflow spill's fresh slot re-resolves as known=1 on the
    retry tick, but the device never wrote it — persisted state must
    still read-through for those rows."""
    from gubernator_tpu.store import MockStore

    store = MockStore()
    eng = MeshTickEngine(
        mesh=make_mesh(), local_capacity=32, max_batch=2, store=store
    )
    # Four keys that all route to one shard: with max_batch=2, two spill.
    shard0 = [
        k for k in (f"sp{i}" for i in range(200))
        if eng._shard_of(f"mesh_{k}") == 0
    ][:4]
    assert len(shard0) == 4
    for k in shard0:
        store.data[f"mesh_{k}"] = {
            "key": f"mesh_{k}", "algorithm": 0, "limit": 10, "remaining": 3,
            "remaining_f": 0.0, "duration": 60_000, "created_at": NOW,
            "updated_at": 0, "burst": 10, "status": 0,
            "expire_at": NOW + 60_000,
        }
    out = eng.process([req(k, hits=1, limit=10) for k in shard0], now=NOW)
    # Every response reflects the persisted remaining=3 minus this hit —
    # including the two spilled into the retry tick.
    assert [r.remaining for r in out] == [2, 2, 2, 2]


# ----------------------------------------------------------------------
# Elastic live resharding (docs/resharding.md)
# ----------------------------------------------------------------------
def test_layout_transition_spec_shard_counts():
    """Pure-spec n→m remap parity at every interesting shard count —
    including 1, odd, prime, and >8 (no engine builds).  The flat remap
    ``owner*cap_to + local`` must be the identity on global slots (the
    invariant that makes the on-device scatter lossless), owners must be
    ``g // cap_to``, and every live slot must land exactly once."""
    from gubernator_tpu.parallel.partition import plan_transition

    for n_to in (1, 2, 3, 5, 7, 8, 13):
        tr = plan_transition(8, 128, n_to)
        assert tr.cap_to == -(-tr.live_slots // n_to)
        rm = tr.remap()
        assert rm.shape == (tr.live_slots, 3)
        g = np.arange(tr.live_slots)
        assert (rm[:, 0] == g // tr.cap_to).all(), n_to
        assert (rm[:, 1] == g % tr.cap_to).all(), n_to
        # Identity on flat slots == bijection: no loss, no double-serve.
        assert (rm[:, 2] == g).all(), n_to
        assert (rm[:, 0] < n_to).all() and (rm[:, 1] < tr.cap_to).all()


def test_layout_transition_round_trip_identity():
    """8→3→8 must be the identity transition: chaining through ``then``
    threads the live-slot count, so the return leg re-derives the
    original per-shard capacity and the composed remap is ``g → g``."""
    from gubernator_tpu.parallel.partition import plan_transition

    tr = plan_transition(8, 128, 3)
    back = tr.then(8)
    assert back.n_to == 8 and back.cap_to == 128
    assert back.live_slots == tr.live_slots == 8 * 128
    assert (back.remap()[:, 2] == np.arange(back.live_slots)).all()


def test_layout_transition_validation():
    from gubernator_tpu.parallel.partition import plan_transition

    with pytest.raises(ValueError):
        plan_transition(0, 128, 4)
    with pytest.raises(ValueError):
        plan_transition(8, 128, 0)
    with pytest.raises(ValueError):
        plan_transition(8, 0, 4)
    with pytest.raises(ValueError):
        plan_transition(8, 128, 4, live_slots=8 * 128 + 1)


def test_relayout_dispatch_lossless_and_trace_stable(engine):
    """Dispatching the relayout collective (no cutover) must produce a
    flat table carrying every live row with identical state, and must
    not retrace any warmed serving program — the transition runs its own
    per-transition jit, never touching the serving widths."""
    from gubernator_tpu.parallel.partition import plan_transition

    engine.process([req(f"rl-{i}", limit=50) for i in range(40)], now=NOW)
    before = {it["key"]: it for it in engine.export_items()}
    traces = dict(engine.ops.trace_counts)
    tr = plan_transition(engine.n_shards, engine.local_capacity,
                         max(1, engine.n_shards // 2))
    flat = engine._dispatch_relayout(tr)
    items, n_live = engine._transition_items(flat)
    assert n_live == len(before)
    after = {it["key"]: it for it in items}
    assert after.keys() == before.keys()
    for k, it in after.items():
        assert it["remaining"] == before[k]["remaining"], k
        assert it["expire_at"] == before[k]["expire_at"], k
    # Serving still on the old layout, and the relayout dispatch did not
    # retrace any serving-width program (the satellite trace pin).
    out = engine.process([req(f"rl-{i}", limit=50) for i in range(40)],
                         now=NOW + 5)
    assert all(r.error == "" for r in out)
    now_traces = dict(engine.ops.trace_counts)
    now_traces.pop("relayout", None)
    traces.pop("relayout", None)
    assert now_traces == traces


@pytest.mark.slow
def test_mesh_reshard_round_trip_under_state():
    """Full 8→4→8 cutover on a dedicated engine: zero loss, value
    parity, zero routing-parity errors, serving resumes on both sides.
    Slow: each transition builds + warms a fresh shard set."""
    eng = MeshTickEngine(
        mesh=make_mesh(jax.devices()), local_capacity=64, max_batch=64
    )
    reqs = [req(f"rs-{i}", limit=100, duration=3_600_000)
            for i in range(150)]
    for s in range(0, len(reqs), 50):
        eng.process(reqs[s:s + 50], now=NOW)
    keys = sorted(it["key"] for it in eng.export_items())
    info = eng.reshard(4, now=NOW + 10)
    assert info["live_items"] == len(keys) and eng.n_shards == 4
    assert sorted(it["key"] for it in eng.export_items()) == keys
    assert eng.routing_parity_errors(keys) == 0
    out = eng.process(reqs[:20], now=NOW + 20)
    assert all(r.error == "" for r in out)
    info = eng.reshard(8, now=NOW + 30)
    assert info["to_shards"] == 8
    assert sorted(it["key"] for it in eng.export_items()) == keys
    assert eng.routing_parity_errors(keys) == 0
    assert eng.reshard(8, now=NOW + 40)["noop"] is True
