"""Unit-level merge correctness: the sorted tick's unit rounds must be
indistinguishable from pure per-duplicate rank rounds (merge_uniform=
False ground truth) on adversarial duplicate mixtures — RESET rows
inside hot groups, parameter flips, queries, negative hits, unknown
rows — and must do it in rounds proportional to UNITS, not duplicates
(the round-3 6.5 s head-of-line corner).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.buckets import BucketState
from gubernator_tpu.ops.engine import (
    REQ32_INDEX, REQ32_ROWS, make_tick_fn, pack_request_matrix32)
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

NOW = 1_700_000_000_000
CAP = 256


def mk_batch(rng, b, n, hot_frac=0.7, reset_frac=0.1, flip_frac=0.1):
    """Sorted batch with a deep hot group and adversarial interleaves."""
    m = np.zeros((REQ32_ROWS, b), np.int32)
    m[REQ32_INDEX["slot"]] = CAP
    n_hot = int(n * hot_frac)
    slots = np.sort(np.concatenate([
        np.zeros(n_hot, np.int64) + 7,
        rng.choice([s for s in range(CAP) if s != 7], n - n_hot,
                   replace=True),
    ]))
    reqs = []
    for i in range(n):
        p = rng.random()
        behavior = Behavior(0)
        hits = 1
        limit, duration, burst = 50, 30_000, 0
        if p < reset_frac:
            behavior = Behavior.RESET_REMAINING
        elif p < reset_frac + flip_frac:
            # parameter flips break runs without RESET semantics
            hits = int(rng.choice([0, 2, 5, -2]))
            limit = int(rng.choice([50, 51]))
        reqs.append(RateLimitRequest(
            name="u", unique_key=f"k{slots[i]}", hits=hits, limit=limit,
            duration=duration, algorithm=Algorithm(int(rng.integers(0, 2))),
            behavior=behavior, burst=burst, created_at=NOW,
        ))
    known = rng.random(n) < 0.9
    pack_request_matrix32(m, np.arange(n), reqs, slots, known, NOW)
    return m


@pytest.mark.parametrize("seed", [21, 22, 23, 24, 25])
def test_unit_rounds_match_rank_rounds(seed):
    rng = np.random.default_rng(seed)
    b = 256
    merged_tick = jax.jit(make_tick_fn(
        CAP, layout="columns", sorted_input=True,
        compact_resp=True, compact_req=True))
    plain_tick = jax.jit(make_tick_fn(
        CAP, layout="columns", sorted_input=True, merge_uniform=False,
        compact_resp=True, compact_req=True))

    sm = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    sp = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    now = NOW
    for step in range(6):
        m = mk_batch(rng, b, int(rng.integers(16, b)))
        sm, rm = merged_tick(sm, jnp.asarray(m), jnp.int64(now))
        sp, rp = plain_tick(sp, jnp.asarray(m), jnp.int64(now))
        np.testing.assert_array_equal(
            np.asarray(rm), np.asarray(rp), err_msg=f"seed {seed} step {step}")
        for f in sm._fields:
            ma, pa = getattr(sm, f), getattr(sp, f)
            ma = ma if isinstance(ma, tuple) else (ma,)
            pa = pa if isinstance(pa, tuple) else (pa,)
            for x, y in zip(ma, pa):
                np.testing.assert_array_equal(
                    np.asarray(x), np.asarray(y),
                    err_msg=f"seed {seed} step {step} field {f}")
        now += int(rng.choice([0, 500, 3_000, 61_000]))


def test_reset_interleaved_hot_group_unit_count():
    """A ~180-deep hot key split by a handful of RESET rows must fold in
    unit-rounds (one per run), not per-duplicate rounds — the semantic
    outcome must still match rank rounds exactly."""
    b = 256
    n = 200
    m = np.zeros((REQ32_ROWS, b), np.int32)
    m[REQ32_INDEX["slot"]] = CAP
    reqs = []
    slots = np.zeros(n, np.int64) + 3
    for i in range(n):
        behavior = (Behavior.RESET_REMAINING
                    if i in (40, 90, 140) else Behavior(0))
        reqs.append(RateLimitRequest(
            name="u", unique_key="hot", hits=1, limit=500,
            duration=60_000, algorithm=Algorithm.TOKEN_BUCKET,
            behavior=behavior, created_at=NOW,
        ))
    pack_request_matrix32(
        m, np.arange(n), reqs, slots, np.ones(n, bool), NOW)

    merged_tick = jax.jit(make_tick_fn(
        CAP, layout="columns", sorted_input=True,
        compact_resp=True, compact_req=True))
    plain_tick = jax.jit(make_tick_fn(
        CAP, layout="columns", sorted_input=True, merge_uniform=False,
        compact_resp=True, compact_req=True))
    sm = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    sp = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    sm, rm = merged_tick(sm, jnp.asarray(m), jnp.int64(NOW))
    sp, rp = plain_tick(sp, jnp.asarray(m), jnp.int64(NOW))
    np.testing.assert_array_equal(np.asarray(rm), np.asarray(rp))
    # spot-check semantics: first RESET row reports a full bucket and the
    # run after it restarts the countdown
    resp = np.asarray(rm)
    assert resp[2, 40] == 500          # RESET row reports a full bucket
    assert resp[2, 41] == 499          # new item after removal: 500 - 1

def test_expired_head_falls_back_per_row():
    """A fold head whose post-state is instantly expired (created_at far
    in the past) must not fold followers; per-slot sequencing holds."""
    b = 64
    n = 8
    m = np.zeros((REQ32_ROWS, b), np.int32)
    m[REQ32_INDEX["slot"]] = CAP
    old = NOW - 10_000_000
    reqs = [RateLimitRequest(
        name="u", unique_key="k", hits=1, limit=10, duration=1_000,
        algorithm=Algorithm.TOKEN_BUCKET, created_at=old)
        for _ in range(n)]
    pack_request_matrix32(
        m, np.arange(n), reqs, np.zeros(n, np.int64),
        np.ones(n, bool), NOW)
    merged_tick = jax.jit(make_tick_fn(
        CAP, layout="columns", sorted_input=True,
        compact_resp=True, compact_req=True))
    plain_tick = jax.jit(make_tick_fn(
        CAP, layout="columns", sorted_input=True, merge_uniform=False,
        compact_resp=True, compact_req=True))
    sm = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    sp = jax.tree.map(jnp.asarray, BucketState.zeros(CAP))
    sm, rm = merged_tick(sm, jnp.asarray(m), jnp.int64(NOW))
    sp, rp = plain_tick(sp, jnp.asarray(m), jnp.int64(NOW))
    np.testing.assert_array_equal(np.asarray(rm), np.asarray(rp))
