"""Columnar request path: ReqColumns, process_columns, pipelined submit.

The columnar path must be observably identical to the dataclass path —
same decisions, same duplicate-key sequencing, same per-item errors —
because the transport feeds it directly (no per-request objects on the
hot path).
"""

import numpy as np
import pytest

from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.ops.reqcols import CREATED_UNSET, ReqColumns
from gubernator_tpu.types import Behavior, RateLimitRequest

NOW = 1_700_000_000_000


def req(key="k", hits=1, limit=10, duration=60_000, **kw):
    return RateLimitRequest(
        name="t", unique_key=key, hits=hits, limit=limit, duration=duration,
        **kw,
    )


def test_from_requests_columns():
    rs = [
        req("a", hits=2, limit=5, burst=7),
        req("b", algorithm=1, behavior=int(Behavior.DRAIN_OVER_LIMIT),
            created_at=123),
    ]
    c = ReqColumns.from_requests(rs)
    assert len(c) == 2
    assert c.key_bytes(0) == b"t_a" and c.key_bytes(1) == b"t_b"
    assert c.hits.tolist() == [2, 1]
    assert c.burst.tolist() == [7, 0]
    assert c.algorithm.tolist() == [0, 1]
    assert c.behavior.tolist() == [0, int(Behavior.DRAIN_OVER_LIMIT)]
    assert c.created_at.tolist() == [CREATED_UNSET, 123]


def test_slice_and_concat_roundtrip():
    rs = [req(f"k{i}", hits=i + 1) for i in range(10)]
    c = ReqColumns.from_requests(rs)
    a, b = c.slice_chunk(0, 4), c.slice_chunk(4, 10)
    assert a.key_bytes(3) == b"t_k3"
    assert b.key_bytes(0) == b"t_k4"
    back = ReqColumns.concat([a, b])
    assert back.key_blob == c.key_blob
    assert back.key_offsets.tolist() == c.key_offsets.tolist()
    assert back.hits.tolist() == c.hits.tolist()


def test_process_columns_matches_process():
    eng_a = TickEngine(capacity=256, max_batch=64)
    eng_b = TickEngine(capacity=256, max_batch=64)
    rs = [req(f"k{i % 5}", hits=1, limit=7) for i in range(20)]
    expected = eng_a.process(rs, now=NOW)
    rm, errors = eng_b.process_columns(
        ReqColumns.from_requests(rs), now=NOW
    )
    assert not errors
    assert rm[0].tolist() == [r.status for r in expected]
    assert rm[2].tolist() == [r.remaining for r in expected]
    assert rm[3].tolist() == [r.reset_time for r in expected]


def test_multi_chunk_pipeline_serializes_duplicates():
    # Batch wider than max_batch: the same key appears in both chunks and
    # the second chunk must observe the first chunk's decrements even
    # though both ticks are dispatched before either is materialized.
    eng = TickEngine(capacity=128, max_batch=16)
    rs = [req("hot", hits=1, limit=100) for _ in range(40)]
    out = eng.process(rs, now=NOW)
    assert [r.remaining for r in out] == list(range(99, 59, -1))


def test_submit_is_pipelined_across_batches():
    eng = TickEngine(capacity=128, max_batch=32)
    s1 = eng.submit([req("x", hits=3, limit=10)], now=NOW)
    s2 = eng.submit([req("x", hits=4, limit=10)], now=NOW)
    # Resolve out of dispatch order: results must still be sequential.
    r2 = s2.responses()[0]
    r1 = s1.responses()[0]
    assert r1.remaining == 7
    assert r2.remaining == 3


def test_gregorian_error_rows_in_columns():
    eng = TickEngine(capacity=64, max_batch=32)
    rs = [
        req("ok", hits=1),
        req("bad", hits=1, duration=99,
            behavior=int(Behavior.DURATION_IS_GREGORIAN)),
        req("ok2", hits=1),
    ]
    rm, errors = eng.process_columns(ReqColumns.from_requests(rs), now=NOW)
    assert list(errors) == [1]
    assert rm[2, 0] == 9 and rm[2, 2] == 9


def test_columns_store_requires_refs():
    from gubernator_tpu.store import MockStore

    eng = TickEngine(capacity=64, max_batch=32, store=MockStore())
    cols = ReqColumns.from_requests([req("s1")])  # no refs kept
    with pytest.raises(ValueError, match="keep_refs"):
        eng.process_columns(cols, now=NOW)
    # With refs the store path works.
    cols = ReqColumns.from_requests([req("s1")], keep_refs=True)
    rm, errors = eng.process_columns(cols, now=NOW)
    assert not errors and rm[2, 0] == 9


def test_resolve_blob_matches_resolve_batch():
    from gubernator_tpu.ops.engine import make_slot_map

    sm = make_slot_map(32)
    keys = [b"alpha", b"beta", b"alpha", b"g"]
    blob = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    s1, k1 = sm.resolve_blob(blob, offsets)
    assert k1.tolist() == [0, 0, 1, 0]  # third is a repeat of "alpha"
    assert s1[0] == s1[2]
    s2, k2 = sm.resolve_batch(keys)
    assert s2.tolist() == s1.tolist()
    assert k2.tolist() == [1, 1, 1, 1]


def test_tickloop_mixes_object_and_columnar_windows():
    """Object and columnar submissions in one window coalesce, resolve in
    one transfer, and each waiter gets its own kind of result."""
    from gubernator_tpu.service.tickloop import TickLoop

    eng = TickEngine(capacity=256, max_batch=64)
    loop = TickLoop(eng, batch_wait=0.05, batch_limit=1000)
    try:
        obj_fut = loop.submit([req("mix", hits=2, limit=10)])
        col_fut = loop.submit_columns(
            ReqColumns.from_requests([req("mix", hits=3, limit=10)])
        )
        obj_out = obj_fut.result(timeout=10)
        mat, errors = col_fut.result(timeout=10)
        assert not errors
        # Same key, same window: the two submissions serialized (object
        # windows dispatch before columnar ones within a flush).
        remains = sorted([obj_out[0].remaining, int(mat[2, 0])])
        assert remains == [5, 8]
    finally:
        loop.close()
        eng.close()


def test_handle_limit_snapshot_survives_caller_mutation():
    """The compact response reconstructs the limit echo from the request
    columns; the handle must snapshot them — callers reuse their buffers
    between submit and resolve (the pipelining pattern)."""
    eng = TickEngine(capacity=64, max_batch=32)
    cols = ReqColumns.from_requests([req("lim", hits=1, limit=100)])
    h = eng.submit_columns(cols, now=NOW)
    cols.limit[:] = 777  # caller rewrites its buffer before resolving
    rm, errors = h.result()
    assert not errors
    assert rm[1, 0] == 100  # the limit at submit time, not 777
    eng.close()


def test_tickloop_pipeline_depth_env_read_at_init(monkeypatch):
    """GUBER_TICK_PIPELINE_DEPTH must take effect at TickLoop
    construction — the old import-time read froze the knob for the
    process, so config changes and tests silently saw the stale
    value."""
    from gubernator_tpu.service.tickloop import TickLoop

    class _NoEngine:  # never flushed: submit is never called
        pass

    monkeypatch.setenv("GUBER_TICK_PIPELINE_DEPTH", "7")
    loop = TickLoop(_NoEngine())
    try:
        assert loop.pipeline_depth == 7
        assert loop._resolve_q.maxsize == 7
    finally:
        loop.close()

    monkeypatch.setenv("GUBER_TICK_PIPELINE_DEPTH", "2")
    loop = TickLoop(_NoEngine())
    try:
        assert loop.pipeline_depth == 2  # no re-import needed
    finally:
        loop.close()

    # Explicit constructor arg beats the environment; junk falls back.
    loop = TickLoop(_NoEngine(), pipeline_depth=3)
    try:
        assert loop.pipeline_depth == 3
    finally:
        loop.close()
    monkeypatch.setenv("GUBER_TICK_PIPELINE_DEPTH", "not-an-int")
    loop = TickLoop(_NoEngine())
    try:
        assert loop.pipeline_depth == 4
    finally:
        loop.close()
