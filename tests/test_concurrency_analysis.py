"""guberlint v2 (analysis/callgraph.py + analysis/concurrency.py):
call-graph resolution and the interprocedural concurrency rules
G007-G010, each fixture shaped like the shipped bug its rule encodes.

Deliberately jax-free, like test_static_analysis.py: everything here is
AST walking over tiny fixture projects.
"""

from __future__ import annotations

import os
import textwrap

from gubernator_tpu.analysis import load_project, run_project
from gubernator_tpu.analysis.callgraph import CallGraph

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MINI_CONFIG = 'ENV_REGISTRY = {\n    "GUBER_GOOD_KNOB": "a knob",\n}\n'
MINI_CONF = "# GUBER_GOOD_KNOB=1\n"


def make_project(tmp_path, files):
    pkg = tmp_path / "pkg"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "utils" / "__init__.py").write_text("")
    (pkg / "config.py").write_text(MINI_CONFIG)
    (tmp_path / "example.conf").write_text(MINI_CONF)
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return load_project(str(tmp_path), "pkg")


def findings(tmp_path, files, rule):
    return run_project(make_project(tmp_path, files), rule_ids=[rule]).findings


# ----------------------------------------------------------------------
# Call graph resolution
# ----------------------------------------------------------------------
def test_callgraph_resolves_methods_functions_and_nested_defs(tmp_path):
    proj = make_project(tmp_path, {
        "a.py": """\
        def helper():
            return 1

        def outer():
            def inner():
                return helper()
            return inner()

        class C:
            def run(self):
                return self.step()

            def step(self):
                return helper()
        """,
    })
    cg = CallGraph.of(proj)
    run = cg.functions["pkg.a.C.run"]
    assert [c.qname for c, _ln in cg.edges(run)] == ["pkg.a.C.step"]
    step = cg.functions["pkg.a.C.step"]
    assert [c.qname for c, _ln in cg.edges(step)] == ["pkg.a.helper"]
    inner = cg.functions["pkg.a.outer.<locals>.inner"]
    assert [c.qname for c, _ln in cg.edges(inner)] == ["pkg.a.helper"]
    outer = cg.functions["pkg.a.outer"]
    assert [c.qname for c, _ln in cg.edges(outer)] == [
        "pkg.a.outer.<locals>.inner"]


def test_callgraph_resolves_aliased_and_from_imports(tmp_path):
    proj = make_project(tmp_path, {
        "lib.py": "def work():\n    return 1\n",
        "user1.py": "import pkg.lib as l\n\ndef f():\n    return l.work()\n",
        "user2.py": "from pkg.lib import work\n\ndef g():\n    return work()\n",
    })
    cg = CallGraph.of(proj)
    for fn in ("pkg.user1.f", "pkg.user2.g"):
        assert [c.qname for c, _ln in cg.edges(cg.functions[fn])] == [
            "pkg.lib.work"], fn


def test_callgraph_dynamic_dispatch_resolves_to_nothing(tmp_path):
    """A callable behind an un-inferable attribute produces NO edge —
    the documented best-effort contract that keeps the transitive rules
    free of dynamic-dispatch false positives."""
    proj = make_project(tmp_path, {
        "a.py": """\
        class C:
            def __init__(self, cb):
                self.cb = cb

            def run(self):
                return self.cb()
        """,
    })
    cg = CallGraph.of(proj)
    assert cg.edges(cg.functions["pkg.a.C.run"]) == []


def test_callgraph_infers_self_attr_types_from_ctor(tmp_path):
    proj = make_project(tmp_path, {
        "a.py": """\
        import queue

        class C:
            def __init__(self):
                self._q = queue.Queue()
        """,
    })
    cg = CallGraph.of(proj)
    assert cg.classes["pkg.a.C"].attr_types["_q"] == "queue.Queue"


# ----------------------------------------------------------------------
# G007 — blocking call under a held lock (transitive)
# ----------------------------------------------------------------------
G007_DIRECT = """\
import threading
import time

class Store:
    def __init__(self):
        self._lock = threading.Lock()

    def sink(self):
        with self._lock:
            time.sleep(0.1)
"""


def test_g007_direct_blocking_under_lock(tmp_path):
    out = findings(tmp_path, {"mod.py": G007_DIRECT}, "G007")
    assert len(out) == 1
    assert "time.sleep" in out[0].message
    assert "Store._lock" in out[0].message


def test_g007_transitive_through_helper_chain(tmp_path):
    src = """\
    import threading

    def read_file(path):
        with open(path, "rb") as f:
            return f.read()

    def load(path):
        return read_file(path)

    class Store:
        def __init__(self):
            self._lock = threading.Lock()

        def fetch(self, path):
            with self._lock:
                return load(path)
    """
    out = findings(tmp_path, {"mod.py": src}, "G007")
    assert len(out) == 1
    assert "'load'" in out[0].message and "open" in out[0].message


def test_g007_negatives(tmp_path):
    src = """\
    import threading
    import time

    async def poller():
        pass

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = __import__("queue").Queue()

        def ok_outside(self):
            with self._lock:
                x = 1
            time.sleep(0.1)
            return x

        def ok_lock_method(self, other_lock):
            with self._lock:
                other_lock.acquire()
                other_lock.release()

        def ok_nonblocking_queue(self):
            with self._lock:
                self._q.put_nowait(1)
                self._q.get(block=False)
    """
    assert findings(tmp_path, {"mod.py": src}, "G007") == []


def test_g007_allow_on_primitive_line_covers_all_callers(tmp_path):
    """One allow-comment at the blocking primitive suppresses every
    transitive caller — the shared-helper suppression contract."""
    src = """\
    import threading
    import time

    def backoff():
        # guber: allow-G007(test fixture - deliberate serialized wait)
        time.sleep(0.1)

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def f(self):
            with self._lock:
                backoff()

    class B:
        def __init__(self):
            self._block = threading.Lock()

        def g(self):
            with self._block:
                backoff()
    """
    assert findings(tmp_path, {"mod.py": src}, "G007") == []


# ----------------------------------------------------------------------
# G008 — lock-order cycles
# ----------------------------------------------------------------------
G008_POS = """\
import threading

class Pair:
    def __init__(self):
        self._lock1 = threading.Lock()
        self._lock2 = threading.Lock()

    def ab(self):
        with self._lock1:
            with self._lock2:
                return 1

    def ba(self):
        with self._lock2:
            with self._lock1:
                return 2
"""


def test_g008_inverted_nesting_is_a_cycle(tmp_path):
    out = findings(tmp_path, {"mod.py": G008_POS}, "G008")
    assert len(out) == 1
    assert "Pair._lock1" in out[0].message
    assert "Pair._lock2" in out[0].message


def test_g008_cycle_through_a_call(tmp_path):
    """The inversion hides behind a method call: ab nests directly,
    ba holds lock2 and calls a helper that takes lock1."""
    src = """\
    import threading

    class Pair:
        def __init__(self):
            self._lock1 = threading.Lock()
            self._lock2 = threading.Lock()

        def ab(self):
            with self._lock1:
                with self._lock2:
                    return 1

        def helper(self):
            with self._lock1:
                return 2

        def ba(self):
            with self._lock2:
                return self.helper()
    """
    out = findings(tmp_path, {"mod.py": src}, "G008")
    assert len(out) == 1


def test_g008_consistent_order_is_clean(tmp_path):
    src = """\
    import threading

    class Pair:
        def __init__(self):
            self._lock1 = threading.Lock()
            self._lock2 = threading.Lock()

        def ab(self):
            with self._lock1:
                with self._lock2:
                    return 1

        def also_ab(self):
            with self._lock1:
                with self._lock2:
                    return 2
    """
    assert findings(tmp_path, {"mod.py": src}, "G008") == []


# ----------------------------------------------------------------------
# G009 — unguarded cross-thread shared state
# ----------------------------------------------------------------------
G009_POS = """\
import threading

class Counter:
    def __init__(self):
        self.count = 0
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        self.count += 1

    def snapshot(self):
        return self.count
"""


def test_g009_thread_written_attr_read_unguarded(tmp_path):
    out = findings(tmp_path, {"mod.py": G009_POS}, "G009")
    assert len(out) == 1
    assert "self.count" in out[0].message
    assert "_run" in out[0].message


def test_g009_allow_comment_suppresses(tmp_path):
    src = G009_POS.replace(
        "        self.count += 1",
        "        # guber: allow-g009(test fixture - GIL-atomic int, "
        "one-tick staleness tolerated)\n        self.count += 1",
    )
    res = run_project(make_project(tmp_path, {"mod.py": src}),
                      rule_ids=["G009"])
    assert res.findings == [] and res.suppressed == 1


def test_g009_negatives(tmp_path):
    src = """\
    import queue
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._q = queue.Queue()
            self.guarded = 0
            self.metric_ticks = 0
            self._running = True

        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            with self._lock:
                self.guarded += 1        # both sides guarded
            self._q.put_nowait(1)        # thread-safe type
            self.metric_ticks += 1       # telemetry convention
            self._running = True         # const-only flag writes

        def read(self):
            with self._lock:
                return self.guarded

        def stop(self):
            self._running = False
    """
    assert findings(tmp_path, {"mod.py": src}, "G009") == []


# ----------------------------------------------------------------------
# G010 — deadline taint into supervised background queues
# ----------------------------------------------------------------------
G010_PRELUDE = """\
from pkg.types import Req
from pkg.utils.supervisor import spawn_supervised

class Manager:
    def __init__(self):
        self._updates = {}
        spawn_supervised(self._loop)

    async def _loop(self):
        self._updates.clear()

"""

G010_TYPES = """\
class Req:
    deadline: float = 0.0
    name: str = ""
"""

G010_SUP = "def spawn_supervised(factory):\n    return factory\n"


def _g010_files(method_body: str):
    return {
        "types.py": G010_TYPES,
        "utils/supervisor.py": G010_SUP,
        "mgr.py": G010_PRELUDE + textwrap.indent(
            textwrap.dedent(method_body), "    "),
    }


def test_g010_tainted_store_flags(tmp_path):
    out = findings(tmp_path, _g010_files("""\
    def queue_update(self, req: Req):
        self._updates[req.name] = req
    """), "G010")
    assert len(out) == 1
    assert "deadline" in out[0].message and "_loop" in out[0].message


def test_g010_clone_keeps_taint(tmp_path):
    out = findings(tmp_path, _g010_files("""\
    def queue_update(self, req: Req):
        clone = Req(**vars(req))
        self._updates[req.name] = clone
    """), "G010")
    assert len(out) == 1


def test_g010_cleared_deadline_is_clean(tmp_path):
    out = findings(tmp_path, _g010_files("""\
    def queue_update(self, req: Req):
        clone = Req(**vars(req))
        clone.deadline = None
        self._updates[req.name] = clone
    """), "G010")
    assert out == []


def test_g010_explicit_deadline_kwarg_is_author_decided(tmp_path):
    out = findings(tmp_path, _g010_files("""\
    def queue_update(self, req: Req):
        clone = Req(deadline=None, name=req.name)
        self._updates[req.name] = clone
    """), "G010")
    assert out == []


def test_g010_store_into_undrained_container_is_clean(tmp_path):
    """Containers the supervised loop never touches are not its
    problem — only loop-drained attrs taint."""
    out = findings(tmp_path, _g010_files("""\
    def queue_update(self, req: Req):
        self._elsewhere = {}
        self._elsewhere[req.name] = req
    """), "G010")
    assert out == []


# ----------------------------------------------------------------------
# The repo itself under the new rules
# ----------------------------------------------------------------------
def test_repo_is_clean_under_concurrency_rules():
    """The zero-findings gate, restricted to G007-G010: every real
    finding at rule-introduction time was fixed or reason-suppressed."""
    proj = load_project(REPO_ROOT, "gubernator_tpu")
    res = run_project(proj, rule_ids=["G007", "G008", "G009", "G010"])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
