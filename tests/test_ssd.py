"""SSD slab tier (docs/tiering.md): staged/flushed roundtrips, TTL
drop-on-read, three-tier engine continuity, snapshot interplay, torn
tails, compaction, capacity eviction, and writer backpressure.

Everything runs on tmp_path with tiny capacities and a fixed clock —
the tier's correctness properties don't need big data or wall time.
"""

import threading
import time

import numpy as np
import pytest

from gubernator_tpu.ops.engine import TickEngine
from gubernator_tpu.tiering import SsdStore
from gubernator_tpu.tiering.coldstore import COLD_FIELDS
from gubernator_tpu.types import Algorithm, RateLimitRequest

NOW = 1_700_000_000_000


def req(key, hits=1, limit=10, duration=600_000, **kw):
    return RateLimitRequest(
        name="t", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=kw.pop("algorithm", Algorithm.TOKEN_BUCKET), **kw,
    )


def mkcols(n, expire=NOW + 600_000, base=0):
    cols = {
        f: np.arange(base, base + n, dtype=np.int64) for f in COLD_FIELDS
    }
    cols["remaining_f"] = np.arange(base, base + n, dtype=np.float64)
    cols["expire_at"] = np.full(n, expire, np.int64)
    return cols


def mkeys(n, prefix="k", base=0):
    return [f"{prefix}{base + i}".encode() for i in range(n)]


def ssd_store(tmp_path, **kw):
    kw.setdefault("capacity_bytes", 1 << 20)
    return SsdStore(str(tmp_path / "ssd"), **kw)


# ---------------------------------------------------------------------------
# Roundtrips: staged (pre-flush) and flushed (disk) reads
# ---------------------------------------------------------------------------

def test_roundtrip_staged_then_flushed(tmp_path):
    s = ssd_store(tmp_path)
    try:
        keys = mkeys(4)
        assert s.put_columns(keys, mkcols(4), NOW) == 4
        # Staged batch is readable before the writer lands it.
        pos, cols = s.take_batch([keys[1], b"absent", keys[3]], NOW)
        assert pos.tolist() == [0, 2]
        assert cols["remaining"].tolist() == [1, 3]
        assert cols["remaining_f"].tolist() == [1.0, 3.0]
        # take is a move: the rows are gone now.
        pos, _ = s.take_batch([keys[1]], NOW)
        assert len(pos) == 0
        # The survivors flush to disk and read back from the slab map.
        s.flush()
        assert s.metric_write_batches == 1
        pos, cols = s.take_batch([keys[0], keys[2]], NOW)
        assert pos.tolist() == [0, 1]
        assert cols["remaining"].tolist() == [0, 2]
        assert len(s) == 0
    finally:
        s.close()


def test_put_supersedes_and_reopen_is_last_wins(tmp_path):
    s = ssd_store(tmp_path)
    try:
        s.put_columns([b"dup"], mkcols(1, base=1), NOW)
        s.flush()
        s.put_columns([b"dup"], mkcols(1, base=2), NOW)
        s.flush()
        assert len(s) == 1
    finally:
        s.close()
    # Reopen replays both records; the newer row wins.
    s2 = ssd_store(tmp_path)
    try:
        assert len(s2) == 1
        pos, cols = s2.take_batch([b"dup"], NOW)
        assert pos.tolist() == [0] and cols["remaining"][0] == 2
        assert s2.metric_corrupt_records == 0
    finally:
        s2.close()


def test_pre_zoo_slabs_and_puts_zero_fill_zoo_columns(tmp_path):
    """Slab records and put_columns batches written before the algorithm
    zoo carry no tat/prev_count; both must read back zero-filled (fresh
    TAT / empty previous window — docs/algorithms.md), not KeyError."""
    import io

    from gubernator_tpu.tiering.ssd import _decode_batch

    # A pre-zoo slab payload: the npz encoding minus the zoo fields.
    keys = mkeys(3)
    blob = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    enc = {"key_blob": np.frombuffer(blob, np.uint8),
           "key_offsets": offsets}
    for f in COLD_FIELDS:
        if f in ("tat", "prev_count"):
            continue
        enc[f] = np.arange(3, dtype=np.float64 if f == "remaining_f"
                           else np.int64)
    buf = io.BytesIO()
    np.savez(buf, **enc)
    got_keys, cols = _decode_batch(buf.getvalue())
    assert got_keys == keys
    assert (cols["tat"] == 0).all()
    assert (cols["prev_count"] == 0).all()
    assert cols["remaining"].tolist() == [0, 1, 2]

    # A pre-zoo demote batch (caller built its dict before the zoo):
    # put_columns zero-fills the missing fields before staging.
    s = ssd_store(tmp_path)
    try:
        legacy = {f: v for f, v in mkcols(2).items()
                  if f not in ("tat", "prev_count")}
        assert s.put_columns(mkeys(2, "pz"), legacy, NOW) == 2
        s.flush()
        pos, out = s.take_batch(mkeys(2, "pz"), NOW)
        assert pos.tolist() == [0, 1]
        assert (out["tat"] == 0).all()
        assert (out["prev_count"] == 0).all()
        assert out["remaining"].tolist() == [0, 1]
    finally:
        s.close()


def test_zoo_state_survives_three_tier_roundtrip(tmp_path):
    """A GCRA bucket demoted through cold→SSD and promoted back keeps
    its theoretical arrival time: the rate smoothing survives tiering."""
    e = TickEngine(capacity=2, max_batch=8, cold_capacity=2,
                   ssd=ssd_store(tmp_path))
    try:
        # limit=10/1000ms -> T=100, tau=900: a full burst pins tat at
        # NOW+1000, so the very next hit only conforms after one T.
        r = e.process([req("g", hits=10, duration=1_000,
                           algorithm=Algorithm.GCRA)], now=NOW)[0]
        assert r.remaining == 0
        # Push the bucket out of the device table and the cold tier.
        for i in range(8):
            e.process([req(f"fill{i}")], now=NOW)
        e.ssd.flush()
        assert len(e.ssd) > 0
        # Promoted back: still OVER until NOW+100, conforms at NOW+100.
        r = e.process([req("g", hits=1, duration=1_000,
                           algorithm=Algorithm.GCRA)], now=NOW + 50)[0]
        assert r.status == 1 and r.reset_time == NOW + 100
        r = e.process([req("g", hits=1, duration=1_000,
                           algorithm=Algorithm.GCRA)], now=NOW + 100)[0]
        assert r.status == 0
    finally:
        e.close()


def test_ttl_drop_on_read(tmp_path):
    s = ssd_store(tmp_path)
    try:
        s.put_columns([b"short"], mkcols(1, expire=NOW + 50), NOW)
        s.put_columns([b"long"], mkcols(1), NOW)
        s.flush()
        pos, _ = s.take_batch([b"short", b"long"], NOW + 100)
        assert pos.tolist() == [1]  # expired row dropped, index-only
        assert s.metric_expired == 1
        assert len(s) == 0
        # Already-expired rows never even stage.
        assert s.put_columns([b"dead"], mkcols(1, expire=NOW - 1), NOW) == 0
    finally:
        s.close()


def test_store_protocol_item_fallbacks(tmp_path):
    s = ssd_store(tmp_path)
    try:
        item = {"key": "t_a", "algorithm": 0, "limit": 10, "remaining": 7,
                "remaining_f": 7.0, "duration": 600_000, "created_at": NOW,
                "updated_at": NOW, "burst": 10, "status": 0,
                "expire_at": NOW + 600_000}
        s.on_change(None, item)
        got = s.get(req("a"))
        assert got is not None and got["remaining"] == 7
        assert len(s) == 1  # get() peeks, never removes
        s.remove("t_a")
        assert s.get(req("a")) is None and len(s) == 0
    finally:
        s.close()


def test_constructor_validation(tmp_path):
    with pytest.raises(ValueError):
        SsdStore(str(tmp_path / "x"), capacity_bytes=0)
    with pytest.raises(ValueError):
        SsdStore(str(tmp_path / "x"), compact_ratio=0.0)
    with pytest.raises(ValueError):
        SsdStore(str(tmp_path / "x"), queue_depth=0)


# ---------------------------------------------------------------------------
# Three-tier engine: hot ↔ cold ↔ SSD continuity
# ---------------------------------------------------------------------------

def test_engine_requires_cold_tier_for_ssd(tmp_path):
    s = ssd_store(tmp_path)
    try:
        with pytest.raises(ValueError):
            TickEngine(capacity=4, max_batch=8, ssd=s)
    finally:
        s.close()


def test_three_tier_churn_keeps_consumed_budget(tmp_path):
    # Working set 4x (hot + cold): every key cycles through the SSD.
    e = TickEngine(capacity=4, max_batch=16, cold_capacity=4,
                   ssd=ssd_store(tmp_path))
    try:
        ws = 32
        for start in range(0, ws, 4):
            rs = e.process(
                [req(f"k{i}", hits=6) for i in range(start, start + 4)],
                now=NOW,
            )
            assert all(r.remaining == 4 for r in rs)
        assert e.ssd.metric_demotions > 0  # cold overflow reached the SSD
        for start in range(0, ws, 4):
            rs = e.process(
                [req(f"k{i}", hits=1) for i in range(start, start + 4)],
                now=NOW + 1,
            )
            assert all(r.remaining == 3 for r in rs), (
                "keys promoted from the SSD must keep their consumed budget"
            )
        assert e.metric_ssd_hits > 0
        # One batched SSD lookup per miss tick, merged into the SAME
        # restore scatter as cold hits — never per-key dispatches.
        assert e.metric_ssd_lookups == e.metric_ssd_miss_ticks
        assert e.metric_promote_dispatches == e.metric_promote_ticks
        # The tick-dispatch block itself never touches the slab store.
        assert e.metric_ssd_tick_path_reads == 0
    finally:
        e.close()


def test_three_tier_preserves_float_level(tmp_path):
    e = TickEngine(capacity=2, max_batch=8, cold_capacity=2,
                   ssd=ssd_store(tmp_path))
    try:
        rs = e.process(
            [req("lk", hits=6, algorithm=Algorithm.LEAKY_BUCKET)], now=NOW
        )
        assert rs[0].remaining == 4
        for i in range(8):  # churn lk through cold and into the SSD
            e.process([req(f"f{i}")], now=NOW)
        rs = e.process(
            [req("lk", hits=1, algorithm=Algorithm.LEAKY_BUCKET)], now=NOW
        )
        assert rs[0].remaining == 3
    finally:
        e.close()


# ---------------------------------------------------------------------------
# Snapshot ↔ tier interplay
# ---------------------------------------------------------------------------

def test_load_columns_overflow_lands_in_ssd_and_roundtrips(tmp_path):
    e = TickEngine(capacity=4, max_batch=8, cold_capacity=64)
    try:
        for i in range(16):
            e.process([req(f"k{i}", hits=i % 8 + 1)], now=NOW)
        snap = e.export_columns()
    finally:
        e.close()
    # Restore into a MUCH smaller pair of RAM tiers: the overflow must
    # land on the SSD, not evaporate.
    e2 = TickEngine(capacity=4, max_batch=8, cold_capacity=4,
                    ssd=ssd_store(tmp_path))
    try:
        e2.load_columns(snap, now=NOW)
        assert e2.ssd.metric_demotions >= 16 - 4 - 4
        assert e2.cache_size() + e2.cold_size() + len(e2.ssd) >= 16
        for i in range(16):
            rs = e2.process([req(f"k{i}", hits=0)], now=NOW)
            assert rs[0].remaining == 10 - (i % 8 + 1), (
                f"k{i} lost its budget through the snapshot→SSD path"
            )
    finally:
        e2.close()


def test_pre_ssd_snapshot_restores_with_empty_tier(tmp_path):
    # Snapshots written before the SSD tier existed carry no slab state;
    # loading one into a three-tier engine must work with an idle SSD.
    e = TickEngine(capacity=8, max_batch=8, cold_capacity=8)
    try:
        for i in range(4):
            e.process([req(f"k{i}", hits=3)], now=NOW)
        snap = e.export_columns()
    finally:
        e.close()
    e2 = TickEngine(capacity=8, max_batch=8, cold_capacity=8,
                    ssd=ssd_store(tmp_path))
    try:
        e2.load_columns(snap, now=NOW)
        e2.ssd.flush()
        assert len(e2.ssd) == 0  # everything fit in the RAM tiers
        for i in range(4):
            assert e2.process(
                [req(f"k{i}", hits=0)], now=NOW
            )[0].remaining == 7
    finally:
        e2.close()


# ---------------------------------------------------------------------------
# Failure modes: torn tail, compaction, capacity, backpressure
# ---------------------------------------------------------------------------

def test_corrupt_slab_tail_stops_at_last_good_record(tmp_path):
    s = ssd_store(tmp_path)
    try:
        s.put_columns(mkeys(2, "good"), mkcols(2), NOW)
        s.flush()
        s.put_columns(mkeys(2, "tail"), mkcols(2, base=5), NOW)
        s.flush()
        path = s._active.path
    finally:
        s.close()
    # Flip one payload byte in the tail record (torn/rotted append).
    with open(path, "r+b") as f:
        f.seek(-1, 2)
        last = f.read(1)
        f.seek(-1, 2)
        f.write(bytes([last[0] ^ 0xFF]))
    s2 = ssd_store(tmp_path)
    try:
        assert s2.metric_corrupt_records >= 1
        assert len(s2) == 2  # the good record survived the torn tail
        pos, cols = s2.take_batch(
            mkeys(2, "good") + mkeys(2, "tail"), NOW
        )
        assert pos.tolist() == [0, 1]
        assert cols["remaining"].tolist() == [0, 1]
    finally:
        s2.close()


def test_compaction_rewrites_live_rows_then_retires(tmp_path):
    # slab_bytes=1: every batch rolls into its own sealed slab, so takes
    # against batch 1 push that slab past the garbage threshold.
    s = ssd_store(tmp_path, slab_bytes=1, compact_ratio=0.4)
    try:
        keys = mkeys(4)
        s.put_columns(keys, mkcols(4), NOW)
        s.flush()
        pos, _ = s.take_batch(keys[:3], NOW)  # 3/4 garbage > 0.4
        assert len(pos) == 3
        s.put_columns(mkeys(2, "next"), mkcols(2), NOW)  # writer maintains
        s.flush()
        assert s.metric_compactions >= 1
        # The survivor moved slabs but kept its row.
        pos, cols = s.take_batch([keys[3]], NOW)
        assert pos.tolist() == [0] and cols["remaining"][0] == 3
    finally:
        s.close()


def test_capacity_retires_oldest_sealed_slab(tmp_path):
    # Budget between one and two sealed slabs (an 8-key record is ~4.4 KB
    # since the zoo columns joined COLD_FIELDS): the oldest slabs retire
    # wholesale and their keys become (cache-semantics) misses while the
    # newest slab stays within budget.
    s = ssd_store(tmp_path, slab_bytes=1, capacity_bytes=6144)
    try:
        s.put_columns(mkeys(8, "old"), mkcols(8), NOW)
        s.flush()
        for g in range(4):
            s.put_columns(mkeys(8, f"g{g}-"), mkcols(8), NOW)
            s.flush()
        assert s.metric_slab_evictions >= 1
        assert s.bytes_used() <= 6144 + s.slab_bytes
        pos, _ = s.take_batch(mkeys(8, "old"), NOW)
        assert len(pos) == 0  # oldest slab's rows are gone
        pos, _ = s.take_batch(mkeys(8, "g3-"), NOW)
        assert len(pos) == 8  # newest survive
    finally:
        s.close()


def test_full_queue_applies_backpressure(tmp_path):
    s = ssd_store(tmp_path, queue_depth=1)
    release = threading.Event()
    orig = s._write_batch

    def gated(bid):
        release.wait(10.0)
        orig(bid)

    s._write_batch = gated
    try:
        s.put_columns(mkeys(1, "a"), mkcols(1), NOW)
        deadline = time.monotonic() + 5.0
        while s._queue.qsize() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)  # writer picked batch A, now gated
        s.put_columns(mkeys(1, "b"), mkcols(1), NOW)  # fills the queue
        t = threading.Thread(
            target=s.put_columns, args=(mkeys(1, "c"), mkcols(1), NOW)
        )
        t.start()
        while s.metric_backpressure == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert s.metric_backpressure >= 1  # full queue counted, not dropped
        release.set()
        t.join(10.0)
        s.flush()
        assert len(s) == 3  # nothing was lost under backpressure
    finally:
        release.set()
        s.close()
