"""Test harness config: run on a virtual 8-device CPU mesh.

Mirrors the reference's trick of testing "multi-node" behavior in one
process (cluster/cluster.go): we test multi-chip sharding on virtual CPU
devices. Must run before jax initializes.

The environment injects a tunneled-TPU PJRT plugin via PYTHONPATH
(.axon_site) whose registration can block on the tunnel even when
JAX_PLATFORMS=cpu — strip it so tests are hermetic and never depend on
tunnel health.
"""

import os
import sys

# GUBER_TEST_TPU=1 runs the suite against the real device (row-layout
# kernels under the actual Mosaic compiler instead of interpret mode);
# default is the hermetic 8-device CPU mesh.
TEST_TPU = os.environ.get("GUBER_TEST_TPU") == "1"
if not TEST_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
if not TEST_TPU:
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    os.environ["PYTHONPATH"] = ":".join(
        p for p in os.environ.get("PYTHONPATH", "").split(":")
        if ".axon_site" not in p
    )

import jax  # noqa: E402

# The tunnel plugin's sitecustomize may have already registered the axon
# backend and forced jax_platforms="axon,cpu" via config (which outranks
# the env var) — force cpu back so tests are hermetic.
if not TEST_TPU:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


# ---------------------------------------------------------------------------
# Minimal asyncio test support (pytest-asyncio isn't in the image):
# coroutine tests run on the module-scoped `event_loop` fixture when they
# (or their fixtures) request it, else on a fresh loop.
# ---------------------------------------------------------------------------
import asyncio
import gc
import inspect

import pytest


@pytest.fixture(autouse=True)
def _boundary_gc():
    """Collect cyclic garbage at test boundaries: grpc.aio servers,
    event loops, and executors carry finalizers that join threads, and
    letting a mid-trace allocation-triggered GC run them deadlocks the
    interpreter against jax's tracing machinery (observed ~1 in 4 full
    runs as a fatal hang in the suite tail).  Boundary collection runs
    those finalizers while the loop infrastructure is still intact."""
    yield
    gc.collect()


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if not inspect.iscoroutinefunction(fn):
        return None
    kwargs = {
        name: pyfuncitem.funcargs[name]
        for name in pyfuncitem._fixtureinfo.argnames
    }
    loop = pyfuncitem.funcargs.get("event_loop")
    if loop is not None:
        loop.run_until_complete(fn(**kwargs))
    else:
        asyncio.run(fn(**kwargs))
    return True
